"""Halo-exchange stencil programs — the long-context / neighbor-comm demo.

The reference's flagship SPMD example is Conway's Game of Life with halo
indexing (docs/src/index.md:160-204) and the 5-point stencil pattern built
from sendto/recvfrom rings (test/spmd.jl:84-101).  BASELINE.json config 4
pins "spmd halo-exchange 5-point stencil on 8192×8192, sendto/recvfrom →
lax.ppermute".

TPU-native: the grid is row-sharded over a 1-D mesh; each step is ONE
compiled shard_map program in which boundary rows ride two ``ppermute``s
over ICI and the interior update fuses into the surrounding elementwise
work.  Multi-step runs roll the whole iteration loop into ``lax.scan`` so
the chain compiles once — this is exactly the communication substrate of
ring attention / context parallelism (halo ↔ block-shift of KV blocks).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import layout as L
from ..darray import DArray, _wrap_global, distribute
from ..parallel.collectives import (axis_size as _axis_size, halo_exchange,
                                    halo_exchange_2d, shard_map_compat)

__all__ = ["stencil5_step", "stencil5", "stencil3x3", "life_step", "life",
           "life2d"]


def _row_mesh(d: DArray):
    pids = [int(p) for p in d.pids.flat]
    n = len(pids)
    if d.pids.ndim != 2 or d.pids.shape[1] != 1 or d.dims[0] % n != 0:
        raise ValueError(
            "stencil programs need a row-sharded even layout: "
            f"dist=({n},1) with rows divisible; got grid {d.pids.shape} "
            f"for dims {d.dims}")
    return L.mesh_for(pids, (n, 1)), pids


def _stencil_kernel(axis: str, use_pallas: bool, weights):
    def step(block):
        lo, hi = halo_exchange(block, axis, halo=1, dim=0, wrap=False)
        if use_pallas:
            # single-pass VMEM-streaming kernel (ops/pallas_stencil.py):
            # approaches the read+write bandwidth roofline where the jnp
            # formulation below costs several HBM round-trips
            from ..ops.pallas_stencil import stencil3x3_block
            return stencil3x3_block(block, lo, hi, weights)
        from ..ops.pallas_stencil import _apply3x3
        ext = jnp.concatenate([lo, block, hi], axis=0)
        return _apply3x3(ext, weights)
    return step


def _stencil_multistep(axis: str, k: int, weights):
    """k steps per launch: k-deep halo + the temporal-blocked kernel."""
    from ..ops.pallas_stencil import stencil3x3_multistep

    def steps(block):
        lo, hi = halo_exchange(block, axis, halo=k, dim=0, wrap=False)
        r = lax.axis_index(axis)
        nr = _axis_size(axis)
        return stencil3x3_multistep(block, lo, hi, k, r == 0, r == nr - 1,
                                    weights)
    return steps


@functools.lru_cache(maxsize=32)
def _stencil_jit(mesh, iters: int, use_pallas: bool, temporal: int,
                 weights):
    axis = mesh.axis_names[0]
    step = _stencil_kernel(axis, use_pallas, weights)

    def many(block):
        if temporal > 1:
            # temporal blocking: scan over k-step launches + remainder
            # (a 1-step remainder takes the cheaper streaming kernel — the
            # multistep path's gather buys nothing at k=1)
            nfull, rem = divmod(iters, temporal)
            if nfull:
                stepk = _stencil_multistep(axis, temporal, weights)

                def body(b, _):
                    return stepk(b), None
                block, _ = lax.scan(body, block, None, length=nfull)
            if rem == 1:
                block = step(block)
            elif rem:
                block = _stencil_multistep(axis, rem, weights)(block)
            return block

        def body(b, _):
            return step(b), None
        out, _ = lax.scan(body, block, None, length=iters)
        return out

    return jax.jit(shard_map_compat(many, mesh=mesh,
                                 in_specs=P(axis, None),
                                 out_specs=P(axis, None), check=False))


def stencil5_step(d: DArray) -> DArray:
    """One 5-point Laplacian step with zero boundary (reference pattern,
    docs/src/index.md:160-181)."""
    return stencil5(d, iters=1)


def stencil3x3(d: DArray, weights, iters: int = 1,
               use_pallas: bool | None = None,
               temporal: int | None = None) -> DArray:
    """``iters`` weighted 3x3 stencil steps compiled as one program
    (lax.scan over the halo-exchange step; communication = 2
    ppermutes/step over ICI): ``out[i,j] = sum_ab w[a][b]*x[i-1+a,j-1+b]``
    with zero boundary.  Diffusion steps, blurs, sharpen filters and the
    5-point Laplacian (``stencil5``) are all instances; weights compile
    into the kernel, so zero taps cost nothing.

    ``use_pallas`` defaults to auto: the Pallas streaming kernel on TPU,
    the jnp formulation elsewhere (pass explicitly to override; off-TPU
    the kernel runs in interpreter mode).

    ``temporal`` (Pallas path only) runs that many steps per kernel launch
    with depth-``temporal`` halos (ghost-zone temporal blocking), cutting
    HBM traffic per step ~``temporal``-fold.  Defaults to an auto depth
    (up to 8) when the layout supports it; pass 1 to force the streaming
    single-step kernel."""
    from ..ops.pallas_stencil import _canon_weights
    w = _canon_weights(weights)
    iters = int(iters)
    if use_pallas is None:
        from ..ops.pallas_gemm import _on_tpu
        from ..ops.pallas_stencil import supports
        use_pallas = (_on_tpu()
                      and supports(d.dims[0] // d.pids.size, d.dims[1],
                                   d.dtype))
    kt = 1
    if use_pallas and iters > 1:
        from ..ops.pallas_stencil import supports
        m_local = d.dims[0] // d.pids.size
        if temporal is None:
            # the multistep launch costs ~2 extra grid passes (the gather
            # materializes through HBM), so depths below 3 don't pay for
            # themselves — auto engages only when a k >= 3 fits
            kt = min(iters, 8, m_local)
            while kt > 2 and not supports(m_local, d.dims[1], d.dtype, kt):
                kt -= 1
            if kt <= 2:
                kt = 1
        else:
            kt = max(1, min(int(temporal), iters))
            if kt > 1 and (kt > m_local
                           or not supports(m_local, d.dims[1], d.dtype, kt)):
                raise ValueError(
                    f"temporal={temporal} unsupported for this layout "
                    f"(local block {m_local}x{d.dims[1]} {d.dtype})")
    mesh, pids = _row_mesh(d)
    res = _stencil_jit(mesh, iters, bool(use_pallas), kt, w)(d.garray)
    return _wrap_global(res, procs=pids, dist=list(d.pids.shape))


def stencil5(d: DArray, iters: int = 1,
             use_pallas: bool | None = None,
             temporal: int | None = None) -> DArray:
    """``iters`` 5-point Laplacian steps with zero boundary — the
    reference pattern (docs/src/index.md:160-181), as ``stencil3x3`` with
    the Laplacian weights.  See ``stencil3x3`` for the knobs.

    Note on bitwise reproducibility: for ``iters > 1`` on TPU the kernel
    auto-enables temporal blocking (up to 8 steps per launch), which
    changes the floating-point summation order — results drift by
    rounding noise, not bitwise-identical to the per-step kernel.  Pass
    ``temporal=1`` to force one halo exchange per step and recover the
    round-2 launch-per-step numerics."""
    from ..ops.pallas_stencil import LAPLACIAN_3X3
    return stencil3x3(d, LAPLACIAN_3X3, iters, use_pallas, temporal)


# ---------------------------------------------------------------------------
# Game of Life (reference docs/src/index.md:160-204)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=32)
def _life_jit(mesh, iters: int):
    axis = mesh.axis_names[0]

    def step(block):
        lo, hi = halo_exchange(block, axis, halo=1, dim=0, wrap=False)
        x = jnp.concatenate([lo, block, hi], axis=0)
        xp = jnp.pad(x, ((0, 0), (1, 1)))
        # 3x3 neighbor sums for the m center rows (halo rows drop out of the
        # row slices; column pad handles the lateral boundary)
        neigh = (xp[:-2, :-2] + xp[:-2, 1:-1] + xp[:-2, 2:] +
                 xp[1:-1, :-2] + xp[1:-1, 2:] +
                 xp[2:, :-2] + xp[2:, 1:-1] + xp[2:, 2:])
        alive = x[1:-1, :]
        born = (alive == 0) & (neigh == 3)
        survive = (alive == 1) & ((neigh == 2) | (neigh == 3))
        return jnp.where(born | survive, 1, 0).astype(block.dtype)

    def many(block):
        def body(b, _):
            return step(b), None
        out, _ = lax.scan(body, block, None, length=iters)
        return out

    return jax.jit(shard_map_compat(many, mesh=mesh,
                                 in_specs=P(axis, None),
                                 out_specs=P(axis, None), check=False))


@functools.lru_cache(maxsize=32)
def _life2d_jit(mesh, iters: int):
    ax0, ax1 = mesh.axis_names[0], mesh.axis_names[1]

    def step(block):
        xp = halo_exchange_2d(block, (ax0, ax1), halo=1, wrap=False)
        neigh = (xp[:-2, :-2] + xp[:-2, 1:-1] + xp[:-2, 2:] +
                 xp[1:-1, :-2] + xp[1:-1, 2:] +
                 xp[2:, :-2] + xp[2:, 1:-1] + xp[2:, 2:])
        alive = xp[1:-1, 1:-1]
        born = (alive == 0) & (neigh == 3)
        survive = (alive == 1) & ((neigh == 2) | (neigh == 3))
        return jnp.where(born | survive, 1, 0).astype(block.dtype)

    def many(block):
        def body(b, _):
            return step(b), None
        out, _ = lax.scan(body, block, None, length=iters)
        return out

    return jax.jit(shard_map_compat(many, mesh=mesh,
                                 in_specs=P(ax0, ax1),
                                 out_specs=P(ax0, ax1), check=False))


def life2d(d: DArray, iters: int = 1) -> DArray:
    """Game of Life on a fully 2-D-sharded grid: both dimensions
    distributed, corners exchanged via the two-phase 2-D halo (the
    reference's Life demo, docs/src/index.md:160-204, at its most general
    layout)."""
    pids = [int(p) for p in d.pids.flat]
    g0, g1 = d.pids.shape
    if d.dims[0] % g0 or d.dims[1] % g1:
        raise ValueError(
            f"life2d needs an even layout; got grid {d.pids.shape} for "
            f"dims {d.dims}")
    mesh = L.mesh_for(pids, (g0, g1))
    res = _life2d_jit(mesh, int(iters))(d.garray)
    return _wrap_global(res, procs=pids, dist=[g0, g1])


def life_step(d: DArray) -> DArray:
    return life(d, iters=1)


def life(d: DArray, iters: int = 1) -> DArray:
    """Conway's Game of Life with zero (dead) boundary, the reference's
    distributed demo (docs/src/index.md:160-204)."""
    mesh, pids = _row_mesh(d)
    res = _life_jit(mesh, int(iters))(d.garray)
    return _wrap_global(res, procs=pids, dist=list(d.pids.shape))
