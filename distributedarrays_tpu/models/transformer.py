"""Flagship model: a GPT-style transformer stack on the framework's kernels.

Composes the pieces this framework provides into one trainable model:

- attention = the Pallas flash kernel (ops/pallas_attention.py) with the
  batch dim folded into the head axis — one kernel call, no vmap, no
  O(S²) score matrix;
- FFN and QKV/projection weights laid out Megatron-style over the ``tp``
  mesh axis (column-parallel up, row-parallel down) so GSPMD inserts the
  contraction psums;
- batch data-parallel over ``dp``; gradients all-reduce over dp
  automatically;
- one jitted train step (cross-entropy on next-token, SGD, donated
  params).

Used by ``__graft_entry__.entry()`` as the flagship forward and by the
multichip dry-run as the dp×tp training step.  For sequence lengths beyond
one chip's HBM, swap the attention call for ``models.ring_attention`` /
``models.ulysses`` — same (S, H, D) contract.
"""

from __future__ import annotations

import functools
import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pallas_attention import flash_attention
from .mlp import make_mesh

__all__ = ["init_params", "forward", "loss_fn", "train_step",
           "make_optax_train_step", "generate",
           "shard_params", "make_mesh", "Config"]


class Config:
    def __init__(self, vocab=256, dim=128, heads=4, layers=2, ffn_mult=4,
                 max_seq=128, dtype=jnp.bfloat16):
        if dim % heads:
            raise ValueError(f"dim {dim} must be divisible by heads {heads}")
        self.vocab, self.dim, self.heads = vocab, dim, heads
        self.layers, self.ffn_mult, self.max_seq = layers, ffn_mult, max_seq
        self.dtype = dtype

    def _key(self):
        return (self.vocab, self.dim, self.heads, self.layers,
                self.ffn_mult, self.max_seq, str(self.dtype))

    # value-hashable so jit's static_argnames reuses one compilation per
    # configuration, not per Config instance
    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, Config) and self._key() == other._key()


def init_params(key, cfg: Config):
    E, F, H = cfg.dim, cfg.dim * cfg.ffn_mult, cfg.heads
    dt = cfg.dtype

    def dense(k, shape, fan_in):
        return jax.random.normal(k, shape, dt) * jnp.asarray(
            np.sqrt(1.0 / fan_in), dt)

    keys = iter(jax.random.split(key, 3 + 4 * cfg.layers))
    params = {
        "embed": dense(next(keys), (cfg.vocab, E), E),
        "pos": dense(next(keys), (cfg.max_seq, E), E),
        "ln_f": jnp.ones((E,), dt),
        "head": dense(next(keys), (E, cfg.vocab), E),
        "blocks": [],
    }
    for _ in range(cfg.layers):
        params["blocks"].append({
            "ln1": jnp.ones((E,), dt),
            "qkv": dense(next(keys), (E, 3 * E), E),
            "proj": dense(next(keys), (E, E), E),
            "ln2": jnp.ones((E,), dt),
            "w1": dense(next(keys), (E, F), E),
            "w2": dense(next(keys), (F, E), F),
        })
    return params


def shard_params(params, mesh: Mesh):
    """Megatron layout: qkv/w1 column-parallel (split output features over
    tp), proj/w2 row-parallel (split input features); embeddings and norms
    replicated."""
    def put(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))  # dalint: disable=DAL007 — initial host→mesh parameter placement, no source layout

    out = {
        "embed": put(params["embed"], P(None, None)),
        "pos": put(params["pos"], P(None, None)),
        "ln_f": put(params["ln_f"], P(None)),
        "head": put(params["head"], P(None, "tp")),
        "blocks": [],
    }
    for b in params["blocks"]:
        out["blocks"].append({
            "ln1": put(b["ln1"], P(None)),
            "qkv": put(b["qkv"], P(None, "tp")),
            "proj": put(b["proj"], P("tp", None)),
            "ln2": put(b["ln2"], P(None)),
            "w1": put(b["w1"], P(None, "tp")),
            "w2": put(b["w2"], P("tp", None)),
        })
    return out


def _rmsnorm(x, scale):
    x32 = x.astype(jnp.float32)
    n = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True)
                            + 1e-6)
    return (n * scale.astype(jnp.float32)).astype(x.dtype)


def _attention(x, blk, heads):
    B, S, E = x.shape
    D = E // heads
    qkv = x @ blk["qkv"]                                  # (B, S, 3E)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    # pad the sequence to a healthy block multiple (tiny or odd S would
    # force degenerate flash blocks); padded KEYS sit at positions >= S so
    # the causal mask hides them from every real query row, and padded
    # query rows are sliced away below.  Pick the largest block whose
    # padding waste stays under ~1/8 of S — a flat 512 would pad S=513 to
    # 1024 and near-double the attention work
    bs = next(b for b in (512, 256, 128, 64, 32)
              if b == 32 or (-(-S // b) * b - S) * 8 <= S)
    Spad = -(-S // bs) * bs

    def fold(t):
        # (B, S, E) -> (Spad, B*heads, D): batch folds into the head axis
        # so ONE flash-kernel call covers the whole batch (causality is
        # per-head, so folding is exact)
        t = jnp.pad(t, ((0, 0), (0, Spad - S), (0, 0)))
        return jnp.transpose(t.reshape(B, Spad, heads, D),
                             (1, 0, 2, 3)).reshape(Spad, B * heads, D)

    o = flash_attention(fold(q), fold(k), fold(v), causal=True,
                        block_q=bs, block_k=bs)
    o = jnp.transpose(o.reshape(Spad, B, heads, D),
                      (1, 0, 2, 3)).reshape(B, Spad, E)[:, :S]
    return o @ blk["proj"]


def forward(params, tokens, cfg: Config):
    """tokens: (B, S) int32 → logits (B, S, vocab)."""
    B, S = tokens.shape
    if S > cfg.max_seq:
        raise ValueError(f"sequence length {S} exceeds max_seq {cfg.max_seq}")
    x = params["embed"][tokens] + params["pos"][:S][None]
    for blk in params["blocks"]:
        x = x + _attention(_rmsnorm(x, blk["ln1"]), blk, cfg.heads)
        h = _rmsnorm(x, blk["ln2"])
        x = x + jax.nn.gelu(h @ blk["w1"]) @ blk["w2"]
    return (_rmsnorm(x, params["ln_f"]) @ params["head"]).astype(jnp.float32)


def loss_fn(params, tokens, cfg: Config):
    """Next-token cross-entropy."""
    logits = forward(params, tokens[:, :-1], cfg)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return -jnp.mean(ll)


def _decode_attn(h, blk, heads, kc, vc, i, t, max_seq):
    """One decode position through layer ``i``'s attention with the
    stacked (L, B, max_seq, H, D) KV caches updated in place at ``t``.
    Full-cache einsum with a position mask — the standard static-shape
    decode step (small, memory-bound; the flash kernel is for prefill/
    training shapes)."""
    B, _, E = h.shape
    D = E // heads
    qkv = h @ blk["qkv"]
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, heads, D).astype(jnp.float32)
    upd = lambda c, val: jax.lax.dynamic_update_slice(
        c, val.reshape(1, B, 1, heads, D).astype(c.dtype), (i, 0, t, 0, 0))
    kc, vc = upd(kc, k), upd(vc, v)
    s = jnp.einsum("bhd,bkhd->bhk", q / np.sqrt(D),
                   kc[i].astype(jnp.float32))
    mask = jnp.arange(max_seq) <= t
    s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhk,bkhd->bhd", p, vc[i].astype(jnp.float32))
    return (o.reshape(B, 1, E).astype(h.dtype) @ blk["proj"]), kc, vc


@functools.partial(jax.jit,
                   static_argnames=("cfg", "n_new", "temperature"))
def generate(params, prompt, n_new: int, cfg: Config,
             temperature: float = 0.0, key=None):
    """Autoregressive generation: ``n_new`` tokens appended to ``prompt``
    (B, S0) int32, returned as (B, S0 + n_new).

    The ENTIRE decode — prompt prefill (teacher-forced through the same
    step) and generation — is one ``lax.scan`` under jit with per-layer
    KV caches as the carry: static shapes, no per-token dispatch, no
    Python in the loop.  ``temperature`` 0 = greedy argmax; > 0 samples
    categorically (``key`` required).  Parameters keep their shardings,
    so the tp/dp layouts of ``shard_params`` decode unchanged.
    """
    B, S0 = prompt.shape
    total = S0 + n_new
    if total > cfg.max_seq:
        raise ValueError(f"prompt {S0} + n_new {n_new} exceeds max_seq "
                         f"{cfg.max_seq}")
    if temperature > 0.0 and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    H, D = cfg.heads, cfg.dim // cfg.heads
    Lb = cfg.layers
    kc = jnp.zeros((Lb, B, cfg.max_seq, H, D), cfg.dtype)
    vc = jnp.zeros_like(kc)
    keys = (jax.random.split(key, max(total - 1, 1)) if key is not None
            else jnp.zeros((max(total - 1, 1), 2), jnp.uint32))

    def step(carry, inputs):
        kc, vc, tok = carry
        t, kt = inputs
        x = (params["embed"][tok][:, None]
             + params["pos"][t][None, None]).astype(cfg.dtype)
        for i, blk in enumerate(params["blocks"]):
            a, kc, vc = _decode_attn(_rmsnorm(x, blk["ln1"]), blk, H,
                                     kc, vc, i, t, cfg.max_seq)
            x = x + a
            h2 = _rmsnorm(x, blk["ln2"])
            x = x + jax.nn.gelu(h2 @ blk["w1"]) @ blk["w2"]
        logits = (_rmsnorm(x[:, 0], params["ln_f"])
                  @ params["head"]).astype(jnp.float32)      # (B, V)
        if temperature > 0.0:
            nxt = jax.random.categorical(kt, logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        nxt = nxt.astype(prompt.dtype)
        # teacher-force while still inside the prompt (index capped at
        # S0-1, so it never reads past the prompt)
        nxt = jnp.where(t + 1 < S0, prompt[:, jnp.minimum(t + 1, S0 - 1)],
                        nxt)
        return (kc, vc, nxt), nxt

    ts = jnp.arange(total - 1)
    (_, _, _), toks = jax.lax.scan(step, (kc, vc, prompt[:, 0]),
                                   (ts, keys[: total - 1]))
    # toks[t] is the token at position t+1
    return jnp.concatenate([prompt[:, :1], jnp.swapaxes(toks, 0, 1)],
                           axis=1)


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def train_step(params, tokens, lr, cfg: Config):
    """One SGD step: value_and_grad of ``loss_fn`` + an fp32 update
    (bf16 params upcast for the arithmetic, downcast after) with donated
    buffers; GSPMD inserts the tp psums and dp grad all-reduce."""
    loss, g = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    new = jax.tree_util.tree_map(
        lambda p, gg: (p.astype(jnp.float32) - lr * gg.astype(jnp.float32))
        .astype(p.dtype), params, g)
    return new, loss


def _optax_f32_step(tx, grad_fn):
    """Shared optax step with fp32 master arithmetic: bf16 params/grads
    upcast before ``tx.update`` + ``apply_updates`` and downcast after —
    at bf16 resolution (~8 mantissa bits) Adam-scale updates against
    O(0.1) weights would otherwise round to zero and training silently
    stalls.  State must be initialized from fp32 params (use the
    returned ``init``)."""
    import optax

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        loss, g = grad_fn(params, tokens)
        p32 = _as_f32(params)
        updates, opt_state = tx.update(_as_f32(g), opt_state, p32)
        new32 = optax.apply_updates(p32, updates)
        new = jax.tree_util.tree_map(
            lambda n, p: n.astype(p.dtype), new32, params)
        return new, opt_state, loss

    def init(params):
        return _optax_f32_init(tx, params)

    return step, init


def _as_f32(t):
    return jax.tree_util.tree_map(lambda x: x.astype(jnp.float32), t)


def _optax_f32_init(tx, params):
    """Optimizer-state init from fp32 master params — the ONE owner of
    the fp32-master policy's init half, shared by every step factory
    (``_optax_f32_step`` here, ``sp_transformer.make_optax_train_step``)
    so the upcast rule cannot silently diverge between them."""
    return tx.init(_as_f32(params))


def make_optax_train_step(cfg: Config, tx):
    """Training with any optax optimizer under the GSPMD model: one jit
    of value_and_grad + ``tx.update`` in fp32 master precision; XLA lays
    the optimizer state out to match each param's sharding
    (Megatron-sharded qkv/proj/w1/w2 moments stay tp-sharded).  Returns
    ``(step, init)``: ``state = init(params)``, then
    ``step(params, opt_state, tokens) -> (params, opt_state, loss)``."""
    def grad_fn(params, tokens):
        return jax.value_and_grad(loss_fn)(params, tokens, cfg)

    return _optax_f32_step(tx, grad_fn)
