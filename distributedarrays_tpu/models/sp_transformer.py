"""Sequence-parallel transformer: long-context training as ONE shard_map
program per step.

Where ``models/transformer.py`` is the GSPMD flagship (XLA infers the
collectives from shardings), this model is the explicit-SPMD composition
of the framework's round-3 pieces — activations stay sequence-sharded
``(s_loc, e)`` end to end, so the full sequence never materializes on any
chip:

- attention: ``ring_flash_attention_kernel`` (context parallelism — K/V
  blocks ride the ppermute ring through Pallas flash hops, differentiable
  FA2 ring backward);
- FFN: ``tp_ffn`` (ring all-gather GEMM -> gelu -> GEMM + reduce-scatter,
  Megatron sequence-parallel layout, both hops pipelined behind the MXU);
- loss: next-token cross-entropy with the shift crossing rank boundaries
  via one ``pshift`` (each rank fetches its right neighbor's first
  token), masked at the global sequence end, averaged with ``psum``.

Batch folds into the head axis for attention (exact — causality is
per-head) and into the row axis for the FFN (exact — the AG->RS ring
returns each rank's rows to it), so one kernel call covers the batch.

The reference's long-context substrate is its SPMD ring programs
(/root/reference/test/spmd.jl:90-101); this is that substrate promoted to
a trainable model family.  See tests/test_transformer.py for the
dense-oracle gradient tests and ``__graft_entry__.dryrun_multichip`` for
the multi-device training leg.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..ops.collective_matmul import tp_ffn
from ..parallel import collectives as C
from ..parallel.collectives import axis_size as _axis_size
from .ring_attention import (ring_flash_attention_kernel,
                             zigzag_ring_flash_attention_kernel)
from .transformer import Config, _rmsnorm
from .transformer import init_params as _transformer_init_params

__all__ = ["SPConfig", "init_params", "param_specs", "forward_local",
           "loss_local", "make_train_step", "make_grad_fn",
           "make_optax_train_step"]


class SPConfig(Config):
    """transformer.Config plus the shard_map knobs: ``block_q``/``block_k``
    feed the Pallas flash hops; ``interpret`` forces interpreter mode
    (auto: on for non-TPU backends); ``zigzag`` switches to the
    load-balanced causal layout (rank i holds sequence-chunk pair
    ``(i, 2P-1-i)`` — feed tokens permuted by ``zigzag_order``)."""

    def __init__(self, vocab=256, dim=128, heads=4, layers=2, ffn_mult=4,
                 max_seq=128, dtype=jnp.bfloat16, block_q=None, block_k=None,
                 interpret=None, zigzag=False, head_fold=None):
        # block_q/block_k/head_fold None = take the autotune registry's
        # tuned hop config (banked by bench.py's hardware sweep), falling
        # back to the kernel's 512²/1 default.  The train-step factories
        # resolve the Nones OUTSIDE their cached jits (``_resolve_cfg``)
        # so a tune banked after the first step is picked up, not
        # silently pinned at first trace (ADVICE round-4).
        super().__init__(vocab, dim, heads, layers, ffn_mult, max_seq,
                         dtype)
        self.block_q, self.block_k = block_q, block_k
        self.head_fold = head_fold
        self.interpret = interpret
        self.zigzag = bool(zigzag)

    def _key(self):
        return super()._key() + (self.block_q, self.block_k, self.head_fold,
                                 self.interpret, self.zigzag)


def init_params(key, cfg: SPConfig):
    """Identical pytree to ``transformer.init_params`` (same family, same
    init scheme); ``param_specs`` shards the FFN weights over the sp axis,
    the rest replicated."""
    return _transformer_init_params(key, cfg)


def param_specs(cfg: SPConfig, axis: str = "p"):
    """PartitionSpec pytree mirroring ``init_params``: w1 column-sharded,
    w2 row-sharded over the sp axis (the Megatron layout ``tp_ffn``
    expects), everything else replicated."""
    blk = {"ln1": P(None), "qkv": P(None, None), "proj": P(None, None),
           "ln2": P(None), "w1": P(None, axis), "w2": P(axis, None)}
    return {"embed": P(None, None), "pos": P(None, None), "ln_f": P(None),
            "head": P(None, None), "blocks": [dict(blk)] * cfg.layers}


def forward_local(params, tokens_loc, cfg: SPConfig, axis: str):
    """Per-rank forward inside shard_map.  ``tokens_loc``: ``(b, s_loc)``
    — this rank's sequence chunk: contiguous by default, or the
    ``(i, 2p-1-i)`` chunk pair when ``cfg.zigzag`` (shard tokens
    pre-permuted by ``ring_attention.zigzag_order``).  Returns ``(b,
    s_loc, vocab)`` f32 logits for the rank's positions (same layout as
    the input chunk)."""
    Bt, S_loc = tokens_loc.shape
    H = cfg.heads
    E = cfg.dim
    D = E // H
    p = _axis_size(axis)                  # static at trace time
    if S_loc * p > cfg.max_seq:
        # dynamic_slice would CLAMP out-of-table position reads (silently
        # reusing earlier ranks' embeddings); fail loudly instead, like
        # the dense transformer.forward does
        raise ValueError(
            f"global sequence length {S_loc * p} exceeds max_seq "
            f"{cfg.max_seq}")
    me = lax.axis_index(axis)

    if cfg.zigzag:
        # rank's positions are the chunk pair (me, 2p-1-me), C2 each
        if S_loc % 2:
            raise ValueError(
                f"zigzag needs an even per-rank length, got {S_loc}")
        C2 = S_loc // 2
        ar = jnp.arange(C2)
        idx = jnp.concatenate([me * C2 + ar, (2 * p - 1 - me) * C2 + ar])
        pos = jnp.take(params["pos"], idx, axis=0)
    else:
        pos = lax.dynamic_slice_in_dim(params["pos"], me * S_loc, S_loc, 0)
    x = params["embed"][tokens_loc] + pos[None]          # (b, s_loc, e)

    for blk in params["blocks"]:
        h = _rmsnorm(x, blk["ln1"])
        qkv = h @ blk["qkv"]                             # (b, s_loc, 3e)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def fold(t):
            # (b, s_loc, e) -> (s_loc, b*h, d): batch folds into heads
            return jnp.transpose(t.reshape(Bt, S_loc, H, D),
                                 (1, 0, 2, 3)).reshape(S_loc, Bt * H, D)

        if cfg.zigzag:
            o = zigzag_ring_flash_attention_kernel(
                fold(q), fold(k), fold(v), axis,
                block_q=cfg.block_q, block_k=cfg.block_k,
                head_fold=cfg.head_fold, interpret=cfg.interpret)
        else:
            o = ring_flash_attention_kernel(
                fold(q), fold(k), fold(v), axis, causal=True,
                block_q=cfg.block_q, block_k=cfg.block_k,
                head_fold=cfg.head_fold, interpret=cfg.interpret)
        o = jnp.transpose(o.reshape(S_loc, Bt, H, D),
                          (1, 0, 2, 3)).reshape(Bt, S_loc, E)
        x = x + o @ blk["proj"]

        h2 = _rmsnorm(x, blk["ln2"])
        # batch folds into rows: the AG->RS ring returns each rank's rows
        f = tp_ffn(h2.reshape(Bt * S_loc, E), blk["w1"], blk["w2"], axis)
        x = x + f.reshape(Bt, S_loc, E)

    return (_rmsnorm(x, params["ln_f"]) @ params["head"]).astype(jnp.float32)


def _loss_partial(params, tokens_loc, cfg: SPConfig, axis: str):
    """This rank's share of the next-token CE: local masked total over
    the GLOBAL valid count.  Summing (psum) over ranks gives the global
    mean loss.  Chunk-tail targets live on statically known neighbor
    ranks, so the shift is one ``pshift`` per chunk; the final global
    position has no target and is masked.

    Contiguous layout: rank i's tail target is rank i+1's first token;
    rank p-1's tail is the global end (masked).  Zigzag layout (chunk
    pair ``(i, 2p-1-i)``): chunk i's successor i+1 is rank i+1's FIRST
    chunk (rank p-1's: its own second chunk), and chunk ``2p-1-i``'s
    successor ``2p-i`` is rank i-1's SECOND chunk (rank 0's: the global
    end, masked)."""
    p = _axis_size(axis)
    me = lax.axis_index(axis)
    Bt, S_loc = tokens_loc.shape

    logits = forward_local(params, tokens_loc, cfg, axis)
    if cfg.zigzag:
        C2 = S_loc // 2
        ta, tb = tokens_loc[:, :C2], tokens_loc[:, C2:]
        nxt_a = C.pshift(ta[:, :1], axis, -1)        # rank i+1's chunk-a head
        nxt_a = jnp.where(me == p - 1, tb[:, :1], nxt_a)
        nxt_b = C.pshift(tb[:, :1], axis, 1)         # rank i-1's chunk-b head
        targets = jnp.concatenate([ta[:, 1:], nxt_a, tb[:, 1:], nxt_b],
                                  axis=1)
        end_rank = 0                                 # chunk 2p-1 sits on rank 0
    else:
        nxt_first = C.pshift(tokens_loc[:, :1], axis, -1)
        targets = jnp.concatenate([tokens_loc[:, 1:], nxt_first], axis=1)
        end_rank = p - 1
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    valid = jnp.ones((Bt, S_loc), jnp.float32)
    valid = valid.at[:, -1].set(jnp.where(me == end_rank, 0.0, 1.0))
    # count is data-independent of params; the psum carries no gradient
    count = lax.psum(jnp.sum(valid), axis)
    return jnp.sum(-ll * valid) / count


def loss_local(params, tokens_loc, cfg: SPConfig, axis: str):
    """Global mean next-token CE (psum'd — identical on every rank).
    For training use ``_loss_partial`` under ``value_and_grad`` and psum
    the value afterwards: differentiating THROUGH this psum scales every
    gradient by the axis size (psum's SPMD transpose is another psum)."""
    return lax.psum(_loss_partial(params, tokens_loc, cfg, axis), axis)


def _resolve_cfg(cfg: SPConfig, mesh, axis: str, tokens_shape) -> SPConfig:
    """Resolve ``None`` hop knobs against the autotune registry OUTSIDE
    any cached jit: returns an SPConfig whose block_q/block_k/head_fold
    are concrete, suitable as a program-cache key.  Resolving at trace
    time inside a cached step would pin the registry's state at first
    trace — a tune banked after step 1 would be silently ignored for the
    life of the program (ADVICE round-4; same contract as
    ``tuned_flash_config`` / models/ulysses.py)."""
    if (cfg.block_q is not None and cfg.block_k is not None
            and cfg.head_fold is not None):
        return cfg
    from .ring_attention import tuned_hop_blocks_for
    B, S = tokens_shape
    p = mesh.shape[axis]
    # forward_local's fold: q is (s_loc, b*heads, head_dim) in cfg.dtype;
    # both ring layouts tune under causal=True
    shape = (S // p, B * cfg.heads, cfg.dim // cfg.heads)
    bq, bk, hf = tuned_hop_blocks_for(shape, jnp.dtype(cfg.dtype), True,
                                      cfg.block_q, cfg.block_k)
    if cfg.head_fold is not None:
        hf = cfg.head_fold
    return SPConfig(cfg.vocab, cfg.dim, cfg.heads, cfg.layers,
                    cfg.ffn_mult, cfg.max_seq, cfg.dtype,
                    block_q=int(bq), block_k=int(bk),
                    interpret=cfg.interpret, zigzag=cfg.zigzag,
                    head_fold=int(hf))


def make_grad_fn(mesh, cfg: SPConfig, axis: str = "p"):
    """The (loss, grads) program shared by both train steps: tokens
    sharded ``(b, s/p)``, replicated-param grads psum'd EXPLICITLY
    (check=False disables shard_map's automatic replication
    accounting), FFN-shard grads staying sharded.  The returned callable
    resolves ``None`` hop knobs per call (``_resolve_cfg``) and
    dispatches to a shard_map program cached on the RESOLVED config, so
    later-banked tunes take effect."""
    def grad_fn(params, tokens):
        rcfg = _resolve_cfg(cfg, mesh, axis, tokens.shape)
        return _grad_program(mesh, rcfg, axis)(params, tokens)

    return grad_fn


@functools.lru_cache(maxsize=32)
def _grad_program(mesh, cfg: SPConfig, axis: str):
    """The shard_map (loss, grads) program for a RESOLVED config (cfg is
    value-hashable; one program per configuration)."""
    specs = param_specs(cfg, axis)

    def local(params, tokens_loc):
        # differentiate the PARTIAL loss: grads of the psum'd mean would
        # come back scaled by the axis size (psum transposes to psum)
        part, g = jax.value_and_grad(_loss_partial)(params, tokens_loc,
                                                    cfg, axis)
        loss = lax.psum(part, axis)
        # check=False puts replication maintenance on us: each rank's
        # grad for a REPLICATED param is only its partial (its own token
        # shard's contribution) — without this psum the per-rank param
        # copies silently diverge after the first update (caught by the
        # checkpoint round-trip test: save() reads shard 0).  Sharded
        # params (w1/w2) already receive their cross-rank contributions
        # through the ring collectives' transposes.
        g = jax.tree_util.tree_map(
            lambda spec, gg: (lax.psum(gg, axis)
                              if all(s is None for s in spec) else gg),
            specs, g)
        return loss, g

    return C.shard_map_compat(local, mesh=mesh,
                         in_specs=(specs, P(None, axis)),
                         out_specs=(P(), specs), check=False)


def make_optax_train_step(mesh, cfg: SPConfig, tx, axis: str = "p"):
    """Training with any optax optimizer: the (loss, grads) shard_map
    program composed with ``tx.update`` under ONE jit, in fp32 master
    precision (bf16 params/grads upcast for the optimizer arithmetic —
    see ``transformer._optax_f32_step``) — GSPMD lays the optimizer
    state out to match each param (Adam moments for the tp-sharded FFN
    weights stay sharded, replicated params' moments replicated).  Hop
    knobs left ``None`` resolve per call against the autotune registry,
    outside the jitted-step cache (``_resolve_cfg``).  Returns ``(step,
    init)``: ``state = init(params)``, then ``step(params, opt_state,
    tokens) -> (params, opt_state, loss)``.

    Example::

        tx = optax.adamw(1e-3)
        step, init = make_optax_train_step(mesh, cfg, tx)
        state = init(params)
        params, state, loss = step(params, state, tokens)
    """
    from .transformer import _optax_f32_step

    built = {}

    def step(params, opt_state, tokens):
        rcfg = _resolve_cfg(cfg, mesh, axis, tokens.shape)
        if rcfg not in built:
            built[rcfg] = _optax_f32_step(
                tx, lambda p, t: _grad_program(mesh, rcfg, axis)(p, t))[0]
        return built[rcfg](params, opt_state, tokens)

    def init(params):
        # block-knob independent; fp32-master policy owned by transformer
        from .transformer import _optax_f32_init
        return _optax_f32_init(tx, params)

    return step, init


def make_train_step(mesh, cfg: SPConfig, axis: str = "p"):
    """One jitted SGD train step over ``mesh``: the gradient program plus
    the SGD update under one jit (use ``make_optax_train_step`` for a
    real optimizer).  Hop knobs left ``None`` resolve per call against
    the autotune registry, outside the jitted-step cache.  Returns
    ``step(params, tokens, lr) -> (params, loss)``."""
    def step(params, tokens, lr):
        rcfg = _resolve_cfg(cfg, mesh, axis, tokens.shape)
        return _sgd_step(mesh, rcfg, axis)(params, tokens, lr)

    return step


@functools.lru_cache(maxsize=32)
def _sgd_step(mesh, cfg: SPConfig, axis: str):
    grad_fn = _grad_program(mesh, cfg, axis)

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(params, tokens, lr):
        loss, g = grad_fn(params, tokens)
        new = jax.tree_util.tree_map(
            lambda pp, gg: (pp.astype(jnp.float32)
                            - lr * gg.astype(jnp.float32)).astype(pp.dtype),
            params, g)
        return new, loss

    return step
