"""Ring attention: sequence-parallel exact attention over the device mesh.

The long-context flagship built on the framework's collective substrate.
The reference's SPMD layer contains the *mechanism* — neighbor ring
send/recv (test/spmd.jl:90-101, docs/src/index.md:356-369) — without the
application; SURVEY.md §5 pins ring attention / context parallelism as the
TPU-native deliverable riding that substrate.

Design (Liu et al., "Ring Attention with Blockwise Transformers", 2023 —
re-derived here for shard_map):

- Q, K, V are sequence-sharded over a 1-D mesh axis: each rank holds a
  ``(seq/P, d)`` block per head.
- P steps: each rank computes blockwise attention of its Q block against
  the K/V block currently resident, maintaining a *numerically stable
  online softmax* (running max ``m``, normalizer ``l``, weighted
  accumulator ``o``), then passes K/V to its ring neighbor via
  ``lax.ppermute`` over ICI; compute and the (tiny) boundary transfer
  overlap because XLA pipelines the permute with the matmuls.
- After P hops every Q block has attended to the full sequence exactly —
  no O(seq²) memory anywhere, communication O(seq·d) per rank.

``ring_attention`` takes/returns DArrays sequence-sharded on dim 0 of
shape (seq, heads, head_dim); ``ring_attention_kernel`` is the raw
shard_map program for embedding in larger jitted models (causal masking
supported via block-index comparison).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import layout as L
from .. import telemetry as _tm
from ..darray import DArray, _wrap_global
from ..parallel.collectives import (axis_size as _axis_size,
                                    shard_map_compat)

__all__ = ["ring_attention", "ring_attention_kernel",
           "ring_attention_prefill",
           "ring_attention_rdma_kernel",
           "ring_flash_attention", "ring_flash_attention_kernel",
           "zigzag_ring_attention", "zigzag_ring_attention_kernel",
           "zigzag_ring_flash_attention",
           "zigzag_ring_flash_attention_kernel",
           "zigzag_order", "zigzag_shard", "zigzag_unshard",
           "tuned_hop_blocks_for", "reference_attention"]


def _online_accumulate(m, l, o, qf, kc, vc, mask=None):
    """One online-softmax block accumulate (running max ``m``, normalizer
    ``l``, weighted sum ``o``, all (h, bq[, dh]) f32).  ``qf``: scaled f32
    (bq, h, d) query rows; ``kc``/``vc``: (bk, h, d) resident key/value
    rows; ``mask``: bool (bq, bk), True = attend (None = attend all).
    Fully-masked rows contribute nothing (the -inf/isfinite guards)."""
    s = jnp.einsum("qhd,khd->hqk", qf, kc.astype(jnp.float32))
    if mask is not None:
        s = jnp.where(mask[None], s, -jnp.inf)
    blk_max = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, blk_max)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[:, :, None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[:, :, None] + jnp.einsum(
        "hqk,khd->hqd", p, vc.astype(jnp.float32))
    return m_new, l_new, o_new


def ring_attention_kernel(q, k, v, axis: str, causal: bool = False,
                          scale: float | None = None):
    """Blockwise ring attention for one (local) block triple.

    q, k, v: ``(block, heads, d)`` — the calling rank's sequence block.
    Runs inside ``shard_map`` with ``axis`` a 1-D mesh axis.
    """
    nblk = _axis_size(axis)
    me = lax.axis_index(axis)
    b, h, dh = q.shape
    sc = jnp.asarray(1.0 / np.sqrt(dh) if scale is None else scale, q.dtype)

    qf = (q * sc).astype(jnp.float32)
    # accumulators: running max m, normalizer l, output o  (per head)
    m0 = jnp.full((h, b), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((h, b), jnp.float32)
    o0 = jnp.zeros((h, b, dh), jnp.float32)

    def accumulate(step, m, l, o, kc, vc):
        # kc/vc currently hold the block that started on rank (me - step)
        src = (me - step) % nblk
        mask = None
        if causal:
            qpos = me * b + jnp.arange(b)[:, None]          # global q index
            kpos = src * b + jnp.arange(b)[None, :]         # global k index
            mask = kpos <= qpos
        return _online_accumulate(m, l, o, qf, kc, vc, mask)

    perm = [(i, (i + 1) % nblk) for i in range(nblk)]

    def body(step, carry):
        m, l, o, kc, vc = carry
        m, l, o = accumulate(step, m, l, o, kc, vc)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return m, l, o, kc, vc

    # nblk-1 accumulate+shift hops, then a final accumulate with no shift
    # (the last rotation's result would be discarded)
    m, l, o, kc, vc = lax.fori_loop(0, nblk - 1, body, (m0, l0, o0, k, v))
    m, l, o = accumulate(nblk - 1, m, l, o, kc, vc)
    l = jnp.where(l == 0.0, 1.0, l)                          # all-masked rows
    out = (o / l[:, :, None]).astype(q.dtype)                # (h, b, dh)
    return jnp.transpose(out, (1, 0, 2))                     # (b, h, dh)


# ---------------------------------------------------------------------------
# RDMA ring attention: the K/V ring and the blockwise online softmax in
# ONE Pallas kernel — the next hop's K/V remote copy is STARTED before
# the resident block's accumulate and WAITED after it, so the einsum
# work covers the wire time (the overlap the XLA ``ppermute`` schedule
# can only hint at).  Semaphore/credit protocol shared with
# ``ops/pallas_collectives`` (see its module docstring).
# ---------------------------------------------------------------------------


def _attn_vmem_bytes(b, h, dh, itemsize, qblk):
    """Scoped-VMEM estimate for the fused kernel: the q input block
    (VMEM in_spec) and its f32 scaled copy, the two revolving K/V slot
    pairs, the (m, l, acc) carries, the per-block score/probability
    tiles (x3: s, p, and the masked intermediate), and the output
    block."""
    return (b * h * dh * itemsize + b * h * dh * 4
            + 4 * b * h * dh * itemsize + 2 * h * b * 4
            + h * b * dh * 4 + 3 * h * qblk * b * 4 + b * h * dh * itemsize)


@functools.lru_cache(maxsize=64)
def _rdma_attn_call(axis, p, b, h, dh, dtype_str, causal, scale, qblk,
                    interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    from ..ops import pallas_collectives as _pc

    dtype = jnp.dtype(dtype_str)
    nq = b // qblk
    sc = float(1.0 / np.sqrt(dh) if scale is None else scale)

    def kernel(q_ref, k_ref, v_ref, o_ref, qf, kv, m_ref, l_ref, acc,
               send_sem, recv_sem, copy_sem, cbuf, csend, crecv):
        me = lax.axis_index(axis)
        left = _pc._mod(me - 1, p)
        right = _pc._mod(me + 1, p)
        credit = _pc._Credit(cbuf, csend, crecv)
        _pc._copy(k_ref, kv.at[0, 0], copy_sem)
        _pc._copy(v_ref, kv.at[0, 1], copy_sem)
        # mirror the lax path exactly: scale in the input dtype, then f32
        qf[...] = (q_ref[...] * jnp.asarray(sc, dtype)).astype(jnp.float32)
        m_ref[...] = jnp.full((h, b), -jnp.inf, jnp.float32)
        l_ref[...] = jnp.zeros((h, b), jnp.float32)
        acc[...] = jnp.zeros((h, b, dh), jnp.float32)
        for t in range(p):
            s = t % 2
            src = _pc._mod(me - t, p)        # resident block's origin
            if t < p - 1:
                # credit window arms at t == 1, mirroring the
                # checker-proven _ag_gemm_prog window (ring_schedules):
                # the step-t forward writes the slot the lagging right
                # neighbor's step-(t-1) attention compute still reads,
                # so every forward after the first must take a credit
                if t >= 1:
                    credit.take(right)       # right freed the slot we hit
                fwd = pltpu.make_async_remote_copy(
                    src_ref=kv.at[s], dst_ref=kv.at[1 - s],
                    send_sem=send_sem.at[s], recv_sem=recv_sem.at[1 - s],
                    device_id=right,
                    device_id_type=pltpu.DeviceIdType.LOGICAL)
                fwd.start()
            # resident block accumulates while the K/V pair rides the
            # ring — blocked over query rows to bound the score tile
            kc = kv[s, 0].astype(jnp.float32)
            vc = kv[s, 1].astype(jnp.float32)
            for qb in range(nq):
                r0 = qb * qblk
                qx = qf[r0:r0 + qblk]
                s_ = jnp.einsum("qhd,khd->hqk", qx, kc)
                if causal:
                    qpos = me * b + r0 + lax.broadcasted_iota(
                        jnp.int32, (qblk, b), 0)
                    kpos = src * b + lax.broadcasted_iota(
                        jnp.int32, (qblk, b), 1)
                    s_ = jnp.where((kpos <= qpos)[None], s_, -jnp.inf)
                mm = m_ref[:, r0:r0 + qblk]
                blk_max = jnp.max(s_, axis=-1)
                m_new = jnp.maximum(mm, blk_max)
                m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
                pr = jnp.exp(s_ - m_safe[:, :, None])
                pr = jnp.where(jnp.isfinite(s_), pr, 0.0)
                alpha = jnp.where(jnp.isfinite(mm), jnp.exp(mm - m_safe),
                                  0.0)
                l_ref[:, r0:r0 + qblk] = (l_ref[:, r0:r0 + qblk] * alpha
                                          + jnp.sum(pr, axis=-1))
                acc[:, r0:r0 + qblk] = (
                    acc[:, r0:r0 + qblk] * alpha[:, :, None]
                    + jnp.einsum("hqk,khd->hqd", pr, vc))
                m_ref[:, r0:r0 + qblk] = m_new
            if t < p - 1:
                fwd.wait()
                if t <= p - 3:               # balance against the takes
                    credit.grant(left)
        ll = jnp.where(l_ref[...] == 0.0, 1.0, l_ref[...])
        out = (acc[...] / ll[:, :, None]).astype(dtype)
        o_ref[...] = jnp.transpose(out, (1, 0, 2))

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, dh), dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((b, h, dh), jnp.float32),
                        pltpu.VMEM((2, 2, b, h, dh), dtype),
                        pltpu.VMEM((h, b), jnp.float32),
                        pltpu.VMEM((h, b), jnp.float32),
                        pltpu.VMEM((h, b, dh), jnp.float32),
                        pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA] + _pc._credit_scratch(),
        interpret=interpret,
    )


def ring_attention_rdma_kernel(q, k, v, axis: str, causal: bool = False,
                               scale: float | None = None,
                               interpret: bool | None = None):
    """The fused Pallas RDMA path of :func:`ring_attention_kernel` —
    same contract, K/V ring hops as in-kernel remote DMAs overlapped
    with the online-softmax accumulates.  Falls back to the ``lax``
    kernel when RDMA is unavailable (platform, kill switch, K/V dtype
    mismatch, VMEM budget)."""
    from ..ops import pallas_collectives as _pc

    p = _axis_size(axis)
    b, h, dh = (int(s) for s in q.shape)
    mode = _pc.rdma_mode(interpret)
    qblk = b // _pc._chunk_fit(b, max(-(-b // 256), 1))
    if mode == "compiled" and _attn_vmem_bytes(
            b, h, dh, jnp.dtype(q.dtype).itemsize,
            qblk) > _pc._VMEM_LIMIT:
        mode = None
    if p == 1 or mode is None or k.dtype != q.dtype or v.dtype != q.dtype:
        return ring_attention_kernel(q, k, v, axis, causal=causal,
                                     scale=scale)
    _pc._record_dispatch("ring_attention", "rdma", k, axis, mode=mode)
    return _rdma_attn_call(axis, p, b, h, dh, str(q.dtype), bool(causal),
                           None if scale is None else float(scale), qblk,
                           mode == "interpret")(q, k, v)


@functools.lru_cache(maxsize=32)
def _ring_jit(mesh, causal: bool, rdma=None):
    axis = mesh.axis_names[0]
    spec = P(axis, None, None)

    def fn(q, k, v):
        if rdma:
            return ring_attention_rdma_kernel(
                q, k, v, axis, causal=causal,
                interpret=rdma == "interpret")
        return ring_attention_kernel(q, k, v, axis, causal=causal)

    return jax.jit(shard_map_compat(fn, mesh=mesh, in_specs=(spec,) * 3,
                                 out_specs=spec, check=False))


@functools.lru_cache(maxsize=32)
def _ring_jit_1d(pids: tuple, causal: bool, rdma: str):
    # the RDMA kernels address ring neighbors by LOGICAL device id,
    # which Pallas only supports under a single named mesh axis — so the
    # armed program runs over the canonical 1-D mesh (same devices, same
    # order; inputs committed to the (n,1,1) mesh relabel for free)
    mesh = L.mesh_for(list(pids), (len(pids),))
    return _ring_jit(mesh, causal, rdma), mesh


def ring_attention(q: DArray, k: DArray, v: DArray,
                   causal: bool = False) -> DArray:
    """Exact attention over sequence-sharded (seq, heads, d) DArrays."""
    for name, a in (("q", q), ("k", k), ("v", v)):
        if a.ndim != 3:
            raise ValueError(f"{name} must be (seq, heads, head_dim), "
                             f"got {a.dims}")
        if a.dims != q.dims:
            raise ValueError("q, k, v dims must match")
    pids = [int(p) for p in q.pids.flat]
    n = len(pids)
    if q.pids.shape[0] != n or q.dims[0] % n != 0:
        raise ValueError(
            "ring attention needs the sequence dim sharded evenly over a "
            f"1-D grid; got grid {q.pids.shape} for dims {q.dims}")
    from ..ops import pallas_collectives as _pc
    from ..telemetry import perf as _perf
    rdma = _pc.rdma_mode()
    s, h, dh = (int(d) for d in q.dims)
    with _tm.span("ring_attention", ranks=n, causal=causal,
                  dispatch="rdma" if rdma else "xla",
                  # cost stamp: two s x s x dh GEMMs per head (halved
                  # causal), q/k/v/o through HBM, k/v chunks rotating
                  # p-1 ring steps over ICI — the doctor's overlap tier
                  # reads comm-vs-compute per step from this
                  **_perf.attention_cost(
                      s, h, dh, np.dtype(q.dtype).itemsize, p=n,
                      causal=causal)):
        out = None
        if rdma:
            fn, _ = _ring_jit_1d(tuple(pids), causal, rdma)
            try:
                out = fn(q.garray, k.garray, v.garray)
            except Exception as e:
                # the RDMA arm must never cost correctness: take the XLA
                # ring, loudly once per failure signature
                from ..utils.debug import warn_once
                warn_once(f"ring_attention:rdma:{type(e).__name__}",
                          f"ring_attention RDMA path failed "
                          f"({type(e).__name__}: {e}); falling back to "
                          f"the XLA ppermute ring")
        if out is None:
            out = _ring_jit(L.mesh_for(pids, (n, 1, 1)), causal)(
                q.garray, k.garray, v.garray)
        return _wrap_global(out, procs=pids, dist=[n, 1, 1])


def ring_attention_prefill(q, k, v, *, causal: bool = True,
                           procs: list[int] | None = None,
                           min_ring_tokens: int | None = None):
    """Cache-aware prefill entry for the decode service: exact causal
    attention over host/device ``(ntok, heads, head_dim)`` q/k/v rows,
    returning a host ``(ntok, heads, head_dim)`` output.

    Long prompts ride the sequence-sharded ring kernel (RDMA when
    armed): the rows are end-padded with zero rows to a multiple of the
    rank count — safe under causal masking, since every real query row
    sits *before* the padded key rows and never attends to them — then
    distributed, run through :func:`ring_attention`, gathered, and
    trimmed, with the scratch DArrays closed before returning (the
    caller's HBM ledger only keeps the KV pages it writes back).  Short
    prompts (below ``min_ring_tokens``, default ``2 * nranks``) take the
    dense :func:`reference_attention` oracle — sharding a handful of
    rows buys nothing and the grid would not divide."""
    q = np.asarray(q, np.float32)
    k = np.asarray(k, np.float32)
    v = np.asarray(v, np.float32)
    if q.ndim != 3:
        raise ValueError(f"q must be (ntok, heads, head_dim), "
                         f"got {q.shape}")
    ntok = q.shape[0]
    pids = [int(p) for p in (procs if procs is not None
                             else L.all_ranks())]
    n = max(1, len(pids))
    floor = 2 * n if min_ring_tokens is None else int(min_ring_tokens)
    if not causal or n < 2 or ntok < max(floor, n):
        return reference_attention(q, k, v, causal)
    from ..darray import distribute
    pad = (-ntok) % n
    if pad:
        z = np.zeros((pad,) + q.shape[1:], q.dtype)
        q, k, v = (np.concatenate([a, z]) for a in (q, k, v))
    dq = dk = dv = dout = None
    try:
        dq = distribute(q, procs=pids, dist=[n, 1, 1])
        dk = distribute(k, procs=pids, dist=[n, 1, 1])
        dv = distribute(v, procs=pids, dist=[n, 1, 1])
        dout = ring_attention(dq, dk, dv, causal=True)
        return np.asarray(dout.garray)[:ntok]
    finally:
        for d in (dq, dk, dv, dout):
            if d is not None:
                d.close()


def _ring_flash_fwd_loop(q, k, v, axis, causal, scale, block_q, block_k,
                         interpret, hfold=1):
    """Shared fused-ring forward.  Returns ``(out (b,h,d), oh (h,b,d),
    lse (h,b))`` — the latter two are the FA2 backward's residuals."""
    from ..ops.pallas_attention import (flash_attention_hop,
                                       flash_carry_finalize,
                                       flash_carry_init)

    nblk = _axis_size(axis)
    me = lax.axis_index(axis)
    b, h, dh = q.shape
    sc = float(1.0 / np.sqrt(dh) if scale is None else scale)

    # kernel layout is (heads, block, d); transpose once, ring-permute the
    # transposed buffers
    qh = jnp.transpose(q, (1, 0, 2))
    kh = jnp.transpose(k, (1, 0, 2))
    vh = jnp.transpose(v, (1, 0, 2))
    m0, l0, a0 = flash_carry_init(h, b, dh)
    perm = [(i, (i + 1) % nblk) for i in range(nblk)]
    qoff = me * b

    def hop(step, m, l, a, kc, vc):
        koff = ((me - step) % nblk) * b
        return flash_attention_hop(qh, kc, vc, m, l, a, qoff, koff,
                                   causal=causal, scale=sc,
                                   block_q=block_q, block_k=block_k,
                                   head_fold=hfold, interpret=interpret)

    def body(step, carry):
        m, l, a, kc, vc = carry
        m, l, a = hop(step, m, l, a, kc, vc)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return m, l, a, kc, vc

    m, l, a, kc, vc = lax.fori_loop(0, nblk - 1, body, (m0, l0, a0, kh, vh))
    m, l, a = hop(nblk - 1, m, l, a, kc, vc)
    oh, lse = flash_carry_finalize(m, l, a, q.dtype)
    return jnp.transpose(oh, (1, 0, 2)), oh, lse


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _ring_flash_core(q, k, v, axis, causal, scale, block_q, block_k,
                     interpret, hfold=1):
    out, _, _ = _ring_flash_fwd_loop(q, k, v, axis, causal, scale,
                                     block_q, block_k, interpret, hfold)
    return out


def _ring_flash_core_fwd(q, k, v, axis, causal, scale, block_q, block_k,
                         interpret, hfold=1):
    out, oh, lse = _ring_flash_fwd_loop(q, k, v, axis, causal, scale,
                                        block_q, block_k, interpret, hfold)
    return out, (q, k, v, oh, lse)


def _ring_flash_core_bwd(axis, causal, scale, block_q, block_k, interpret,
                         hfold, res, g):
    # FA2 ring backward: p = exp(s - lse) is exact given the FINAL lse, so
    # every (q block, k/v block) pair's gradient contribution is
    # independent and additive.  Mirror the forward's ring schedule: dq
    # accumulates locally; dk/dv accumulators TRAVEL with their k/v blocks
    # through the same ppermute, and one extra rotation after the last hop
    # returns each block's gradient to its home rank.
    from ..ops.pallas_attention import _LANE, flash_attention_hop_bwd

    q, k, v, oh, lse = res
    nblk = _axis_size(axis)
    me = lax.axis_index(axis)
    b, h, dh = q.shape
    sc = float(1.0 / np.sqrt(dh) if scale is None else scale)

    qh = jnp.transpose(q, (1, 0, 2))
    kh = jnp.transpose(k, (1, 0, 2))
    vh = jnp.transpose(v, (1, 0, 2))
    gf = jnp.transpose(g, (1, 0, 2)).astype(jnp.float32)   # (h, b, dh)
    # dd from the FULL-precision cotangent (matches _flash_bwd); only the
    # kernel operand gh is downcast to the MXU input dtype
    dd = jnp.einsum("hbd,hbd->hb", gf, oh.astype(jnp.float32))
    gh = gf.astype(q.dtype)
    ddb = jnp.broadcast_to(dd[:, :, None], (h, b, _LANE))
    lseb = jnp.broadcast_to(lse[:, :, None], (h, b, _LANE))
    perm = [(i, (i + 1) % nblk) for i in range(nblk)]
    qoff = me * b
    zeros = lambda: jnp.zeros((h, b, dh), jnp.float32)

    def hop_bwd(step, dqa, dka, dva, kc, vc):
        koff = ((me - step) % nblk) * b
        dqc, dkc, dvc = flash_attention_hop_bwd(
            qh, kc, vc, gh, lseb, ddb, qoff, koff, causal=causal, scale=sc,
            block_q=block_q, block_k=block_k, interpret=interpret)
        return dqa + dqc, dka + dkc, dva + dvc

    def body(step, carry):
        dqa, dka, dva, kc, vc = carry
        dqa, dka, dva = hop_bwd(step, dqa, dka, dva, kc, vc)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        dka = lax.ppermute(dka, axis, perm)
        dva = lax.ppermute(dva, axis, perm)
        return dqa, dka, dva, kc, vc

    dqa, dka, dva, kc, vc = lax.fori_loop(
        0, nblk - 1, body, (zeros(), zeros(), zeros(), kh, vh))
    dqa, dka, dva = hop_bwd(nblk - 1, dqa, dka, dva, kc, vc)
    # block r's dk/dv sits one rank behind home after nblk-1 rotations
    dka = lax.ppermute(dka, axis, perm)
    dva = lax.ppermute(dva, axis, perm)
    back = lambda t: jnp.transpose(t, (1, 0, 2)).astype(q.dtype)
    return back(dqa), back(dka), back(dva)


_ring_flash_core.defvjp(_ring_flash_core_fwd, _ring_flash_core_bwd)


def ring_flash_attention_kernel(q, k, v, axis: str, causal: bool = False,
                                scale: float | None = None,
                                block_q: int | None = None,
                                block_k: int | None = None,
                                head_fold: int | None = None,
                                interpret: bool | None = None):
    """Fused ring attention: each hop's blockwise accumulate is ONE Pallas
    flash program (VMEM-resident online softmax, no (h, b, b) score
    materialization in HBM) and the online-softmax carry (m, l, acc) flows
    around the ``ppermute`` ring.  XLA schedules the next hop's K/V
    permute concurrently with the current hop's kernel, overlapping ICI
    with MXU compute (VERDICT round-2 item 7 / design.md round-2 item 5).

    q, k, v: ``(block, heads, d)`` — the calling rank's sequence block,
    inside ``shard_map``.  DIFFERENTIABLE end to end: the FA2-style ring
    backward (custom_vjp) saves only O(B) logsumexp rows per rank and
    re-runs the ring with Pallas recompute kernels, circulating dk/dv
    accumulators with their blocks — sequence-parallel training runs at
    Pallas speed (VERDICT round-3 item 3).
    """
    block_q, block_k, hfold = _tuned_hop_blocks(
        q, bool(causal), block_q, block_k)
    if head_fold is not None:
        hfold = head_fold
    sc = None if scale is None else float(scale)
    return _ring_flash_core(q, k, v, axis, bool(causal), sc,
                            int(block_q), int(block_k), interpret,
                            int(hfold))


def _tuned_hop_blocks(q, causal: bool, block_q, block_k):
    """Per-hop block sizes for an actual (local block, heads, d) array —
    see ``tuned_hop_blocks_for``."""
    return tuned_hop_blocks_for(q.shape, q.dtype, causal, block_q, block_k)


def tuned_hop_blocks_for(shape, dtype, causal: bool, block_q, block_k):
    """Per-hop block sizes: explicit values win; ``None`` consults the
    ``"ring_flash"`` autotune entry for this (local block, heads, d,
    dtype, causal) — banked by bench.py's hardware hop sweep — falling
    back to 512².  Shared by the contiguous and zigzag fused kernels
    (the hop programs fit blocks to their half/full extents anyway;
    both thread a 3-tuple entry's head fold through
    ``flash_attention_hop``).  Callers that cache jitted programs must
    resolve through here OUTSIDE the cache and key on the resolved
    values (see models/sp_transformer._resolve_cfg) — resolving at trace
    time inside a cached program silently pins the registry's state at
    first trace."""
    if block_q is not None and block_k is not None:
        return block_q, block_k, 1
    from ..utils import autotune
    vals = autotune.valid_ints(
        autotune.get("ring_flash",
                     autotune.device_key_for(shape[0], shape[1],
                                             shape[2], dtype, causal)),
        (2, 3))
    tq, tk = (vals[0], vals[1]) if vals else (512, 512)
    # the tuned fold was measured WITH the tuned blocks (same policy as
    # tuned_flash_config)
    hf = vals[2] if (vals and len(vals) == 3
                     and block_q is None and block_k is None) else 1
    return (tq if block_q is None else block_q,
            tk if block_k is None else block_k, hf)


@functools.lru_cache(maxsize=32)
def _ring_flash_jit(mesh, causal: bool, block_q: int, block_k: int,
                    head_fold: int = 1):
    axis = mesh.axis_names[0]
    spec = P(axis, None, None)

    def fn(q, k, v):
        return ring_flash_attention_kernel(q, k, v, axis, causal=causal,
                                           block_q=block_q, block_k=block_k,
                                           head_fold=head_fold)

    return jax.jit(shard_map_compat(fn, mesh=mesh, in_specs=(spec,) * 3,
                                 out_specs=spec, check=False))


def ring_flash_attention(q: DArray, k: DArray, v: DArray,
                         causal: bool = False, block_q: int | None = None,
                         block_k: int | None = None) -> DArray:
    """Fused (Pallas per-hop) exact attention over sequence-sharded
    (seq, heads, d) DArrays — the performance path of ``ring_attention``."""
    for name, a in (("q", q), ("k", k), ("v", v)):
        if a.ndim != 3:
            raise ValueError(f"{name} must be (seq, heads, head_dim), "
                             f"got {a.dims}")
        if a.dims != q.dims:
            raise ValueError("q, k, v dims must match")
    pids = [int(p) for p in q.pids.flat]
    n = len(pids)
    if q.pids.shape[0] != n or q.dims[0] % n != 0:
        raise ValueError(
            "ring attention needs the sequence dim sharded evenly over a "
            f"1-D grid; got grid {q.pids.shape} for dims {q.dims}")
    blk = q.dims[0] // n
    lq = jax.ShapeDtypeStruct((blk, q.dims[1], q.dims[2]), q.dtype)
    block_q, block_k, hf = _tuned_hop_blocks(lq, bool(causal), block_q,
                                             block_k)
    bq = min(block_q, blk)
    bk = min(block_k, blk)
    while blk % bq:
        bq //= 2
    while blk % bk:
        bk //= 2
    mesh = L.mesh_for(pids, (n, 1, 1))
    out = _ring_flash_jit(mesh, causal, bq, bk, hf)(
        q.garray, k.garray, v.garray)
    return _wrap_global(out, procs=pids, dist=[n, 1, 1])


# ---------------------------------------------------------------------------
# Zigzag (load-balanced) causal ring attention.
#
# With the contiguous layout above, causal masking makes the USEFUL work
# per rank proportional to its position (rank P-1's block attends to the
# whole prefix, rank 0's almost nothing), and the dense per-hop einsum
# spends full FLOPs either way.  The zigzag layout (as popularized by the
# zigzag/"striped" ring-attention schemes in the long-context literature)
# splits the sequence into 2P chunks and gives rank i the PAIR
# (chunk i, chunk 2P-1-i).  Chunk-level causal structure then becomes
# static per quadrant:
#
#   local (q1, q2) = chunks (me, 2P-1-me); visiting (k1, k2) from src:
#     q1 x k2 : ALWAYS fully masked  -> never computed
#     q2 x k1 : ALWAYS fully unmasked -> computed maskless
#     q1 x k1 : unmasked iff src < me, diagonal iff src == me
#     q2 x k2 : unmasked iff src > me, diagonal iff src == me
#
# so each rank computes ~2 of 4 quadrants every hop — half the dense
# FLOPs, evenly balanced — selected with lax.switch on sign(src - me).
# ---------------------------------------------------------------------------


def zigzag_order(S: int, nranks: int) -> np.ndarray:
    """Permutation taking a natural-order sequence to zigzag-shard order:
    rank i's rows are [chunk i, chunk 2P-1-i] of 2P equal chunks."""
    if S % (2 * nranks):
        raise ValueError(f"sequence length {S} must divide 2*nranks "
                         f"({2 * nranks})")
    half = S // (2 * nranks)
    chunks = np.arange(S).reshape(2 * nranks, half)
    order = [c for i in range(nranks)
             for c in (chunks[i], chunks[2 * nranks - 1 - i])]
    return np.concatenate(order)


def zigzag_shard(x, nranks: int):
    """Reorder dim 0 of ``x`` (length S, natural order) into zigzag-shard
    order.  Apply before distributing over the ring."""
    return jnp.asarray(x)[jnp.asarray(zigzag_order(x.shape[0], nranks))]


def zigzag_unshard(x, nranks: int):
    """Inverse of ``zigzag_shard``."""
    inv = np.argsort(zigzag_order(x.shape[0], nranks))
    return jnp.asarray(x)[jnp.asarray(inv)]


def zigzag_ring_attention_kernel(q, k, v, axis: str,
                                 scale: float | None = None):
    """Causal blockwise ring attention on zigzag-ordered blocks.

    q, k, v: ``(block, heads, d)`` — the calling rank's zigzag PAIR
    (chunk me, chunk 2P-1-me concatenated), inside ``shard_map``.
    Exact; computes only the ~2 useful quadrants per hop (see the scheme
    note above).  Causal only — for non-causal use the plain ring (the
    mask is the whole point of the layout).
    """
    nblk = _axis_size(axis)
    me = lax.axis_index(axis)
    b, h, dh = q.shape
    if b % 2:
        raise ValueError(f"zigzag needs an even local block; got {b}")
    half = b // 2
    sc = jnp.asarray(1.0 / np.sqrt(dh), q.dtype) if scale is None \
        else jnp.asarray(scale, q.dtype)

    qf = (q * sc).astype(jnp.float32)
    q1, q2 = qf[:half], qf[half:]

    def acc_half(m, l, o, qh_, kc, vc, mask=None):
        # one (half x half) quadrant through the shared accumulate
        return _online_accumulate(m, l, o, qh_, kc, vc, mask)

    diag = jnp.tril(jnp.ones((half, half), bool))   # intra-chunk causal

    init = (jnp.full((h, half), -jnp.inf, jnp.float32),
            jnp.zeros((h, half), jnp.float32),
            jnp.zeros((h, half, dh), jnp.float32))

    def accumulate(step, c1, c2, kc, vc):
        src = (me - step) % nblk
        k1, v1 = kc[:half], vc[:half]
        k2, v2 = kc[half:], vc[half:]
        # q2 x k1: always fully unmasked
        c2 = acc_half(*c2, q2, k1, v1)

        def lt(ops):                       # src < me: q1 attends all of k1
            c1, c2, k1, v1, k2, v2 = ops
            return acc_half(*c1, q1, k1, v1), c2

        def eq(ops):                       # src == me: both diagonals
            c1, c2, k1, v1, k2, v2 = ops
            return (acc_half(*c1, q1, k1, v1, diag),
                    acc_half(*c2, q2, k2, v2, diag))

        def gt(ops):                       # src > me: q2 attends all of k2
            c1, c2, k1, v1, k2, v2 = ops
            return c1, acc_half(*c2, q2, k2, v2)

        idx = jnp.clip(jnp.sign(src - me) + 1, 0, 2).astype(jnp.int32)
        c1, c2 = lax.switch(idx, (lt, eq, gt), (c1, c2, k1, v1, k2, v2))
        return c1, c2

    perm = [(i, (i + 1) % nblk) for i in range(nblk)]

    def body(step, carry):
        c1, c2, kc, vc = carry
        c1, c2 = accumulate(step, c1, c2, kc, vc)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return c1, c2, kc, vc

    c1, c2, kc, vc = lax.fori_loop(0, nblk - 1, body, (init, init, k, v))
    c1, c2 = accumulate(nblk - 1, c1, c2, kc, vc)

    outs = []
    for m, l, o in (c1, c2):
        l = jnp.where(l == 0.0, 1.0, l)
        outs.append((o / l[:, :, None]).astype(q.dtype))     # (h, half, dh)
    return jnp.transpose(jnp.concatenate(outs, axis=1), (1, 0, 2))


def _zigzag_flash_fwd_loop(q, k, v, axis, scale, block_q, block_k,
                           interpret, hfold=1):
    """Shared fused-zigzag forward.  Returns ``(out (b,h,d), oh (h,b,d),
    lse (h,b))`` with the two half-chunks concatenated on the row axis."""
    from ..ops.pallas_attention import (flash_attention_hop,
                                       flash_carry_finalize,
                                       flash_carry_init)

    nblk = _axis_size(axis)
    me = lax.axis_index(axis)
    b, h, dh = q.shape
    if b % 2:
        raise ValueError(f"zigzag needs an even local block; got {b}")
    half = b // 2
    sc = float(1.0 / np.sqrt(dh) if scale is None else scale)

    qh = jnp.transpose(q, (1, 0, 2))                     # (h, b, dh)
    kh = jnp.transpose(k, (1, 0, 2))
    vh = jnp.transpose(v, (1, 0, 2))
    q1, q2 = qh[:, :half], qh[:, half:]
    qoff1 = me * half
    qoff2 = (2 * nblk - 1 - me) * half

    def hop(causal_, qx, kx, vx, carry, qoff, koff):
        m, l, a = carry
        return flash_attention_hop(qx, kx, vx, m, l, a, qoff, koff,
                                   causal=causal_, scale=sc,
                                   block_q=block_q, block_k=block_k,
                                   head_fold=hfold, interpret=interpret)

    init = flash_carry_init(h, half, dh)

    def accumulate(step, c1, c2, kc, vc):
        src = (me - step) % nblk
        k1, v1 = kc[:, :half], vc[:, :half]
        k2, v2 = kc[:, half:], vc[:, half:]
        koff1 = src * half
        koff2 = (2 * nblk - 1 - src) * half
        # q2 x k1: always fully unmasked
        c2 = hop(False, q2, k1, v1, c2, qoff2, koff1)

        def lt(ops):
            c1, c2, k1, v1, k2, v2 = ops
            return hop(False, q1, k1, v1, c1, qoff1, koff1), c2

        def eq(ops):
            c1, c2, k1, v1, k2, v2 = ops
            return (hop(True, q1, k1, v1, c1, qoff1, koff1),
                    hop(True, q2, k2, v2, c2, qoff2, koff2))

        def gt(ops):
            c1, c2, k1, v1, k2, v2 = ops
            return c1, hop(False, q2, k2, v2, c2, qoff2, koff2)

        idx = jnp.clip(jnp.sign(src - me) + 1, 0, 2).astype(jnp.int32)
        c1, c2 = lax.switch(idx, (lt, eq, gt), (c1, c2, k1, v1, k2, v2))
        return c1, c2

    perm = [(i, (i + 1) % nblk) for i in range(nblk)]

    def body(step, carry):
        c1, c2, kc, vc = carry
        c1, c2 = accumulate(step, c1, c2, kc, vc)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return c1, c2, kc, vc

    c1, c2, kc, vc = lax.fori_loop(0, nblk - 1, body, (init, init, kh, vh))
    c1, c2 = accumulate(nblk - 1, c1, c2, kc, vc)

    oh1, lse1 = flash_carry_finalize(*c1, q.dtype)
    oh2, lse2 = flash_carry_finalize(*c2, q.dtype)
    oh = jnp.concatenate([oh1, oh2], axis=1)             # (h, b, dh)
    lse = jnp.concatenate([lse1, lse2], axis=1)          # (h, b)
    return jnp.transpose(oh, (1, 0, 2)), oh, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _zigzag_flash_core(q, k, v, axis, scale, block_q, block_k, interpret,
                       hfold=1):
    out, _, _ = _zigzag_flash_fwd_loop(q, k, v, axis, scale,
                                       block_q, block_k, interpret, hfold)
    return out


def _zigzag_flash_core_fwd(q, k, v, axis, scale, block_q, block_k,
                           interpret, hfold=1):
    out, oh, lse = _zigzag_flash_fwd_loop(q, k, v, axis, scale,
                                          block_q, block_k, interpret,
                                          hfold)
    return out, (q, k, v, oh, lse)


def _zigzag_flash_core_bwd(axis, scale, block_q, block_k, interpret, hfold,
                           res, g):
    # the ring FA2 backward (see _ring_flash_core_bwd) specialized to the
    # zigzag quadrant schedule: each hop re-runs exactly the quadrants the
    # forward computed (the same lax.switch on sign(src - me)), adding
    # each quadrant's (dq, dk, dv) contribution — dq into the local half
    # accumulators, dk/dv into the accumulators TRAVELING with the k/v
    # halves around the ring.
    from ..ops.pallas_attention import _LANE, flash_attention_hop_bwd

    q, k, v, oh, lse = res
    nblk = _axis_size(axis)
    me = lax.axis_index(axis)
    b, h, dh = q.shape
    half = b // 2
    sc = float(1.0 / np.sqrt(dh) if scale is None else scale)

    qh = jnp.transpose(q, (1, 0, 2))
    kh = jnp.transpose(k, (1, 0, 2))
    vh = jnp.transpose(v, (1, 0, 2))
    gf = jnp.transpose(g, (1, 0, 2)).astype(jnp.float32)   # (h, b, dh)
    # dd from the FULL-precision cotangent (matches _flash_bwd); only the
    # kernel operand gh is downcast to the MXU input dtype
    dd = jnp.einsum("hbd,hbd->hb", gf, oh.astype(jnp.float32))
    gh = gf.astype(q.dtype)
    ddb = jnp.broadcast_to(dd[:, :, None], (h, b, _LANE))
    lseb = jnp.broadcast_to(lse[:, :, None], (h, b, _LANE))
    q1, q2 = qh[:, :half], qh[:, half:]
    g1, g2 = gh[:, :half], gh[:, half:]
    dd1, dd2 = ddb[:, :half], ddb[:, half:]
    lse1, lse2 = lseb[:, :half], lseb[:, half:]
    qoff1 = me * half
    qoff2 = (2 * nblk - 1 - me) * half

    def hb(causal_, qx, gx, lsex, ddx, qoff, kx, vx, koff):
        return flash_attention_hop_bwd(
            qx, kx, vx, gx, lsex, ddx, qoff, koff, causal=causal_, scale=sc,
            block_q=block_q, block_k=block_k, interpret=interpret)

    def accumulate_bwd(step, dq1a, dq2a, dka, dva, kc, vc):
        src = (me - step) % nblk
        k1, v1 = kc[:, :half], vc[:, :half]
        k2, v2 = kc[:, half:], vc[:, half:]
        koff1 = src * half
        koff2 = (2 * nblk - 1 - src) * half
        # q2 x k1: always computed in the forward
        dqc, dkc, dvc = hb(False, q2, g2, lse2, dd2, qoff2, k1, v1, koff1)
        dq2a = dq2a + dqc
        dka = dka.at[:, :half].add(dkc)
        dva = dva.at[:, :half].add(dvc)

        def lt(ops):
            dq1a, dq2a, dka, dva = ops
            dqc, dkc, dvc = hb(False, q1, g1, lse1, dd1, qoff1,
                               k1, v1, koff1)
            return (dq1a + dqc, dq2a, dka.at[:, :half].add(dkc),
                    dva.at[:, :half].add(dvc))

        def eq(ops):
            dq1a, dq2a, dka, dva = ops
            dqc1, dkc1, dvc1 = hb(True, q1, g1, lse1, dd1, qoff1,
                                  k1, v1, koff1)
            dqc2, dkc2, dvc2 = hb(True, q2, g2, lse2, dd2, qoff2,
                                  k2, v2, koff2)
            return (dq1a + dqc1, dq2a + dqc2,
                    dka.at[:, :half].add(dkc1).at[:, half:].add(dkc2),
                    dva.at[:, :half].add(dvc1).at[:, half:].add(dvc2))

        def gt(ops):
            dq1a, dq2a, dka, dva = ops
            dqc, dkc, dvc = hb(False, q2, g2, lse2, dd2, qoff2,
                               k2, v2, koff2)
            return (dq1a, dq2a + dqc, dka.at[:, half:].add(dkc),
                    dva.at[:, half:].add(dvc))

        idx = jnp.clip(jnp.sign(src - me) + 1, 0, 2).astype(jnp.int32)
        return lax.switch(idx, (lt, eq, gt), (dq1a, dq2a, dka, dva))

    perm = [(i, (i + 1) % nblk) for i in range(nblk)]
    zh = lambda: jnp.zeros((h, half, dh), jnp.float32)
    zb = lambda: jnp.zeros((h, b, dh), jnp.float32)

    def body(step, carry):
        dq1a, dq2a, dka, dva, kc, vc = carry
        dq1a, dq2a, dka, dva = accumulate_bwd(step, dq1a, dq2a, dka, dva,
                                              kc, vc)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        dka = lax.ppermute(dka, axis, perm)
        dva = lax.ppermute(dva, axis, perm)
        return dq1a, dq2a, dka, dva, kc, vc

    dq1a, dq2a, dka, dva, kc, vc = lax.fori_loop(
        0, nblk - 1, body, (zh(), zh(), zb(), zb(), kh, vh))
    dq1a, dq2a, dka, dva = accumulate_bwd(nblk - 1, dq1a, dq2a, dka, dva,
                                          kc, vc)
    # block r's dk/dv sits one rank behind home after nblk-1 rotations
    dka = lax.ppermute(dka, axis, perm)
    dva = lax.ppermute(dva, axis, perm)
    dq = jnp.concatenate([dq1a, dq2a], axis=1)
    back = lambda t: jnp.transpose(t, (1, 0, 2)).astype(q.dtype)
    return back(dq), back(dka), back(dva)


_zigzag_flash_core.defvjp(_zigzag_flash_core_fwd, _zigzag_flash_core_bwd)


def zigzag_ring_flash_attention_kernel(q, k, v, axis: str,
                                       scale: float | None = None,
                                       block_q: int | None = None,
                                       block_k: int | None = None,
                                       head_fold: int | None = None,
                                       interpret: bool | None = None):
    """Fused zigzag ring attention: the quadrant schedule of
    ``zigzag_ring_attention_kernel`` with each computed quadrant running
    as ONE Pallas flash hop (``flash_attention_hop`` on half-blocks, the
    online-softmax carry flowing around the ring).  Cross quadrants use
    the maskless kernel; diagonal quadrants the causal kernel with global
    chunk offsets.  DIFFERENTIABLE end to end (custom_vjp): the backward
    re-runs the quadrant schedule with the FA2 recompute kernels, so
    load-balanced causal training also runs at Pallas speed.
    """
    block_q, block_k, hfold = _tuned_hop_blocks(q, True, block_q, block_k)
    if head_fold is not None:
        hfold = head_fold
    sc = None if scale is None else float(scale)
    return _zigzag_flash_core(q, k, v, axis, sc, int(block_q),
                              int(block_k), interpret, int(hfold))


@functools.lru_cache(maxsize=32)
def _zigzag_flash_jit(mesh, block_q: int, block_k: int,
                      head_fold: int = 1):
    axis = mesh.axis_names[0]
    spec = P(axis, None, None)

    def fn(q, k, v):
        return zigzag_ring_flash_attention_kernel(q, k, v, axis,
                                                  block_q=block_q,
                                                  block_k=block_k,
                                                  head_fold=head_fold)

    return jax.jit(shard_map_compat(fn, mesh=mesh, in_specs=(spec,) * 3,
                                 out_specs=spec, check=False))


def zigzag_ring_flash_attention(q: DArray, k: DArray, v: DArray,
                                block_q: int | None = None,
                                block_k: int | None = None) -> DArray:
    """Fused (Pallas per-quadrant) zigzag causal ring attention over
    zigzag-ordered sequence-sharded DArrays — the performance path of
    ``zigzag_ring_attention``."""
    for name, a in (("q", q), ("k", k), ("v", v)):
        if a.ndim != 3:
            raise ValueError(f"{name} must be (seq, heads, head_dim), "
                             f"got {a.dims}")
        if a.dims != q.dims:
            raise ValueError("q, k, v dims must match")
    pids = [int(p) for p in q.pids.flat]
    n = len(pids)
    if q.pids.shape[0] != n or q.dims[0] % (2 * n) != 0:
        raise ValueError(
            "zigzag ring attention needs the sequence dim divisible by "
            f"2*nranks over a 1-D grid; got grid {q.pids.shape} for dims "
            f"{q.dims}")
    half = q.dims[0] // (2 * n)
    # None blocks: the registry default (keyed on the per-rank local
    # block the kernel will see) before fitting to the half extent
    lq = jax.ShapeDtypeStruct((q.dims[0] // n, q.dims[1], q.dims[2]),
                              q.dtype)
    block_q, block_k, zhf = _tuned_hop_blocks(lq, True, block_q, block_k)
    bq = min(block_q, half)
    bk = min(block_k, half)
    while half % bq:
        bq //= 2
    while half % bk:
        bk //= 2
    mesh = L.mesh_for(pids, (n, 1, 1))
    out = _zigzag_flash_jit(mesh, bq, bk, zhf)(
        q.garray, k.garray, v.garray)
    return _wrap_global(out, procs=pids, dist=[n, 1, 1])


@functools.lru_cache(maxsize=32)
def _zigzag_jit(mesh):
    axis = mesh.axis_names[0]
    spec = P(axis, None, None)

    def fn(q, k, v):
        return zigzag_ring_attention_kernel(q, k, v, axis)

    return jax.jit(shard_map_compat(fn, mesh=mesh, in_specs=(spec,) * 3,
                                 out_specs=spec, check=False))


def zigzag_ring_attention(q: DArray, k: DArray, v: DArray) -> DArray:
    """Load-balanced causal ring attention over sequence-sharded
    (seq, heads, d) DArrays whose rows are already in zigzag order
    (``zigzag_shard``).  Returns zigzag-ordered output — ``zigzag_unshard``
    to recover natural order.  ~2x the useful-FLOP efficiency of
    ``ring_attention(causal=True)`` per rank, evenly balanced."""
    for name, a in (("q", q), ("k", k), ("v", v)):
        if a.ndim != 3:
            raise ValueError(f"{name} must be (seq, heads, head_dim), "
                             f"got {a.dims}")
        if a.dims != q.dims:
            raise ValueError("q, k, v dims must match")
    pids = [int(p) for p in q.pids.flat]
    n = len(pids)
    if q.pids.shape[0] != n or q.dims[0] % (2 * n) != 0:
        raise ValueError(
            "zigzag ring attention needs the sequence dim divisible by "
            f"2*nranks over a 1-D grid; got grid {q.pids.shape} for dims "
            f"{q.dims}")
    mesh = L.mesh_for(pids, (n, 1, 1))
    out = _zigzag_jit(mesh)(q.garray, k.garray, v.garray)
    return _wrap_global(out, procs=pids, dist=[n, 1, 1])


def reference_attention(q, k, v, causal: bool = False):
    """Dense O(seq²) oracle for tests."""
    q, k, v = (np.asarray(x, np.float32) for x in (q, k, v))
    s = np.einsum("qhd,khd->hqk", q / np.sqrt(q.shape[-1]), k)
    if causal:
        qi = np.arange(q.shape[0])[:, None]
        ki = np.arange(k.shape[0])[None, :]
        s = np.where((ki <= qi)[None], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    o = np.einsum("hqk,khd->hqd", p, v)
    return np.transpose(o, (1, 0, 2))
