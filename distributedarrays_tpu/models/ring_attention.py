"""Ring attention: sequence-parallel exact attention over the device mesh.

The long-context flagship built on the framework's collective substrate.
The reference's SPMD layer contains the *mechanism* — neighbor ring
send/recv (test/spmd.jl:90-101, docs/src/index.md:356-369) — without the
application; SURVEY.md §5 pins ring attention / context parallelism as the
TPU-native deliverable riding that substrate.

Design (Liu et al., "Ring Attention with Blockwise Transformers", 2023 —
re-derived here for shard_map):

- Q, K, V are sequence-sharded over a 1-D mesh axis: each rank holds a
  ``(seq/P, d)`` block per head.
- P steps: each rank computes blockwise attention of its Q block against
  the K/V block currently resident, maintaining a *numerically stable
  online softmax* (running max ``m``, normalizer ``l``, weighted
  accumulator ``o``), then passes K/V to its ring neighbor via
  ``lax.ppermute`` over ICI; compute and the (tiny) boundary transfer
  overlap because XLA pipelines the permute with the matmuls.
- After P hops every Q block has attended to the full sequence exactly —
  no O(seq²) memory anywhere, communication O(seq·d) per rank.

``ring_attention`` takes/returns DArrays sequence-sharded on dim 0 of
shape (seq, heads, head_dim); ``ring_attention_kernel`` is the raw
shard_map program for embedding in larger jitted models (causal masking
supported via block-index comparison).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import layout as L
from ..darray import DArray, _wrap_global

__all__ = ["ring_attention", "ring_attention_kernel",
           "ring_flash_attention", "ring_flash_attention_kernel",
           "reference_attention"]


def ring_attention_kernel(q, k, v, axis: str, causal: bool = False,
                          scale: float | None = None):
    """Blockwise ring attention for one (local) block triple.

    q, k, v: ``(block, heads, d)`` — the calling rank's sequence block.
    Runs inside ``shard_map`` with ``axis`` a 1-D mesh axis.
    """
    nblk = lax.axis_size(axis)
    me = lax.axis_index(axis)
    b, h, dh = q.shape
    sc = jnp.asarray(1.0 / np.sqrt(dh) if scale is None else scale, q.dtype)

    qf = (q * sc).astype(jnp.float32)
    # accumulators: running max m, normalizer l, output o  (per head)
    m0 = jnp.full((h, b), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((h, b), jnp.float32)
    o0 = jnp.zeros((h, b, dh), jnp.float32)

    def accumulate(step, m, l, o, kc, vc):
        # kc/vc currently hold the block that started on rank (me - step)
        src = (me - step) % nblk
        # scores: (h, b, b) = q-block x k-block^T per head
        s = jnp.einsum("qhd,khd->hqk", qf, kc.astype(jnp.float32))
        if causal:
            qpos = me * b + jnp.arange(b)[:, None]          # global q index
            kpos = src * b + jnp.arange(b)[None, :]         # global k index
            s = jnp.where((kpos <= qpos)[None, :, :], s, -jnp.inf)
        blk_max = jnp.max(s, axis=-1)                        # (h, b)
        m_new = jnp.maximum(m, blk_max)
        # guard fully-masked rows (blk_max = -inf): contribute nothing
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - m_safe[:, :, None])
        p = jnp.where(jnp.isfinite(s), p, 0.0)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha[:, :, None] + jnp.einsum(
            "hqk,khd->hqd", p, vc.astype(jnp.float32))
        return m_new, l_new, o_new

    perm = [(i, (i + 1) % nblk) for i in range(nblk)]

    def body(step, carry):
        m, l, o, kc, vc = carry
        m, l, o = accumulate(step, m, l, o, kc, vc)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return m, l, o, kc, vc

    # nblk-1 accumulate+shift hops, then a final accumulate with no shift
    # (the last rotation's result would be discarded)
    m, l, o, kc, vc = lax.fori_loop(0, nblk - 1, body, (m0, l0, o0, k, v))
    m, l, o = accumulate(nblk - 1, m, l, o, kc, vc)
    l = jnp.where(l == 0.0, 1.0, l)                          # all-masked rows
    out = (o / l[:, :, None]).astype(q.dtype)                # (h, b, dh)
    return jnp.transpose(out, (1, 0, 2))                     # (b, h, dh)


@functools.lru_cache(maxsize=32)
def _ring_jit(mesh, causal: bool):
    axis = mesh.axis_names[0]
    spec = P(axis, None, None)

    def fn(q, k, v):
        return ring_attention_kernel(q, k, v, axis, causal=causal)

    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(spec,) * 3,
                                 out_specs=spec, check_vma=False))


def ring_attention(q: DArray, k: DArray, v: DArray,
                   causal: bool = False) -> DArray:
    """Exact attention over sequence-sharded (seq, heads, d) DArrays."""
    for name, a in (("q", q), ("k", k), ("v", v)):
        if a.ndim != 3:
            raise ValueError(f"{name} must be (seq, heads, head_dim), "
                             f"got {a.dims}")
        if a.dims != q.dims:
            raise ValueError("q, k, v dims must match")
    pids = [int(p) for p in q.pids.flat]
    n = len(pids)
    if q.pids.shape[0] != n or q.dims[0] % n != 0:
        raise ValueError(
            "ring attention needs the sequence dim sharded evenly over a "
            f"1-D grid; got grid {q.pids.shape} for dims {q.dims}")
    mesh = L.mesh_for(pids, (n, 1, 1))
    out = _ring_jit(mesh, causal)(q.garray, k.garray, v.garray)
    return _wrap_global(out, procs=pids, dist=[n, 1, 1])


def ring_flash_attention_kernel(q, k, v, axis: str, causal: bool = False,
                                scale: float | None = None,
                                block_q: int = 512, block_k: int = 512,
                                interpret: bool | None = None):
    """Fused ring attention: each hop's blockwise accumulate is ONE Pallas
    flash program (VMEM-resident online softmax, no (h, b, b) score
    materialization in HBM) and the online-softmax carry (m, l, acc) flows
    around the ``ppermute`` ring.  XLA schedules the next hop's K/V
    permute concurrently with the current hop's kernel, overlapping ICI
    with MXU compute (VERDICT round-2 item 7 / design.md round-2 item 5).

    q, k, v: ``(block, heads, d)`` — the calling rank's sequence block,
    inside ``shard_map``.  Forward-only (use ``ring_attention_kernel`` for
    the differentiable path).
    """
    from ..ops.pallas_attention import flash_attention_hop, flash_carry_init

    nblk = lax.axis_size(axis)
    me = lax.axis_index(axis)
    b, h, dh = q.shape
    sc = float(1.0 / np.sqrt(dh) if scale is None else scale)

    # kernel layout is (heads, block, d); transpose once, ring-permute the
    # transposed buffers
    qh = jnp.transpose(q, (1, 0, 2))
    kh = jnp.transpose(k, (1, 0, 2))
    vh = jnp.transpose(v, (1, 0, 2))
    m0, l0, a0 = flash_carry_init(h, b, dh)
    perm = [(i, (i + 1) % nblk) for i in range(nblk)]
    qoff = me * b

    def hop(step, m, l, a, kc, vc):
        koff = ((me - step) % nblk) * b
        return flash_attention_hop(qh, kc, vc, m, l, a, qoff, koff,
                                   causal=causal, scale=sc,
                                   block_q=block_q, block_k=block_k,
                                   interpret=interpret)

    def body(step, carry):
        m, l, a, kc, vc = carry
        m, l, a = hop(step, m, l, a, kc, vc)
        kc = lax.ppermute(kc, axis, perm)
        vc = lax.ppermute(vc, axis, perm)
        return m, l, a, kc, vc

    m, l, a, kc, vc = lax.fori_loop(0, nblk - 1, body, (m0, l0, a0, kh, vh))
    m, l, a = hop(nblk - 1, m, l, a, kc, vc)
    ln = l[:, :, :1]                                         # (h, b, 1)
    ln = jnp.where(ln == 0.0, 1.0, ln)
    out = (a / ln).astype(q.dtype)                           # (h, b, dh)
    return jnp.transpose(out, (1, 0, 2))                     # (b, h, dh)


@functools.lru_cache(maxsize=32)
def _ring_flash_jit(mesh, causal: bool, block_q: int, block_k: int):
    axis = mesh.axis_names[0]
    spec = P(axis, None, None)

    def fn(q, k, v):
        return ring_flash_attention_kernel(q, k, v, axis, causal=causal,
                                           block_q=block_q, block_k=block_k)

    return jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=(spec,) * 3,
                                 out_specs=spec, check_vma=False))


def ring_flash_attention(q: DArray, k: DArray, v: DArray,
                         causal: bool = False, block_q: int = 512,
                         block_k: int = 512) -> DArray:
    """Fused (Pallas per-hop) exact attention over sequence-sharded
    (seq, heads, d) DArrays — the performance path of ``ring_attention``."""
    for name, a in (("q", q), ("k", k), ("v", v)):
        if a.ndim != 3:
            raise ValueError(f"{name} must be (seq, heads, head_dim), "
                             f"got {a.dims}")
        if a.dims != q.dims:
            raise ValueError("q, k, v dims must match")
    pids = [int(p) for p in q.pids.flat]
    n = len(pids)
    if q.pids.shape[0] != n or q.dims[0] % n != 0:
        raise ValueError(
            "ring attention needs the sequence dim sharded evenly over a "
            f"1-D grid; got grid {q.pids.shape} for dims {q.dims}")
    blk = q.dims[0] // n
    bq = min(block_q, blk)
    bk = min(block_k, blk)
    while blk % bq:
        bq //= 2
    while blk % bk:
        bk //= 2
    mesh = L.mesh_for(pids, (n, 1, 1))
    out = _ring_flash_jit(mesh, causal, bq, bk)(q.garray, k.garray, v.garray)
    return _wrap_global(out, procs=pids, dist=[n, 1, 1])


def reference_attention(q, k, v, causal: bool = False):
    """Dense O(seq²) oracle for tests."""
    q, k, v = (np.asarray(x, np.float32) for x in (q, k, v))
    s = np.einsum("qhd,khd->hqk", q / np.sqrt(q.shape[-1]), k)
    if causal:
        qi = np.arange(q.shape[0])[:, None]
        ki = np.arange(k.shape[0])[None, :]
        s = np.where((ki <= qi)[None], s, -np.inf)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    o = np.einsum("hqk,khd->hqd", p, v)
    return np.transpose(o, (1, 0, 2))
