"""Expert parallelism: top-k mixture-of-experts with all_to_all dispatch.

Completes the parallelism inventory (SURVEY.md §2: "EP absent in
reference — all-to-all covers the communication substrate it needs").  The
substrate is exactly the reference's sample-sort scatter (sort.jl:24-55):
bucketize locally, exchange buckets all-to-all, process, exchange back.
Here the buckets are tokens routed to experts, the exchange is
``lax.all_to_all`` over the ``ep`` mesh axis, and the whole
route→dispatch→FFN→return→combine path is ONE compiled shard_map program.

Routing (GShard/Switch-style):

- top-``k`` experts per token, gates renormalized over the selected k;
- per-(rank, expert) ``capacity`` slots, default ``ceil(capacity_factor *
  k * n_local / E)`` — slot-major assignment so a token's primary expert
  wins capacity before anyone's secondary;
- tokens whose every slot overflowed pass through on the residual path;
- the auxiliary load-balance loss ``E * Σ_e f_e · P_e`` (Switch eq. 4:
  f_e = fraction of tokens whose top-1 is e, P_e = mean router prob),
  psum-averaged over the expert axis, returned for the trainer to scale.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.collectives import run_spmd, spmd_mesh

__all__ = ["moe_forward", "init_moe_params", "make_ep_mesh",
           "reference_moe"]


def make_ep_mesh(n_experts: int, axis: str = "ep") -> Mesh:
    return spmd_mesh(n_experts, axis)


def init_moe_params(key, n_experts: int, hidden: int, ffn: int,
                    dtype=jnp.float32):
    """Router + per-expert FFN weights, experts stacked on a leading axis
    (shards P('ep', ...))."""
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = jnp.asarray(np.sqrt(2.0 / hidden), dtype)
    s2 = jnp.asarray(np.sqrt(2.0 / ffn), dtype)
    return {
        "Wg": jax.random.normal(k1, (hidden, n_experts), dtype) * s1,
        "W1": jax.random.normal(k2, (n_experts, hidden, ffn), dtype) * s1,
        "W2": jax.random.normal(k3, (n_experts, ffn, hidden), dtype) * s2,
    }


def _expert_ffn(x, W1, W2):
    return jax.nn.gelu(x @ W1) @ W2


def _route_topk(x, Wg, n_experts, k, capacity):
    """Top-k routing with per-(rank, expert) capacity.

    Returns per-token/slot expert ids (n, k), renormalized gates (n, k),
    capacity positions (n, k), keep masks (n, k), and the Switch aux-loss
    ingredients (f_e, P_e) over the local tokens.  Slot-major position
    assignment: ALL slot-0 (primary) picks claim capacity before any
    slot-1 pick, mirroring GShard's priority."""
    n = x.shape[0]
    logits = x @ Wg                                     # (n, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate, eidx = lax.top_k(probs, k)                    # (n, k)
    if k > 1:
        # GShard: renormalize over the selected k.  Top-1 (Switch) keeps
        # the RAW router prob — it is the router's gradient path.
        gate = gate / jnp.sum(gate, axis=-1, keepdims=True)
    flat_e = eidx.T.reshape(-1)                         # (k*n,) slot-major
    onehot = jax.nn.one_hot(flat_e, n_experts, dtype=jnp.int32)
    pos = (jnp.cumsum(onehot, axis=0) - 1)[
        jnp.arange(k * n), flat_e].reshape(k, n).T      # (n, k)
    keep = pos < capacity
    # Switch aux ingredients over the local shard: f_e from the top-1
    # assignment, P_e the mean router prob
    f_e = jnp.mean(jax.nn.one_hot(eidx[:, 0], n_experts,
                                  dtype=probs.dtype), axis=0)
    P_e = jnp.mean(probs, axis=0)
    return eidx, gate, pos, keep, f_e, P_e


@functools.lru_cache(maxsize=32)
def _moe_jit(mesh, capacity: int, k: int):
    axis = mesh.axis_names[0]
    E = mesh.shape[axis]

    def kernel(x, Wg, W1, W2):
        # x: (n, H) local tokens; W1/W2: (1, H, F)/(1, F, H) local expert
        n, H = x.shape
        eidx, gate, pos, keep, f_e, P_e = _route_topk(x, Wg, E, k, capacity)
        posc = jnp.clip(pos, 0, capacity - 1)
        # dispatch buffer: (E, C, H); dropped slots contribute zeros
        buf = jnp.zeros((E, capacity, H), x.dtype)
        for j in range(k):                               # k is small/static
            buf = buf.at[eidx[:, j], posc[:, j]].add(
                x * keep[:, j, None].astype(x.dtype))
        recv = lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                              tiled=True)               # (E, C, H)
        y = _expert_ffn(recv.reshape(E * capacity, H), W1[0], W2[0])
        back = lax.all_to_all(y.reshape(E, capacity, H), axis,
                              split_axis=0, concat_axis=0, tiled=True)
        # combine: gated sum over kept slots; residual passthrough only
        # when EVERY slot of a token overflowed
        out = jnp.zeros_like(x)
        for j in range(k):
            yi = back[eidx[:, j], posc[:, j]]           # (n, H)
            out = out + jnp.where(keep[:, j, None],
                                  gate[:, j, None] * yi, 0.0)
        any_kept = jnp.any(keep, axis=-1)
        out = jnp.where(any_kept[:, None], out, x)
        # Switch aux loss, averaged over the expert-parallel ranks
        aux = E * jnp.sum(f_e * P_e)
        aux = lax.psum(aux, axis) / E
        return out, aux

    return run_spmd(
        kernel, mesh,
        in_specs=(P(axis, None), P(), P(axis, None, None),
                  P(axis, None, None)),
        out_specs=(P(axis, None), P()))


def moe_forward(params, x, mesh: Mesh, capacity: int | None = None,
                k: int = 1, capacity_factor: float = 2.0,
                return_aux: bool = False):
    """Route the (N, H) token-sharded batch through the expert-parallel
    layer; returns (N, H) with the same sharding (and the scalar
    load-balance aux loss when ``return_aux``).

    ``capacity`` (per rank per expert) defaults to
    ``ceil(capacity_factor * k * n_local / E)``."""
    x = jnp.asarray(x)
    E = mesh.shape[mesh.axis_names[0]]
    if params["W1"].shape[0] != E:
        raise ValueError(
            f"params have {params['W1'].shape[0]} experts, mesh has {E}")
    if x.shape[0] % E:
        raise ValueError(f"token count {x.shape[0]} must be divisible by "
                         f"the {E} expert ranks")
    if not 1 <= k <= E:
        raise ValueError(f"k must be in [1, {E}], got {k}")
    n_local = x.shape[0] // E
    if capacity is None:
        capacity = max(1, int(np.ceil(capacity_factor * k * n_local / E)))
    if capacity <= 0:
        raise ValueError(f"capacity must be positive, got {capacity}")
    out, aux = _moe_jit(mesh, int(capacity), int(k))(
        x, params["Wg"], params["W1"], params["W2"])
    return (out, aux) if return_aux else out


def reference_moe(params, x, capacity_per_rank_expert: int, n_ranks: int,
                  k: int = 1):
    """Dense oracle replicating the top-k routing + slot-major capacity
    semantics."""
    x = np.asarray(x, np.float32)
    E = params["Wg"].shape[1]
    out = np.zeros_like(x)
    n_local = x.shape[0] // n_ranks
    for r in range(n_ranks):
        xs = x[r * n_local:(r + 1) * n_local]
        logits = xs @ np.asarray(params["Wg"])
        pz = np.exp(logits - logits.max(-1, keepdims=True))
        pz = pz / pz.sum(-1, keepdims=True)
        top = np.argsort(-pz, axis=-1, kind="stable")[:, :k]   # (n, k)
        gates = np.take_along_axis(pz, top, axis=-1)
        if k > 1:
            gates = gates / gates.sum(-1, keepdims=True)
        counts = {e: 0 for e in range(E)}
        kept = np.zeros((n_local, k), bool)
        for j in range(k):                       # slot-major priority
            for i in range(n_local):
                ei = int(top[i, j])
                if counts[ei] < capacity_per_rank_expert:
                    counts[ei] += 1
                    kept[i, j] = True
        for i in range(n_local):
            if not kept[i].any():
                out[r * n_local + i] = xs[i]
                continue
            acc = np.zeros(x.shape[1], np.float32)
            for j in range(k):
                if kept[i, j]:
                    ei = int(top[i, j])
                    h = np.asarray(_expert_ffn(
                        jnp.asarray(xs[i:i + 1]),
                        jnp.asarray(params["W1"][ei]),
                        jnp.asarray(params["W2"][ei])))[0]
                    acc += gates[i, j] * h
            out[r * n_local + i] = acc
    return out
