"""Expert parallelism: top-1 mixture-of-experts with all_to_all dispatch.

Completes the parallelism inventory (SURVEY.md §2: "EP absent in
reference — all-to-all covers the communication substrate it needs").  The
substrate is exactly the reference's sample-sort scatter (sort.jl:24-55):
bucketize locally, exchange buckets all-to-all, process, exchange back.
Here the buckets are tokens routed to experts, the exchange is
``lax.all_to_all`` over the ``ep`` mesh axis, and the whole
route→dispatch→FFN→return→combine path is ONE compiled shard_map program.

Top-1 routing with a capacity limit: each rank sends at most ``capacity``
tokens to each expert; overflowing tokens pass through on the residual
path (standard Switch-style behavior).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.collectives import run_spmd, spmd_mesh

__all__ = ["moe_forward", "init_moe_params", "make_ep_mesh",
           "reference_moe"]


def make_ep_mesh(n_experts: int, axis: str = "ep") -> Mesh:
    return spmd_mesh(n_experts, axis)


def init_moe_params(key, n_experts: int, hidden: int, ffn: int,
                    dtype=jnp.float32):
    """Router + per-expert FFN weights, experts stacked on a leading axis
    (shards P('ep', ...))."""
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = jnp.asarray(np.sqrt(2.0 / hidden), dtype)
    s2 = jnp.asarray(np.sqrt(2.0 / ffn), dtype)
    return {
        "Wg": jax.random.normal(k1, (hidden, n_experts), dtype) * s1,
        "W1": jax.random.normal(k2, (n_experts, hidden, ffn), dtype) * s1,
        "W2": jax.random.normal(k3, (n_experts, ffn, hidden), dtype) * s2,
    }


def _expert_ffn(x, W1, W2):
    return jax.nn.gelu(x @ W1) @ W2


def _route(x, Wg, n_experts, capacity):
    """Top-1 routing with per-(rank, expert) capacity; returns expert id,
    gate prob, bucket position, and keep mask per local token."""
    logits = x @ Wg                                     # (n, E)
    e = jnp.argmax(logits, axis=-1)                     # (n,)
    p = jax.nn.softmax(logits, axis=-1)[jnp.arange(x.shape[0]), e]
    onehot = jax.nn.one_hot(e, n_experts, dtype=jnp.int32)
    pos = (jnp.cumsum(onehot, axis=0) - 1)[jnp.arange(x.shape[0]), e]
    keep = pos < capacity
    return e, p, pos, keep


@functools.lru_cache(maxsize=32)
def _moe_jit(mesh, capacity: int):
    axis = mesh.axis_names[0]
    E = mesh.shape[axis]

    def kernel(x, Wg, W1, W2):
        # x: (n, H) local tokens; W1/W2: (1, H, F)/(1, F, H) local expert
        n, H = x.shape
        e, p, pos, keep = _route(x, Wg, E, capacity)
        posc = jnp.clip(pos, 0, capacity - 1)
        # dispatch buffer: (E, C, H); dropped tokens contribute zeros
        buf = jnp.zeros((E, capacity, H), x.dtype)
        buf = buf.at[e, posc].add(x * keep[:, None])
        recv = lax.all_to_all(buf, axis, split_axis=0, concat_axis=0,
                              tiled=True)               # (E, C, H)
        y = _expert_ffn(recv.reshape(E * capacity, H), W1[0], W2[0])
        back = lax.all_to_all(y.reshape(E, capacity, H), axis,
                              split_axis=0, concat_axis=0, tiled=True)
        yi = back[e, posc]                              # (n, H)
        # combine: gated expert output for kept tokens, residual passthrough
        # for capacity overflow
        return jnp.where(keep[:, None], p[:, None] * yi, x)

    return run_spmd(
        kernel, mesh,
        in_specs=(P(axis, None), P(), P(axis, None, None),
                  P(axis, None, None)),
        out_specs=P(axis, None))


def moe_forward(params, x, mesh: Mesh, capacity: int | None = None):
    """Route the (N, H) token-sharded batch through the expert-parallel
    layer; returns (N, H) with the same sharding."""
    x = jnp.asarray(x)
    E = mesh.shape[mesh.axis_names[0]]
    if params["W1"].shape[0] != E:
        raise ValueError(
            f"params have {params['W1'].shape[0]} experts, mesh has {E}")
    if x.shape[0] % E:
        raise ValueError(f"token count {x.shape[0]} must be divisible by "
                         f"the {E} expert ranks")
    n_local = x.shape[0] // E
    if capacity is None:
        capacity = max(1, int(np.ceil(2.0 * n_local / E)))
    if capacity <= 0:
        raise ValueError(f"capacity must be positive, got {capacity}")
    return _moe_jit(mesh, int(capacity))(
        x, params["Wg"], params["W1"], params["W2"])


def reference_moe(params, x, capacity_per_rank_expert: int, n_ranks: int):
    """Dense oracle replicating the routing + capacity semantics."""
    x = np.asarray(x, np.float32)
    E = params["Wg"].shape[1]
    out = np.empty_like(x)
    n_local = x.shape[0] // n_ranks
    for r in range(n_ranks):
        xs = x[r * n_local:(r + 1) * n_local]
        logits = xs @ np.asarray(params["Wg"])
        e = np.argmax(logits, axis=-1)
        pz = np.exp(logits - logits.max(-1, keepdims=True))
        pz = pz / pz.sum(-1, keepdims=True)
        counts = {k: 0 for k in range(E)}
        for i in range(n_local):
            ei = int(e[i])
            if counts[ei] < capacity_per_rank_expert:
                counts[ei] += 1
                h = np.asarray(_expert_ffn(jnp.asarray(xs[i:i + 1]),
                                           jnp.asarray(params["W1"][ei]),
                                           jnp.asarray(params["W2"][ei])))
                out[r * n_local + i] = pz[i, ei] * h[0]
            else:
                out[r * n_local + i] = xs[i]
    return out
