"""Ulysses-style all-to-all sequence parallelism — the second long-context
strategy (complementing ring attention).

Where ring attention keeps Q resident and rotates K/V around the mesh
(O(P) ppermute hops), the Ulysses pattern re-partitions once: tokens are
sequence-sharded; one ``lax.all_to_all`` turns the layout into
head-sharded-with-full-sequence, each rank runs *complete* attention for
its heads (here: the Pallas flash kernel or a dense jnp path), and a
second all_to_all restores sequence sharding.  Two collectives total,
O(seq·d/P) traffic per rank — the better trade when heads ≥ ranks and ICI
all-to-all bandwidth is plentiful; ring wins when sequence lengths dwarf
HBM.  Both ride the same substrate the reference exposes as its sample-sort
scatter (sort.jl:24-55 → lax.all_to_all).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import layout as L
from ..darray import DArray, _wrap_global
from ..parallel.collectives import run_spmd

__all__ = ["ulysses_attention"]


from ..ops.pallas_attention import (_dense_attention_shd as _dense_attention,
                                    flash_block_size as _flash_block)


@functools.lru_cache(maxsize=32)
def _ulysses_jit(mesh, causal: bool, scale: float, use_flash: bool,
                 bq: int = 0, bk: int = 0, hfold: int = 1):
    # blocks are resolved OUTSIDE this cache (ulysses_attention) and are
    # part of the key — a tune banked after the first call must not be
    # shadowed by a stale cached program
    axis = mesh.axis_names[0]

    def kernel(q, k, v):
        # in: (S/P, H, d) sequence-sharded blocks
        def to_heads(x):
            # all_to_all: gather full sequence, scatter heads
            # (S/P, H, d) -> (S, H/P, d)
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=0,
                                  tiled=True)
        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
        if use_flash:
            # per-rank compute = the Pallas flash kernel: no O(S^2) score
            # matrix, VMEM-resident online softmax
            from ..ops.pallas_attention import flash_attention
            oh = flash_attention(qh, kh, vh, causal=causal, scale=scale,
                                 block_q=bq, block_k=bk, head_fold=hfold)
        else:
            oh = _dense_attention(qh, kh, vh, causal, scale)
        # inverse: scatter sequence, gather heads: (S, H/P, d) -> (S/P, H, d)
        return lax.all_to_all(oh, axis, split_axis=0, concat_axis=1,
                              tiled=True)

    spec = P(axis, None, None)
    return run_spmd(kernel, mesh, in_specs=(spec,) * 3, out_specs=spec)


def ulysses_attention(q: DArray, k: DArray, v: DArray,
                      causal: bool = False,
                      use_flash: bool = True) -> DArray:
    """Exact attention over sequence-sharded (seq, heads, d) DArrays via
    head-scatter all_to_all.  Requires heads divisible by the rank count.

    Per-rank compute defaults to the Pallas flash kernel (O(seq·d) memory);
    ``use_flash=False`` selects the dense O(seq²) jnp path."""
    for name, a in (("q", q), ("k", k), ("v", v)):
        if a.ndim != 3:
            raise ValueError(f"{name} must be (seq, heads, head_dim), "
                             f"got {a.dims}")
        if a.dims != q.dims:
            raise ValueError("q, k, v dims must match")
    pids = [int(p) for p in q.pids.flat]
    n = len(pids)
    S, H, D = q.dims
    if q.pids.shape[0] != n or S % n:
        raise ValueError(
            f"ulysses needs the sequence dim sharded evenly over a 1-D "
            f"grid; got grid {q.pids.shape} for dims {q.dims}")
    if H % n:
        raise ValueError(f"heads {H} must be divisible by {n} ranks")
    mesh = L.mesh_for(pids, (n, 1, 1))
    scale = float(1.0 / np.sqrt(D))
    bq = bk = hf = 0
    if use_flash:
        # resolve the flash config HERE (registry, falling back to the
        # always-valid power-of-two block) so the cached jit is keyed on
        # the resolved values
        from ..ops.pallas_attention import tuned_flash_config
        bq, bk, hf = tuned_flash_config(S, H // n, D, q.dtype,
                                        bool(causal),
                                        default=_flash_block(S))
    out = _ulysses_jit(mesh, bool(causal), scale, bool(use_flash),
                       bq, bk, hf)(q.garray, k.garray, v.garray)
    return _wrap_global(out, procs=pids, dist=[n, 1, 1])
