"""Asyncio front-end for the serving layer.

The server and engine are thread-based (dispatch loops own the GIL story
near the device runtime); a web front-end is usually an event loop.
This module is the bridge, with the same typed-error contract:

- :func:`submit` — awaitable wrapper over ``Server.submit`` (the
  ``concurrent.futures.Future`` adapted via ``asyncio.wrap_future``;
  typed rejections raise immediately in the caller's task).
- :func:`stream_tokens` — async iterator over a
  :class:`~.decode.TokenStream`: tokens are forwarded from the engine
  thread onto the event loop via ``loop.call_soon_threadsafe`` (history
  replays first, so a late subscriber misses nothing).  Cancelling the
  consuming task cancels the *sequence* — its KV pages free immediately.
- :func:`generate` — the end-to-end decode call: submit through the
  server's admission gates, await the stream handle, then yield tokens.

No event loop is ever blocked: every wait point is an ``await``.
"""

from __future__ import annotations

import asyncio
from typing import Any, AsyncIterator

from .decode import TokenStream

__all__ = ["submit", "stream_tokens", "generate"]


async def submit(server, endpoint: str, payload: Any, **kw) -> Any:
    """Awaitable ``Server.submit``: returns the resolved result or
    raises the typed ``ServeError`` the request ended with.  Admission
    rejections (``Overloaded``/``QuotaExceeded``/``Draining``) raise
    right here, before any await."""
    fut = server.submit(endpoint, payload, **kw)
    return await asyncio.wrap_future(fut)


async def stream_tokens(stream: TokenStream,
                        *, cancel_on_exit: bool = True
                        ) -> AsyncIterator[int]:
    """Async-iterate a :class:`TokenStream`.  The engine thread's
    pushes land on the event loop threadsafe; a typed terminal error
    re-raises in the consumer.  When the consuming task is cancelled
    (client disconnect), the sequence is cancelled too — pages free
    immediately — unless ``cancel_on_exit=False``."""
    loop = asyncio.get_running_loop()
    q: asyncio.Queue = asyncio.Queue()

    def _cb(kind: str, value) -> None:
        loop.call_soon_threadsafe(q.put_nowait, (kind, value))

    stream.add_listener(_cb)
    try:
        while True:
            kind, value = await q.get()
            if kind == "token":
                yield value
            else:               # ("done", error_or_None)
                if value is not None:
                    raise value
                return
    finally:
        if cancel_on_exit and not stream.done():
            stream.cancel()


async def generate(server, prompt, *, endpoint: str = "decode",
                   tenant: str = "default", **kw) -> AsyncIterator[int]:
    """Submit a decode request through the server's admission gates and
    stream its tokens.  ``kw`` passes through to ``Server.submit``
    (``deadline_s``, ``trace_id``, ...); the payload may be a bare
    prompt or a dict with per-sequence knobs."""
    handle = await submit(server, endpoint, prompt, tenant=tenant, **kw)
    if not isinstance(handle, TokenStream):
        raise TypeError(f"endpoint {endpoint!r} did not return a "
                        f"TokenStream (got {type(handle).__name__}); "
                        "is a DecodeEngine attached?")
    async for tok in stream_tokens(handle):
        yield tok
