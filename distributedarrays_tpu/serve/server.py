"""Multi-tenant async serving executor over resident sharded model state.

The layer between ``spmd()``/``djit`` and a user (ROADMAP item 2): callers
submit requests against named *endpoints* (closures over resident sharded
state — a transformer's params, a MoE's experts, a ring-attention cache)
and get back futures; dispatch workers run an async loop that forms
continuously-batched device dispatches, executes them under the
resilience stack's retry discipline, and resolves every future with a
result or a typed error.  Nothing hangs and nothing grows unboundedly:

- **admission control** at submit (per-tenant token buckets, bounded
  queue, HBM + rolling-p99 backpressure) — see ``admission.py``;
- **continuous batching** (coalesce compatible requests, flush on
  batch-full or deadline) — see ``batching.py``;
- **deadline propagation** — budgets enforced at enqueue, batch
  formation, and dispatch; expired work is never dispatched;
- **fault tolerance** — each batch dispatch runs under
  ``resilience.recovery.run_with_recovery``: a device loss mid-batch
  restores/shrinks/retries per the PR 6 verdict table, and a batch the
  executor gives up on fails every member future with a typed
  :class:`~.errors.RequestFailed` carrying the cause;
- **graceful drain** — ``drain()``/``close()`` (and the SIGTERM hook)
  stop admission, flush queued batches, wake any sleeping retry
  backoff, then optionally ``d_closeall()``.

Telemetry: ``serve.submitted/admitted/shed{reason}/expired{stage}/
completed/failed/batches`` counters, the ``serve.queue_depth`` gauge,
``serve.batch_size``/``serve.batch_latency_s``/``serve.request_latency_s``
histograms, and a ``serve.dispatch`` span per batch (so Perfetto shows
the dispatch timeline per worker thread).
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import signal
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable

from .. import core
from .. import telemetry as _tm
from ..telemetry import stream as _tstream
from ..resilience import elastic, faults as _fl, recovery
from .admission import AdmissionController
from .batching import BatchQueue, Request, payload_key
from .errors import DeadlineExceeded, Draining, RequestFailed, ServeError

__all__ = ["ServeConfig", "Endpoint", "Server", "install_sigterm"]

# request trace ids: pid-scoped monotonic counter — unique within the
# process, readable in a journal ("req-<pid>-<n>"), deterministic in
# tests.  Minted at submit(), carried on every span to resolve.
_REQ_IDS = itertools.count(1)

# SLO histogram bucket bounds (seconds) for the per-endpoint
# ``serve.slo.request_s`` family (da_tpu_serve_slo_* in Prometheus)
_SLO_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


@dataclasses.dataclass
class ServeConfig:
    """Serving knobs (see docs/serving.md for the policy walkthrough).

    ``hbm_budget_bytes=None`` reads ``DA_TPU_SERVE_HBM_BUDGET_MB`` (unset
    → the HBM shed signal is off); ``p99_shed_s=None`` disables the
    latency shed signal."""

    max_batch: int = 8
    flush_s: float = 0.005            # straggler wait past the head arrival
    max_queue: int = 64               # bounded queue depth (all endpoints)
    default_deadline_s: float = 30.0
    tenant_rate: float = 100.0        # default per-tenant tokens/second
    tenant_burst: float = 200.0
    hbm_budget_bytes: int | None = None
    hbm_shed_fraction: float = 0.9
    p99_shed_s: float | None = None
    latency_window: int = 256
    # per-endpoint p99 window-size overrides ({endpoint: maxlen}); an
    # endpoint absent here uses latency_window (register(...,
    # latency_window=) overrides both)
    endpoint_latency_windows: dict[str, int] | None = None
    workers: int = 1                  # dispatch loop threads
    drain_timeout_s: float = 30.0

    def resolved_hbm_budget(self) -> int | None:
        if self.hbm_budget_bytes is not None:
            return int(self.hbm_budget_bytes)
        mb = os.environ.get("DA_TPU_SERVE_HBM_BUDGET_MB")
        if not mb:
            return None
        try:
            return int(float(mb) * (1 << 20))
        except ValueError:
            return None


@dataclasses.dataclass
class Endpoint:
    """A named batched entry point over resident state.

    ``fn(payloads: list) -> list`` receives the coalesced batch (same
    compatibility key throughout) and must return one result per payload,
    in order.  ``key_fn`` overrides the default payload signature."""

    name: str
    fn: Callable[[list], list]
    max_batch: int
    flush_s: float
    key_fn: Callable[[Any], Any] = payload_key


class Server:
    """The async serving executor.  Use as a context manager, or call
    :meth:`close` explicitly; dispatch workers are daemon threads started
    lazily on the first submit."""

    def __init__(self, config: ServeConfig | None = None, *,
                 policy: recovery.RetryPolicy | None = None,
                 checkpoints=None, restore_fn=None, devices=None):
        self.config = config or ServeConfig()
        self._admission = AdmissionController(
            max_queue=self.config.max_queue,
            tenant_rate=self.config.tenant_rate,
            tenant_burst=self.config.tenant_burst,
            hbm_budget_bytes=self.config.resolved_hbm_budget(),
            hbm_shed_fraction=self.config.hbm_shed_fraction,
            p99_shed_s=self.config.p99_shed_s,
            max_batch=self.config.max_batch,
            window=self.config.latency_window,
            endpoint_windows=self.config.endpoint_latency_windows)
        self._queue = BatchQueue()
        self._endpoints: dict[str, Endpoint] = {}
        self._policy = policy
        self._checkpoints = checkpoints
        self._restore_fn = restore_fn
        self._devices = devices if devices is not None else elastic.manager()
        # reentrant: the SIGTERM handler runs close() on whatever thread
        # the signal lands on — possibly one already inside submit()'s
        # locked section; a plain Lock would self-deadlock the shutdown
        self._lock = threading.RLock()
        self._workers: list[threading.Thread] = []
        self._started = False
        self._draining = False
        self._closed = False
        # drain wakes sleeping recovery backoffs promptly (the
        # interruptible-backoff contract: a draining server never blocks
        # on a retry sleeping out its exponential delay)
        self._drain_wake = threading.Event()
        self._inflight = 0
        self._inflight_cv = threading.Condition()

    # -- endpoints ---------------------------------------------------------

    def register(self, name: str, fn: Callable[[list], list], *,
                 max_batch: int | None = None, flush_s: float | None = None,
                 key_fn: Callable[[Any], Any] | None = None,
                 latency_window: int | None = None) -> Endpoint:
        """Register a batched endpoint.  ``fn`` takes the list of
        coalesced payloads and returns one result per payload.
        ``latency_window`` overrides the endpoint's rolling-p99 window
        size (else ``ServeConfig.endpoint_latency_windows``, else the
        global ``latency_window``)."""
        ep = Endpoint(
            name=name, fn=fn,
            max_batch=int(max_batch if max_batch is not None
                          else self.config.max_batch),
            flush_s=float(flush_s if flush_s is not None
                          else self.config.flush_s),
            key_fn=key_fn or payload_key)
        if latency_window is not None:
            self._admission.set_endpoint_window(name, latency_window)
        with self._lock:
            if self._closed:
                raise ServeError("server is closed")
            self._endpoints[name] = ep
        return ep

    def set_quota(self, tenant: str, rate: float, burst: float) -> None:
        self._admission.set_quota(tenant, rate, burst)

    def set_reclaimable(self, fn: Callable[[], int] | None) -> None:
        """Wire a reclaimable-bytes signal (e.g. the decode engine's
        ``PagedKVCache.idle_evictable_bytes``) into admission: an HBM
        shed whose pressure eviction can clear ships the clamp-floor
        ``retry_after`` instead of the queue drain estimate."""
        self._admission.reclaimable_fn = fn

    # -- submission --------------------------------------------------------

    def submit(self, endpoint: str, payload: Any, *, tenant: str = "default",
               deadline_s: float | None = None, key: Any = None,
               trace_id: str | None = None) -> Future:
        """Admit one request; returns its future, or raises a typed
        rejection (:class:`Draining`, :class:`DeadlineExceeded`,
        :class:`QuotaExceeded`, :class:`Overloaded`) without enqueueing.
        The future resolves to the endpoint's result for this payload, or
        raises the typed error the request ended with.

        Every request gets a trace id (``trace_id`` to propagate a
        caller-supplied one, else minted here): the ``serve.submit``
        span, the batch's ``serve.dispatch``/``serve.resolve`` spans,
        recovery retries, and SPMD rank steps under the dispatch all
        carry it — the submit-to-resolve journey reconstructs from the
        journal and exports as a Perfetto flow."""
        tid = trace_id or f"req-{os.getpid()}-{next(_REQ_IDS)}"
        _tm.count("serve.submitted", tenant=tenant)
        with _tm.trace_ctx(tid), \
                _tm.span("serve.submit", endpoint=endpoint, tenant=tenant,
                         bytes_hbm=_tm.nbytes_of(payload)):
            # ONE locked section from the draining check through the
            # enqueue: a request is admitted iff it is enqueued before
            # drain() flips _draining (so the flush is guaranteed to
            # cover it), and the queue-depth bound is checked atomically
            # with the put (so concurrent submitters cannot overshoot
            # max_queue)
            with self._lock:
                # partition health gate: a minority-side server must
                # drain typed, not time requests out — the quorum verdict
                # rides the elastic manager's probe epochs
                part = getattr(self._devices, "partition_verdict",
                               lambda: None)()
                if part is not None and part.get("verdict") == "minority" \
                        and not self._draining:
                    self._draining = True
                    self._drain_wake.set()
                    _tm.count("serve.partition_drains")
                    if _tm.enabled():
                        # cold path: one event per partition drain
                        _tm.event("serve", "partition_drain",
                                  side=part.get("side", []),
                                  lost=part.get("lost", []))
                if self._draining or self._closed:
                    _tm.count("serve.shed", reason="draining",
                              tenant=tenant)
                    raise Draining(tenant=tenant)
                ep = self._endpoints.get(endpoint)
                if ep is None:
                    raise ServeError(
                        f"unknown endpoint {endpoint!r} "
                        f"(registered: {sorted(self._endpoints)})")
                budget = (self.config.default_deadline_s
                          if deadline_s is None else float(deadline_s))
                now = time.monotonic()
                if budget <= 0:
                    _tm.count("serve.expired", stage="enqueue")
                    raise DeadlineExceeded(
                        f"request arrived with no budget "
                        f"(deadline_s={budget:g})", stage="enqueue")
                # the admission gate: queue bound -> HBM -> p99 -> quota
                # (the consuming token bucket last; see admission.admit)
                self._admission.admit(tenant, self._queue.depth())
                req = Request(endpoint=endpoint, payload=payload,
                              tenant=tenant, key=ep.key_fn(payload),
                              deadline=now + budget, enqueued=now,
                              trace_id=tid)
                self._ensure_started()
                try:
                    self._queue.put(req)  # dalint: disable=DAL008 — BatchQueue.put only appends + notifies under its own condition (never waits); depth is bounded at admission
                except RuntimeError:
                    # close() raced this submit: typed, never bare
                    _tm.count("serve.shed", reason="draining",
                              tenant=tenant)
                    raise Draining(tenant=tenant) from None
        _tm.count("serve.admitted", tenant=tenant)
        if _tm.enabled():
            # journaled AFTER self._lock drops (the write is file I/O;
            # doing it under the lock would serialize all submitters on
            # the journal disk): per-tenant token-level history
            # reconstructs as a Perfetto counter track next to queue
            # depth
            _tm.set_gauge("serve.tokens",
                          self._admission.token_level(tenant),
                          tenant=tenant, journal=True)
        return req.future

    # -- dispatch loop -----------------------------------------------------

    def _ensure_started(self) -> None:
        with self._lock:
            if self._started or self._closed:
                return
            self._started = True
            for i in range(max(1, int(self.config.workers))):
                t = threading.Thread(target=self._worker, daemon=True,
                                     name=f"serve-dispatch-{i}")
                self._workers.append(t)
                t.start()

    def _worker(self) -> None:
        while True:
            batch = self._queue.next_batch(self._limits)
            if batch is None:
                if self._draining and self._queue.depth() == 0:
                    return
                if self._closed:
                    return
                continue
            with self._inflight_cv:
                self._inflight += 1
            try:
                self._dispatch(batch)
            finally:
                with self._inflight_cv:
                    self._inflight -= 1
                    self._inflight_cv.notify_all()
                self._queue.task_done()

    def _limits(self, endpoint: str) -> tuple[int, float]:
        """Per-endpoint (max_batch, flush_s) for the batcher, resolved
        from the head request's endpoint so every endpoint gets exactly
        the bounds it registered with."""
        ep = self._endpoints.get(endpoint)
        if ep is None:   # pragma: no cover — endpoints are never removed
            return self.config.max_batch, self.config.flush_s
        return ep.max_batch, ep.flush_s

    def _dispatch(self, batch: list[Request]) -> None:
        ep = self._endpoints[batch[0].endpoint]
        # dispatch gate: expired work is never dispatched
        now = time.monotonic()
        live = [r for r in batch if r.deadline > now]
        for r in batch:
            if r.deadline <= now:
                r.expire("dispatch")
        if not live:
            return
        # the batch's trace context: every member request's id, so the
        # dispatch span, recovery retries, and any SPMD rank spans under
        # the endpoint body carry the submit-minted ids end to end
        with _tm.trace_ctx(*(r.trace_id for r in live)):
            self._dispatch_traced(ep, live)

    def _record_latency(self, dt: float,
                        endpoint: str | None = None) -> None:
        self._admission.record_latency(dt, endpoint)
        # rolling p99 as a gauge: the alerts module's serve_p99 burn-rate
        # rule (and any scraper) samples it without reaching into the
        # admission controller.  The unlabeled gauge is the global shed
        # signal; the labeled one is the per-endpoint window (its own
        # maxlen per ServeConfig/register)
        p99 = self._admission.latency.p99()
        _tm.set_gauge("serve.request_p99_s", p99)
        # live plane: every p99 update reaches the aggregator's burn
        # windows with its own wall stamp, not just the last value per
        # exporter tick (a single is-None check when no exporter is armed)
        _tstream.note("serve.request_p99_s", p99)
        if endpoint is not None:
            _tm.set_gauge(
                "serve.request_p99_s",
                self._admission.endpoint_latency(endpoint).p99(),
                endpoint=endpoint)

    def _dispatch_traced(self, ep: Endpoint, live: list[Request]) -> None:
        payloads = [r.payload for r in live]
        t0 = time.monotonic()
        _tm.count("serve.batches", endpoint=ep.name)
        try:
            with _tm.span("serve.dispatch", endpoint=ep.name,
                          size=len(live),
                          bytes_hbm=sum(_tm.nbytes_of(p)
                                        for p in payloads)):
                def _run():
                    # chaos site: a fault plan can kill a device mid-batch
                    # here; recovery re-invokes this closure on retry
                    _fl.check("serve.dispatch", endpoint=ep.name)
                    return ep.fn(payloads)
                results = recovery.run_with_recovery(
                    _run, policy=self._policy,
                    checkpoints=self._checkpoints,
                    restore_fn=self._restore_fn, devices=self._devices,
                    stop_event=self._drain_wake)
        except recovery.MinorityPartitionExit as e:
            # this controller lost quorum mid-dispatch: initiate the
            # typed drain (admission closes, workers flush and stop) and
            # fail the batch Draining — the client-visible story is
            # "server going away", not a generic dispatch failure
            dt = time.monotonic() - t0
            self._record_latency(dt, ep.name)
            with self._lock:
                self._draining = True
            self._drain_wake.set()
            _tm.count("serve.partition_drains")
            _tm.count("serve.failed", n=len(live), endpoint=ep.name)
            if _tm.enabled():
                # cold path: one event per partition drain; the exit
                # carries the (already-closed) incident id so the drain
                # attributes to the episode without window guessing
                extra = {"incident": e.incident} if e.incident else {}
                _tm.event("serve", "partition_drain", side=e.side,
                          lost=e.lost, endpoint=ep.name, **extra)
            err = Draining("server lost partition quorum; draining")
            err.__cause__ = e
            for r in live:
                r.fail(err)
            return
        except Exception as e:  # noqa: BLE001 — typed and shipped to futures
            dt = time.monotonic() - t0
            self._record_latency(dt, ep.name)
            err = e if isinstance(e, ServeError) else RequestFailed(
                f"batch dispatch failed after recovery gave up "
                f"(endpoint={ep.name}, size={len(live)}): "
                f"{type(e).__name__}: {e}")
            if err is not e:
                err.__cause__ = e
            _tm.count("serve.failed", n=len(live), endpoint=ep.name)
            for r in live:
                r.fail(err)
            return
        dt = time.monotonic() - t0
        self._record_latency(dt, ep.name)
        _tm.observe("serve.batch_latency_s", dt, endpoint=ep.name)
        _tm.observe("serve.batch_size", len(live), endpoint=ep.name)
        if not isinstance(results, (list, tuple)) or \
                len(results) != len(live):
            got = (len(results) if isinstance(results, (list, tuple))
                   else type(results).__name__)
            err = RequestFailed(
                f"endpoint {ep.name!r} returned {got} results for "
                f"{len(live)} requests (contract: one per payload, "
                "in order)")
            _tm.count("serve.failed", n=len(live), endpoint=ep.name)
            for r in live:
                r.fail(err)
            return
        with _tm.span("serve.resolve", endpoint=ep.name, size=len(live)):
            done = time.monotonic()
            for r, v in zip(live, results):
                r.resolve(v)
                _tm.observe("serve.request_latency_s", done - r.enqueued,
                            endpoint=ep.name)
                # per-endpoint SLO histogram: submit-to-resolve latency
                # into fixed buckets -> da_tpu_serve_slo_request_s_bucket
                _tm.observe("serve.slo.request_s", done - r.enqueued,
                            buckets=_SLO_BUCKETS, endpoint=ep.name)
            _tm.count("serve.completed", n=len(live), endpoint=ep.name)

    # -- lifecycle ---------------------------------------------------------

    def drain(self, timeout: float | None = None) -> bool:
        """Graceful drain: stop admitting (submits now raise
        :class:`Draining`), wake any sleeping retry backoff, flush every
        queued batch, and wait for in-flight dispatches.  Returns True
        when the queue and in-flight set emptied within ``timeout``."""
        with self._lock:
            if self._closed and not self._started:
                return True
            self._draining = True
        if _tm.enabled():
            # cold path: one event per drain
            _tm.event("serve", "drain", depth=self._queue.depth())
        self._drain_wake.set()
        self._queue.wake()
        deadline = time.monotonic() + (self.config.drain_timeout_s
                                       if timeout is None else timeout)
        # idle() counts claimed-but-not-yet-dispatched batches under the
        # queue's own lock, so "queue empty" can never race a batch that
        # left the queue but hasn't reached its dispatcher yet
        while time.monotonic() < deadline:
            if self._queue.idle() and self._inflight == 0:
                return True
            with self._inflight_cv:
                self._inflight_cv.wait(0.02)
        return self._queue.idle() and self._inflight == 0

    def close(self, *, drain: bool = True, timeout: float | None = None,
              closeall: bool = False) -> None:
        """Shut down: optionally drain first, stop workers, and (with
        ``closeall=True`` — the SIGTERM path) release every registered
        DArray via ``d_closeall``.  Requests still queued after the drain
        timeout fail typed, never silently."""
        drained = self.drain(timeout) if drain else False
        with self._lock:
            self._closed = True
        self._queue.close()
        if not drained:
            # whatever is still queued resolves typed — never a hang
            while True:
                batch = self._queue.next_batch(
                    lambda _e: (1 << 30, 0.0), wait_s=0.0)
                if not batch:
                    break
                for r in batch:
                    r.fail(Draining("server closed before this request "
                                    "could be dispatched"))
                self._queue.task_done()
        for t in self._workers:
            t.join(2.0)
        if closeall:
            core.d_closeall()
        if _tm.enabled():
            # cold path: one event per close
            _tm.event("serve", "close", drained=drained)

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        """Live snapshot for dashboards/tests: queue depth, rolling
        latency percentiles, in-flight batches, drain state."""
        return {
            "queue_depth": self._queue.depth(),
            "inflight": self._inflight,
            "draining": self._draining,
            "closed": self._closed,
            "latency_p50_s": self._admission.latency.p50(),
            "latency_p99_s": self._admission.latency.p99(),
            "latency_samples": self._admission.latency.count(),
            "endpoints": sorted(self._endpoints),
        }


def install_sigterm(server: Server, *, closeall: bool = True) -> bool:
    """Install a SIGTERM handler that gracefully drains ``server`` (stop
    admitting → flush batches → ``d_closeall`` when ``closeall``) and
    then honors the previous disposition: a callable prior handler is
    chained; ``SIG_DFL`` is restored and the signal re-delivered, so the
    process still terminates after the drain (a k8s/systemd stop must
    not leave a drained-but-running zombie sitting out its grace
    period).  Main thread only (signal module restriction); returns
    False when not installable."""
    if threading.current_thread() is not threading.main_thread():
        return False
    prev = signal.getsignal(signal.SIGTERM)

    def _handler(signum, frame):
        server.close(drain=True, closeall=closeall)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL or prev is None:
            # None = a disposition installed by non-Python code we cannot
            # re-invoke; default-terminate is the only no-zombie choice
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    signal.signal(signal.SIGTERM, _handler)
    return True
