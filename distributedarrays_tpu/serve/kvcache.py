"""Paged KV-cache: per-sequence attention state as resident DArray pages.

The decode service's working set is the KV cache — per-sequence key/value
rows that grow one token at a time and dominate HBM at scale.  This
module holds that state the way the rest of the stack holds model
state: as *registered sharded DArrays*, so every byte is visible to the
PR 5 HBM ledger (owner ``serve.kv`` via the allocation span) and every
page is re-laid onto survivors by ``resilience.elastic`` on shrink/grow
exactly like model parameters.

Layout (vLLM-style paging, re-derived for DArrays):

- Storage is a pool of **page blocks** — DArrays of shape
  ``(block_pages, 2, page_tokens, heads, head_dim)`` (dim 1 = K/V),
  sharded over the page dim so each page lives whole on one device.
  Blocks are allocated on demand and **reaped** (closed) when fully
  free, so the ledger's live-byte gauge tracks real cache usage and the
  admission layer's live-bytes-vs-budget signal stays honest.
- A **page** is ``page_tokens`` rows of K and V for one sequence; a
  sequence owns an ordered page table (list of ``(block, slot)`` ids).
  Writes land via incremental region mutation (one device's chunk);
  reads gather the sequence's pages into contiguous ``(ntok, h, d)``
  K/V arrays for the attention step.
- **Backpressure**: allocation first evicts idle (unpinned,
  least-recently-used) sequences when the pool or the HBM budget is
  short; if eviction cannot cover the request the caller gets a typed
  :class:`~.errors.Overloaded` (``reason="kv"``) with a honest
  ``retry_after``.  Eviction frees the sequence's pages but keeps its
  identity — the engine re-prefills it (K/V are a pure function of the
  token history, so the rebuild is bit-identical).
- ``idle_evictable_bytes()`` is the admission controller's *reclaimable*
  signal: bytes a shed could free right now by evicting idle sequences
  (so ``retry_after`` does not over-estimate when eviction can clear
  budget immediately).

Telemetry: ``serve.kv.pages_live/pages_free/seqs/bytes`` gauges,
``serve.kv.evictions/blocks_created/blocks_reaped/sheds`` counters.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any

import numpy as np

import jax.numpy as jnp

from .. import telemetry as _tm
from ..darray import dzeros
from ..resilience import elastic
from .errors import Overloaded, Rejected, ServeError

__all__ = ["KVCacheConfig", "PagedKVCache"]


@dataclasses.dataclass
class KVCacheConfig:
    """Paged-cache knobs.  ``hbm_budget_bytes`` bounds the *whole*
    ledger (weights + cache + payloads), matching the admission gate's
    signal; ``None`` disables budget-driven eviction (pool-size pressure
    still applies)."""

    page_tokens: int = 16             # K/V rows per page
    heads: int = 4
    head_dim: int = 8
    dtype: Any = jnp.float32
    max_pages: int = 256              # hard pool bound (all blocks)
    block_pages: int = 8              # pages per DArray block (alloc granule)
    hbm_budget_bytes: int | None = None
    hbm_evict_fraction: float = 0.9   # evict when live >= fraction * budget
    retry_after_s: float = 0.05       # shipped when eviction cannot cover


@dataclasses.dataclass
class _Block:
    """One pool DArray: ``block_pages`` pages, sharded over the page
    dim.  ``free`` is the set of unused slot indices."""

    d: Any
    free: set[int]


@dataclasses.dataclass
class _Seq:
    """Per-sequence cache record: the page table plus the LRU/pin state
    the eviction policy reads."""

    seq_id: int
    tenant: str
    pages: list[tuple[int, int]] = dataclasses.field(default_factory=list)
    ntok: int = 0                     # K/V rows written so far
    last_use: float = 0.0
    pinned: bool = False              # in-flight dispatch: never evicted


class PagedKVCache:
    """Fixed-size KV pages as resident sharded DArray state.

    Thread-safe; never calls out of module under its lock (eviction
    returns the evicted sequence ids to the caller instead of invoking
    callbacks, so the engine's lock order stays engine -> cache)."""

    def __init__(self, config: KVCacheConfig | None = None):
        self.config = config or KVCacheConfig()
        c = self.config
        if c.page_tokens <= 0 or c.block_pages <= 0 or c.max_pages <= 0:
            raise ValueError("page_tokens, block_pages and max_pages must "
                             "be positive")
        self._blocks: dict[int, _Block] = {}
        self._next_block = 0
        self._free: list[tuple[int, int]] = []   # (block_id, slot)
        self._seqs: dict[int, _Seq] = {}
        self._lock = threading.RLock()
        self._closed = False
        self.evictions = 0

    # -- geometry ----------------------------------------------------------

    @property
    def page_nbytes(self) -> int:
        c = self.config
        item = jnp.dtype(c.dtype).itemsize
        return 2 * c.page_tokens * c.heads * c.head_dim * item

    def pages_for(self, ntok: int) -> int:
        """Pages needed to hold ``ntok`` K/V rows."""
        return max(1, -(-int(ntok) // self.config.page_tokens))

    def capacity_pages(self) -> int:
        return self.config.max_pages

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            live = sum(len(s.pages) for s in self._seqs.values())
            return {
                "seqs": len(self._seqs),
                "pages_live": live,
                "pages_free": len(self._free),
                "blocks": len(self._blocks),
                "bytes_live": live * self.page_nbytes,
                "evictions": self.evictions,
            }

    def live_bytes(self) -> int:
        """Nominal bytes held by allocated pages (the ledger's view also
        counts block padding; this is the policy-side number)."""
        with self._lock:
            return sum(len(s.pages) for s in self._seqs.values()) \
                * self.page_nbytes

    def idle_evictable_bytes(self) -> int:
        """Bytes a shed could reclaim *right now*: pages of idle
        (unpinned) sequences plus fully-free blocks awaiting reap.  The
        admission controller's ``reclaimable_fn`` — a cache-full server
        whose budget eviction can clear must not ship a drain-rate
        ``retry_after``."""
        with self._lock:
            pages = sum(len(s.pages) for s in self._seqs.values()
                        if not s.pinned)
            free_block_pages = sum(
                len(b.free) for b in self._blocks.values()
                if len(b.free) == self.config.block_pages)
            return (pages + free_block_pages) * self.page_nbytes

    # -- gauges ------------------------------------------------------------

    def _gauges_locked(self) -> None:
        if not _tm.enabled():
            return
        live = sum(len(s.pages) for s in self._seqs.values())
        _tm.set_gauge("serve.kv.pages_live", live)
        _tm.set_gauge("serve.kv.pages_free", len(self._free))
        _tm.set_gauge("serve.kv.seqs", len(self._seqs))
        _tm.set_gauge("serve.kv.bytes", live * self.page_nbytes)

    # -- pool management ---------------------------------------------------

    def _grow_locked(self) -> bool:
        """Allocate one more page block if the pool bound and the HBM
        budget allow.  The DArray is created inside a ``serve.kv`` span
        so the ledger attributes its bytes to the cache owner."""
        c = self.config
        total = len(self._blocks) * c.block_pages
        if total + c.block_pages > c.max_pages:
            return False
        if c.hbm_budget_bytes is not None:
            block_bytes = c.block_pages * self.page_nbytes
            bound = c.hbm_evict_fraction * c.hbm_budget_bytes
            if _tm.memory.live_bytes() + block_bytes > bound:
                return False
        ranks = elastic.manager().live_ranks()
        n = max(1, min(len(ranks), c.block_pages))
        with _tm.span("serve.kv", op="alloc_block",
                      pages=c.block_pages):
            d = dzeros((c.block_pages, 2, c.page_tokens, c.heads,
                        c.head_dim), dtype=c.dtype,
                       procs=ranks[:n], dist=[n, 1, 1, 1, 1])
        bid = self._next_block
        self._next_block += 1
        self._blocks[bid] = _Block(d=d, free=set(range(c.block_pages)))
        self._free.extend((bid, s) for s in range(c.block_pages))
        _tm.count("serve.kv.blocks_created")
        return True

    def _reap_locked(self) -> None:
        """Close fully-free blocks so the ledger drains with usage."""
        for bid in [b for b, blk in self._blocks.items()
                    if len(blk.free) == self.config.block_pages]:
            blk = self._blocks.pop(bid)
            self._free = [(b, s) for (b, s) in self._free if b != bid]
            blk.d.close()
            _tm.count("serve.kv.blocks_reaped")

    def _budget_pressure_locked(self) -> bool:
        c = self.config
        if c.hbm_budget_bytes is None:
            return False
        return _tm.memory.live_bytes() >= \
            c.hbm_evict_fraction * c.hbm_budget_bytes

    def _evict_lru_locked(self) -> int | None:
        """Evict the least-recently-used unpinned sequence; returns its
        id (pages freed, record dropped) or None when nothing is
        evictable."""
        victims = [s for s in self._seqs.values()
                   if not s.pinned and s.pages]
        if not victims:
            return None
        v = min(victims, key=lambda s: s.last_use)
        for bid, slot in v.pages:
            blk = self._blocks.get(bid)
            if blk is not None:
                blk.free.add(slot)
                self._free.append((bid, slot))
        del self._seqs[v.seq_id]
        self.evictions += 1
        _tm.count("serve.kv.evictions", tenant=v.tenant)
        return v.seq_id

    def maybe_evict(self) -> list[int]:
        """Budget-driven eviction sweep: while the ledger sits over the
        eviction fraction of the budget, evict idle sequences LRU-first
        and reap freed blocks.  Returns the evicted sequence ids (the
        engine re-queues them for re-prefill)."""
        evicted: list[int] = []
        with self._lock:
            while self._budget_pressure_locked():
                sid = self._evict_lru_locked()
                if sid is None:
                    break
                evicted.append(sid)
                self._reap_locked()
            if evicted:
                self._gauges_locked()
        return evicted

    # -- sequence lifecycle ------------------------------------------------

    def ensure(self, seq_id: int, ntok: int, *,
               tenant: str = "default") -> list[int]:
        """Grow ``seq_id``'s page table to cover ``ntok`` rows,
        allocating (and evicting idle sequences, LRU-first) as needed.
        Returns the ids of sequences evicted to make room.  Raises
        :class:`Overloaded` (``reason="kv"``) when the demand cannot be
        covered even after evicting every idle sequence."""
        if self.pages_for(ntok) > self.config.max_pages:
            # permanent: no amount of eviction covers this — reject
            # before evicting innocents
            raise Rejected(
                f"sequence needs {self.pages_for(ntok)} pages; the pool "
                f"holds {self.config.max_pages} at its hard bound",
                reason="kv", tenant=tenant)
        with self._lock:
            if self._closed:
                raise ServeError("kv cache is closed")
            seq = self._seqs.get(seq_id)
            if seq is None:
                seq = self._seqs[seq_id] = _Seq(seq_id=seq_id,
                                                tenant=tenant)
            seq.last_use = time.monotonic()
            need = self.pages_for(ntok) - len(seq.pages)
            if need <= 0:
                return []
            evicted: list[int] = []
            was_pinned = seq.pinned
            seq.pinned = True      # never LRU-evict the seq being grown
            try:
                while len(self._free) < need:
                    if self._grow_locked():
                        continue
                    sid = self._evict_lru_locked()
                    if sid is None:
                        _tm.count("serve.kv.sheds", tenant=tenant)
                        self._gauges_locked()
                        raise Overloaded(
                            f"kv cache exhausted: need {need} pages for "
                            f"seq {seq_id}, {len(self._free)} free of "
                            f"{self.config.max_pages} max; retry in "
                            f"{self.config.retry_after_s:.3f}s",
                            retry_after=self.config.retry_after_s,
                            reason="kv", tenant=tenant)
                    evicted.append(sid)
            finally:
                seq.pinned = was_pinned
            for _ in range(need):
                bid, slot = self._free.pop()
                self._blocks[bid].free.discard(slot)
                seq.pages.append((bid, slot))
            self._gauges_locked()
            return evicted

    def write(self, seq_id: int, start: int, k, v) -> None:
        """Write K/V rows for tokens ``[start, start + n)`` of
        ``seq_id`` (``k``/``v``: ``(n, heads, head_dim)``).  Pages must
        already be ensured; writes are incremental region mutations so
        only the owning device's chunk is touched per page."""
        k = np.asarray(k)
        v = np.asarray(v)
        n = k.shape[0]
        pt = self.config.page_tokens
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is None:
                raise ServeError(f"unknown kv sequence {seq_id}")
            if self.pages_for(start + n) > len(seq.pages):
                raise ServeError(
                    f"seq {seq_id}: write [{start}, {start + n}) exceeds "
                    f"{len(seq.pages)} ensured pages")
            off = 0
            while off < n:
                tok = start + off
                page, po = divmod(tok, pt)
                take = min(n - off, pt - po)
                bid, slot = seq.pages[page]
                d = self._blocks[bid].d
                d[slot, 0, po:po + take] = k[off:off + take]
                d[slot, 1, po:po + take] = v[off:off + take]
                off += take
            seq.ntok = max(seq.ntok, start + n)
            seq.last_use = time.monotonic()

    def read(self, seq_id: int):
        """Gather ``seq_id``'s resident K/V as ``(ntok, heads,
        head_dim)`` arrays (the decode step's contiguous view)."""
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is None:
                raise ServeError(f"unknown kv sequence {seq_id}")
            ks, vs = [], []
            for bid, slot in seq.pages:
                g = self._blocks[bid].d.garray
                ks.append(g[slot, 0])
                vs.append(g[slot, 1])
            seq.last_use = time.monotonic()
            ntok = seq.ntok
        k = jnp.concatenate(ks)[:ntok]
        v = jnp.concatenate(vs)[:ntok]
        return k, v

    def ntok(self, seq_id: int) -> int:
        with self._lock:
            seq = self._seqs.get(seq_id)
            return 0 if seq is None else seq.ntok

    def has(self, seq_id: int) -> bool:
        with self._lock:
            return seq_id in self._seqs

    def pin(self, seq_id: int) -> None:
        """Exclude ``seq_id`` from eviction (in-flight dispatch)."""
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is not None:
                seq.pinned = True

    def unpin(self, seq_id: int) -> None:
        with self._lock:
            seq = self._seqs.get(seq_id)
            if seq is not None:
                seq.pinned = False
                seq.last_use = time.monotonic()

    def release(self, seq_id: int) -> None:
        """Free ``seq_id``'s pages (completion or cancellation) and reap
        any block the release fully emptied — cancellation must return
        HBM immediately, not at the next sweep."""
        with self._lock:
            seq = self._seqs.pop(seq_id, None)
            if seq is None:
                return
            for bid, slot in seq.pages:
                blk = self._blocks.get(bid)
                if blk is not None:
                    blk.free.add(slot)
                    self._free.append((bid, slot))
            self._reap_locked()
            self._gauges_locked()

    def close(self) -> None:
        """Release every sequence and close every block DArray (drains
        the ledger to zero for this owner)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._seqs.clear()
            self._free.clear()
            for blk in self._blocks.values():
                blk.d.close()
            self._blocks.clear()
            self._gauges_locked()

    def __enter__(self) -> "PagedKVCache":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
