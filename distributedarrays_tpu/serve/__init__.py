"""Multi-tenant async serving layer over resident sharded model state.

Quick use::

    from distributedarrays_tpu import serve

    srv = serve.Server(serve.ServeConfig(max_batch=8, max_queue=64))
    srv.register("score", lambda xs: [score_one(x) for x in xs])
    fut = srv.submit("score", x, tenant="team-a", deadline_s=0.5)
    y = fut.result()          # result, or a typed ServeError
    srv.close()               # graceful: stop admitting, flush, stop

Architecture, admission/shedding policy knobs, deadline semantics, and a
worked overload walkthrough: docs/serving.md.
"""

from .admission import AdmissionController, LatencyWindow, TokenBucket
from .batching import BatchQueue, Request, payload_key
from .errors import (DeadlineExceeded, Draining, Overloaded, QuotaExceeded,
                     Rejected, RequestFailed, ServeError)
from .server import Endpoint, ServeConfig, Server, install_sigterm

__all__ = [
    "Server", "ServeConfig", "Endpoint", "install_sigterm",
    "AdmissionController", "LatencyWindow", "TokenBucket",
    "BatchQueue", "Request", "payload_key",
    "ServeError", "Rejected", "Overloaded", "QuotaExceeded", "Draining",
    "DeadlineExceeded", "RequestFailed",
]
