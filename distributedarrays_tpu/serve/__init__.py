"""Multi-tenant async serving layer over resident sharded model state.

Quick use::

    from distributedarrays_tpu import serve

    srv = serve.Server(serve.ServeConfig(max_batch=8, max_queue=64))
    srv.register("score", lambda xs: [score_one(x) for x in xs])
    fut = srv.submit("score", x, tenant="team-a", deadline_s=0.5)
    y = fut.result()          # result, or a typed ServeError
    srv.close()               # graceful: stop admitting, flush, stop

LLM decode (paged KV cache + continuous batching)::

    eng = serve.DecodeEngine()            # toy model; pass your own
    eng.attach(srv, "decode")             # server admission fronts it
    stream = srv.submit("decode", [1, 2, 3]).result()
    for tok in stream:                    # tokens as they land
        ...

Architecture, admission/shedding policy knobs, deadline semantics, and a
worked overload walkthrough: docs/serving.md.  Asyncio front-end:
``serve.aio``.
"""

from . import aio
from .admission import AdmissionController, LatencyWindow, TokenBucket
from .batching import BatchQueue, Request, payload_key
from .decode import (DecodeConfig, DecodeEngine, TinyLM, TokenStream,
                     WeightedFairQueue)
from .errors import (Cancelled, DeadlineExceeded, Draining, Overloaded,
                     QuotaExceeded, Rejected, RequestFailed, ServeError)
from .kvcache import KVCacheConfig, PagedKVCache
from .server import Endpoint, ServeConfig, Server, install_sigterm

__all__ = [
    "Server", "ServeConfig", "Endpoint", "install_sigterm",
    "AdmissionController", "LatencyWindow", "TokenBucket",
    "BatchQueue", "Request", "payload_key",
    "KVCacheConfig", "PagedKVCache",
    "DecodeConfig", "DecodeEngine", "TinyLM", "TokenStream",
    "WeightedFairQueue", "aio",
    "ServeError", "Rejected", "Overloaded", "QuotaExceeded", "Draining",
    "DeadlineExceeded", "Cancelled", "RequestFailed",
]
