"""Typed serving errors: every request resolves to a result or one of these.

The serving layer's core robustness contract is that nothing is ever
silent: an inadmissible request is *rejected at submit time* with a typed
exception the caller can act on (``retry_after`` for backoff, ``reason``
for dashboards), and an admitted request's future always resolves — a
correct result, a :class:`DeadlineExceeded`, or a :class:`RequestFailed`
wrapping the root cause after the recovery executor gave up.  A client
should never need to string-match error text to decide whether to retry.

Hierarchy::

    ServeError
    ├── Rejected            (refused at admission — never enqueued)
    │   ├── Overloaded      (backpressure: queue/HBM/latency; retry_after)
    │   │   └── QuotaExceeded  (per-tenant token bucket; retry_after)
    │   └── Draining        (server is shutting down; do not retry here)
    ├── DeadlineExceeded    (budget expired at enqueue/batch/dispatch)
    ├── Cancelled           (client cancelled a streaming sequence;
    │                        its pages were freed immediately)
    └── RequestFailed       (dispatch failed after recovery gave up;
                             __cause__ carries the root failure)
"""

from __future__ import annotations

__all__ = ["ServeError", "Rejected", "Overloaded", "QuotaExceeded",
           "Draining", "DeadlineExceeded", "Cancelled", "RequestFailed"]


class ServeError(RuntimeError):
    """Base class for every typed serving-layer error."""


class Rejected(ServeError):
    """Refused at admission — the request was never enqueued.

    ``reason`` is a stable machine-readable slug (``"queue"``, ``"hbm"``,
    ``"latency"``, ``"quota"``, ``"draining"``); ``tenant`` the submitting
    tenant."""

    def __init__(self, message: str, *, reason: str, tenant: str = ""):
        super().__init__(message)
        self.reason = reason
        self.tenant = tenant


class Overloaded(Rejected):
    """Backpressure rejection: the server is shedding load instead of
    growing its queue or HBM footprint without bound.

    ``retry_after`` (seconds) is the server's drain-rate estimate of when
    capacity returns — clients should back off at least that long."""

    def __init__(self, message: str, *, retry_after: float,
                 reason: str = "overloaded", tenant: str = ""):
        super().__init__(message, reason=reason, tenant=tenant)
        self.retry_after = float(retry_after)


class QuotaExceeded(Overloaded):
    """The tenant's token bucket is empty; ``retry_after`` is the refill
    time for one token.  A subclass of :class:`Overloaded` so generic
    back-off handling catches both."""

    def __init__(self, message: str, *, retry_after: float,
                 tenant: str = ""):
        super().__init__(message, retry_after=retry_after, reason="quota",
                         tenant=tenant)


class Draining(Rejected):
    """The server is draining (shutdown/SIGTERM): admission is closed,
    in-flight and queued work still completes.  Retrying against this
    instance is pointless — failover elsewhere."""

    def __init__(self, message: str = "server is draining; "
                 "admission closed", *, tenant: str = ""):
        super().__init__(message, reason="draining", tenant=tenant)


class DeadlineExceeded(ServeError):
    """The request's deadline budget expired — at enqueue (already dead on
    arrival), at batch formation, or at dispatch.  Expired work is never
    dispatched; ``stage`` says which gate tripped."""

    def __init__(self, message: str, *, stage: str = "enqueue"):
        super().__init__(message)
        self.stage = stage


class Cancelled(ServeError):
    """The client cancelled a streaming sequence (``TokenStream.cancel``).
    Cancellation is immediate on the resource side — the sequence's KV
    pages return to the pool before this surfaces to any waiter."""


class RequestFailed(ServeError):
    """Dispatch failed and the recovery executor gave up (or was
    interrupted by drain).  ``__cause__`` carries the root failure —
    classification, retries, shrink/restore already happened per the
    resilience decision table before this surfaced."""
