"""Continuous batching: coalesce compatible requests into one dispatch.

The unit of device work is a *batch*: the oldest queued request picks the
batch's compatibility key (endpoint + payload signature), and the batcher
collects up to ``max_batch`` same-key requests, waiting at most
``flush_s`` past the head request's arrival for stragglers — flush on
batch-full OR deadline, whichever first.  Requests with other keys stay
queued in arrival order for the next batch, so one hot shape cannot
starve another endpoint forever (each pass re-starts from the current
head).

Deadline discipline: a request whose budget expires while queued is
resolved with :class:`~.errors.DeadlineExceeded` *at batch formation* and
never dispatched; the server applies the same check once more immediately
before dispatch.  Futures are resolved exactly once — late outcomes
(e.g. a batch result arriving after the request was expired) are dropped.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any

import numpy as np

from .. import telemetry as _tm
from .errors import DeadlineExceeded

__all__ = ["Request", "payload_key", "BatchQueue"]


def payload_key(payload: Any) -> Any:
    """Default batch-compatibility signature of a payload: arrays by
    (shape, dtype); tuples/lists elementwise; everything else by type.
    Two requests coalesce only when their keys match — stacking
    mixed-shape payloads into one device program would retrace per
    batch instead of reusing one compilation."""
    if hasattr(payload, "shape") and hasattr(payload, "dtype"):
        if str(payload.dtype) == "object":
            # ragged container: (shape, dtype) says nothing about the
            # elements — two object arrays holding different-length
            # prompts must NOT coalesce (stacking them crashes at
            # dispatch); key elementwise like a sequence
            return ("array_obj", tuple(payload.shape),
                    tuple(payload_key(p) for p in np.asarray(payload).flat))
        return ("array", tuple(payload.shape), str(payload.dtype))
    if isinstance(payload, (tuple, list)):
        return ("seq", type(payload).__name__,
                tuple(payload_key(p) for p in payload))
    if isinstance(payload, dict):
        # sort by repr so mixed-type keys (a legal JSON-ish payload)
        # cannot raise an untyped TypeError out of submit()
        return ("map", tuple(sorted(((repr(k), payload_key(v))
                                     for k, v in payload.items()))))
    if isinstance(payload, (int, float, complex, bool, str, bytes,
                            np.generic)) or payload is None:
        return ("scalar", type(payload).__name__)
    return ("obj", type(payload).__name__)


@dataclasses.dataclass
class Request:
    """One admitted request: payload plus routing/budget metadata and the
    future the caller holds.  ``deadline``/``enqueued`` are monotonic
    seconds (``time.monotonic``).  ``trace_id`` is the request-scoped
    trace id minted at submit — every span from submit through batch
    dispatch, recovery retries, and rank steps carries it."""

    endpoint: str
    payload: Any
    tenant: str
    key: Any
    deadline: float
    enqueued: float
    trace_id: str = ""
    future: Future = dataclasses.field(default_factory=Future)

    def remaining(self, now: float | None = None) -> float:
        return self.deadline - (time.monotonic() if now is None else now)

    def resolve(self, value: Any) -> bool:
        """Resolve the future with a result; False if already resolved."""
        if self.future.set_running_or_notify_cancel():
            self.future.set_result(value)
            return True
        return False   # pragma: no cover — cancelled future

    def fail(self, exc: BaseException) -> bool:
        """Resolve the future with a typed error; False if already done."""
        if self.future.set_running_or_notify_cancel():
            self.future.set_exception(exc)
            return True
        return False   # pragma: no cover — cancelled future

    def expire(self, stage: str) -> None:
        """Resolve with DeadlineExceeded at gate ``stage`` and count it."""
        _tm.count("serve.expired", stage=stage)
        self.fail(DeadlineExceeded(
            f"request deadline expired at {stage} "
            f"(budget overrun {-self.remaining():.3f}s, "
            f"endpoint={self.endpoint}, tenant={self.tenant})",
            stage=stage))


class BatchQueue:
    """Bounded FIFO of admitted requests with key-coalescing batch
    extraction.  Thread-safe; multiple dispatch workers may call
    :meth:`next_batch` concurrently."""

    def __init__(self):
        self._q: list[Request] = []
        self._cond = threading.Condition()
        self._closed = False
        # batches handed out by next_batch but not yet task_done()'d:
        # counted under the SAME lock as the removal, so an emptiness
        # check can never observe "queue empty" while a claimed batch
        # has not yet reached its dispatcher (the drain TOCTOU)
        self._claimed = 0
        # set under the condition when the queue shrinks; the journaled
        # depth gauge (file I/O) is emitted only after the lock drops
        self._depth_dirty = False

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def idle(self) -> bool:
        """True iff nothing is queued AND nothing is claimed-in-flight —
        the drain/close emptiness predicate."""
        with self._cond:
            return not self._q and self._claimed == 0

    def task_done(self) -> None:
        """The dispatcher finished (or typed-failed) a claimed batch."""
        with self._cond:
            self._claimed -= 1
            self._cond.notify_all()

    def put(self, req: Request) -> None:
        with self._cond:
            if self._closed:
                raise RuntimeError("queue closed")   # server gates earlier
            self._q.append(req)
            depth = len(self._q)
            self._cond.notify_all()
        # journaled gauge OUTSIDE the condition (the queue-depth history
        # reconstructs as a Perfetto counter track): the journal write is
        # file I/O and must never serialize producers on the queue lock
        _tm.set_gauge("serve.queue_depth", depth, journal=True)

    def close(self) -> None:
        """Stop waits: next_batch drains what is queued, then returns
        None forever.  put() after close is a server bug, not a client
        error — the server rejects at admission first."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def wake(self) -> None:
        with self._cond:
            self._cond.notify_all()

    def _reap_expired_locked(self, now: float, dead: list) -> None:
        expired = [r for r in self._q if r.deadline <= now]
        if expired:
            self._q = [r for r in self._q if r.deadline > now]
            self._depth_dirty = True     # gauge emitted after unlock
            dead.extend(expired)

    def next_batch(self, limits, wait_s: float = 0.2) -> \
            list[Request] | None:
        """Form the next batch, blocking up to ``wait_s`` for work.

        ``limits(endpoint) -> (max_batch, flush_s)`` resolves the head
        request's per-endpoint bounds once its endpoint is known — the
        queue itself is endpoint-agnostic.  Returns None on a
        (momentarily) empty queue — the caller loops, checking its own
        stop condition — and None immediately once closed AND empty.
        Otherwise returns 1..max_batch same-key requests, all with
        unexpired deadlines, counted as claimed until the caller's
        :meth:`task_done`.
        """
        dead: list[Request] = []
        try:
            return self._form_batch(limits, wait_s, dead)
        finally:
            # futures resolve OUTSIDE the queue lock: Future callbacks
            # are user code and must not run with internal locks held —
            # and the journaled depth gauge (file I/O) flushes here for
            # the same reason
            if self._depth_dirty:
                with self._cond:
                    self._depth_dirty = False
                    depth = len(self._q)
                _tm.set_gauge("serve.queue_depth", depth, journal=True)
            for r in dead:
                r.expire("batch")

    def _form_batch(self, limits, wait_s: float,
                    dead: list) -> list[Request] | None:
        deadline = time.monotonic() + wait_s
        with self._cond:
            while True:
                self._reap_expired_locked(time.monotonic(), dead)
                if self._q:
                    break
                if self._closed:
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(min(remaining, 0.05))
            head = self._q[0]
            key = (head.endpoint, head.key)
            max_batch, flush_s = limits(head.endpoint)
            flush_at = head.enqueued + flush_s
            while True:
                now = time.monotonic()
                self._reap_expired_locked(now, dead)
                matching = [r for r in self._q
                            if (r.endpoint, r.key) == key]
                if not matching:
                    # every candidate expired while we waited: start over
                    return None
                if (len(matching) >= max_batch or now >= flush_at
                        or self._closed):
                    batch = matching[:max_batch]
                    taken = set(map(id, batch))
                    self._q = [r for r in self._q if id(r) not in taken]
                    self._claimed += 1     # atomic with the removal
                    self._depth_dirty = True
                    return batch
                self._cond.wait(min(flush_at - now, 0.05))
