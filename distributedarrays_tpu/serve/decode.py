"""Continuous-batching decode engine: prefill/decode disaggregated serving.

The production traffic shape ROADMAP item 2 names: autoregressive decode
over resident per-sequence state.  A :class:`DecodeEngine` owns a
:class:`~.kvcache.PagedKVCache` and runs a scheduler loop forming two
*disaggregated batch classes* per round:

- **prefill** — compute-bound: the whole prompt's attention in one shot,
  dispatched through the ring-attention prefill entry
  (``models.ring_attention.ring_attention_prefill``, RDMA when armed)
  with K/V written back into cache pages.  Stamped with
  ``perf.attention_cost`` (O(s²) flops over O(s) bytes) so the roofline
  doctor classifies it compute-bound.
- **decode** — HBM-bound: one token per sequence per round, a single
  query row attending the sequence's entire gathered page set.  Stamped
  with ``perf.decode_step_cost`` (~0.5 flop/byte) so the doctor shows
  the memory-bound regime next to prefill's compute-bound one.

Scheduling: a per-round **token budget** is spent on the decode batch
first (latency: admitted sequences keep streaming), then on prefills
picked by **strict priority classes** and, within a class,
**weighted-fair queuing** between tenants (start-time fair queuing on
virtual finish tags — a saturated pair of tenants with weights 1 and 3
sees ~1:3 prefill service).  Results stream through
:class:`TokenStream` futures; cancellation frees the sequence's pages
immediately.

Resilience: every dispatch runs under ``recovery.run_with_recovery``
with the elastic device manager — an injected device loss mid-decode
probes, shrinks (re-laying the registered cache pages onto survivors),
and retries the step; sequences evicted under HBM pressure re-enter the
prefill class and rebuild their pages **bit-identically** (the toy
model's K/V rows are pure per-token functions — and for real models the
same holds given the token history).  A minority-partition verdict
drains the engine typed, matching the server's behavior.

``attach()`` registers the engine as a :class:`~.server.Server`
endpoint (payload = prompt or ``{"prompt": ..., "tenant": ...,
"priority": ..., "max_new_tokens": ..., "deadline_s": ...}``), wiring
the cache's ``idle_evictable_bytes`` into the server's admission
controller so HBM sheds ship an eviction-aware ``retry_after``.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
import threading
import time
from typing import Any, Callable

import numpy as np

from .. import telemetry as _tm
from ..resilience import elastic, faults as _fl, recovery
from ..telemetry import perf as _perf
from .errors import (Cancelled, DeadlineExceeded, Draining, Overloaded,
                     Rejected, RequestFailed, ServeError)
from .kvcache import KVCacheConfig, PagedKVCache
from .server import _SLO_BUCKETS

__all__ = ["DecodeConfig", "DecodeEngine", "TokenStream", "TinyLM",
           "WeightedFairQueue"]


# ---------------------------------------------------------------------------
# toy model
# ---------------------------------------------------------------------------


class TinyLM:
    """Deterministic single-layer toy decode model for tests and benches.

    The K/V projections are *elementwise* over per-token rows
    (embedding + positional table, scaled per channel), so a sequence's
    K/V rows are a pure function of ``(token, position)`` — an evicted
    sequence's re-prefilled cache is bit-identical to the original
    incremental writes, which is what lets the acceptance soak demand
    bit-equality between an evicted run and an unevicted oracle.  The
    attention itself is real (stable softmax over the full context), so
    the cache contents actually matter."""

    def __init__(self, vocab: int = 64, heads: int = 4, head_dim: int = 8,
                 max_pos: int = 4096, seed: int = 0):
        rng = np.random.default_rng(seed)
        e = heads * head_dim
        self.vocab = int(vocab)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.max_pos = int(max_pos)
        # scales picked so argmax decoding actually wanders the vocab
        # (a flat toy model emits one token forever, which would let a
        # broken cache pass the bit-identity oracle tests)
        self.emb = rng.standard_normal((vocab, e)).astype(np.float32)
        self.pos = (rng.standard_normal((max_pos, e)) * 2.0).astype(
            np.float32)
        self.wq = (0.5 + rng.random(e)).astype(np.float32)
        self.wk = (0.5 + rng.random(e)).astype(np.float32)
        self.wv = (0.5 + rng.random(e)).astype(np.float32)

    def qkv(self, tokens, pos0: int):
        """Per-token q/k/v rows ``(n, heads, head_dim)`` for ``tokens``
        occupying positions ``pos0..pos0+n``.  Row ``i`` depends only on
        ``(tokens[i], pos0 + i)`` — batch size never changes a row."""
        idx = np.asarray(tokens, np.int64)
        if pos0 + len(idx) > self.max_pos:
            raise ServeError(f"sequence length {pos0 + len(idx)} exceeds "
                             f"the model's max_pos {self.max_pos}")
        x = self.emb[idx % self.vocab] + self.pos[pos0:pos0 + len(idx)]
        shape = (-1, self.heads, self.head_dim)
        return ((x * self.wq).reshape(shape),
                (x * self.wk).reshape(shape),
                (x * self.wv).reshape(shape))

    def logits(self, out) -> np.ndarray:
        """Vocabulary logits for one attention output row ``(heads,
        head_dim)`` (a fixed-shape GEMV — deterministic)."""
        return self.emb @ np.asarray(out, np.float32).reshape(-1)


def _decode_attention(q, K, V) -> np.ndarray:
    """One decode step: ``(h, d)`` query row against the full resident
    context ``(ctx, h, d)`` — numerically stable softmax in f32.  The
    query is the sequence's *last* token, so it attends every cached row
    including its own (causal needs no mask at the frontier)."""
    q = np.asarray(q, np.float32)
    K = np.asarray(K, np.float32)
    V = np.asarray(V, np.float32)
    s = np.einsum("hd,khd->hk", q / np.sqrt(q.shape[-1]), K)
    s = s - s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p = p / p.sum(axis=-1, keepdims=True)
    return np.einsum("hk,khd->hd", p, V)


# ---------------------------------------------------------------------------
# streaming futures
# ---------------------------------------------------------------------------


class TokenStream:
    """Streaming handle for one decode sequence.

    Iterate for tokens as they land, ``result()`` for the full list,
    ``cancel()`` to abandon — cancellation frees the sequence's KV pages
    *immediately* and resolves the stream with
    :class:`~.errors.Cancelled`.  ``add_listener(fn)`` subscribes an
    ``fn(kind, value)`` callback (``("token", t)`` per token, one final
    ``("done", error_or_None)``), replaying history first — the asyncio
    adapter's bridge."""

    def __init__(self, seq_id: int, tenant: str, prompt_len: int,
                 cancel_fn: Callable[[int], bool]):
        self.seq_id = int(seq_id)
        self.tenant = tenant
        self.prompt_len = int(prompt_len)
        self._cancel_fn = cancel_fn
        self._cv = threading.Condition()
        self._tokens: list[int] = []
        self._done = False
        self._error: BaseException | None = None
        self._listeners: list[Callable[[str, Any], None]] = []

    # engine side -----------------------------------------------------------

    def _push(self, tok: int) -> None:
        with self._cv:
            if self._done:
                return
            self._tokens.append(int(tok))
            self._cv.notify_all()
            for fn in self._listeners:
                fn("token", int(tok))

    def _finish(self, error: BaseException | None = None) -> None:
        with self._cv:
            if self._done:
                return
            self._done = True
            self._error = error
            self._cv.notify_all()
            for fn in self._listeners:
                fn("done", error)
            self._listeners.clear()

    # client side -----------------------------------------------------------

    def add_listener(self, fn: Callable[[str, Any], None]) -> None:
        with self._cv:
            for t in self._tokens:
                fn("token", t)
            if self._done:
                fn("done", self._error)
            else:
                self._listeners.append(fn)

    def cancel(self) -> bool:
        """Abandon the sequence; pages free before this returns."""
        return self._cancel_fn(self.seq_id)

    def done(self) -> bool:
        with self._cv:
            return self._done

    def error(self) -> BaseException | None:
        with self._cv:
            return self._error

    @property
    def tokens(self) -> list[int]:
        with self._cv:
            return list(self._tokens)

    def __iter__(self):
        i = 0
        while True:
            with self._cv:
                while i >= len(self._tokens) and not self._done:
                    self._cv.wait(0.05)
                if i < len(self._tokens):
                    t = self._tokens[i]
                    i += 1
                else:
                    if self._error is not None:
                        raise self._error
                    return
            yield t

    def result(self, timeout: float | None = None) -> list[int]:
        """Block for completion; the generated tokens, or the typed
        error the sequence ended with."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while not self._done:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    raise TimeoutError(
                        f"sequence {self.seq_id} still running after "
                        f"{timeout:g}s")
                self._cv.wait(0.05 if left is None else min(left, 0.05))
            if self._error is not None:
                raise self._error
            return list(self._tokens)


# ---------------------------------------------------------------------------
# weighted-fair queuing
# ---------------------------------------------------------------------------


class WeightedFairQueue:
    """Strict priority classes; start-time fair queuing within a class.

    ``push`` assigns a virtual finish tag ``max(vtime, tenant_last) +
    cost / weight``; ``pop`` serves the (priority, finish-tag) minimum
    and advances virtual time.  Under saturation each tenant's served
    cost converges to its weight share — the classic SCFQ bound.  Not
    thread-safe (the engine calls it under its own lock)."""

    def __init__(self):
        self._vtime = 0.0
        self._tenant_vf: dict[str, float] = {}
        self._heap: list = []
        self._n = itertools.count()

    def push(self, item, *, tenant: str, cost: float,
             weight: float = 1.0, priority: int = 0) -> None:
        vf = max(self._vtime, self._tenant_vf.get(tenant, 0.0)) \
            + float(cost) / max(float(weight), 1e-9)
        self._tenant_vf[tenant] = vf
        heapq.heappush(self._heap, (int(priority), vf, next(self._n), item))

    def pop(self):
        prio, vf, _, item = heapq.heappop(self._heap)
        self._vtime = max(self._vtime, vf)
        return item

    def __len__(self) -> int:
        return len(self._heap)


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DecodeConfig:
    """Scheduler knobs.  ``token_budget`` is the per-round spend across
    both batch classes: the decode batch (1 token per ready sequence)
    takes what it needs first — admitted sequences keep streaming under
    load — and prefills consume the rest at prompt-length cost (the
    head-of-line prefill always runs, even oversized, so long prompts
    cannot starve)."""

    max_new_tokens: int = 16
    token_budget: int = 256
    max_decode_batch: int = 8
    max_prefill_seqs: int = 2
    max_sequences: int = 64            # admission bound on live sequences
    default_deadline_s: float = 30.0
    eos_token: int | None = None
    use_ring_prefill: bool = True
    min_ring_tokens: int | None = None
    poll_s: float = 0.02               # idle loop wait
    retry_after_s: float = 0.05
    drain_timeout_s: float = 30.0


@dataclasses.dataclass
class _Seq:
    seq_id: int
    tenant: str
    priority: int
    tokens: list[int]
    prompt_len: int
    max_new: int
    deadline: float
    stream: TokenStream
    enqueued: float
    state: str = "prefill"       # prefill | active | done/failed/cancelled
    inflight: bool = False
    generated: int = 0
    re_prefill: bool = False     # evicted at least once: rebuild-only
    last_step: float = 0.0
    first_token_at: float | None = None


class DecodeEngine:
    """Continuous-batching decode over a paged KV cache.  See the module
    docstring for the scheduling and resilience contracts."""

    def __init__(self, model: TinyLM | None = None,
                 cache: PagedKVCache | None = None,
                 config: DecodeConfig | None = None, *,
                 policy: recovery.RetryPolicy | None = None,
                 devices=None, name: str = "decode"):
        self.model = model or TinyLM()
        if cache is None:
            cache = PagedKVCache(KVCacheConfig(
                heads=self.model.heads, head_dim=self.model.head_dim))
        self.cache = cache
        self.config = config or DecodeConfig()
        self.name = name
        self._policy = policy
        self._devices = devices if devices is not None else elastic.manager()
        self._lock = threading.RLock()
        self._seqs: dict[int, _Seq] = {}
        self._prefill = WeightedFairQueue()
        self._weights: dict[str, float] = {}
        self._service: dict[str, float] = {}   # per-tenant tokens served
        self._ids = itertools.count(1)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._draining = False
        self._closed = False

    # -- admission -----------------------------------------------------------

    def set_weight(self, tenant: str, weight: float) -> None:
        """WFQ weight for ``tenant`` (default 1.0; higher = more prefill
        service under contention)."""
        with self._lock:
            self._weights[tenant] = float(weight)

    def submit(self, prompt, *, tenant: str = "default", priority: int = 0,
               deadline_s: float | None = None,
               max_new_tokens: int | None = None) -> TokenStream:
        """Admit one sequence; returns its :class:`TokenStream` or
        raises a typed rejection (:class:`Draining`,
        :class:`Overloaded` with ``retry_after``, :class:`Rejected` for
        prompts the pool can never hold)."""
        toks = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not toks:
            raise ServeError("empty prompt")
        _tm.count("serve.decode.submitted", tenant=tenant)
        max_new = int(self.config.max_new_tokens if max_new_tokens is None
                      else max_new_tokens)
        budget = (self.config.default_deadline_s if deadline_s is None
                  else float(deadline_s))
        with self._lock:
            if self._draining or self._closed:
                _tm.count("serve.shed", reason="draining", tenant=tenant)
                raise Draining(tenant=tenant)
            if self.cache.pages_for(len(toks) + max_new) > \
                    self.cache.capacity_pages():
                _tm.count("serve.shed", reason="kv", tenant=tenant)
                raise Rejected(
                    f"prompt of {len(toks)} tokens (+{max_new} new) "
                    f"exceeds the cache's {self.cache.capacity_pages()} "
                    "page capacity", reason="kv", tenant=tenant)
            if len(self._seqs) >= self.config.max_sequences:
                ra = self.config.retry_after_s
                _tm.count("serve.shed", reason="queue", tenant=tenant)
                raise Overloaded(
                    f"{len(self._seqs)} live sequences at bound "
                    f"{self.config.max_sequences}; retry in {ra:.3f}s",
                    retry_after=ra, reason="queue", tenant=tenant)
            sid = next(self._ids)
            now = time.monotonic()
            stream = TokenStream(sid, tenant, len(toks), self.cancel)
            seq = _Seq(seq_id=sid, tenant=tenant, priority=int(priority),
                       tokens=toks, prompt_len=len(toks), max_new=max_new,
                       deadline=now + budget, stream=stream, enqueued=now)
            self._seqs[sid] = seq
            self._prefill.push(sid, tenant=tenant, cost=float(len(toks)),
                               weight=self._weights.get(tenant, 1.0),
                               priority=int(priority))
            self._ensure_loop()
        self._wake.set()
        return stream

    def cancel(self, seq_id: int) -> bool:
        """Abandon a sequence: pages return to the pool before this
        returns; the stream resolves :class:`~.errors.Cancelled`."""
        with self._lock:
            seq = self._seqs.pop(seq_id, None)
            if seq is None:
                return False
            seq.state = "cancelled"
            self.cache.release(seq_id)
            _tm.count("serve.decode.cancelled", tenant=seq.tenant)
        seq.stream._finish(Cancelled(f"sequence {seq_id} cancelled"))
        return True

    # -- server integration --------------------------------------------------

    def attach(self, server, name: str | None = None, *,
               max_batch: int | None = None, flush_s: float | None = None):
        """Register this engine as a batched :class:`~.server.Server`
        endpoint and wire the cache's reclaimable-bytes signal into the
        server's admission controller (HBM sheds then ship an
        eviction-aware ``retry_after``).  The endpoint resolves each
        payload to its :class:`TokenStream` — admission is the server's
        job; token generation streams through the engine loop."""
        name = name or self.name

        def _fn(payloads: list) -> list:
            return [self._submit_payload(p) for p in payloads]

        ep = server.register(name, _fn, max_batch=max_batch,
                             flush_s=flush_s,
                             key_fn=lambda _p: ("decode", name))
        server.set_reclaimable(self.cache.idle_evictable_bytes)
        return ep

    def _submit_payload(self, p) -> TokenStream:
        if isinstance(p, dict):
            return self.submit(
                p["prompt"], tenant=p.get("tenant", "default"),
                priority=p.get("priority", 0),
                deadline_s=p.get("deadline_s"),
                max_new_tokens=p.get("max_new_tokens"))
        return self.submit(p)

    # -- scheduler loop ------------------------------------------------------

    def _ensure_loop(self) -> None:
        with self._lock:
            if self._thread is not None or self._closed:
                return
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"serve-decode-{self.name}")
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                did = self._round()
            except Exception:  # noqa: BLE001 — the loop must not die silent
                _tm.count("serve.decode.loop_errors")
                did = False
            if not did:
                with self._lock:
                    if (self._draining or self._closed) \
                            and not self._seqs:
                        return
                self._wake.wait(self.config.poll_s)
                self._wake.clear()

    def _round(self) -> bool:
        """One scheduling round: deadline sweep, budget eviction sweep,
        decode batch, then prefill picks under the remaining token
        budget.  Returns whether any work was dispatched."""
        finished: list[tuple[TokenStream, BaseException | None]] = []
        with self._lock:
            now = time.monotonic()
            for s in list(self._seqs.values()):
                if s.inflight or s.state not in ("prefill", "active"):
                    continue
                if now > s.deadline:
                    stage = "decode" if s.state == "active" else "prefill"
                    finished.append(self._finish_locked(
                        s, DeadlineExceeded(
                            f"sequence {s.seq_id} deadline expired after "
                            f"{s.generated} tokens", stage=stage)))
            for sid in self.cache.maybe_evict():
                self._on_evicted_locked(sid)
            budget = self.config.token_budget
            ready = [s for s in self._seqs.values()
                     if s.state == "active" and not s.inflight]
            ready.sort(key=lambda s: (s.priority, s.last_step))
            dec = ready[:max(0, min(self.config.max_decode_batch, budget))]
            for s in dec:
                s.inflight = True
                self.cache.pin(s.seq_id)
            budget -= len(dec)
            pre: list[_Seq] = []
            while len(self._prefill) and \
                    len(pre) < self.config.max_prefill_seqs:
                sid = self._prefill.pop()
                s = self._seqs.get(sid)
                if s is None or s.state != "prefill" or s.inflight:
                    continue
                cost = len(s.tokens)
                if pre and cost > budget:
                    # head-of-line (first pick) always runs; later picks
                    # respect the round budget — push back for next round
                    self._prefill.push(
                        sid, tenant=s.tenant, cost=float(cost),
                        weight=self._weights.get(s.tenant, 1.0),
                        priority=s.priority)
                    break
                s.inflight = True
                pre.append(s)
                budget -= cost
        for stream, err in finished:
            stream._finish(err)
        if dec:
            self._dispatch_decode(dec)
        if pre:
            self._dispatch_prefill(pre)
        return bool(dec or pre or finished)

    # -- bookkeeping (engine lock held) --------------------------------------

    def _finish_locked(self, s: _Seq, error: BaseException | None):
        """Terminal transition: release pages, drop the record; the
        caller fires the stream OUTSIDE the lock."""
        s.state = "failed" if error is not None else "done"
        self._seqs.pop(s.seq_id, None)
        self.cache.release(s.seq_id)
        if error is None:
            _tm.count("serve.decode.completed", tenant=s.tenant)
            _tm.observe("serve.decode.request_s",
                        time.monotonic() - s.enqueued, endpoint=self.name)
        else:
            _tm.count("serve.decode.failed", tenant=s.tenant,
                      kind=type(error).__name__)
        return (s.stream, error)

    def _on_evicted_locked(self, sid: int) -> None:
        """An eviction (budget sweep or allocation pressure) freed this
        sequence's pages: it re-enters the prefill class and rebuilds —
        bit-identically, since K/V are a function of the token history."""
        s = self._seqs.get(sid)
        if s is None or s.state not in ("prefill", "active"):
            return
        s.state = "prefill"
        s.re_prefill = True
        _tm.count("serve.decode.evicted", tenant=s.tenant)
        self._prefill.push(sid, tenant=s.tenant, cost=float(len(s.tokens)),
                           weight=self._weights.get(s.tenant, 1.0),
                           priority=s.priority)

    def _served_locked(self, tenant: str, cost: float) -> None:
        self._service[tenant] = self._service.get(tenant, 0.0) + cost

    # -- dispatch: decode (HBM-bound) ----------------------------------------

    def _dispatch_decode(self, batch: list[_Seq]) -> None:
        model = self.model
        ctx_total = sum(len(s.tokens) for s in batch)
        t0 = time.monotonic()
        try:
            with _tm.span("serve.decode", endpoint=self.name,
                          size=len(batch),
                          **_perf.decode_step_cost(
                              ctx_total, model.heads, model.head_dim,
                              4, new_tokens=len(batch))):
                def _run():
                    # chaos site: a fault plan can down a device
                    # mid-step; recovery probes, shrinks (re-laying the
                    # cache pages onto survivors) and re-invokes
                    _fl.check("serve.decode", size=len(batch))
                    outs = []
                    for s in batch:
                        try:
                            K, V = self.cache.read(s.seq_id)
                        except ServeError:
                            # cancelled mid-flight: its pages are gone
                            outs.append(None)
                            continue
                        qr, _, _ = model.qkv([s.tokens[-1]],
                                             len(s.tokens) - 1)
                        out = _decode_attention(qr[0], K, V)
                        outs.append(int(np.argmax(model.logits(out))))
                    return outs
                toks = recovery.run_with_recovery(
                    _run, policy=self._policy, devices=self._devices,
                    stop_event=self._stop)
        except recovery.MinorityPartitionExit as e:
            self._partition_drain(batch, e)
            return
        except Exception as e:  # noqa: BLE001 — typed onto the streams
            self._fail_batch(batch, e)
            return
        self._apply_decode(batch, toks, time.monotonic() - t0)

    def _apply_decode(self, batch: list[_Seq], toks: list,
                      dt: float) -> None:
        finished = []
        pushes: list[tuple[TokenStream, int]] = []
        with self._lock:
            for s, t in zip(batch, toks):
                s.inflight = False
                s.last_step = time.monotonic()
                self.cache.unpin(s.seq_id)
                if s.state != "active" or t is None:
                    continue
                pos = len(s.tokens)
                s.tokens.append(int(t))
                s.generated += 1
                self._served_locked(s.tenant, 1.0)
                pushes.append((s.stream, int(t)))
                _tm.count("serve.decode.tokens", tenant=s.tenant)
                if _tm.enabled():
                    _tm.observe("serve.decode.token_s", dt,
                                endpoint=self.name)
                    _tm.observe("serve.slo.request_s", dt,
                                buckets=_SLO_BUCKETS,
                                endpoint=f"{self.name}.decode")
                done = (s.generated >= s.max_new
                        or (self.config.eos_token is not None
                            and int(t) == self.config.eos_token))
                if done:
                    finished.append(self._finish_locked(s, None))
                    continue
                _, kr, vr = self.model.qkv([int(t)], pos)
                try:
                    for sid in self.cache.ensure(s.seq_id, pos + 1,
                                                 tenant=s.tenant):
                        self._on_evicted_locked(sid)
                    self.cache.write(s.seq_id, pos, kr, vr)
                except Overloaded:
                    # the pool cannot hold even this sequence's next
                    # page: it joins the evicted set and rebuilds when
                    # pressure clears (the emitted token stands)
                    self.cache.release(s.seq_id)
                    self._on_evicted_locked(s.seq_id)
        for stream, t in pushes:
            stream._push(t)
        for stream, err in finished:
            stream._finish(err)
        self._wake.set()

    # -- dispatch: prefill (compute-bound) -----------------------------------

    def _dispatch_prefill(self, batch: list[_Seq]) -> None:
        for s in batch:
            self._prefill_one(s)
        self._wake.set()

    def _prefill_one(self, s: _Seq) -> None:
        model = self.model
        ntok = len(s.tokens)
        # capacity first, OUTSIDE the recovery closure: a typed
        # Overloaded is backpressure, not a transient to retry
        try:
            with self._lock:
                for sid in self.cache.ensure(s.seq_id, ntok + 1,
                                             tenant=s.tenant):
                    self._on_evicted_locked(sid)
                self.cache.pin(s.seq_id)
        except Overloaded:
            # every page is pinned by in-flight work: stay queued; the
            # next round's eviction/completions free room
            with self._lock:
                s.inflight = False
                if s.state == "prefill":
                    _tm.count("serve.decode.kv_wait", tenant=s.tenant)
                    self._prefill.push(
                        s.seq_id, tenant=s.tenant, cost=float(ntok),
                        weight=self._weights.get(s.tenant, 1.0),
                        priority=s.priority)
            return
        except Rejected as e:
            with self._lock:
                finished = self._finish_locked(s, e)
            finished[0]._finish(finished[1])
            return
        rebuild = s.re_prefill
        t0 = time.monotonic()
        try:
            with _tm.span("serve.prefill", endpoint=self.name, ntok=ntok,
                          rebuild=rebuild,
                          **_perf.attention_cost(
                              ntok, model.heads, model.head_dim, 4,
                              causal=True)):
                def _run():
                    # chaos site: device loss mid-prefill probes,
                    # shrinks, and re-invokes this closure
                    _fl.check("serve.prefill", ntok=ntok)
                    qr, kr, vr = model.qkv(s.tokens, 0)
                    first = None
                    if not rebuild:
                        if self.config.use_ring_prefill:
                            from ..models.ring_attention import \
                                ring_attention_prefill
                            out = ring_attention_prefill(
                                qr, kr, vr, causal=True,
                                procs=self._devices.live_ranks(),
                                min_ring_tokens=self.config
                                .min_ring_tokens)
                        else:
                            from ..models.ring_attention import \
                                reference_attention
                            out = reference_attention(qr, kr, vr, True)
                        first = int(np.argmax(model.logits(out[-1])))
                    return kr, vr, first
                kr, vr, first = recovery.run_with_recovery(
                    _run, policy=self._policy, devices=self._devices,
                    stop_event=self._stop)
        except recovery.MinorityPartitionExit as e:
            self._partition_drain([s], e)
            return
        except Exception as e:  # noqa: BLE001 — typed onto the stream
            self._fail_batch([s], e)
            return
        dt = time.monotonic() - t0
        finished = []
        push = None
        with self._lock:
            s.inflight = False
            s.last_step = time.monotonic()
            self.cache.unpin(s.seq_id)
            if s.state != "prefill":
                return
            try:
                # the K/V write-back: all rows the closure computed
                # (prompt on a fresh prefill; prompt + generated on a
                # rebuild — bit-identical to the incremental original)
                self.cache.write(s.seq_id, 0, kr, vr)
                if first is not None:
                    pos = len(s.tokens)
                    s.tokens.append(first)
                    s.generated += 1
                    _, k1, v1 = model.qkv([first], pos)
                    self.cache.write(s.seq_id, pos, k1, v1)
                    push = (s.stream, first)
                    s.first_token_at = time.monotonic()
                    self._served_locked(s.tenant, float(ntok) + 1.0)
                    _tm.count("serve.decode.tokens", tenant=s.tenant)
                    if _tm.enabled():
                        ttft = s.first_token_at - s.enqueued
                        _tm.observe("serve.decode.ttft_s", ttft,
                                    endpoint=self.name)
                        _tm.observe("serve.slo.request_s", dt,
                                    buckets=_SLO_BUCKETS,
                                    endpoint=f"{self.name}.prefill")
                else:
                    self._served_locked(s.tenant, float(ntok))
                s.state = "active"
                if s.generated >= s.max_new or \
                        (self.config.eos_token is not None and s.tokens
                         and s.tokens[-1] == self.config.eos_token
                         and s.generated > 0):
                    finished.append(self._finish_locked(s, None))
            except ServeError as e:
                finished.append(self._finish_locked(s, e))
        if push is not None:
            push[0]._push(push[1])
        for stream, err in finished:
            stream._finish(err)

    # -- failure paths -------------------------------------------------------

    def _fail_batch(self, batch: list[_Seq], exc: Exception) -> None:
        finished = []
        with self._lock:
            for s in batch:
                s.inflight = False
                self.cache.unpin(s.seq_id)
                if s.state not in ("prefill", "active"):
                    continue
                err = exc if isinstance(exc, ServeError) else RequestFailed(
                    f"decode dispatch failed after recovery gave up "
                    f"(seq={s.seq_id}): {type(exc).__name__}: {exc}")
                if err is not exc:
                    err.__cause__ = exc
                finished.append(self._finish_locked(s, err))
        for stream, err in finished:
            stream._finish(err)

    def _partition_drain(self, batch: list[_Seq],
                         e: recovery.MinorityPartitionExit) -> None:
        """Minority side of a partition: drain typed (the PR 13
        contract — clients failover, they don't wait out a timeout)."""
        with self._lock:
            self._draining = True
        _tm.count("serve.partition_drains")
        if _tm.enabled():
            extra = {"incident": e.incident} if e.incident else {}
            _tm.event("serve", "partition_drain", side=e.side, lost=e.lost,
                      endpoint=self.name, **extra)
        finished = []
        with self._lock:
            for s in list(self._seqs.values()):
                err = Draining("decode engine lost partition quorum; "
                               "draining")
                err.__cause__ = e
                finished.append(self._finish_locked(s, err))
        for stream, err in finished:
            stream._finish(err)
        self._wake.set()

    # -- lifecycle -----------------------------------------------------------

    def drain(self, timeout: float | None = None) -> bool:
        """Stop admitting; let live sequences finish.  True when the
        engine emptied within ``timeout``."""
        with self._lock:
            self._draining = True
        self._wake.set()
        deadline = time.monotonic() + (self.config.drain_timeout_s
                                       if timeout is None else timeout)
        while time.monotonic() < deadline:
            with self._lock:
                if not self._seqs:
                    return True
            time.sleep(0.01)
        with self._lock:
            return not self._seqs

    def close(self, *, drain: bool = True,
              timeout: float | None = None) -> None:
        """Shut down: optionally drain, stop the loop, fail whatever is
        left typed (:class:`Draining`), release the cache."""
        if drain:
            self.drain(timeout)
        with self._lock:
            self._closed = True
            self._draining = True
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(2.0)
        finished = []
        with self._lock:
            for s in list(self._seqs.values()):
                finished.append(self._finish_locked(
                    s, Draining("decode engine closed before this "
                                "sequence completed")))
        for stream, err in finished:
            stream._finish(err)
        self.cache.close()

    def __enter__(self) -> "DecodeEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- introspection -------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            states: dict[str, int] = {}
            for s in self._seqs.values():
                states[s.state] = states.get(s.state, 0) + 1
            return {
                "sequences": len(self._seqs),
                "states": states,
                "prefill_queued": len(self._prefill),
                "service_by_tenant": dict(self._service),
                "cache": self.cache.stats(),
                "draining": self._draining,
                "closed": self._closed,
            }
