"""Admission control and backpressure signals for the serving layer.

Three gates decide whether a request is admitted.  The server-capacity
gates run first; the per-tenant token bucket runs LAST because it
consumes a token on success — a request the server's own capacity
refuses must not drain the tenant's allowance:

1. **Bounded queue depth** — the queue never grows past
   ``max_queue``; excess requests get :class:`~.errors.Overloaded` with a
   ``retry_after`` derived from the measured drain rate.
2. **Live backpressure signals** — the PR 2/5 instruments paying rent:
   the HBM ledger's live-byte gauge against a configured budget, and the
   rolling p99 of dispatch latency (:class:`LatencyWindow` over the
   ``serve.dispatch`` span durations).  Either signal over threshold
   sheds with a typed ``Overloaded`` instead of letting the queue (and
   HBM) grow unboundedly.
3. **Per-tenant token bucket** (:class:`TokenBucket`) — sustained
   request-rate quotas with a burst allowance.  An empty bucket rejects
   with :class:`~.errors.QuotaExceeded` carrying the exact refill time.

Every rejection is counted (``serve.shed{reason=}``) so the Prometheus
export shows shed rate next to queue depth and admitted throughput.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from .. import telemetry as _tm
from .errors import Overloaded, QuotaExceeded

__all__ = ["TokenBucket", "LatencyWindow", "AdmissionController"]


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/second refill, ``burst``
    capacity.  ``try_take`` returns 0.0 on success, else the seconds
    until one token is available (the ``retry_after`` the caller ships)."""

    def __init__(self, rate: float, burst: float):
        if rate <= 0 or burst <= 0:
            raise ValueError(f"rate ({rate}) and burst ({burst}) must be "
                             "positive")
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._stamp = time.monotonic()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> float:
        now = time.monotonic()
        with self._lock:
            self._tokens = min(self.burst,
                               self._tokens + (now - self._stamp) * self.rate)
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate

    def level(self) -> float:
        """Tokens remaining as of the last refill (no refill applied —
        an observability read, not an admission decision)."""
        with self._lock:
            return self._tokens


class LatencyWindow:
    """Rolling window of recent latencies with percentile queries — the
    *rolling* complement of ``telemetry.span_stats`` (which aggregates
    since process start and can never recover from a slow past).  Feeds
    both the p99 shed signal and the drain-rate ``retry_after`` estimate."""

    def __init__(self, maxlen: int = 256):
        self._samples: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(float(seconds))

    def count(self) -> int:
        with self._lock:
            return len(self._samples)

    def mean(self) -> float:
        with self._lock:
            return (sum(self._samples) / len(self._samples)
                    if self._samples else 0.0)

    def percentile(self, q: float) -> float:
        """q in [0, 100]; 0.0 with no samples."""
        with self._lock:
            if not self._samples:
                return 0.0
            s = sorted(self._samples)
        idx = min(len(s) - 1, max(0, int(round((q / 100.0) * (len(s) - 1)))))
        return s[idx]

    def p50(self) -> float:
        return self.percentile(50)

    def p99(self) -> float:
        return self.percentile(99)


class AdmissionController:
    """The submit-time gatekeeper.  Owns the per-tenant buckets and the
    rolling dispatch-latency window; the server calls :meth:`admit` with
    the current queue depth and either returns (admitted) or receives a
    typed rejection to raise."""

    def __init__(self, *, max_queue: int, tenant_rate: float,
                 tenant_burst: float, hbm_budget_bytes: int | None = None,
                 hbm_shed_fraction: float = 0.9,
                 p99_shed_s: float | None = None,
                 max_batch: int = 8, window: int = 256,
                 endpoint_windows: dict[str, int] | None = None,
                 reclaimable_fn=None,
                 min_retry_after: float = 0.05,
                 max_retry_after: float = 5.0):
        self.max_queue = int(max_queue)
        self.tenant_rate = float(tenant_rate)
        self.tenant_burst = float(tenant_burst)
        self.hbm_budget_bytes = hbm_budget_bytes
        self.hbm_shed_fraction = float(hbm_shed_fraction)
        self.p99_shed_s = p99_shed_s
        self.max_batch = int(max_batch)
        self.min_retry_after = float(min_retry_after)
        self.max_retry_after = float(max_retry_after)
        self.window = int(window)
        self.latency = LatencyWindow(maxlen=window)
        # bytes an HBM shed could reclaim right now (idle-evictable KV
        # pages); set by the owner of reclaimable state (decode engine)
        self.reclaimable_fn = reclaimable_fn
        self._endpoint_windows = dict(endpoint_windows or {})
        self._ep_latency: dict[str, LatencyWindow] = {}
        self._buckets: dict[str, TokenBucket] = {}
        self._bucket_overrides: dict[str, tuple[float, float]] = {}
        self._lock = threading.Lock()

    # -- quotas ------------------------------------------------------------

    def set_quota(self, tenant: str, rate: float, burst: float) -> None:
        """Per-tenant override of the default (rate, burst) quota."""
        with self._lock:
            self._bucket_overrides[tenant] = (float(rate), float(burst))
            self._buckets.pop(tenant, None)   # rebuilt with the new quota

    def _bucket(self, tenant: str) -> TokenBucket:
        with self._lock:
            b = self._buckets.get(tenant)
            if b is None:
                rate, burst = self._bucket_overrides.get(
                    tenant, (self.tenant_rate, self.tenant_burst))
                b = self._buckets[tenant] = TokenBucket(rate, burst)
            return b

    def token_level(self, tenant: str) -> float:
        """Remaining quota tokens for ``tenant`` — an observability
        read (no refill applied), for the server's per-tenant token
        gauge."""
        return self._bucket(tenant).level()

    # -- per-endpoint latency windows --------------------------------------

    def set_endpoint_window(self, endpoint: str, maxlen: int) -> None:
        """Per-endpoint p99 window size override (a cheap endpoint with
        high request rates wants a larger window than a heavy one —
        otherwise the percentile flaps on a handful of samples).  Drops
        any window already accumulated for ``endpoint``."""
        with self._lock:
            self._endpoint_windows[endpoint] = int(maxlen)
            self._ep_latency.pop(endpoint, None)

    def endpoint_latency(self, endpoint: str) -> LatencyWindow:
        """The rolling window for ``endpoint``, created lazily at its
        configured size (``endpoint_windows`` override, else the global
        ``window``)."""
        with self._lock:
            w = self._ep_latency.get(endpoint)
            if w is None:
                size = self._endpoint_windows.get(endpoint, self.window)
                w = self._ep_latency[endpoint] = LatencyWindow(maxlen=size)
            return w

    def record_latency(self, seconds: float,
                       endpoint: str | None = None) -> None:
        """Record one dispatch latency into the global window (the shed
        signal) and, when named, the endpoint's own window (the
        per-endpoint p99 gauge)."""
        self.latency.record(seconds)
        if endpoint is not None:
            self.endpoint_latency(endpoint).record(seconds)

    # -- retry_after estimation --------------------------------------------

    def _clamp(self, s: float) -> float:
        return min(self.max_retry_after, max(self.min_retry_after, s))

    def drain_estimate(self, queue_depth: int) -> float:
        """Seconds until the current backlog drains: depth over measured
        throughput (max_batch requests per mean batch latency).  With no
        latency samples yet, the clamp floor is the honest answer."""
        mean = self.latency.mean()
        if mean <= 0:
            return self.min_retry_after
        per_req = mean / max(1, self.max_batch)
        return self._clamp(queue_depth * per_req)

    # -- the gate ----------------------------------------------------------

    def admit(self, tenant: str, queue_depth: int) -> None:
        """Raise a typed rejection, or return on admission.  Order:
        queue depth, HBM budget, rolling p99, then quota — the token
        bucket CONSUMES on success, so it must be the last gate: a
        request shed by an earlier gate never drains the tenant's
        bucket (it was the server's capacity that refused, not the
        tenant's allowance, and the shipped retry_after must reflect
        the real reason)."""
        if queue_depth >= self.max_queue:
            ra = self.drain_estimate(queue_depth)
            _tm.count("serve.shed", reason="queue", tenant=tenant)
            raise Overloaded(
                f"queue depth {queue_depth} at bound {self.max_queue}; "
                f"retry in {ra:.3f}s", retry_after=ra, reason="queue",
                tenant=tenant)
        if self.hbm_budget_bytes is not None:
            live = _tm.memory.live_bytes()
            bound = self.hbm_shed_fraction * self.hbm_budget_bytes
            if live >= bound:
                # retry_after must not over-estimate when the pressure
                # is reclaimable: idle-evictable KV pages free at the
                # next eviction sweep, not at queue-drain pace
                reclaim = 0
                if self.reclaimable_fn is not None:
                    try:
                        reclaim = int(self.reclaimable_fn())
                    except Exception:   # noqa: BLE001 — advisory signal
                        reclaim = 0
                if live - reclaim < bound:
                    ra = self.min_retry_after
                else:
                    ra = self.drain_estimate(max(queue_depth, 1))
                _tm.count("serve.shed", reason="hbm", tenant=tenant)
                raise Overloaded(
                    f"HBM live bytes {live} over "
                    f"{self.hbm_shed_fraction:.0%} of budget "
                    f"{self.hbm_budget_bytes}"
                    + (f" ({reclaim} reclaimable by eviction)"
                       if reclaim else "")
                    + f"; retry in {ra:.3f}s",
                    retry_after=ra, reason="hbm", tenant=tenant)
        if self.p99_shed_s is not None and self.latency.count() >= 8:
            p99 = self.latency.p99()
            if p99 >= self.p99_shed_s:
                ra = self.drain_estimate(max(queue_depth, 1))
                _tm.count("serve.shed", reason="latency", tenant=tenant)
                raise Overloaded(
                    f"rolling dispatch p99 {p99:.3f}s over shed threshold "
                    f"{self.p99_shed_s:.3f}s; retry in {ra:.3f}s",
                    retry_after=ra, reason="latency", tenant=tenant)
        wait = self._bucket(tenant).try_take()
        if wait > 0:
            _tm.count("serve.shed", reason="quota", tenant=tenant)
            # unclamped: retry_after here is the EXACT token refill time
            # (the clamp is for the capacity gates' drain estimates); a
            # clamped value would tell a slow-quota client to retry
            # before its bucket can possibly hold a token
            raise QuotaExceeded(
                f"tenant {tenant!r} quota exhausted "
                f"(rate={self._bucket(tenant).rate:g}/s, "
                f"burst={self._bucket(tenant).burst:g}); "
                f"retry in {wait:.3f}s",
                retry_after=wait, tenant=tenant)
