"""distributedarrays_tpu — a TPU-native distributed-array framework.

A ground-up re-design of the capabilities of
JuliaParallel/DistributedArrays.jl (reference mounted at /root/reference)
for TPU: the global array is one sharded ``jax.Array`` over a device
``Mesh``; elementwise, reduction, and linear-algebra ops are jitted XLA
programs whose cross-chip communication is compiler-inserted collectives
over ICI; the MPI-style SPMD mode lowers to ``shard_map`` + ``lax.ppermute``
/ ``psum`` / ``all_to_all`` for static patterns with a host-eager
rank/mailbox runtime for fully dynamic send/recv.

See SURVEY.md at the repo root for the layer-by-layer mapping.
"""

from .core import (allowscalar, close, d_closeall, next_did, procs, registry,
                   live_ids, live_arrays, current_rank)
from .darray import (DArray, SubDArray, SubOrDArray, DData, darray,
                     darray_like, dfromfunction, from_chunks, dzeros, dones, dfill, drand,
                     drandint, dsample, drandn, distribute, ddata, gather, localpart,
                     localindices, locate, makelocal, seed, copyto_, dcat,
                     dfetch, isassigned)
from .layout import (defaultdist, defaultdist_1d, chunk_idxs, mesh_for,
                     sharding_for, nranks, all_ranks)
from .ops.broadcast import dmap, dmap_into, djit, broadcasted
from .ops.mapreduce import (dreduce, dmapreduce, dsum, dprod, dmaximum,
                            dminimum, dmean, dstd, dvar, dall, dany, dcount,
                            dextrema, dcumsum, dcumprod, dcummax, dcummin, map_localparts,
                            map_localparts_into, samedist, mapslices, ppeval)
from .ops.conv import dconv2d
from .ops.fft import dfft, difft, dfft2, difft2
from .ops.linalg import (axpy_, ddot, dnorm, rmul_, lmul_, lmul_diag,
                         rmul_diag, matmul, mul_into, dtranspose, dadjoint,
                         tune_matmul_impl, tune_matmul_impl_dist,
                         tune_matmul_impl_summa, dmatmul_int8)
from .ops.sort import dsort
from .ops.sparse import dnnz, ddata_bcoo
from . import parallel
from . import resilience
from . import serve
from . import solvers
from . import telemetry
from . import train

__version__ = "0.1.0"
