"""Block-layout math: process grids, chunk boundaries, and mesh construction.

TPU-native re-design of the reference's layout machinery
(/root/reference/src/darray.jl:249-318):

- ``defaultdist(dims, pids)``  — prime-factorize the process count and assign
  the largest factors to the largest dimensions (darray.jl:251-276).
- ``defaultdist(sz, nc)``      — 1-D cut points with the remainder spread over
  the *leading* chunks (darray.jl:279-296); uneven chunks are first-class.
- ``chunk_idxs(dims, chunks)`` — full N-D grid of per-chunk index ranges plus
  the per-dimension cut vectors (darray.jl:299-307).
- ``locate(cuts, *I)``         — binary-search the cuts for the owning chunk
  (darray.jl:448-456).

Unlike the reference (1-based, master/worker), everything here is 0-based and
"process" means a *device rank*: an index into ``jax.devices()``.  The chunk
grid maps onto a ``jax.sharding.Mesh`` whose axes are the distributed
dimensions; XLA's GSPMD partitioner then owns the physical placement, while
the logical cuts computed here remain the source of truth for the user-visible
API (``localindices``, ``localpart``, chunk ownership).

Note on uneven layouts: ``NamedSharding`` shards a non-divisible dimension in
ceil-sized pieces (last shard short), whereas the reference spreads the
remainder over the leading chunks.  We keep the reference's *logical* cuts for
API parity; the physical XLA layout may differ at the ragged edge.  All
compute is expressed on the global array, so this never changes results.
"""

from __future__ import annotations

import functools
import math
import threading
from typing import Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import telemetry as _tm

__all__ = [
    "defaultdist",
    "defaultdist_1d",
    "chunk_idxs",
    "locate",
    "mesh_for",
    "sharding_for",
    "padded_sharding_for",
    "block_sizes",
    "padded_dims",
    "prime_factors",
    "nranks",
    "all_ranks",
    "cut_intersections",
    "chunk_span",
    "even_cuts",
]


def nranks() -> int:
    """Number of device ranks available (reference: ``nworkers()``)."""
    return len(jax.devices())


def all_ranks() -> list[int]:
    """All device ranks (reference: ``workers()``; we have no master/worker
    split — the single controller drives every device)."""
    return list(range(len(jax.devices())))


def prime_factors(n: int) -> list[int]:
    """Prime factorization of ``n`` (ascending, with multiplicity).

    Stands in for the reference's ``Primes.factor`` dependency
    (/root/reference/src/darray.jl:251)."""
    if n < 1:
        raise ValueError(f"cannot factorize {n}")
    out: list[int] = []
    d = 2
    while d * d <= n:
        while n % d == 0:
            out.append(d)
            n //= d
        d += 1
    if n > 1:
        out.append(n)
    return out


def defaultdist(dims: Sequence[int], pids: Sequence[int]) -> list[int]:
    """Decide how many chunks each dimension is divided into.

    Port of the algorithm at /root/reference/src/darray.jl:251-276: factorize
    the number of processes and greedily assign the largest prime factors to
    the dimensions with the most remaining extent.  A factor that fits no
    dimension is dropped (fewer ranks end up used), matching the reference's
    behavior of leaving workers idle rather than over-chunking.
    """
    dims = list(dims)
    chunks = [1] * len(dims)
    np_ = len(pids)
    if np_ == 0:
        raise ValueError("no processes")
    if len(dims) == 0:
        return chunks
    remaining = list(dims)
    for fac in sorted(prime_factors(np_), reverse=True):
        # dimension with the largest remaining extent that can absorb `fac`
        order = sorted(range(len(dims)), key=lambda i: remaining[i], reverse=True)
        placed = False
        for i in order:
            if remaining[i] >= fac:
                remaining[i] //= fac
                chunks[i] *= fac
                placed = True
                break
        if not placed:
            # factor dropped: some ranks stay unused (darray.jl:262-270 spirit)
            continue
    return chunks


def defaultdist_1d(sz: int, nc: int) -> list[int]:
    """1-D cut points (0-based, length ``nc + 1``) splitting ``sz`` into
    ``nc`` chunks, remainder spread over the *leading* chunks.

    Port of /root/reference/src/darray.jl:279-296.  The reference's 1-based
    ``defaultdist(50, 4) == [1, 14, 27, 39, 51]`` becomes
    ``[0, 13, 26, 38, 50]`` here (chunk sizes 13, 13, 12, 12).
    If ``sz < nc`` the first ``sz`` chunks have one element and the rest are
    empty.
    """
    if nc <= 0:
        raise ValueError(f"need at least one chunk, got {nc}")
    if sz >= nc:
        base, rem = divmod(sz, nc)
        cuts = [0]
        for i in range(nc):
            cuts.append(cuts[-1] + base + (1 if i < rem else 0))
        return cuts
    # more chunks than elements: leading singleton chunks, trailing empties
    return [min(i, sz) for i in range(nc + 1)]


def chunk_idxs(dims: Sequence[int], chunks: Sequence[int]):
    """Build the full chunk grid.

    Returns ``(idxs, cuts)`` where ``cuts[d]`` is the 0-based cut vector for
    dimension ``d`` and ``idxs`` is an object ndarray of shape ``chunks``
    whose entry ``idxs[i, j, ...]`` is the tuple of ``range`` objects
    addressing that chunk in the global array.

    Port of /root/reference/src/darray.jl:299-307.
    """
    dims = tuple(dims)
    chunks = tuple(chunks)
    if len(dims) != len(chunks):
        raise ValueError(f"dims {dims} and chunks {chunks} rank mismatch")
    cuts = [defaultdist_1d(d, c) for d, c in zip(dims, chunks)]
    idxs = np.empty(chunks, dtype=object)
    for cidx in np.ndindex(*chunks) if chunks else [()]:
        idxs[cidx] = tuple(
            range(cuts[d][cidx[d]], cuts[d][cidx[d] + 1]) for d in range(len(dims))
        )
    return idxs, cuts


def locate(cuts: Sequence[Sequence[int]], *I: int) -> tuple[int, ...]:
    """Chunk-grid coordinates of global index ``I`` (0-based).

    Port of /root/reference/src/darray.jl:448-456 (binary search of cuts).
    """
    out = []
    for d, i in enumerate(I):
        c = cuts[d]
        if i < 0 or i >= c[-1]:
            raise IndexError(f"index {i} out of bounds for dim {d} (size {c[-1]})")
        # rightmost chunk j with c[j] <= i < c[j+1]; skip empty chunks
        j = int(np.searchsorted(np.asarray(c), i, side="right")) - 1
        while c[j + 1] == c[j]:  # land past empty chunks
            j += 1
        out.append(j)
    return tuple(out)


# ---------------------------------------------------------------------------
# Block algebra between two layouts (reshard planning support)
# ---------------------------------------------------------------------------


def cut_intersections(a_cuts: Sequence[int],
                      b_cuts: Sequence[int]) -> list[tuple[int, int, int, int]]:
    """Overlaps between the chunks of two cut vectors of one global extent.

    Returns ``[(ai, bi, lo, hi), ...]``: the half-open interval ``[lo, hi)``
    lies in chunk ``ai`` of ``a_cuts`` and chunk ``bi`` of ``b_cuts``.
    Empty chunks produce no entries.  This is the 1-D kernel of the
    reshard planner's chunk-intersection transfer plan: the N-D plan is
    the cross product of the per-dimension overlap lists.
    """
    if a_cuts[-1] != b_cuts[-1]:
        raise ValueError(
            f"cut vectors cover different extents: {a_cuts[-1]} vs "
            f"{b_cuts[-1]}")
    out: list[tuple[int, int, int, int]] = []
    ai = bi = 0
    na, nb = len(a_cuts) - 1, len(b_cuts) - 1
    while ai < na and bi < nb:
        lo = max(a_cuts[ai], b_cuts[bi])
        hi = min(a_cuts[ai + 1], b_cuts[bi + 1])
        if lo < hi:
            out.append((ai, bi, int(lo), int(hi)))
        # advance whichever chunk ends first (ties advance both)
        ae, be = a_cuts[ai + 1], b_cuts[bi + 1]
        if ae <= be:
            ai += 1
        if be <= ae:
            bi += 1
    return out


def chunk_span(cuts: Sequence[int], lo: int, hi: int) -> tuple[int, int]:
    """Indices ``(first, last)`` (inclusive) of the non-empty chunks of
    ``cuts`` intersecting the half-open interval ``[lo, hi)``.  Returns
    ``(0, -1)`` for an empty interval.  The owner-block enumeration for
    incremental region mutation."""
    if hi <= lo:
        return (0, -1)
    first = locate([cuts], lo)[0]
    last = locate([cuts], hi - 1)[0]
    return (first, last)


def even_cuts(dims: Sequence[int], grid: Sequence[int]) -> list[list[int]]:
    """Cut vectors of an exactly-even chunk grid (the only grids XLA
    shards physically).  Raises when a dim does not divide."""
    cuts = []
    for d, g in zip(dims, grid):
        g = max(int(g), 1)
        if d % g:
            raise ValueError(f"extent {d} not divisible by {g} chunks")
        step = d // g
        cuts.append([step * i for i in range(g + 1)])
    return cuts


# ---------------------------------------------------------------------------
# Mesh construction
# ---------------------------------------------------------------------------

_mesh_lock = threading.Lock()
_mesh_cache: dict[tuple, Mesh] = {}


def mesh_for(pids: Sequence[int], chunks: Sequence[int]) -> Mesh:
    """A ``jax.sharding.Mesh`` whose axes mirror the chunk grid.

    This is the TPU-native replacement of the reference's
    ``pids::Array{Int,N}`` process grid (/root/reference/src/darray.jl:28):
    the grid of chunk owners *is* the device mesh, and communication between
    chunks rides ICI collectives instead of TCP messages.

    Meshes are cached so identical layouts share one ``Mesh`` object, which
    keeps ``NamedSharding`` equality (and therefore jit caches) warm.
    """
    chunks = tuple(int(c) for c in chunks)
    need = math.prod(chunks) if chunks else 1
    use = tuple(int(p) for p in pids[:need])
    if len(use) < need:
        raise ValueError(f"layout {chunks} needs {need} ranks, got {len(pids)}")
    ndev = len(jax.devices())
    bad = [p for p in use if not 0 <= p < ndev]
    if bad:
        # a raw numpy IndexError here would leak the indexing internals;
        # surface the same rank-validation family as the count check
        raise ValueError(
            f"rank ids {bad} out of range: only {ndev} devices visible")
    key = (use, chunks)
    with _mesh_lock:
        m = _mesh_cache.get(key)
        if m is None:
            devs = np.asarray(jax.devices(), dtype=object)[list(use)].reshape(
                chunks if chunks else (1,)
            )
            names = tuple(f"d{i}" for i in range(max(len(chunks), 1)))
            m = Mesh(devs, axis_names=names)
            _mesh_cache[key] = m
            _tm.count("mesh.builds")
            # cold path: cache-miss body, once per distinct layout
            _tm.event("mesh", "build", grid=list(chunks),  # dalint: disable=DAL003
                      ranks=len(use))
        return m


def sharding_for(pids: Sequence[int], chunks: Sequence[int],
                 dims: Sequence[int] | None = None) -> NamedSharding:
    """NamedSharding matching the chunk grid: dim ``i`` is split over mesh
    axis ``d{i}``.

    XLA shardings must divide evenly (jax requires ``dims[i] % chunks[i] ==
    0``), while the reference supports uneven chunk grids
    (darray.jl:279-296).  Resolution: a dimension that does not divide
    evenly is left *physically* unsharded (replicated over that mesh axis);
    the logical cuts remain the source of truth for ``localpart`` /
    ``localindices`` semantics.  Even layouts — the performance path — get
    the full distributed sharding.
    """
    mesh = mesh_for(pids, chunks)
    if not chunks:
        return NamedSharding(mesh, P())
    names = []
    for i, c in enumerate(chunks):
        even = dims is None or (c > 0 and dims[i] % c == 0)
        names.append(f"d{i}" if (c > 1 and even) else None)
    return NamedSharding(mesh, P(*names))


# ---------------------------------------------------------------------------
# Blocked padding: physical storage for uneven layouts
# ---------------------------------------------------------------------------
#
# XLA shardings must divide evenly, but the reference's uneven chunk grids
# are first-class and physically distributed (darray.jl:279-296).  The
# resolution (VERDICT round-1 item 2): store an uneven DArray as a
# *blocked-padded* buffer — each logical chunk padded at its high end to the
# per-dimension max chunk extent and placed in its own (now even) physical
# shard — so device k holds exactly logical chunk k plus zeros.  The logical
# cuts remain the API surface; ops see the reassembled logical array, and
# ``localpart`` slices the owning device's shard with no cross-device
# traffic.  Even layouts have block size == chunk size and are stored
# unpadded, exactly as before.


def block_sizes(cuts: Sequence[Sequence[int]]) -> list[int]:
    """Per-dimension physical block extent: the max chunk size (== the even
    chunk size for even layouts)."""
    out = []
    for c in cuts:
        sizes = np.diff(np.asarray(c, dtype=np.int64))
        out.append(int(sizes.max()) if sizes.size else 0)
    return out


def padded_dims(cuts: Sequence[Sequence[int]]) -> tuple[int, ...]:
    """Global shape of the blocked-padded buffer: nchunks * block size per
    dim.  Equals the logical dims iff the layout is even."""
    return tuple(int(b) * (len(c) - 1)
                 for b, c in zip(block_sizes(cuts), cuts))


def padded_sharding_for(pids: Sequence[int], chunks: Sequence[int],
                        pdims: Sequence[int]) -> NamedSharding:
    """Fully-distributed NamedSharding for the blocked-padded buffer —
    every axis with more than one chunk is sharded (padding guarantees
    divisibility)."""
    mesh = mesh_for(pids, chunks)
    if not chunks:
        return NamedSharding(mesh, P())
    names = [f"d{i}" if (c > 1 and pdims[i] > 0) else None
             for i, c in enumerate(chunks)]
    return NamedSharding(mesh, P(*names))
