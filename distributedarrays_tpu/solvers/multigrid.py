"""Geometric multigrid V-cycle preconditioner for the stencil operator.

One V-cycle approximately inverts the 5-point Poisson operator on a
row-sharded 2-D grid, built entirely from sharded stencil ops:

- smoother: weighted Jacobi ``x += (ω / diag)(b - A x)`` — the matvec is
  the same ``models.stencil`` halo program the operator uses;
- restriction: 2×2 cell agglomeration (block mean), prolongation: 2×
  piecewise-constant ``repeat`` — both ONE jitted program whose
  ``out_shardings`` keeps every level row-sharded on the same ranks;
- coarse operator: ``scale/2`` per level, which is exactly the Galerkin
  product ``R A P`` for this R/P pair (``R = ¼ Pᵀ``) — so the V-cycle is
  a symmetric preconditioner, safe as CG's ``M⁻¹``;
- coarse solve: below ``coarse_cells`` unknowns (or when the grid stops
  dividing over the ranks) the residual is replicated to the host and
  solved against a cached dense factorization.

``apply(r) -> z`` makes it pluggable anywhere a preconditioner goes
(``cg(..., M=Multigrid(op))``).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from .. import layout as L
from .. import telemetry as _tm
from ..darray import DArray, _wrap_global, distribute
from ..ops.linalg import axpy_, rmul_
from .operators import StencilOperator, poisson2d_dense

__all__ = ["Multigrid"]


@functools.lru_cache(maxsize=64)
def _restrict_jit(out_sharding):
    def f(a):
        return a.reshape(a.shape[0] // 2, 2, a.shape[1] // 2, 2).mean(
            axis=(1, 3))
    return jax.jit(f, out_shardings=out_sharding)


@functools.lru_cache(maxsize=64)
def _prolong_jit(out_sharding):
    def f(a):
        return jnp.repeat(jnp.repeat(a, 2, axis=0), 2, axis=1)
    return jax.jit(f, out_shardings=out_sharding)


@functools.lru_cache(maxsize=16)
def _coarse_solver(nx: int, ny: int, scale: float):
    """Cached dense factorization of the coarse Poisson operator; the
    replicated coarse solve is one host GEMV against it."""
    A = poisson2d_dense(nx, ny, scale).astype(np.float64)
    return np.linalg.inv(A)


class Multigrid:
    """V-cycle preconditioner for :class:`StencilOperator`.

    ``apply`` re-reads the grid partition from its operand, so it keeps
    working unchanged after an elastic shrink re-lays the solver's
    vectors on the survivors.
    """

    def __init__(self, op: StencilOperator, *, omega: float = 0.8,
                 presmooth: int = 2, postsmooth: int = 2,
                 coarse_cells: int = 256, max_levels: int = 16):
        if not isinstance(op, StencilOperator):
            raise TypeError("Multigrid preconditions the stencil Poisson "
                            f"operator, got {type(op).__name__}")
        self.op = op
        self.omega = float(omega)
        self.presmooth = int(presmooth)
        self.postsmooth = int(postsmooth)
        self.coarse_cells = int(coarse_cells)
        self.max_levels = int(max_levels)

    # -- level ops ---------------------------------------------------------

    def _matvec(self, x: DArray, scale: float) -> DArray:
        from ..models.stencil import stencil3x3
        s = scale
        w = tuple(tuple(s * v for v in row)
                  for row in ((0.0, -1.0, 0.0), (-1.0, 4.0, -1.0),
                              (0.0, -1.0, 0.0)))
        return stencil3x3(x, w, iters=1)

    def _smooth(self, x: DArray, b: DArray, scale: float, sweeps: int):
        damp = self.omega / (4.0 * scale)
        for _ in range(sweeps):
            Ax = self._matvec(x, scale)
            rmul_(Ax, -1.0)
            axpy_(1.0, b, Ax)          # Ax now holds the residual
            axpy_(damp, Ax, x)
            Ax.close()

    def _residual(self, x: DArray, b: DArray, scale: float) -> DArray:
        r = b.copy()
        Ax = self._matvec(x, scale)
        axpy_(-1.0, Ax, r)
        Ax.close()
        return r

    @staticmethod
    def _wrap(garr, pids) -> DArray:
        return _wrap_global(garr, procs=pids, dist=[len(pids), 1])

    def _restrict(self, r: DArray) -> DArray:
        pids = [int(q) for q in r.pids.flat]
        p = len(pids)
        dims = (r.dims[0] // 2, r.dims[1] // 2)
        sh = L.sharding_for(pids, (p, 1), dims)
        return self._wrap(_restrict_jit(sh)(r.garray), pids)

    def _prolong(self, e: DArray) -> DArray:
        pids = [int(q) for q in e.pids.flat]
        p = len(pids)
        dims = (e.dims[0] * 2, e.dims[1] * 2)
        sh = L.sharding_for(pids, (p, 1), dims)
        return self._wrap(_prolong_jit(sh)(e.garray), pids)

    def _coarse_solve(self, b: DArray, scale: float) -> DArray:
        nx, ny = b.dims
        inv = _coarse_solver(nx, ny, round(scale, 12))
        host = np.asarray(b.garray, dtype=np.float64).reshape(-1)
        x = (inv @ host).astype(np.float32).reshape(nx, ny)
        return distribute(x, like=b)

    # -- the cycle ---------------------------------------------------------

    def _vcycle(self, b: DArray, scale: float, depth: int) -> DArray:
        nx, ny = b.dims
        p = b.pids.size
        if (depth >= self.max_levels or nx * ny <= self.coarse_cells
                or nx % (2 * p) or ny % 2 or nx // 2 < p):
            return self._coarse_solve(b, scale)
        x = b.copy()
        x.fill_(0)
        self._smooth(x, b, scale, self.presmooth)
        r = self._residual(x, b, scale)
        rc = self._restrict(r)
        r.close()
        # Galerkin coarse operator: R A P = (scale/2) * 5-point for this
        # agglomeration pair (h doubles, PC transfer loses one h order)
        ec = self._vcycle(rc, scale / 2.0, depth + 1)
        rc.close()
        e = self._prolong(ec)
        ec.close()
        axpy_(1.0, e, x)
        e.close()
        self._smooth(x, b, scale, self.postsmooth)
        return x

    def apply(self, r: DArray) -> DArray:
        """One V-cycle: ``z ≈ A⁻¹ r`` (a new DArray; caller closes)."""
        with _tm.span("solver.mg_vcycle", n=r.dims[0] * r.dims[1]):
            return self._vcycle(r, self.op.scale, 0)
