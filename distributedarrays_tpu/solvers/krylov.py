"""Krylov solvers over sharded DArrays: CG, BiCGStab, restarted GMRES(m).

Iteration loops are plain host Python over the existing BLAS-1
primitives (``ops.linalg.ddot`` / ``dnorm`` / ``axpy_``) — every vector
op is one compiled SPMD program, and the per-iteration matvec is the
operator's own communication schedule (see ``solvers.operators``).

Fault tolerance: every solve segment runs under
``resilience.recovery.run_with_recovery`` with ``solver.iterate`` as the
chaos-injection site.  A device loss mid-solve shrinks the registered
operands through ``elastic.shrink()`` onto the survivors; the retry
re-enters the segment, which re-derives the operator partition for the
live set (``A.prepare``), re-seats ``x``/``b`` on the operator's layout
(planner-routed ``samedist``), and rebuilds the Krylov space from the
current iterate — the Krylov restart from ``x`` is the natural recovery
point, so no per-iteration checkpointing is needed.

Outcomes are typed (:class:`SolveResult.outcome`): ``converged``,
``maxiter``, ``breakdown`` (numerical — non-SPD curvature in CG, a
vanishing ``rho``/``omega`` in BiCGStab, a zero Arnoldi norm in GMRES),
or ``cancelled`` (the caller's ``should_stop`` fired — the streaming
solve service routes stream cancellation through it).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

from .. import telemetry as _tm
from ..darray import DArray
from ..ops.linalg import axpy_, ddot, dnorm, rmul_
from ..resilience import elastic, faults as _fl, recovery
from .operators import LinearOperator

__all__ = ["SolveResult", "cg", "bicgstab", "gmres", "SOLVERS"]


@dataclasses.dataclass
class SolveResult:
    """Typed solve outcome.  ``x`` is live (caller closes); ``history``
    holds one residual norm per accepted iteration, across recoveries."""

    outcome: str                 # converged | maxiter | breakdown | cancelled
    x: DArray
    iterations: int
    residual: float
    history: list[float]
    solver: str
    recoveries: int = 0
    detail: str = ""

    @property
    def converged(self) -> bool:
        return self.outcome == "converged"


_TINY = 1e-30


def _close_all(*arrs):
    for a in arrs:
        if a is not None:
            a.close()


class _Solve:
    """Shared per-solve state: the persistent iterate, convergence
    target, residual history, and the recovery re-seat step."""

    def __init__(self, name, A, b, x0, tol, atol, maxiter, M, callback,
                 should_stop):
        self.name = name
        self.A = A
        self.M = M
        self.callback = callback
        self.should_stop = should_stop
        self.maxiter = int(maxiter if maxiter is not None
                           else 10 * A.shape[0])
        self.b = b
        self.b_owned: DArray | None = None
        self.x = x0.copy() if x0 is not None else A.align(b)
        if x0 is None:
            self.x.fill_(0)
        nb = float(dnorm(b))
        self.target = max(float(tol) * nb, float(atol))
        self.history: list[float] = []
        self.iterations = 0
        self.attempts = 0

    def reseat(self, devices) -> tuple[DArray, DArray]:
        """Entry of every recovery attempt: re-derive the operator
        partition for the live ranks and move ``x``/``b`` onto its
        layout (free when already aligned)."""
        self.attempts += 1
        devs = devices if devices is not None else elastic.manager()
        self.A.prepare(devs.live_ranks())
        procs, _ = self.A.vector_layout()
        if [int(q) for q in self.x.pids.flat] != procs:
            old = self.x
            self.x = self.A.align(old)
            old.close()
        src = self.b_owned if self.b_owned is not None else self.b
        if [int(q) for q in src.pids.flat] != procs:
            moved = self.A.align(src)
            _close_all(self.b_owned)
            self.b_owned = moved
        return self.x, (self.b_owned if self.b_owned is not None
                        else self.b)

    def step(self, res: float) -> str | None:
        """Record one accepted iteration; returns a terminal outcome or
        None to continue."""
        self.history.append(res)
        self.iterations += 1
        _tm.count("solver.iterations", solver=self.name)
        if self.callback is not None:
            self.callback(self.iterations, res)
        if res <= self.target or not math.isfinite(res):
            return "converged" if math.isfinite(res) else "breakdown"
        if self.iterations >= self.maxiter:
            return "maxiter"
        return None

    def check_faults(self):
        _fl.check("solver.iterate", solver=self.name)
        if self.should_stop is not None and self.should_stop():
            return "cancelled"
        return None

    def finish(self, outcome: str, detail: str = "") -> SolveResult:
        _close_all(self.b_owned)
        self.b_owned = None
        res = self.history[-1] if self.history else float(dnorm(self.b))
        _tm.count("solver.solves", solver=self.name, outcome=outcome)
        return SolveResult(outcome=outcome, x=self.x,
                           iterations=self.iterations, residual=float(res),
                           history=self.history, solver=self.name,
                           recoveries=self.attempts - 1, detail=detail)


def _run(st: _Solve, segment, policy, devices) -> SolveResult:
    with _tm.span("solver.solve", solver=st.name, n=st.A.shape[0]):
        try:
            outcome, detail = recovery.run_with_recovery(
                segment, policy=policy, devices=devices)
        except BaseException:
            _close_all(st.x, st.b_owned)
            raise
        if _tm.enabled():
            # aggregate stamp on the solve span: per-matvec cost times
            # the iterations run, plus ~10 whole-vector BLAS-1 passes
            # per iteration — a stamped parent covers its subtree, so
            # the doctor's coverage never opens a gap under a solve
            per = st.A.apply_cost()
            iters = max(st.iterations, 1)
            vec = 10 * st.A.shape[0] * np.dtype(st.A.dtype).itemsize
            _tm.annotate(flops=per["flops"] * iters,
                         bytes_hbm=(per["bytes_hbm"] + vec) * iters,
                         bytes_ici=per["bytes_ici"] * iters)
        return st.finish(outcome, detail)


def _residual(A: LinearOperator, x: DArray, b: DArray) -> DArray:
    r = b.copy()
    Ax = A.apply(x)
    axpy_(-1.0, Ax, r)
    Ax.close()
    return r


# ---------------------------------------------------------------------------
# CG
# ---------------------------------------------------------------------------


def cg(A: LinearOperator, b: DArray, *, x0: DArray | None = None,
       tol: float = 1e-6, atol: float = 0.0, maxiter: int | None = None,
       M=None, callback=None, should_stop=None,
       policy: recovery.RetryPolicy | None = None, devices=None
       ) -> SolveResult:
    """Preconditioned conjugate gradients for SPD systems.  ``M`` is an
    optional preconditioner applied as ``z = M.apply(r)`` (e.g.
    ``solvers.multigrid.Multigrid``); convergence is
    ``||r|| <= max(tol*||b||, atol)``."""
    st = _Solve("cg", A, b, x0, tol, atol, maxiter, M, callback,
                should_stop)

    def segment():
        x, bb = st.reseat(devices)
        r = _residual(st.A, x, bb)
        z = st.M.apply(r) if st.M is not None else None
        p = (z if z is not None else r).copy()
        try:
            rz = float(ddot(r, z if z is not None else r))
            while True:
                stop = st.check_faults()
                if stop is not None:
                    return stop, ""
                Ap = st.A.apply(p)
                try:
                    pAp = float(ddot(p, Ap))
                    if pAp <= _TINY:
                        return "breakdown", f"non-positive curvature {pAp:g}"
                    alpha = rz / pAp
                    axpy_(alpha, p, x)
                    axpy_(-alpha, Ap, r)
                finally:
                    Ap.close()
                outcome = st.step(float(dnorm(r)))
                if outcome is not None:
                    return outcome, ""
                if st.M is not None:
                    znew = st.M.apply(r)
                    z.close()
                    z = znew
                rz_new = float(ddot(r, z if z is not None else r))
                beta = rz_new / rz
                rmul_(p, beta)
                axpy_(1.0, z if z is not None else r, p)
                rz = rz_new
        finally:
            _close_all(r, p, z)

    return _run(st, segment, policy, devices)


# ---------------------------------------------------------------------------
# BiCGStab
# ---------------------------------------------------------------------------


def bicgstab(A: LinearOperator, b: DArray, *, x0: DArray | None = None,
             tol: float = 1e-6, atol: float = 0.0,
             maxiter: int | None = None, M=None, callback=None,
             should_stop=None, policy: recovery.RetryPolicy | None = None,
             devices=None) -> SolveResult:
    """BiCGStab for general (nonsymmetric) systems, optionally
    right-preconditioned (``M.apply`` maps search directions)."""
    st = _Solve("bicgstab", A, b, x0, tol, atol, maxiter, M, callback,
                should_stop)

    def segment():
        x, bb = st.reseat(devices)
        r = _residual(st.A, x, bb)
        rhat = r.copy()
        p = r.copy()
        v = phat = shat = t = None
        try:
            rho = float(ddot(rhat, r))
            while True:
                stop = st.check_faults()
                if stop is not None:
                    return stop, ""
                if abs(rho) <= _TINY:
                    return "breakdown", f"rho underflow {rho:g}"
                phat = st.M.apply(p) if st.M is not None else p
                vn = st.A.apply(phat)
                _close_all(v)
                v = vn
                denom = float(ddot(rhat, v))
                if abs(denom) <= _TINY:
                    return "breakdown", f"(rhat, Ap) underflow {denom:g}"
                alpha = rho / denom
                axpy_(-alpha, v, r)              # r becomes s
                res_s = float(dnorm(r))
                if res_s <= st.target:
                    axpy_(alpha, phat, x)
                    outcome = st.step(res_s)
                    return outcome or "converged", ""
                shat = st.M.apply(r) if st.M is not None else r
                tn = st.A.apply(shat)
                _close_all(t)
                t = tn
                tt = float(ddot(t, t))
                if tt <= _TINY:
                    return "breakdown", f"(t, t) underflow {tt:g}"
                omega = float(ddot(t, r)) / tt
                if abs(omega) <= _TINY:
                    return "breakdown", f"omega underflow {omega:g}"
                axpy_(alpha, phat, x)
                axpy_(omega, shat, x)
                axpy_(-omega, t, r)
                if st.M is not None:
                    _close_all(phat, shat)
                phat = shat = None
                outcome = st.step(float(dnorm(r)))
                if outcome is not None:
                    return outcome, ""
                rho_new = float(ddot(rhat, r))
                beta = (rho_new / rho) * (alpha / omega)
                axpy_(-omega, v, p)
                rmul_(p, beta)
                axpy_(1.0, r, p)
                rho = rho_new
        finally:
            if st.M is not None:
                _close_all(phat, shat)
            _close_all(r, rhat, p, v, t)

    return _run(st, segment, policy, devices)


# ---------------------------------------------------------------------------
# restarted GMRES(m)
# ---------------------------------------------------------------------------


def gmres(A: LinearOperator, b: DArray, *, x0: DArray | None = None,
          tol: float = 1e-6, atol: float = 0.0, maxiter: int | None = None,
          restart: int = 30, M=None, callback=None, should_stop=None,
          policy: recovery.RetryPolicy | None = None, devices=None
          ) -> SolveResult:
    """Restarted GMRES(m): modified Gram-Schmidt Arnoldi over DArrays,
    Givens-rotated Hessenberg on the host, optional right preconditioner.
    A restart (every ``restart`` iterations) discards the basis — which
    is also what makes recovery free: the device-loss retry simply
    restarts from the current ``x``."""
    st = _Solve("gmres", A, b, x0, tol, atol, maxiter, M, callback,
                should_stop)
    m = max(1, int(restart))

    def segment():
        while True:
            x, bb = st.reseat(devices)
            r = _residual(st.A, x, bb)
            beta = float(dnorm(r))
            if beta <= st.target:
                r.close()
                if not st.history:
                    st.history.append(beta)
                return "converged", ""
            V: list[DArray] = [rmul_(r, 1.0 / beta)]   # r consumed into V
            Z: list[DArray] = []
            H = np.zeros((m + 1, m), dtype=np.float64)
            cs = np.zeros(m)
            sn = np.zeros(m)
            g = np.zeros(m + 1)
            g[0] = beta
            outcome = None
            try:
                j = 0
                for j in range(m):
                    stop = st.check_faults()
                    if stop is not None:
                        return stop, ""
                    zj = (st.M.apply(V[j]) if st.M is not None else V[j])
                    if st.M is not None:
                        Z.append(zj)
                    w = st.A.apply(zj)
                    for i in range(j + 1):
                        H[i, j] = float(ddot(V[i], w))
                        axpy_(-H[i, j], V[i], w)
                    H[j + 1, j] = float(dnorm(w))
                    lucky = H[j + 1, j] <= _TINY
                    if not lucky:
                        V.append(rmul_(w, 1.0 / H[j + 1, j]))
                    else:
                        w.close()
                    for i in range(j):                 # apply stored Givens
                        h0 = cs[i] * H[i, j] + sn[i] * H[i + 1, j]
                        H[i + 1, j] = -sn[i] * H[i, j] + cs[i] * H[i + 1, j]
                        H[i, j] = h0
                    d = math.hypot(H[j, j], H[j + 1, j])
                    cs[j], sn[j] = ((1.0, 0.0) if d <= _TINY
                                    else (H[j, j] / d, H[j + 1, j] / d))
                    H[j, j] = d
                    H[j + 1, j] = 0.0
                    g[j + 1] = -sn[j] * g[j]
                    g[j] = cs[j] * g[j]
                    res = abs(g[j + 1])
                    outcome = st.step(res)
                    if outcome is None and lucky:
                        outcome = "breakdown"
                    if outcome is not None:
                        break
                k = j + 1
                y = np.linalg.lstsq(H[:k, :k], g[:k], rcond=None)[0]
                basis = Z if st.M is not None else V
                for i in range(k):
                    axpy_(float(y[i]), basis[i], x)
            finally:
                _close_all(*V, *Z)
            if outcome in ("converged", "maxiter", "breakdown"):
                return outcome, ""
            # else: restart with a fresh Krylov space from the updated x

    return _run(st, segment, policy, devices)


SOLVERS = {"cg": cg, "bicgstab": bicgstab, "gmres": gmres}
