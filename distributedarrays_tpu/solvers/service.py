"""Streaming solve service: the serving layer beyond token generation.

``SolverService`` registers a long-lived ``solve`` endpoint on a
``serve.Server``.  ``submit`` admits one linear system and returns a
:class:`SolveStream` — the solver analog of the decode engine's
``TokenStream``: iterate for ``(iter, residual)`` tuples as iterations
land, ``result()`` for the final :class:`~.krylov.SolveResult` summary,
``cancel()`` to abandon mid-solve.  Cancellation is checked between
iterations (``should_stop``); the dispatch's ``finally`` closes every
operand DArray, so cancel frees the system's HBM residency immediately
and the stream resolves with :class:`serve.errors.Cancelled`.

Systems are submitted as host-side specs (the operands are *built* —
and owned — inside the dispatch, so their residency is exactly the
request's lifetime):

- ``{"kind": "poisson", "grid": (nx, ny), "b": <(nx, ny) array>}``
- ``{"kind": "dense",  "A": <(n, n) array>, "b": <(n,) array>}``
- ``{"kind": "sparse", "A": <dense/scipy matrix>, "b": <(n,) array>}``

plus ``method`` (``cg`` | ``bicgstab`` | ``gmres``), ``tol`` /
``maxiter``, and ``precond="multigrid"`` (Poisson systems only).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Callable

import numpy as np

from .. import telemetry as _tm
from ..darray import distribute
from ..serve import errors
from ..serve.server import Server
from . import krylov
from .multigrid import Multigrid
from .operators import DenseOperator, SparseOperator, StencilOperator

__all__ = ["SolveStream", "SolverService"]


class SolveStream:
    """Streaming handle for one solve: ``(iter, residual)`` tuples as
    they land, a final summary via ``result()``, ``cancel()`` to
    abandon (operand residency frees before the stream resolves)."""

    def __init__(self, req_id: int, tenant: str):
        self.req_id = int(req_id)
        self.tenant = tenant
        self._cv = threading.Condition()
        self._updates: list[tuple[int, float]] = []
        self._cancelled = threading.Event()
        self._done = False
        self._error: BaseException | None = None
        self._summary: dict | None = None
        self._listeners: list[Callable[[str, Any], None]] = []

    # engine side ----------------------------------------------------------

    def _push(self, it: int, residual: float) -> None:
        with self._cv:
            if self._done:
                return
            self._updates.append((int(it), float(residual)))
            self._cv.notify_all()
            for fn in self._listeners:
                fn("iter", self._updates[-1])

    def _finish(self, summary: dict | None = None,
                error: BaseException | None = None) -> None:
        with self._cv:
            if self._done:
                return
            self._done = True
            self._summary = summary
            self._error = error
            self._cv.notify_all()
            for fn in self._listeners:
                fn("done", error)
            self._listeners.clear()

    # client side ----------------------------------------------------------

    def add_listener(self, fn: Callable[[str, Any], None]) -> None:
        with self._cv:
            for u in self._updates:
                fn("iter", u)
            if self._done:
                fn("done", self._error)
            else:
                self._listeners.append(fn)

    def cancel(self) -> bool:
        """Abandon the solve: the loop stops at its next iteration check
        and the dispatch frees the system's operand residency."""
        self._cancelled.set()
        return True

    def cancelled(self) -> bool:
        return self._cancelled.is_set()

    def done(self) -> bool:
        with self._cv:
            return self._done

    def error(self) -> BaseException | None:
        with self._cv:
            return self._error

    @property
    def updates(self) -> list[tuple[int, float]]:
        with self._cv:
            return list(self._updates)

    def __iter__(self):
        i = 0
        while True:
            with self._cv:
                while i >= len(self._updates) and not self._done:
                    self._cv.wait(0.05)
                if i < len(self._updates):
                    u = self._updates[i]
                    i += 1
                else:
                    if self._error is not None:
                        raise self._error
                    return
            yield u

    def result(self, timeout: float | None = None) -> dict:
        """Block for the final summary (outcome, iterations, residual,
        x as a host array); raises the solve's typed error."""
        with self._cv:
            if not self._cv.wait_for(lambda: self._done, timeout):
                raise TimeoutError("solve still running")
            if self._error is not None:
                raise self._error
            return self._summary


class SolverService:
    """Owns (or attaches to) a ``serve.Server`` and registers the
    ``solve`` endpoint.  Solves run one-per-dispatch (``max_batch=1`` —
    a solve is minutes of iterations, not a coalescable micro-op) under
    the server's recovery/chaos discipline."""

    def __init__(self, server: Server | None = None, *,
                 endpoint: str = "solve", **server_kw):
        self._own = server is None
        self.server = server if server is not None else Server(**server_kw)
        self.endpoint = endpoint
        self.server.register(endpoint, self._dispatch, max_batch=1)
        self._seq = itertools.count(1)

    # -- client ------------------------------------------------------------

    def submit(self, system: dict, *, method: str = "cg",
               tol: float = 1e-6, maxiter: int | None = None,
               precond: str | None = None, tenant: str = "default",
               deadline_s: float | None = None) -> SolveStream:
        if method not in krylov.SOLVERS:
            raise ValueError(f"unknown method {method!r}: "
                             f"{sorted(krylov.SOLVERS)}")
        stream = SolveStream(next(self._seq), tenant)
        payload = {"system": system, "method": method, "tol": float(tol),
                   "maxiter": maxiter, "precond": precond,
                   "stream": stream}
        try:
            future = self.server.submit(self.endpoint, payload,
                                        tenant=tenant,
                                        deadline_s=deadline_s)
        except errors.ServeError as e:
            stream._finish(error=e)
            raise
        stream.future = future

        def _relay(f):
            # terminal failure (recovery retries exhausted, rejection,
            # expiry) resolves the stream; success/cancel already did
            try:
                f.result()
            except BaseException as e:  # noqa: BLE001 — relayed, not handled
                stream._finish(error=e)
        future.add_done_callback(_relay)
        _tm.count("solver.serve.submitted", method=method)
        return stream

    def close(self, **kw):
        if self._own:
            self.server.close(**kw)

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, payloads: list) -> list:
        return [self._solve_one(pl) for pl in payloads]

    def _solve_one(self, pl: dict) -> dict:
        stream: SolveStream = pl["stream"]
        A = b = M = None
        res = None
        try:
            A, b = self._build(pl["system"])
            if pl.get("precond") == "multigrid":
                if not isinstance(A, StencilOperator):
                    raise errors.RequestFailed(
                        "multigrid preconditions poisson systems only")
                M = Multigrid(A)
            solve = krylov.SOLVERS[pl["method"]]
            res = solve(A, b, tol=pl["tol"], maxiter=pl.get("maxiter"),
                        M=M, callback=stream._push,
                        should_stop=stream.cancelled)
            summary = {
                "outcome": res.outcome,
                "iterations": res.iterations,
                "residual": res.residual,
                "history": list(res.history),
                "recoveries": res.recoveries,
                "x": np.asarray(res.x.garray),
            }
            if res.outcome == "cancelled":
                # the future resolves with the summary (the dispatch did
                # not fail — raising here would read as transient to the
                # recovery loop and re-run a solve nobody wants); the
                # stream carries the typed cancellation
                _tm.count("solver.serve.cancelled")
                stream._finish(error=errors.Cancelled(
                    f"solve cancelled after {res.iterations} iterations"))
            else:
                _tm.count("solver.serve.completed", outcome=res.outcome)
                stream._finish(summary=summary)
            return summary
        finally:
            # operand residency is the request's lifetime: converged,
            # failed or cancelled, the system's DArrays close here
            if res is not None:
                res.x.close()
            if b is not None:
                b.close()
            if A is not None and hasattr(A, "close"):
                A.close()

    @staticmethod
    def _build(system: dict):
        kind = system.get("kind", "poisson")
        if kind == "poisson":
            nx, ny = system["grid"]
            op = StencilOperator((int(nx), int(ny)))
            rhs = np.asarray(system["b"], dtype=np.float32)
            if rhs.shape != op.grid:
                raise errors.RequestFailed(
                    f"rhs shape {rhs.shape} != grid {op.grid}")
            procs, dist = op.vector_layout()
            b = distribute(rhs, procs=procs, dist=list(dist))
            return op, b
        if kind in ("dense", "sparse"):
            op = (DenseOperator(system["A"]) if kind == "dense"
                  else SparseOperator(system["A"]))
            rhs = np.asarray(system["b"], dtype=np.float32).reshape(-1)
            if rhs.shape[0] != op.shape[0]:
                raise errors.RequestFailed(
                    f"rhs length {rhs.shape[0]} != n {op.shape[0]}")
            procs, dist = op.vector_layout()
            b = distribute(rhs, procs=procs, dist=list(dist))
            return op, b
        raise errors.RequestFailed(f"unknown system kind {kind!r}")
