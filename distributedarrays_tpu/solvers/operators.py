"""Distributed matrix-free operators for the iterative solver suite.

A :class:`LinearOperator` is the solver-facing contract: ``apply(x:
DArray) -> DArray`` maps a row-sharded vector to a row-sharded vector.
The reference solves ``A \\ b`` by shipping whole blocks between workers;
here the operator IS the communication schedule, and each concrete
operator picks the cheapest one the layout allows:

- :class:`DenseOperator` — one sharded GEMV through ``ops.linalg.matmul``
  (XLA/GSPMD inserts the all-gather of ``x`` over ICI).
- :class:`SparseOperator` — row-sharded BCOO SpMV built on
  ``ops.sparse.ddata_bcoo``: each rank's block splits into a local
  *diagonal* part (columns it already owns) and a *halo* part (columns
  within ``h`` rows of its range).  ``apply`` dispatches the diagonal
  SpMV first — JAX's async dispatch overlaps it with the halo
  ``ppermute`` program that ships the needed remote vector slices — then
  finishes with the halo SpMV over the extended slab.  Only ``2*h``
  vector elements per neighbor cross ICI; the matrix never moves.
- :class:`StencilOperator` — the 2-D Poisson (5-point) operator as one
  ``models.stencil`` halo-exchange program; "vectors" are the 2-D grids
  themselves.

Every operator re-derives its partition from the live device set on
``prepare(live_ranks)`` so a mid-solve ``elastic.shrink()`` (device loss)
leaves the solver with a working operator on the survivors.
"""

from __future__ import annotations

import functools
import threading

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import layout as L
from .. import telemetry as _tm
from ..telemetry import perf as _perf
from ..darray import DArray, _wrap_global, dzeros
from ..ops.mapreduce import samedist
from ..ops.sparse import ddata_bcoo, jsparse
from ..parallel.collectives import halo_exchange, shard_map_compat

__all__ = ["LinearOperator", "DenseOperator", "SparseOperator",
           "StencilOperator", "POISSON_WEIGHTS", "poisson2d_dense"]


POISSON_WEIGHTS = ((0.0, -1.0, 0.0), (-1.0, 4.0, -1.0), (0.0, -1.0, 0.0))


def poisson2d_dense(nx: int, ny: int, scale: float = 1.0) -> np.ndarray:
    """Dense (nx*ny, nx*ny) matrix of the 5-point Poisson operator with
    zero Dirichlet boundary — the oracle for :class:`StencilOperator`
    (``A = scale * (kron(Tx, I) + kron(I, Ty))``)."""
    def trid(n):
        return (2.0 * np.eye(n) - np.eye(n, k=1) - np.eye(n, k=-1))
    A = np.kron(trid(nx), np.eye(ny)) + np.kron(np.eye(nx), trid(ny))
    return (scale * A).astype(np.float32)


class LinearOperator:
    """Protocol for distributed matrix-free operators.

    ``shape``/``dtype`` describe the square system; ``vector_layout()``
    is the row layout ``apply`` expects its operand on (solvers re-seat
    their persistent vectors there after an elastic shrink); ``prepare``
    re-derives internal structure for a new live rank set.
    """

    shape: tuple[int, ...]
    dtype = jnp.float32

    def apply(self, x: DArray) -> DArray:
        raise NotImplementedError

    def prepare(self, live_ranks: list[int]) -> None:  # noqa: ARG002
        """Adapt to the live device set (default: nothing to rebuild)."""

    def vector_layout(self) -> tuple[list[int], tuple[int, ...]]:
        raise NotImplementedError

    def apply_cost(self) -> dict:
        """Analytic roofline stamp for ONE ``apply`` (aggregate volumes;
        see ``telemetry.perf``) — the solve span multiplies it out so an
        unstamped-coverage gap never opens under the solver."""
        return {"flops": 0, "bytes_hbm": 0, "bytes_ici": 0}

    def new_vector(self) -> DArray:
        """A zeroed solution/workspace vector on the preferred layout."""
        procs, dist = self.vector_layout()
        return dzeros(self.shape[:1] if len(self.shape) == 1 else
                      self._vector_dims(), dtype=self.dtype, procs=procs,
                      dist=list(dist))

    def _vector_dims(self) -> tuple[int, ...]:
        return (self.shape[0],)

    def align(self, x: DArray) -> DArray:
        """A copy of ``x`` on the operator's preferred layout (the input
        is left untouched); aligned inputs come back via the free
        shared-buffer samedist path."""
        like = self.new_vector()
        try:
            return samedist(x, like)
        finally:
            like.close()


# ---------------------------------------------------------------------------
# dense: one sharded GEMV
# ---------------------------------------------------------------------------


class DenseOperator(LinearOperator):
    """Row-sharded dense operator: ``apply`` is ``ops.linalg.matmul``'s
    matvec path (result row-sharded like ``A``).  ``A`` may be a host
    array (distributed here) or an existing DArray (borrowed — the
    caller keeps ownership)."""

    def __init__(self, A, *, procs=None):
        from ..darray import distribute
        if isinstance(A, DArray):
            self._A, self._owned = A, False
        else:
            A = np.asarray(A, dtype=np.float32)
            n = len(procs) if procs is not None else L.nranks()
            p = _largest_divisor(A.shape[0], n)
            use = list(procs)[:p] if procs is not None else L.all_ranks()[:p]
            self._A = distribute(A, procs=use, dist=[p, 1])
            self._owned = True
        if self._A.ndim != 2 or self._A.dims[0] != self._A.dims[1]:
            raise ValueError(f"square operator required, got {self._A.dims}")
        self.shape = self._A.dims
        self.dtype = self._A.dtype

    def apply(self, x: DArray) -> DArray:
        from ..ops.linalg import matmul
        return matmul(self._A, x)

    def vector_layout(self):
        procs = [int(p) for p in self._A.pids.flat]
        return procs, (self._A.pids.shape[0],)

    def apply_cost(self):
        n = self.shape[0]
        return _perf.gemm_cost(n, 1, n, np.dtype(self.dtype).itemsize)

    def close(self):
        if self._owned:
            self._A.close()


# ---------------------------------------------------------------------------
# sparse: BCOO SpMV with halo exchange of remote vector slices
# ---------------------------------------------------------------------------


def _largest_divisor(n: int, cap: int) -> int:
    for p in range(min(n, cap), 0, -1):
        if n % p == 0:
            return p
    return 1


@functools.lru_cache(maxsize=32)
def _halo_ext_jit(mesh, halo: int):
    """Compiled halo program: each rank's vector block comes back extended
    to ``[lo | block | hi]`` — two ``ppermute``s over ICI, zero slabs at
    the open ends (which is exactly the zero-Dirichlet/out-of-range
    contract the halo column blocks are built against)."""
    ax = mesh.axis_names[0]

    def prog(xb):
        lo, hi = halo_exchange(xb, ax, halo=halo, dim=0, wrap=False)
        return jnp.concatenate([lo, xb, hi], axis=0)

    return jax.jit(shard_map_compat(prog, mesh=mesh, in_specs=P(ax),
                                    out_specs=P(ax), check=False))


@functools.lru_cache(maxsize=None)
def _spmv_pair_jit():
    # one compiled kernel for every rank: nse is padded uniform across
    # ranks so the (diag, halo) matvec traces once per partition shape
    return jax.jit(lambda d, h, x, e: d @ x + h @ e)


@functools.lru_cache(maxsize=None)
def _spmv_diag_jit():
    return jax.jit(lambda d, x: d @ x)


def _to_coo(A):
    """Normalize dense/scipy/BCOO-DData input to host COO triples."""
    try:
        import scipy.sparse as sps
    except Exception:  # pragma: no cover - scipy is baked into the image
        sps = None
    if sps is not None and sps.issparse(A):
        coo = A.tocoo()
        return (np.asarray(coo.row), np.asarray(coo.col),
                np.asarray(coo.data, dtype=np.float32), A.shape)
    A = np.asarray(A, dtype=np.float32)
    r, c = np.nonzero(A)
    return r, c, A[r, c], A.shape


class SparseOperator(LinearOperator):
    """Row-sharded BCOO SpMV.  Construction routes a DArray operand
    through ``ops.sparse.ddata_bcoo`` (per-rank BCOO parts), then splits
    each rank's block into the local-diagonal part and the halo part in
    *extended* coordinates; host COO triples are kept so ``prepare`` can
    re-partition onto survivors after an elastic shrink.

    Columns must reach at most one neighbor block away (banded systems;
    bandwidth ≤ rows-per-rank) — the halo program exchanges with adjacent
    mesh ranks only.  A wider reach raises at partition time.
    """

    def __init__(self, A, *, procs=None):
        if jsparse is None:  # pragma: no cover - jsparse ships with jax
            raise ImportError("jax.experimental.sparse is unavailable")
        if isinstance(A, DArray):
            parts = ddata_bcoo(A)
            try:
                # each part is one chunk of A's (possibly 2-D) grid with
                # chunk-local indices; the chunk's cuts give the offsets
                rows, cols, vals = [], [], []
                for gidx in np.ndindex(*A.pids.shape):
                    part = parts.localpart(int(A.pids[gidx]))
                    idx = np.asarray(part.indices)
                    r0 = int(A.cuts[0][gidx[0]])
                    c0 = int(A.cuts[1][gidx[1]]) if A.pids.ndim > 1 else 0
                    rows.append(idx[:, 0] + r0)
                    cols.append(idx[:, 1] + c0)
                    vals.append(np.asarray(part.data, dtype=np.float32))
                self._coo = (np.concatenate(rows), np.concatenate(cols),
                             np.concatenate(vals), A.dims)
            finally:
                parts.close()
        else:
            self._coo = _to_coo(A)
        r, c, v, shp = self._coo
        if len(shp) != 2 or shp[0] != shp[1]:
            raise ValueError(f"square operator required, got {shp}")
        keep = v != 0
        self._coo = (r[keep], c[keep], v[keep], shp)
        self.shape = tuple(int(s) for s in shp)
        self.dtype = jnp.float32
        self.nnz = int(keep.sum())
        self._procs_hint = list(procs) if procs is not None else None
        self._lock = threading.Lock()
        self._ranks: tuple[int, ...] | None = None
        self._partition(self._procs_hint or L.all_ranks())

    # -- partitioning ------------------------------------------------------

    def _partition(self, ranks: list[int]) -> None:
        n = self.shape[0]
        rows, cols, vals, _ = self._coo
        reach = int(np.max(np.abs(rows - cols))) if len(rows) else 0
        p = _largest_divisor(n, len(ranks))
        m = n // p
        while p > 1 and reach > m:
            # bandwidth wider than a block: coarsen the partition until
            # each halo reaches at most the adjacent block
            p = _largest_divisor(n, p - 1)
            m = n // p
        if reach > m:
            raise ValueError(
                f"bandwidth {reach} exceeds rows-per-rank {m}: halo SpMV "
                "exchanges with adjacent ranks only")
        self._p, self._m, self._h = p, m, max(reach, 0)
        self._pids = [int(x) for x in ranks[:p]]
        devs = np.asarray(jax.devices(), dtype=object)[self._pids]
        order = np.argsort(rows, kind="stable")
        rows, cols, vals = rows[order], cols[order], vals[order]
        bounds = np.searchsorted(rows, np.arange(0, n + 1, m))
        diag, halo = [], []
        for k in range(p):
            lo, hi = bounds[k], bounds[k + 1]
            rr = rows[lo:hi] - k * m
            cc = cols[lo:hi]
            vv = vals[lo:hi]
            local = (cc >= k * m) & (cc < (k + 1) * m)
            diag.append((rr[local], cc[local] - k * m, vv[local]))
            # halo part in extended coordinates [0, m + 2h): the slab
            # arriving from the previous rank occupies [0, h)
            halo.append((rr[~local], cc[~local] - k * m + self._h,
                         vv[~local]))
        self._diag = [_pad_bcoo(d, (m, m), _max_nse(diag), devs[k])
                      for k, d in enumerate(diag)]
        self._halo = [_pad_bcoo(hp, (m, m + 2 * self._h), _max_nse(halo),
                                devs[k])
                      for k, hp in enumerate(halo)]
        self._mesh = L.mesh_for(self._pids, (p,))
        self._ranks = tuple(ranks)

    def prepare(self, live_ranks: list[int]) -> None:
        with self._lock:
            live = [int(r) for r in live_ranks]
            if self._procs_hint is not None:
                live = [r for r in self._procs_hint if r in live] or live
            if tuple(live) != self._ranks:
                self._partition(live)

    def vector_layout(self):
        return list(self._pids), (self._p,)

    def apply_cost(self):
        itemsize = np.dtype(self.dtype).itemsize
        return _perf.spmv_cost(
            self.nnz, self.shape[0], itemsize,
            bytes_ici=(2 * (self._p - 1) * self._h * itemsize
                       if self._p > 1 else 0))

    # -- apply -------------------------------------------------------------

    def apply(self, x: DArray) -> DArray:
        n, p, h = self.shape[0], self._p, self._h
        owned = None
        if [int(q) for q in x.pids.flat] != self._pids or x.pids.size != p:
            owned = x = self.align(x)
        try:
            with _tm.span("solver.spmv", op="bcoo", n=n, ranks=p,
                          **self.apply_cost()):
                shards = {s.device: s.data
                          for s in x.garray.addressable_shards}
                xs = [shards[d] for d in self._mesh.devices.flat]
                if h == 0 or p == 1:
                    ys = [_spmv_diag_jit()(self._diag[k], xs[k])
                          for k in range(p)]
                    if p == 1 and self._halo[0].nse:
                        # single-rank extended part degenerates to local
                        ext = jnp.pad(xs[0], (h, h))
                        ys[0] = ys[0] + self._halo[0] @ ext
                else:
                    # local-diagonal SpMV dispatches first; JAX's async
                    # dispatch overlaps it with the halo ppermute program
                    y_diag = [_spmv_diag_jit()(self._diag[k], xs[k])
                              for k in range(p)]
                    ext = _halo_ext_jit(self._mesh, h)(x.garray)
                    eshards = {s.device: s.data
                               for s in ext.addressable_shards}
                    es = [eshards[d] for d in self._mesh.devices.flat]
                    ys = [y_diag[k] + self._halo[k] @ es[k]
                          for k in range(p)]
                sharding = L.sharding_for(self._pids, (p,), (n,))
                ys = [jax.device_put(y, d)
                      for y, d in zip(ys, self._mesh.devices.flat)]
                garr = jax.make_array_from_single_device_arrays(
                    (n,), sharding, ys)
                return _wrap_global(garr, procs=self._pids, dist=[p])
        finally:
            if owned is not None:
                owned.close()


def _max_nse(triples) -> int:
    return max(1, max(len(t[2]) for t in triples))


def _pad_bcoo(triple, shape, nse, device):
    """Build a rank's BCOO block padded to the partition-wide ``nse`` so
    every rank shares one compiled matvec (pad entries are explicit
    zeros at (0, 0); BCOO sums duplicates)."""
    rr, cc, vv = triple
    pad = nse - len(vv)
    idx = np.zeros((nse, 2), dtype=np.int32)
    dat = np.zeros((nse,), dtype=np.float32)
    idx[:len(vv), 0] = rr
    idx[:len(vv), 1] = cc
    dat[:len(vv)] = vv
    mat = jsparse.BCOO((jnp.asarray(dat), jnp.asarray(idx)), shape=shape)
    del pad
    return jax.device_put(mat, device)


# ---------------------------------------------------------------------------
# stencil: 2-D Poisson through the models.stencil halo program
# ---------------------------------------------------------------------------


class StencilOperator(LinearOperator):
    """5-point Poisson operator ``A·x = scale * (4x - Σ neighbors)`` with
    zero Dirichlet boundary, applied as ONE ``models.stencil`` program
    (interior update fused around two halo ``ppermute``s).  Vectors are
    the row-sharded 2-D grids themselves; the dense oracle is
    :func:`poisson2d_dense` on the flattened grid."""

    def __init__(self, grid: tuple[int, int], *, scale: float = 1.0,
                 procs=None):
        nx, ny = int(grid[0]), int(grid[1])
        self.grid = (nx, ny)
        self.shape = (nx * ny, nx * ny)
        self.scale = float(scale)
        self.dtype = jnp.float32
        self._procs_hint = list(procs) if procs is not None else None
        self._pids: list[int] = []
        self.prepare(self._procs_hint or L.all_ranks())

    @property
    def weights(self):
        s = self.scale
        return tuple(tuple(s * w for w in row) for row in POISSON_WEIGHTS)

    def prepare(self, live_ranks: list[int]) -> None:
        live = [int(r) for r in live_ranks]
        if self._procs_hint is not None:
            live = [r for r in self._procs_hint if r in live] or live
        p = _largest_divisor(self.grid[0], len(live))
        self._pids = live[:p]

    def vector_layout(self):
        return list(self._pids), (len(self._pids), 1)

    def apply_cost(self):
        nx, ny = self.grid
        itemsize = np.dtype(self.dtype).itemsize
        p = len(self._pids)
        return _perf.spmv_cost(
            5 * nx * ny, nx * ny, itemsize, index_itemsize=0,
            bytes_ici=2 * (p - 1) * ny * itemsize if p > 1 else 0)

    def _vector_dims(self):
        return self.grid

    def apply(self, x: DArray) -> DArray:
        from ..models.stencil import stencil3x3
        owned = None
        if ([int(q) for q in x.pids.flat] != self._pids
                or tuple(x.dims) != self.grid):
            owned = x = self.align(x)
        try:
            nx, ny = self.grid
            with _tm.span("solver.spmv", op="stencil", n=nx * ny,
                          ranks=len(self._pids), **self.apply_cost()):
                return stencil3x3(x, self.weights, iters=1)
        finally:
            if owned is not None:
                owned.close()
