"""Distributed iterative solver suite (ROADMAP item 4).

The classic DistributedArrays workload — iterative linear solvers over
sharded operands — as a vertical slice through the stack: matrix-free
operators whose ``apply`` is a compiled communication schedule
(:mod:`.operators`), Krylov loops with typed outcomes and device-loss
recovery (:mod:`.krylov`), a geometric multigrid preconditioner
(:mod:`.multigrid`), and a streaming ``solve`` endpoint on the serving
layer (:mod:`.service`).
"""

from .operators import (DenseOperator, LinearOperator, SparseOperator,
                        StencilOperator, poisson2d_dense)
from .krylov import SolveResult, bicgstab, cg, gmres
from .multigrid import Multigrid
from .service import SolverService, SolveStream

__all__ = [
    "LinearOperator", "DenseOperator", "SparseOperator", "StencilOperator",
    "poisson2d_dense", "SolveResult", "cg", "bicgstab", "gmres",
    "Multigrid", "SolverService", "SolveStream",
]
