"""Framework-wide telemetry: counters, communication byte accounting, and
a structured event journal.

The observability layer the reference never had (SURVEY.md §5) and the
`jax.profiler` wrappers in ``utils/profiling.py`` cannot provide: every
reshard, eager transfer, traced collective, SPMD mailbox send, fallback
hit, retrace, autotune lookup, and checkpoint phase in this framework
reports here, so one process can answer "how many bytes did this workload
move and how many reshards/retraces/fallbacks did it take?" without a
profiler run.

Quick use::

    import distributedarrays_tpu as dat
    from distributedarrays_tpu import telemetry

    telemetry.configure("run.jsonl")      # optional JSONL journal
    ...workload...
    print(telemetry.report())             # nested dict
    telemetry.dump("telemetry.json")      # JSON export

    # attribute time/bytes to phases with hierarchical spans
    with telemetry.span("train.step", step=i):
        ...                            # comm/events inside carry span_id

    # offline: summarize / export a journal
    #   python -m distributedarrays_tpu.telemetry summarize run.jsonl
    #   python -m distributedarrays_tpu.telemetry trace run.jsonl -o t.json
    #   python -m distributedarrays_tpu.telemetry prom report.json

Disable with ``DA_TPU_TELEMETRY=0`` (or :func:`disable`): every recording
call becomes a boolean check and an immediate return, no journal file is
ever created, and :func:`report` stays empty.

Metric catalog and the journal schema: ``docs/telemetry.md``.
"""

from .core import (enabled, enable, disable, configure, reset, count,
                   set_gauge, observe, event, record_comm, counter_value,
                   gauge_value, comm_bytes, events, journal_path, nbytes_of,
                   report, dump, begin_incident, current_incident,
                   end_incident)
from .summarize import read_journal, summarize, format_summary
from .tracing import (Span, span, traced, current_span, current_span_id,
                      spans, span_stats, open_spans, annotate, trace_ctx,
                      current_trace_ids, bind_trace_ids,
                      record_external_span)
from .export import to_perfetto, to_prometheus
from . import memory
from . import flight
from . import perf
from . import regress
from . import tracing
from . import cluster
from . import alerts
from . import advisor
from . import stream
from . import agg
from .memory import leak_census
from .flight import postmortem, record_crash
from .cluster import merge_journals, reconstruct_incidents
from .alerts import AlertRule, AlertManager, default_rules, start_sampler, \
    stop_sampler

__all__ = [
    "enabled", "enable", "disable", "configure", "reset",
    "count", "set_gauge", "observe", "event", "record_comm",
    "counter_value", "gauge_value", "comm_bytes", "events",
    "journal_path", "nbytes_of", "report", "dump",
    "begin_incident", "current_incident", "end_incident",
    "read_journal", "summarize", "format_summary",
    "Span", "span", "traced", "current_span", "current_span_id",
    "spans", "span_stats", "open_spans", "annotate", "trace_ctx",
    "current_trace_ids", "bind_trace_ids", "record_external_span",
    "to_perfetto", "to_prometheus",
    "memory", "flight", "perf", "regress", "tracing", "cluster", "alerts",
    "advisor", "stream", "agg",
    "leak_census", "postmortem", "record_crash",
    "merge_journals", "reconstruct_incidents",
    "AlertRule", "AlertManager", "default_rules",
    "start_sampler", "stop_sampler",
]

# arm the always-on health sampler when the env interval is set — same
# import-time auto-install pattern as flight's SIGUSR1 handler; with
# DA_TPU_TELEMETRY=0 or no interval this is a no-op
alerts._maybe_autostart()
# arm the live-plane streaming exporter when DA_TPU_STREAM_AGG is set
# (same pattern); no-op when unset or telemetry is disabled
stream._maybe_autostart()
