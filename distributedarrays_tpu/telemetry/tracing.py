"""Hierarchical span tracing: *where* time and bytes go.

PR 1's counters and journal answer "how many bytes / how many
reshards" — this module answers *which phase they belong to*.  A span is
a named, labeled interval with a ``span_id``/``parent_id`` pair; spans
nest through a contextvar parent stack, so every :func:`core.event` and
:func:`core.record_comm` issued while a span is open is stamped with its
``span_id`` — comm bytes and fallbacks become attributable to the
reshard, GEMM stage, or checkpoint phase that caused them.

- :class:`span` — context manager: ``with span("matmul", grid="2x2"):``.
- :func:`traced` — decorator form: ``@traced(name="reshard")``.
- Start times share the journal's monotonic origin (``core._T0``), so
  span intervals and journal events live on one timeline (and one
  Perfetto track per thread, see ``telemetry/export.py``).
- Disabled telemetry (``DA_TPU_TELEMETRY=0``): entering a span is the
  same single boolean check as a counter — no ids, no contextvar write,
  no journal, nothing allocated beyond the context-manager object.

Spans are *host-side* intervals.  Inside traced code (jit/shard_map
bodies) a span measures trace time, like PR 1's ``traced=True`` comm
records — flag such spans with a label if the distinction matters.

Finished spans land in a bounded buffer (:func:`spans`), per-name
aggregates (:func:`span_stats`: count, total time, self time = total
minus child time, own bytes, rolled-up child bytes), one journal event
per span (category ``"span"``, suppressible per call site with
``_journal=False`` for high-frequency phases), and the ``"spans"``
section of :func:`core.report`.

Stdlib only, like ``core`` — importable from any layer without cycles.
"""

from __future__ import annotations

import functools
import itertools
import threading
import time
from collections import deque

from . import core

__all__ = ["Span", "span", "traced", "current_span", "current_span_id",
           "spans", "span_stats", "open_spans", "annotate", "trace_ctx",
           "current_trace_ids", "bind_trace_ids", "record_external_span"]

_SPAN_BUFFER_MAX = 8192
_ids = itertools.count(1)        # CPython-atomic; no lock needed
_finished: deque = deque(maxlen=_SPAN_BUFFER_MAX)
_finished_total = 0
# name -> {count, total_s, self_s, bytes, child_bytes}
_stats: dict[str, dict] = {}
# span_id -> Span, for every span currently OPEN on any thread — the
# flight recorder's "what was in progress when we crashed" snapshot
_open: dict[int, "Span"] = {}


class Span:
    """One open (then finished) traced interval.  Created by :class:`span`
    — not directly.  ``bytes`` accumulates every ``record_comm`` issued
    while this span is innermost; ``child_s``/``child_bytes`` roll up
    from directly nested spans as they finish."""

    __slots__ = ("name", "labels", "span_id", "parent_id", "parent",
                 "start", "_t0", "dur", "bytes", "child_s", "child_bytes",
                 "tid", "tname", "journaled", "trace")

    def __init__(self, name: str, labels: dict, parent: "Span | None",
                 journaled: bool = True):
        self.name = name
        self.labels = labels
        # request-scoped trace ids: every span opened while a trace
        # context is set carries them — submit-to-resolve journeys
        # reconstruct from the journal (and export as Perfetto flows)
        self.trace = core._TRACE_CTX.get()
        self.span_id = next(_ids)
        self.parent = parent
        self.parent_id = parent.span_id if parent is not None else None
        self.journaled = journaled
        self._t0 = time.monotonic()
        self.start = self._t0 - core._T0
        self.dur = None           # None while open
        self.bytes = 0
        self.child_s = 0.0
        self.child_bytes = 0
        t = threading.current_thread()
        self.tid = t.ident or 0
        self.tname = t.name

    @property
    def self_s(self) -> float:
        return (self.dur or 0.0) - self.child_s

    def to_dict(self) -> dict:
        d = {"name": self.name, "span_id": self.span_id,
             "parent_id": self.parent_id,
             "start": round(self.start, 6),
             "dur": round(self.dur, 6) if self.dur is not None else None,
             "bytes": self.bytes, "child_bytes": self.child_bytes,
             "tid": self.tid, "tname": self.tname}
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.trace:
            d["trace_id"] = list(self.trace)
        return d

    def __repr__(self):
        state = f"dur={self.dur:.6f}s" if self.dur is not None else "open"
        return f"<Span {self.name!r} id={self.span_id} {state}>"


class span:
    """Context manager opening a :class:`Span` named ``name`` with
    ``labels``.  Yields the Span (or ``None`` when telemetry is
    disabled).  ``_journal=False`` makes the span aggregate-only: it
    updates :func:`span_stats` (and parent rollups) but skips BOTH the
    journal and the bounded :func:`spans` buffer — for phases that fire
    thousands of times per run (e.g. the SPMD mailbox drain), which
    would otherwise evict every other span from the buffer."""

    __slots__ = ("_name", "_labels", "_journal", "_sp", "_tok")

    def __init__(self, name: str, _journal: bool = True, **labels):
        self._name = name
        self._labels = labels
        self._journal = _journal
        self._sp = None

    def __enter__(self):
        if not core._ENABLED:        # the single-boolean disabled path
            return None
        parent = core._CURRENT_SPAN.get()
        sp = Span(self._name, self._labels, parent, self._journal)
        self._tok = core._CURRENT_SPAN.set(sp)
        self._sp = sp
        with core._LOCK:
            _open[sp.span_id] = sp
        return sp

    def __exit__(self, exc_type, exc, tb):
        sp = self._sp
        if sp is None:
            return False
        self._sp = None
        core._CURRENT_SPAN.reset(self._tok)
        _finish(sp, self._journal, error=exc_type is not None)
        return False


def _finish(sp: Span, journal: bool, error: bool = False) -> None:
    global _finished_total
    sp.dur = time.monotonic() - sp._t0
    with core._LOCK:
        _open.pop(sp.span_id, None)
        parent = sp.parent
        if parent is not None and parent.dur is None:
            # parent still open on this stack: roll this span's time and
            # byte totals (own + descendants) up one level
            parent.child_s += sp.dur
            parent.child_bytes += sp.bytes + sp.child_bytes
        if journal:
            _finished.append(sp.to_dict())
        _finished_total += 1
        st = _stats.get(sp.name)
        if st is None:
            _stats[sp.name] = {"count": 1, "total_s": sp.dur,
                               "self_s": sp.self_s, "bytes": sp.bytes,
                               "child_bytes": sp.child_bytes}
        else:
            st["count"] += 1
            st["total_s"] += sp.dur
            st["self_s"] += sp.self_s
            st["bytes"] += sp.bytes
            st["child_bytes"] += sp.child_bytes
    if journal:
        # the journal only sees journaled spans, so its parent link must
        # skip aggregate-only ancestors or offline tools dangle; bytes
        # carry the child rollup too — descendant comm may have landed on
        # aggregate-only children that never reach the journal
        parent = sp.parent
        while parent is not None and not parent.journaled:
            parent = parent.parent
        fields = {"span_id": sp.span_id,
                  "parent_id": parent.span_id if parent is not None else None,
                  "start": round(sp.start, 6), "dur": round(sp.dur, 6),
                  "bytes": sp.bytes, "child_bytes": sp.child_bytes,
                  "tid": sp.tid, "tname": sp.tname}
        if sp.labels:
            fields["labels"] = sp.labels
        if sp.trace:
            fields["trace_id"] = list(sp.trace)
        if error:
            fields["error"] = True
        core.event("span", sp.name, **fields)


def traced(fn=None, *, name: str | None = None, _journal: bool = True,
           **labels):
    """Decorator running the function body inside a span.

    Bare (``@traced``) the span is named after the function's qualname;
    ``@traced(name="matmul", grid="2x2")`` overrides name and attaches
    labels.  Disabled telemetry short-circuits to a direct call.
    """
    def deco(f):
        sname = name or f.__qualname__

        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            if not core._ENABLED:
                return f(*args, **kwargs)
            with span(sname, _journal=_journal, **labels):
                return f(*args, **kwargs)
        return wrapper
    if fn is not None:
        return deco(fn)
    return deco


def current_span() -> Span | None:
    """The innermost open span on this thread/context, or None."""
    return core._CURRENT_SPAN.get()


def annotate(**labels) -> None:
    """Merge ``labels`` into the innermost open span — for call sites
    whose interesting labels (shapes, analytic cost stamps) only exist
    after the span opened (e.g. a ``@traced`` function that derives its
    operand shapes in its body).  No-op when telemetry is disabled or no
    span is open."""
    if not core._ENABLED:
        return
    sp = core._CURRENT_SPAN.get()
    if sp is None:
        return
    with core._LOCK:
        # fresh dict: the span CM may share its labels dict across
        # re-entries of the same context-manager object
        sp.labels = {**sp.labels, **labels}


class trace_ctx:
    """Context manager binding one or more request trace ids to the
    current context: every span opened (and journal event recorded)
    inside carries them.  Nesting unions the ids (a batch dispatch holds
    every member request's id).  Single boolean check when disabled."""

    __slots__ = ("_ids", "_tok")

    def __init__(self, *ids):
        self._ids = tuple(str(i) for i in ids if i)
        self._tok = None

    def __enter__(self):
        if not core._ENABLED or not self._ids:
            return None
        cur = core._TRACE_CTX.get() or ()
        merged = cur + tuple(i for i in self._ids if i not in cur)
        self._tok = core._TRACE_CTX.set(merged)
        return merged

    def __exit__(self, exc_type, exc, tb):
        if self._tok is not None:
            core._TRACE_CTX.reset(self._tok)
            self._tok = None
        return False


def current_trace_ids() -> tuple:
    """The trace ids bound to the current context (empty tuple when
    none) — capture these before handing work to another thread and
    rebind there with :class:`trace_ctx` or :func:`bind_trace_ids`
    (contextvars do not cross thread starts)."""
    return core._TRACE_CTX.get() or ()


def bind_trace_ids(ids) -> None:
    """Bind ``ids`` to THIS context with no reset token — for the entry
    point of a worker thread whose context dies with it (SPMD rank
    tasks).  Use :class:`trace_ctx` anywhere the context outlives the
    work."""
    if ids and core._ENABLED:
        core._TRACE_CTX.set(tuple(str(i) for i in ids))


def record_external_span(name: str, start: float, dur: float, *,
                         labels: dict | None = None, tid: int = 0,
                         tname: str = "", error: bool = False) -> None:
    """Record a span measured OUTSIDE this process's tracing machinery —
    e.g. a forked SPMD rank child measures its own step and ships the
    interval home; the parent records it here so both backends produce
    rank-labeled ``spmd.step`` spans.  ``start`` is seconds relative to
    the telemetry origin (``core._T0`` — inherited across fork), ``dur``
    in seconds.  Stamped with the caller's trace context."""
    global _finished_total
    if not core._ENABLED:
        return
    # a root span, like the thread backend's rank steps (fresh threads
    # have no contextvar parent): concurrent rank durations must not
    # roll up into one parent's child time and drive its self time
    # negative
    sp = Span(name, dict(labels or {}), None)
    sp.start = float(start)
    sp.dur = float(dur)
    if tid:
        sp.tid = tid
    if tname:
        sp.tname = tname
    with core._LOCK:
        _finished.append(sp.to_dict())
        _finished_total += 1
        st = _stats.get(sp.name)
        if st is None:
            _stats[sp.name] = {"count": 1, "total_s": sp.dur,
                               "self_s": sp.dur, "bytes": 0,
                               "child_bytes": 0}
        else:
            st["count"] += 1
            st["total_s"] += sp.dur
            st["self_s"] += sp.dur
    fields = {"span_id": sp.span_id, "parent_id": sp.parent_id,
              "start": round(sp.start, 6), "dur": round(sp.dur, 6),
              "bytes": 0, "child_bytes": 0, "tid": sp.tid,
              "tname": sp.tname}
    if sp.labels:
        fields["labels"] = sp.labels
    if sp.trace:
        fields["trace_id"] = list(sp.trace)
    if error:
        fields["error"] = True
    core.event("span", sp.name, **fields)


def current_span_id() -> int | None:
    sp = core._CURRENT_SPAN.get()
    return sp.span_id if sp is not None else None


def spans(name: str | None = None) -> list[dict]:
    """Snapshot of finished spans (most recent ``_SPAN_BUFFER_MAX``),
    optionally filtered by name.  Aggregate-only spans
    (``_journal=False``) are not buffered — see :func:`span_stats` for
    the complete per-name totals."""
    with core._LOCK:
        out = list(_finished)
    if name is None:
        return out
    return [s for s in out if s["name"] == name]


def open_spans() -> list[dict]:
    """Every span currently open on any thread (oldest first) — the
    flight recorder's in-progress stack.  ``dur`` is None on each."""
    with core._LOCK:
        sps = sorted(_open.values(), key=lambda s: s.span_id)
        return [s.to_dict() for s in sps]


def span_stats() -> dict[str, dict]:
    """Per-name aggregates over every finished span: count, total wall
    time, self time (total minus directly-nested child time), own comm
    bytes, and rolled-up child bytes."""
    with core._LOCK:
        return {k: dict(v) for k, v in _stats.items()}


def _report_section(top_n: int = 10) -> dict:
    """The ``"spans"`` section of :func:`core.report`: per-name rollups
    plus top-N rankings by self-time and by total-time."""
    with core._LOCK:
        by_name = {k: dict(v) for k, v in _stats.items()}
        finished = _finished_total
    def _round(d):
        return {**d, "total_s": round(d["total_s"], 6),
                "self_s": round(d["self_s"], 6)}
    return {
        "finished": finished,
        "by_name": {k: _round(v) for k, v in sorted(by_name.items())},
        "top_by_self_s": [
            [k, round(v["self_s"], 6)] for k, v in sorted(
                by_name.items(), key=lambda kv: -kv[1]["self_s"])[:top_n]],
        "top_by_total_s": [
            [k, round(v["total_s"], 6)] for k, v in sorted(
                by_name.items(), key=lambda kv: -kv[1]["total_s"])[:top_n]],
    }


def _reset() -> None:
    global _finished_total
    with core._LOCK:
        _finished.clear()
        _stats.clear()
        _open.clear()
        _finished_total = 0


core.register_report_section("spans", _report_section)
core.register_reset_hook(_reset)
