"""CLI: summarize or export a telemetry journal / report.

Usage::

    python -m distributedarrays_tpu.telemetry summarize RUN.jsonl [--json]
    python -m distributedarrays_tpu.telemetry trace RUN.jsonl [-o out.json]
    python -m distributedarrays_tpu.telemetry prom REPORT.json [-o out.prom]
    python -m distributedarrays_tpu.telemetry RUN.jsonl [--json]   # legacy

``summarize`` prints event counts by category, communication bytes by
kind (eager vs traced), span rollups, and top fallback keys; ``trace``
converts a journal to Perfetto/Chrome trace-event JSON (open at
ui.perfetto.dev); ``prom`` renders a ``telemetry.dump()`` report — or,
given a journal, the registry reconstructed from it — in Prometheus
text exposition format.  ``-`` reads stdin.  The first form without a
subcommand is the PR-1 interface and behaves exactly like ``summarize``.

The converters (``summarize.py``, ``export.py``) are pure stdlib;
running via ``-m`` imports the parent package (JAX present), so on a
JAX-less machine import those modules directly instead.
"""

from __future__ import annotations

import argparse
import io
import json
import sys

from .export import to_perfetto, to_prometheus
from .summarize import read_journal, summarize, format_summary


def _read_events(path: str) -> list[dict]:
    return read_journal(sys.stdin if path == "-" else path)


def _write_out(text: str, out_path: str | None) -> None:
    if out_path and out_path != "-":
        with open(out_path, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)


def _cmd_summarize(args) -> int:
    s = summarize(_read_events(args.journal))
    if args.json:
        json.dump(s, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        format_summary(s, sys.stdout)
    return 0


def _cmd_trace(args) -> int:
    trace = to_perfetto(_read_events(args.journal))
    _write_out(json.dumps(trace, indent=None if args.out else 2) + "\n",
               args.out)
    return 0


def _registry_from_journal(events: list[dict]) -> dict:
    """Rebuild a report-shaped registry from a journal so ``prom`` works
    on either input: comm kinds and span rollups survive; counters that
    never hit the journal (hot-path increments) do not."""
    s = summarize(events)
    return {
        "counters": {f"journal.events{{cat={c}}}": n
                     for c, n in s["by_category"].items()},
        "gauges": {}, "histograms": {},
        "comm": {"total_bytes": s["comm"]["total_bytes"],
                 "total_ops": s["comm"]["total_ops"],
                 "by_kind": s["comm"]["by_kind"]},
        "spans": {"by_name": {k: {"count": v["count"],
                                  "total_s": v["total_s"],
                                  "self_s": 0.0, "bytes": v["bytes"]}
                              for k, v in s["spans"].items()}},
        "events": {"recorded": s["events"]},
    }


def _cmd_prom(args) -> int:
    raw = sys.stdin.read() if args.report == "-" else \
        open(args.report).read()
    try:
        doc = json.loads(raw)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "counters" in doc:
        registry = doc                      # a telemetry.dump() report
    else:                                   # a JSONL journal
        events = read_journal(io.StringIO(raw))
        registry = _registry_from_journal(events)
    _write_out(to_prometheus(registry), args.out)
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("summarize", "trace", "prom"):
        ap = argparse.ArgumentParser(
            prog="python -m distributedarrays_tpu.telemetry",
            description="Summarize or export a telemetry journal/report.")
        sub = ap.add_subparsers(dest="cmd", required=True)
        p = sub.add_parser("summarize", help="journal -> text/JSON summary")
        p.add_argument("journal", help="JSONL journal path ('-' = stdin)")
        p.add_argument("--json", action="store_true",
                       help="emit the summary as JSON")
        p.set_defaults(fn=_cmd_summarize)
        p = sub.add_parser("trace",
                           help="journal -> Perfetto trace-event JSON")
        p.add_argument("journal", help="JSONL journal path ('-' = stdin)")
        p.add_argument("-o", "--out", default=None,
                       help="output path (default stdout)")
        p.set_defaults(fn=_cmd_trace)
        p = sub.add_parser("prom",
                           help="report JSON (telemetry.dump) or journal "
                                "-> Prometheus text exposition")
        p.add_argument("report", help="report/journal path ('-' = stdin)")
        p.add_argument("-o", "--out", default=None,
                       help="output path (default stdout)")
        p.set_defaults(fn=_cmd_prom)
        args = ap.parse_args(argv)
        try:
            return args.fn(args)
        except OSError as e:
            print(f"cannot read input: {e}", file=sys.stderr)
            return 2
    # legacy interface: bare journal path == `summarize`
    ap = argparse.ArgumentParser(
        prog="python -m distributedarrays_tpu.telemetry",
        description="Summarize a telemetry journal (JSONL).")
    ap.add_argument("journal", help="path to the JSONL journal "
                                    "(or '-' for stdin)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON")
    args = ap.parse_args(argv)
    try:
        return _cmd_summarize(args)
    except OSError as e:
        print(f"cannot read journal: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
