"""CLI: summarize a telemetry journal (JSONL) or report export (JSON).

Usage::

    python -m distributedarrays_tpu.telemetry JOURNAL.jsonl [--json]

Prints event counts by category, communication bytes by kind, and the
journal's time span.  ``--json`` emits the summary as JSON instead of the
text table.  The summarizer itself (``telemetry/summarize.py``) is pure
stdlib; running it via ``-m`` imports the parent package (JAX present),
so on a JAX-less machine import ``summarize.py`` directly instead.
"""

from __future__ import annotations

import argparse
import json
import sys

from .summarize import read_journal, summarize, format_summary


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributedarrays_tpu.telemetry",
        description="Summarize a telemetry journal (JSONL).")
    ap.add_argument("journal", help="path to the JSONL journal "
                                    "(or '-' for stdin)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON")
    args = ap.parse_args(argv)
    try:
        events = read_journal(sys.stdin if args.journal == "-"
                              else args.journal)
    except OSError as e:
        print(f"cannot read journal: {e}", file=sys.stderr)
        return 2
    s = summarize(events)
    if args.json:
        json.dump(s, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        format_summary(s, sys.stdout)
    return 0


if __name__ == "__main__":
    sys.exit(main())
