"""CLI: summarize or export a telemetry journal / report / bundle.

Usage::

    python -m distributedarrays_tpu.telemetry summarize RUN.jsonl [--json]
    python -m distributedarrays_tpu.telemetry trace RUN.jsonl [-o out.json]
    python -m distributedarrays_tpu.telemetry prom REPORT.json [-o out.prom]
    python -m distributedarrays_tpu.telemetry mem RUN.jsonl|REPORT.json [--json]
    python -m distributedarrays_tpu.telemetry postmortem BUNDLE.json [--json]
    python -m distributedarrays_tpu.telemetry doctor RUN.jsonl [--platform P]
        [--min-findings N] [--json]
    python -m distributedarrays_tpu.telemetry regress FRESH.json
        [--baseline DIR_OR_FILE ...] [--json] [--strict] [--explain]
    python -m distributedarrays_tpu.telemetry advise RUN.jsonl
        [--apply] [--json] [--platform P] [--min-actions N]
    python -m distributedarrays_tpu.telemetry incident RUN.jsonl [RUN2.jsonl
        ...] [--bundles DIR_OR_FILE ...] [--json] [--trace OUT.json]
        [--strict-bundles]
    python -m distributedarrays_tpu.telemetry flame RUN.jsonl [--min-frac F]
    python -m distributedarrays_tpu.telemetry flame --url http://AGG:PORT
    python -m distributedarrays_tpu.telemetry top --url http://AGG:PORT
        [--interval S] [--once] [--json]
    python -m distributedarrays_tpu.telemetry agg [--port 9900]
        [--p99-slo S] [--duration S]
    python -m distributedarrays_tpu.telemetry stream RUN.jsonl
        --agg http://AGG:PORT [--interval S] [--duration S]
    python -m distributedarrays_tpu.telemetry RUN.jsonl [--json]   # legacy

``summarize`` prints event counts by category (grouped per host when the
journal spans more than one), communication bytes by kind (eager vs
traced), span rollups, and top fallback keys; ``trace`` converts a
journal to Perfetto/Chrome trace-event JSON (open at ui.perfetto.dev) —
including an ``hbm_bytes`` counter track; ``prom`` renders a
``telemetry.dump()`` report — or, given a journal, the registry
reconstructed from it — in Prometheus text exposition format; ``mem``
renders the HBM-ledger view (live/peak bytes, per-device when given a
report, the alloc/free timeline reconstruction when given a journal);
``postmortem`` renders a flight-recorder bundle; ``doctor`` runs the
performance observatory (roofline classification of cost-stamped spans,
comm/compute overlap, the critical path, ranked findings — see
``telemetry/perf.py``); ``regress`` judges a fresh bench run against the
banked ``BENCH_r*`` trajectory with noise-aware thresholds and exits 1
on a significant slowdown (``telemetry/regress.py``); ``incident``
merges one or more per-host journals onto a single timeline and
reconstructs ordered incident reports from them plus any flight bundles
(``telemetry/cluster.py``) — ``--trace`` additionally writes the merged
Perfetto trace with incident flow arrows, and ``--strict-bundles``
exits 1 if any bundle or recovery attempt could not be attributed (the
CI orphan gate).  The live-plane commands (``docs/telemetry.md``):
``flame`` renders collapsed-stack flame format (Brendan Gregg style,
feed to flamegraph.pl or speedscope) from a journal's span self-times —
or, with ``--url``, the continuous sampling profile of a live
aggregator; ``top`` is the real-time cluster dashboard refreshing from
an aggregator's ``/snapshot``; ``agg`` runs the streaming aggregator
(POST ``/ingest``, Prometheus ``/metrics``, ``/healthz``,
``/snapshot``, ``/flame``, chunked Perfetto ``/trace``); ``stream`` is
the out-of-process exporter, tailing a journal file (rotation-aware)
and shipping bounded delta frames to an aggregator.  ``-`` reads
stdin.  The first form without a subcommand is the PR-1 interface and
behaves exactly like ``summarize``.

A missing or empty journal exits with a one-line message and status 2
instead of a traceback.  At the size cap journals now ROTATE to
``<path>.1`` (the ``incident``/``summarize`` readers pick the sibling up
automatically); a legacy ``journal.capped`` latch from an older writer
still exits 2 with the truncation details.

The converters (``summarize.py``, ``export.py``, ``memory.py``) are pure
stdlib; running via ``-m`` imports the parent package (JAX present), so
on a JAX-less machine import those modules directly instead.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys

from .export import to_perfetto, to_prometheus
from .summarize import read_journal, summarize, format_summary, _fmt_bytes


def _read_events(path: str) -> list[dict]:
    if path == "-":
        return read_journal(sys.stdin)
    events: list[dict] = []
    if os.path.exists(path + ".1"):
        # rotated sibling from the size cap: oldest generation first so
        # the timeline reads in order
        events.extend(read_journal(path + ".1"))
    events.extend(read_journal(path))
    return events


class _JournalUnusable(Exception):
    """One-line diagnostic; the CLI prints it and exits 2."""


def _check_events(events: list[dict], path: str) -> list[dict]:
    if not events:
        raise _JournalUnusable(f"journal is empty: {path}")
    cap = next((e for e in events
                if e.get("cat") == "journal" and e.get("name") == "capped"),
               None)
    if cap is not None:
        # legacy latch (pre-rotation writers, or a writer whose rotation
        # os.replace failed): the file is truncated, not rotated
        raise _JournalUnusable(
            f"journal is cap-truncated: {path} stopped at "
            f"{cap.get('bytes_written', '?')} bytes "
            f"(max {cap.get('max_bytes', '?')}; journal.capped at "
            f"t={cap.get('t', '?')}) — raise "
            f"DA_TPU_TELEMETRY_JOURNAL_MAX_MB and rerun "
            f"(current writers rotate to {path}.1 instead)")
    return events


def _read_events_checked(path: str) -> list[dict]:
    return _check_events(_read_events(path), path)


def _write_out(text: str, out_path: str | None) -> None:
    if out_path and out_path != "-":
        with open(out_path, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)


def _cmd_summarize(args) -> int:
    s = summarize(_read_events_checked(args.journal))
    if args.json:
        json.dump(s, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        format_summary(s, sys.stdout)
    return 0


def _cmd_trace(args) -> int:
    trace = to_perfetto(_read_events_checked(args.journal))
    _write_out(json.dumps(trace, indent=None if args.out else 2) + "\n",
               args.out)
    return 0


def _registry_from_journal(events: list[dict]) -> dict:
    """Rebuild a report-shaped registry from a journal so ``prom`` works
    on either input: comm kinds and span rollups survive; counters that
    never hit the journal (hot-path increments) do not."""
    s = summarize(events)
    return {
        "counters": {f"journal.events{{cat={c}}}": n
                     for c, n in s["by_category"].items()},
        "gauges": {}, "histograms": {},
        "comm": {"total_bytes": s["comm"]["total_bytes"],
                 "total_ops": s["comm"]["total_ops"],
                 "by_kind": s["comm"]["by_kind"]},
        "spans": {"by_name": {k: {"count": v["count"],
                                  "total_s": v["total_s"],
                                  "self_s": 0.0, "bytes": v["bytes"]}
                              for k, v in s["spans"].items()}},
        "memory": _mem_from_journal(events),
        "events": {"recorded": s["events"]},
    }


def _cmd_prom(args) -> int:
    raw = sys.stdin.read() if args.report == "-" else \
        open(args.report).read()
    try:
        doc = json.loads(raw)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "counters" in doc:
        registry = doc                      # a telemetry.dump() report
    else:                                   # a JSONL journal
        events = _check_events(read_journal(io.StringIO(raw)), args.report)
        registry = _registry_from_journal(events)
    _write_out(to_prometheus(registry), args.out)
    return 0


# ---------------------------------------------------------------------------
# mem: the HBM-ledger view
# ---------------------------------------------------------------------------


def _mem_from_journal(events: list[dict]) -> dict:
    """Reconstruct the ledger timeline from a journal's ``hbm`` events:
    final/peak live bytes, alloc/free counts, staging peaks per tag,
    and top allocation sites by bytes allocated."""
    live = peak = allocs = frees = 0
    staging_peak = 0
    staging_tags: dict[str, int] = {}
    sites: dict[str, dict] = {}
    for e in events:
        if e.get("cat") != "hbm":
            continue
        name = e.get("name")
        if e.get("live") is not None:
            live = int(e["live"])
            peak = max(peak, live)
        if name == "alloc":
            allocs += 1
            site = str(e.get("site") or "?")
            s = sites.setdefault(site, {"bytes": 0, "count": 0})
            s["bytes"] += int(e.get("bytes", 0) or 0)
            s["count"] += 1
        elif name == "free":
            frees += 1
        elif name == "staging":
            sl = int(e.get("staging_live", 0) or 0)
            staging_peak = max(staging_peak, sl)
            tag = str(e.get("tag") or "?")
            staging_tags[tag] = max(staging_tags.get(tag, 0), sl)
    return {
        "live_bytes": live, "peak_bytes": peak,
        "allocs": allocs, "frees": frees,
        "staging": {"peak_bytes": staging_peak,
                    "peak_by_tag": dict(sorted(staging_tags.items()))},
        "top_sites": sorted(([k, v["bytes"], v["count"]]
                             for k, v in sites.items()),
                            key=lambda kv: -kv[1])[:10],
    }


def _format_mem(mem: dict, out) -> None:
    out.write(f"hbm live:  {_fmt_bytes(mem.get('live_bytes', 0))}\n")
    out.write(f"hbm peak:  {_fmt_bytes(mem.get('peak_bytes', 0))}\n")
    if "tracked_arrays" in mem:
        out.write(f"tracked arrays: {mem['tracked_arrays']}\n")
    if "allocs" in mem:
        out.write(f"allocs/frees:   {mem['allocs']}/{mem['frees']}\n")
    by_dev = mem.get("by_device") or {}
    if by_dev:
        out.write("per device:\n")
        for dev, d in sorted(by_dev.items()):
            out.write(f"  dev {dev:<6} live {_fmt_bytes(d['live_bytes']):>12}"
                      f"  peak {_fmt_bytes(d['peak_bytes']):>12}\n")
    st = mem.get("staging") or {}
    if st:
        out.write(f"staging peak: {_fmt_bytes(st.get('peak_bytes', 0))}\n")
        for tag, v in (st.get("peak_by_tag") or {}).items():
            out.write(f"  {tag:<28} {_fmt_bytes(v)}\n")
    sites = mem.get("top_sites") or []
    if sites:
        out.write("top allocation sites:\n")
        for site, b, n in sites:
            out.write(f"  {site:<28} {n:>5} x  {_fmt_bytes(b)}\n")


def _cmd_mem(args) -> int:
    raw = sys.stdin.read() if args.input == "-" else open(args.input).read()
    try:
        doc = json.loads(raw)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "memory" in doc:
        mem = doc["memory"]                  # a telemetry.dump() report
    elif isinstance(doc, dict) and "live_bytes" in doc:
        mem = doc                            # a bare memory section
    else:                                    # a JSONL journal
        events = _check_events(read_journal(io.StringIO(raw)), args.input)
        mem = _mem_from_journal(events)
    if args.json:
        json.dump(mem, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        _format_mem(mem, sys.stdout)
    return 0


# ---------------------------------------------------------------------------
# doctor: the performance observatory
# ---------------------------------------------------------------------------


def _cmd_doctor(args) -> int:
    from . import perf
    events = _read_events_checked(args.journal)
    analysis = perf.analyze(events, platform=args.platform)
    if args.json:
        json.dump(analysis, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        perf.format_analysis(analysis, sys.stdout)
    if args.min_findings and len(analysis["findings"]) < args.min_findings:
        print(f"doctor: {len(analysis['findings'])} finding(s), "
              f"required at least {args.min_findings}", file=sys.stderr)
        return 2
    return 0


# ---------------------------------------------------------------------------
# regress: the bench regression sentinel
# ---------------------------------------------------------------------------


def _cmd_regress(args) -> int:
    from . import regress as rg
    try:
        with open(args.fresh) as f:
            fresh_doc = json.load(f)
    except ValueError:
        print(f"regress: not JSON: {args.fresh}", file=sys.stderr)
        return 2
    row = fresh_doc.get("parsed") if isinstance(fresh_doc, dict) and \
        isinstance(fresh_doc.get("parsed"), dict) else fresh_doc
    if isinstance(row, dict) and rg.is_replay(row):
        # a replay is the OLD number wearing a new timestamp — judging it
        # would always pass; say so loudly and judge nothing
        print(f"SKIPPED: {args.fresh} is a replayed row, not a fresh "
              "measurement — nothing to judge", file=sys.stdout)
        return 2 if args.strict else 0
    fresh = rg.load_rows(args.fresh)
    if not fresh:
        print(f"regress: no judgeable metrics in {args.fresh}",
              file=sys.stderr)
        return 2
    baseline = rg.load_baseline(args.baseline or ["."])
    if not any(baseline.values()):
        # an empty or all-replay bank is not a baseline: every banked row
        # was itself a replay of an older number, so there is no live
        # trajectory to judge drift against
        print("NO_LIVE_TRAJECTORY: banked baseline has no live "
              "measurements (empty or all replays) — nothing to judge "
              "against", file=sys.stdout)
        return 2 if args.strict else 0
    results = rg.compare(fresh, baseline, mad_k=args.mad_k,
                         rel_floor=args.rel_floor)
    if args.json:
        json.dump({"results": results}, sys.stdout, indent=2,
                  sort_keys=True)
        sys.stdout.write("\n")
    else:
        rg.format_results(results, sys.stdout, explain=args.explain)
    judged = [r for r in results if r["status"] != "skipped"]
    if not judged:
        print("regress: no metric had a banked baseline to judge "
              "against", file=sys.stderr)
        return 2 if args.strict else 0
    if any(r["status"] == "regression" for r in judged):
        return 1
    return 0


# ---------------------------------------------------------------------------
# advise: doctor findings -> guarded autotune writes
# ---------------------------------------------------------------------------


def _cmd_advise(args) -> int:
    from . import advisor, perf
    events = _read_events_checked(args.journal)
    analysis = perf.analyze(events, platform=args.platform)
    actions = advisor.advise(analysis)
    if args.max_actions:
        actions = actions[:args.max_actions]
    results = None
    if args.apply and actions:
        results = advisor.apply(actions, repeats=args.repeats,
                                mad_k=args.mad_k,
                                rel_floor=args.rel_floor,
                                persist=not args.no_persist)
    if args.json:
        json.dump({"actions": [a.to_dict() for a in actions],
                   "results": results}, sys.stdout, indent=2,
                  sort_keys=True)
        sys.stdout.write("\n")
    else:
        advisor.format_results(actions, results, sys.stdout)
    if args.min_actions and len(actions) < args.min_actions:
        print(f"advise: {len(actions)} action(s), required at least "
              f"{args.min_actions}", file=sys.stderr)
        return 2
    return 0


# ---------------------------------------------------------------------------
# incident: cross-host merge + causal incident reconstruction
# ---------------------------------------------------------------------------


def _cmd_incident(args) -> int:
    from . import cluster
    per_host: list[list[dict]] = []
    for path in args.journals:
        evs = _check_events(_read_events(path), path)
        per_host.append(evs)
    merged = cluster.merge_journals(per_host, slack_s=args.slack)
    try:
        bundles = cluster.load_bundles(args.bundles or [])
    except ValueError as e:
        print(f"incident: {e}", file=sys.stderr)
        return 2
    report = cluster.reconstruct_incidents(merged, bundles,
                                           slack_s=args.slack)
    if args.trace:
        trace = cluster.incident_trace(merged, report)
        with open(args.trace, "w") as f:
            json.dump(trace, f)
        print(f"merged trace with incident flows -> {args.trace}",
              file=sys.stderr)
    if args.json:
        json.dump(report, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
    else:
        cluster.format_incidents(report, sys.stdout)
    if args.strict_bundles and (report["bundles_unattributed"]
                                or report["unattributed_recovery_events"]):
        print(f"incident: {len(report['bundles_unattributed'])} orphaned "
              f"bundle(s), {report['unattributed_recovery_events']} "
              f"unattributed recovery event(s) — reconstruction is "
              f"incomplete", file=sys.stderr)
        return 1
    return 0


# ---------------------------------------------------------------------------
# postmortem: render a flight-recorder bundle
# ---------------------------------------------------------------------------


def _cmd_postmortem(args) -> int:
    raw = sys.stdin.read() if args.bundle == "-" else open(args.bundle).read()
    try:
        b = json.loads(raw)
    except ValueError:
        print(f"not a postmortem bundle (invalid JSON): {args.bundle}",
              file=sys.stderr)
        return 2
    if not isinstance(b, dict) or b.get("kind") != "da_tpu_postmortem":
        print(f"not a postmortem bundle: {args.bundle}", file=sys.stderr)
        return 2
    if args.json:
        json.dump(b, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
        return 0
    out = sys.stdout
    out.write(f"postmortem: {b.get('reason')}  "
              f"(host {b.get('host')}, pid {b.get('pid')}, "
              f"t={b.get('t')}s)\n")
    exc = b.get("exception")
    if exc:
        out.write(f"exception: {exc.get('type')}: "
                  f"{str(exc.get('message', ''))[:500]}\n")
    opens = b.get("open_spans") or []
    out.write(f"\nopen spans at crash ({len(opens)}):\n")
    for s in opens:
        out.write(f"  {s.get('name'):<28} id={s.get('span_id')} "
                  f"tname={s.get('tname')}\n")
    _format_mem(b.get("ledger") or {}, out)
    census = b.get("registry_census") or {}
    out.write(f"\nregistry census: {census.get('live', '?')} live arrays\n")
    leak = b.get("leak_census") or {}
    for klass in ("ledger_tracked", "untracked_foreign",
                  "deleted_but_registered"):
        c = leak.get(klass) or {}
        out.write(f"  {klass:<24} {c.get('count', 0):>5} x  "
                  f"{_fmt_bytes(c.get('bytes', 0))}\n")
    div = b.get("divergence") or []
    if div:
        out.write(f"\ndivergence events ({len(div)}):\n")
        for e in div[-5:]:
            out.write(f"  t={e.get('t')} {e.get('why', '')[:120]}\n")
    ring = b.get("ring") or []
    out.write(f"\nevent ring tail ({len(ring)} events, last 10):\n")
    for e in ring[-10:]:
        out.write(f"  t={e.get('t')} {e.get('cat')}/{e.get('name')}\n")
    return 0


# ---------------------------------------------------------------------------
# live plane: flame / top / agg / stream
# ---------------------------------------------------------------------------


def _http_get(url: str, path: str, timeout: float = 5.0) -> bytes:
    import urllib.request
    base = url.rstrip("/")
    if not base.startswith("http://") and not base.startswith("https://"):
        base = "http://" + base
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return r.read()


def _cmd_flame(args) -> int:
    from . import stream as _stream
    if args.url:
        try:
            text = _http_get(args.url, "/flame").decode()
        except OSError as e:
            print(f"cannot reach aggregator {args.url}: {e}",
                  file=sys.stderr)
            return 2
        _write_out(text if text.endswith("\n") or not text else text + "\n",
                   args.out)
        return 0
    if not args.journal:
        print("flame: need a journal path or --url", file=sys.stderr)
        return 2
    events = _read_events_checked(args.journal)
    counts, stats = _stream.collapsed_from_events(events)
    if args.json:
        _write_out(json.dumps({"counts": counts, "stats": stats},
                              indent=2, sort_keys=True) + "\n", args.out)
    else:
        text = _stream.collapsed_lines(counts)
        _write_out(text + "\n" if text else "", args.out)
        print(f"flame: {stats['spans']} spans, "
              f"{stats['attributed_s']:.3f}s attributed / "
              f"{stats['wall_s']:.3f}s wall "
              f"({stats['attributed_frac']:.1%})", file=sys.stderr)
    if args.min_frac and stats["attributed_frac"] < args.min_frac:
        print(f"flame attribution {stats['attributed_frac']:.1%} below "
              f"--min-frac {args.min_frac:.1%}", file=sys.stderr)
        return 2
    return 0


def _fmt_ms(v) -> str:
    return "-" if v is None else f"{float(v) * 1e3:8.1f}"


def _render_top(snap: dict) -> str:
    out = io.StringIO()
    hosts = snap.get("hosts") or {}
    out.write(f"da-tpu top — {len(hosts)} host(s), "
              f"uptime {snap.get('uptime_s', 0)}s, "
              f"{snap.get('frames_ingested', 0)} frames ingested\n\n")
    hdr = (f"{'HOST':<20} {'AGE':>5} {'HBM LIVE':>10} {'PEAK':>10} "
           f"{'DEV':>4} {'P99 ms':>8} {'SHED':>6} {'STEP s':>8} "
           f"{'DROP':>5} {'EVTS':>7}")
    out.write(hdr + "\n")
    for key in sorted(hosts):
        h = hosts[key]
        age = h.get("age_s")
        age_s = "-" if age is None else f"{age:.1f}"
        if h.get("stale"):
            age_s += "!"
        shed = h.get("shed_fraction")
        step = h.get("train_step_s")
        dev = h.get("live_devices")
        drops = (int(h.get("dropped_frames") or 0)
                 + int(h.get("lost_frames") or 0))
        shed_s = f"{shed:.1%}" if shed is not None else "-"
        step_s = f"{step:.3f}" if step is not None else "-"
        dev_s = str(dev) if dev is not None else "-"
        out.write(
            f"{key:<20} {age_s:>5} "
            f"{_fmt_bytes(h.get('hbm_live_bytes') or 0):>10} "
            f"{_fmt_bytes(h.get('hbm_peak_bytes') or 0):>10} "
            f"{dev_s:>4} {_fmt_ms(h.get('serve_p99_s')):>8} "
            f"{shed_s:>6} {step_s:>8} "
            f"{drops:>5} {h.get('events', 0):>7}\n")
    alerts = snap.get("alerts") or []
    out.write(f"\nalerts firing: "
              f"{', '.join(sorted(alerts)) if alerts else 'none'}\n")
    incidents = snap.get("incidents") or []
    if incidents:
        out.write(f"open incidents: {', '.join(incidents)}\n")
    return out.getvalue()


def _cmd_top(args) -> int:
    import time as _time

    def _snap():
        return json.loads(_http_get(args.url, "/snapshot").decode())

    try:
        snap = _snap()
    except OSError as e:
        print(f"cannot reach aggregator {args.url}: {e}", file=sys.stderr)
        return 2
    except ValueError:
        print(f"aggregator {args.url} returned non-JSON snapshot "
              f"(telemetry disabled on the aggregator?)", file=sys.stderr)
        return 2
    if args.json:
        json.dump(snap, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
        return 0
    if args.once:
        sys.stdout.write(_render_top(snap))
        return 0
    try:
        while True:
            # home + clear-to-end keeps the repaint flicker-free without
            # pulling in curses
            sys.stdout.write("\x1b[H\x1b[2J" + _render_top(snap))
            sys.stdout.flush()
            _time.sleep(max(0.1, args.interval))
            try:
                snap = _snap()
            except (OSError, ValueError):
                sys.stdout.write("\n(aggregator unreachable — retrying)\n")
                sys.stdout.flush()
    except KeyboardInterrupt:
        return 0


def _cmd_agg(args) -> int:
    import time as _time
    from . import core as _core
    from . import agg as _agg
    if not _core.enabled():
        print("telemetry is disabled (DA_TPU_TELEMETRY=0): "
              "aggregator refusing to start", file=sys.stderr)
        return 2
    srv = _agg.serve(host=args.host, port=args.port,
                     advertise=not args.no_advertise,
                     eval_interval_s=args.eval_interval,
                     p99_slo_s=args.p99_slo)
    print(f"aggregator listening on {srv.url}", file=sys.stderr)
    print(f"  POST {srv.url}/ingest     (exporter frames)", file=sys.stderr)
    print(f"  GET  {srv.url}/metrics    (Prometheus scrape)",
          file=sys.stderr)
    print(f"  GET  {srv.url}/healthz /snapshot /flame /trace",
          file=sys.stderr)
    try:
        if args.duration:
            _time.sleep(args.duration)
        else:
            while True:
                _time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        srv.close()
    return 0


def _cmd_stream(args) -> int:
    import time as _time
    from . import core as _core
    from . import stream as _stream
    if not _core.enabled():
        print("telemetry is disabled (DA_TPU_TELEMETRY=0): "
              "exporter refusing to start", file=sys.stderr)
        return 2
    if not os.path.exists(args.journal):
        print(f"cannot read journal: {args.journal}", file=sys.stderr)
        return 2
    exp = _stream.StreamExporter(args.agg, interval_s=args.interval,
                                 ring_frames=args.ring,
                                 journal=args.journal)
    exp.start()
    print(f"streaming {args.journal} -> {args.agg} "
          f"every {args.interval}s (ring {args.ring} frames)",
          file=sys.stderr)
    try:
        if args.duration:
            _time.sleep(args.duration)
        else:
            while True:
                _time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        exp.stop()
    st = exp.stats_dict()
    print(f"stream: {st['frames_sent']} frames sent, "
          f"{st['frames_dropped']} dropped, "
          f"{st['events_shipped']} events shipped, "
          f"{st['events_dropped']} events dropped, "
          f"{st['send_errors']} send errors", file=sys.stderr)
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("summarize", "trace", "prom", "mem",
                            "postmortem", "doctor", "regress", "incident",
                            "advise", "flame", "top", "agg", "stream"):
        ap = argparse.ArgumentParser(
            prog="python -m distributedarrays_tpu.telemetry",
            description="Summarize or export a telemetry journal/report.")
        sub = ap.add_subparsers(dest="cmd", required=True)
        p = sub.add_parser("summarize", help="journal -> text/JSON summary")
        p.add_argument("journal", help="JSONL journal path ('-' = stdin)")
        p.add_argument("--json", action="store_true",
                       help="emit the summary as JSON")
        p.set_defaults(fn=_cmd_summarize)
        p = sub.add_parser("trace",
                           help="journal -> Perfetto trace-event JSON")
        p.add_argument("journal", help="JSONL journal path ('-' = stdin)")
        p.add_argument("-o", "--out", default=None,
                       help="output path (default stdout)")
        p.set_defaults(fn=_cmd_trace)
        p = sub.add_parser("prom",
                           help="report JSON (telemetry.dump) or journal "
                                "-> Prometheus text exposition")
        p.add_argument("report", help="report/journal path ('-' = stdin)")
        p.add_argument("-o", "--out", default=None,
                       help="output path (default stdout)")
        p.set_defaults(fn=_cmd_prom)
        p = sub.add_parser("mem",
                           help="HBM ledger view of a journal or report")
        p.add_argument("input", help="journal/report path ('-' = stdin)")
        p.add_argument("--json", action="store_true",
                       help="emit the memory section as JSON")
        p.set_defaults(fn=_cmd_mem)
        p = sub.add_parser("postmortem",
                           help="render a flight-recorder bundle")
        p.add_argument("bundle", help="bundle path ('-' = stdin)")
        p.add_argument("--json", action="store_true",
                       help="re-emit the bundle as JSON")
        p.set_defaults(fn=_cmd_postmortem)
        p = sub.add_parser("doctor",
                           help="journal -> roofline/overlap/critical-path"
                                " findings")
        p.add_argument("journal", help="JSONL journal path ('-' = stdin)")
        p.add_argument("--platform", default=None,
                       help="peak-table platform (v5e/v5p/cpu; default "
                            "cpu, DA_TPU_PEAKS overrides values)")
        p.add_argument("--min-findings", type=int, default=0,
                       help="exit 2 unless at least N findings (CI gate)")
        p.add_argument("--json", action="store_true",
                       help="emit the full analysis as JSON")
        p.set_defaults(fn=_cmd_doctor)
        p = sub.add_parser("regress",
                           help="judge a fresh bench row/table against "
                                "the banked BENCH_r* trajectory")
        p.add_argument("fresh", help="fresh bench row / BENCH_r wrapper / "
                                     "details table (JSON)")
        p.add_argument("--baseline", action="append", default=None,
                       help="baseline dir (BENCH_r*.json scanned) or "
                            "file; repeatable; default '.'")
        p.add_argument("--mad-k", type=float, default=3.0,
                       help="MAD multiplier for the noise threshold")
        p.add_argument("--rel-floor", type=float, default=0.15,
                       help="relative degradation floor")
        p.add_argument("--strict", action="store_true",
                       help="exit 2 when nothing could be judged")
        p.add_argument("--explain", action="store_true",
                       help="print the per-metric median/MAD baseline "
                            "and direction next to each verdict")
        p.add_argument("--json", action="store_true",
                       help="emit results as JSON")
        p.set_defaults(fn=_cmd_regress)
        p = sub.add_parser("advise",
                           help="doctor findings -> tuning actions; "
                                "--apply executes them under the "
                                "micro-probe rollback guard")
        p.add_argument("journal", help="JSONL journal path ('-' = stdin)")
        p.add_argument("--platform", default=None,
                       help="peak-table platform for the doctor pass")
        p.add_argument("--apply", action="store_true",
                       help="write the proposals (provenance-stamped), "
                            "micro-probe before/after, auto-roll-back "
                            "regressions")
        p.add_argument("--repeats", type=int, default=3,
                       help="micro-probe samples per side (default 3)")
        p.add_argument("--mad-k", type=float, default=3.0,
                       help="MAD multiplier for the rollback threshold")
        p.add_argument("--rel-floor", type=float, default=0.15,
                       help="relative regression floor for rollback")
        p.add_argument("--max-actions", type=int, default=0,
                       help="cap the number of actions taken (0 = all)")
        p.add_argument("--min-actions", type=int, default=0,
                       help="exit 2 unless at least N actions (CI gate)")
        p.add_argument("--no-persist", action="store_true",
                       help="keep applied tunes in-process only (default "
                            "persists to the autotune cache file)")
        p.add_argument("--json", action="store_true",
                       help="emit actions + apply results as JSON")
        p.set_defaults(fn=_cmd_advise)
        p = sub.add_parser("incident",
                           help="merge per-host journals and reconstruct "
                                "ordered incident reports")
        p.add_argument("journals", nargs="+",
                       help="per-host JSONL journal paths ('-' = stdin); "
                            "rotated <path>.1 siblings read automatically")
        p.add_argument("--bundles", action="append", default=None,
                       help="flight-bundle file or directory (scanned for "
                            "*.json postmortems); repeatable")
        p.add_argument("--trace", default=None, metavar="OUT.json",
                       help="also write the merged Perfetto trace with "
                            "incident flow arrows")
        p.add_argument("--slack", type=float, default=5.0,
                       help="seconds of window slack for attributing "
                            "unstamped events/bundles (default 5)")
        p.add_argument("--strict-bundles", action="store_true",
                       help="exit 1 if any bundle or recovery attempt "
                            "is unattributed (CI orphan gate)")
        p.add_argument("--json", action="store_true",
                       help="emit the incident report as JSON")
        p.set_defaults(fn=_cmd_incident)
        p = sub.add_parser("flame",
                           help="journal (or live aggregator) -> "
                                "collapsed-stack flame format")
        p.add_argument("journal", nargs="?", default=None,
                       help="JSONL journal path ('-' = stdin); omit "
                            "with --url")
        p.add_argument("--url", default=None,
                       help="fetch the live flame profile from an "
                            "aggregator instead of a journal")
        p.add_argument("-o", "--out", default=None,
                       help="output path (default stdout)")
        p.add_argument("--min-frac", type=float, default=0.0,
                       help="exit 2 unless at least this fraction of "
                            "wall time is attributed (CI gate; journal "
                            "mode only)")
        p.add_argument("--json", action="store_true",
                       help="emit counts + attribution stats as JSON")
        p.set_defaults(fn=_cmd_flame)
        p = sub.add_parser("top",
                           help="live terminal dashboard refreshing "
                                "from an aggregator")
        p.add_argument("--url", required=True,
                       help="aggregator base URL (telemetry agg prints "
                            "it)")
        p.add_argument("--interval", type=float, default=1.0,
                       help="refresh interval seconds (default 1)")
        p.add_argument("--once", action="store_true",
                       help="render one frame and exit (no screen "
                            "clearing; scripts/tests)")
        p.add_argument("--json", action="store_true",
                       help="dump the raw snapshot JSON once and exit")
        p.set_defaults(fn=_cmd_top)
        p = sub.add_parser("agg",
                           help="run the streaming aggregator "
                                "(ingest/metrics/healthz/flame/trace)")
        p.add_argument("--host", default="127.0.0.1",
                       help="bind address (default 127.0.0.1)")
        p.add_argument("--port", type=int, default=9900,
                       help="bind port (default 9900; 0 = ephemeral)")
        p.add_argument("--p99-slo", type=float, default=0.5,
                       help="serve p99 SLO seconds for the live alert "
                            "rules (default 0.5)")
        p.add_argument("--eval-interval", type=float, default=0.5,
                       help="alert evaluation interval seconds")
        p.add_argument("--duration", type=float, default=0.0,
                       help="exit after N seconds (0 = run until ^C)")
        p.add_argument("--no-advertise", action="store_true",
                       help="skip publishing the URL to the multihost "
                            "coordination KV")
        p.set_defaults(fn=_cmd_agg)
        p = sub.add_parser("stream",
                           help="external exporter: tail a journal file "
                                "and stream frames to an aggregator")
        p.add_argument("journal", help="JSONL journal path to tail")
        p.add_argument("--agg", required=True,
                       help="aggregator base URL")
        p.add_argument("--interval", type=float, default=0.5,
                       help="frame interval seconds (default 0.5)")
        p.add_argument("--ring", type=int, default=256,
                       help="bounded frame-ring capacity (default 256)")
        p.add_argument("--duration", type=float, default=0.0,
                       help="exit after N seconds (0 = run until ^C)")
        p.set_defaults(fn=_cmd_stream)
        args = ap.parse_args(argv)
        try:
            return args.fn(args)
        except _JournalUnusable as e:
            print(str(e), file=sys.stderr)
            return 2
        except OSError as e:
            print(f"cannot read input: {e}", file=sys.stderr)
            return 2
    # legacy interface: bare journal path == `summarize`
    ap = argparse.ArgumentParser(
        prog="python -m distributedarrays_tpu.telemetry",
        description="Summarize a telemetry journal (JSONL).")
    ap.add_argument("journal", help="path to the JSONL journal "
                                    "(or '-' for stdin)")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON")
    args = ap.parse_args(argv)
    try:
        return _cmd_summarize(args)
    except _JournalUnusable as e:
        print(str(e), file=sys.stderr)
        return 2
    except OSError as e:
        print(f"cannot read journal: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
