"""Journal summarization: turn a JSONL event journal into a compact
human/machine summary.

Shared by the CLI (``python -m distributedarrays_tpu.telemetry``) and by
tests; pure stdlib so it can run on a machine without JAX (e.g. pulling a
journal off a pod worker and summarizing it on a laptop).
"""

from __future__ import annotations

import json
from typing import Iterable, TextIO

__all__ = ["read_journal", "summarize", "format_summary"]


def read_journal(path_or_file) -> list[dict]:
    """Parse a JSONL journal.  Malformed lines are skipped and counted
    (a process killed mid-write leaves a torn final line; that must not
    make the whole journal unreadable)."""
    if hasattr(path_or_file, "read"):
        lines: Iterable[str] = path_or_file
    else:
        with open(path_or_file) as f:
            lines = f.readlines()
    events, skipped = [], 0
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if isinstance(ev, dict):
            events.append(ev)
        else:
            skipped += 1
    if skipped:
        events.append({"cat": "_journal", "name": "malformed_lines",
                       "count": skipped})
    return events


def summarize(events: list[dict]) -> dict:
    """Aggregate a journal event list into the summary dict the CLI
    prints: counts by category and by (category, name), communication
    bytes/ops by kind — split into trace-time (``traced: true``) vs
    eager records — fallback hits by key, tracing-span rollups, and the
    monotonic time span covered."""
    by_cat: dict[str, int] = {}
    by_name: dict[str, int] = {}
    comm: dict[str, dict] = {}
    fallbacks: dict[str, int] = {}
    spans: dict[str, dict] = {}
    incidents: set = set()
    alerts: dict[str, int] = {}
    tuning: list[dict] = []
    # per-host rollups: multihost journals are merged by concatenation
    # (every event carries host/pid), so the summary re-groups them
    by_host: dict[str, dict] = {}
    tmin = tmax = None
    for e in events:
        cat = str(e.get("cat", "?"))
        by_cat[cat] = by_cat.get(cat, 0) + 1
        host = e.get("host")
        if host is not None:
            h = by_host.setdefault(str(host), {"events": 0, "comm_bytes": 0,
                                               "by_category": {}})
            h["events"] += 1
            h["by_category"][cat] = h["by_category"].get(cat, 0) + 1
            if cat == "comm":
                h["comm_bytes"] += int(e.get("bytes", 0) or 0)
        name = e.get("name")
        if name is not None:
            k = f"{cat}/{name}"
            by_name[k] = by_name.get(k, 0) + 1
        inc = e.get("incident")
        if inc:
            incidents.add(str(inc))
        if cat == "alert" and name is not None:
            ak = f"{name}:{e.get('state', '?')}"
            alerts[ak] = alerts.get(ak, 0) + 1
        if cat == "autotune" and name in ("advise", "undo"):
            # the advisor's provenance trail: what was written, from
            # which finding, and whether the micro-probe kept it
            tuning.append({k: e.get(k) for k in
                           ("name", "kernel", "key", "kind", "finding",
                            "old", "new", "status", "reason")
                           if e.get(k) is not None})
        if cat == "comm":
            kind = str(name)
            c = comm.setdefault(kind, {"ops": 0, "bytes": 0,
                                       "traced_ops": 0, "traced_bytes": 0,
                                       "eager_ops": 0, "eager_bytes": 0})
            b = int(e.get("bytes", 0) or 0)
            c["ops"] += 1
            c["bytes"] += b
            leg = "traced" if e.get("traced") else "eager"
            c[leg + "_ops"] += 1
            c[leg + "_bytes"] += b
        elif cat == "fallback" and name is not None:
            fallbacks[str(name)] = fallbacks.get(str(name), 0) + 1
        elif cat == "span" and name is not None:
            s = spans.setdefault(str(name),
                                 {"count": 0, "total_s": 0.0, "bytes": 0})
            s["count"] += 1
            s["total_s"] += float(e.get("dur", 0.0) or 0.0)
            # own + rolled-up child bytes: descendant comm may have landed
            # on aggregate-only child spans that never reach the journal
            s["bytes"] += int(e.get("bytes", 0) or 0) + \
                int(e.get("child_bytes", 0) or 0)
        t = e.get("t")
        if isinstance(t, (int, float)):
            tmin = t if tmin is None else min(tmin, t)
            tmax = t if tmax is None else max(tmax, t)
    for s in spans.values():
        s["total_s"] = round(s["total_s"], 6)
    return {
        "events": len(events),
        "hosts": sorted(by_host),
        "by_host": dict(sorted(by_host.items())),
        "span_s": round(tmax - tmin, 6) if tmin is not None else 0.0,
        "by_category": dict(sorted(by_cat.items())),
        "by_name": dict(sorted(by_name.items())),
        "comm": {
            "total_bytes": sum(c["bytes"] for c in comm.values()),
            "total_ops": sum(c["ops"] for c in comm.values()),
            "traced_bytes": sum(c["traced_bytes"] for c in comm.values()),
            "eager_bytes": sum(c["eager_bytes"] for c in comm.values()),
            "by_kind": dict(sorted(comm.items())),
        },
        "fallbacks": dict(sorted(fallbacks.items(),
                                 key=lambda kv: (-kv[1], kv[0]))),
        "spans": dict(sorted(spans.items())),
        "incidents": sorted(incidents),
        "alerts": dict(sorted(alerts.items())),
        "tuning": tuning,
    }


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{n} B"
        n /= 1024
    return f"{n} B"  # pragma: no cover


def format_summary(summary: dict, out: TextIO) -> None:
    """Render :func:`summarize`'s dict as an aligned text table."""
    out.write(f"events: {summary['events']}  "
              f"(span {summary['span_s']:.3f}s)\n")
    hosts = summary.get("hosts") or []
    if len(hosts) > 1:
        # merged multihost journal: group the tables per host first
        out.write(f"\nhosts ({len(hosts)}):\n")
        for host in hosts:
            h = summary["by_host"][host]
            cats = ", ".join(f"{c}={n}" for c, n in
                             sorted(h["by_category"].items()))
            out.write(f"  {host:<24} {h['events']:>7} events  "
                      f"{_fmt_bytes(h['comm_bytes'])} comm  [{cats}]\n")
    incidents = summary.get("incidents") or []
    if incidents:
        out.write(f"\nincidents ({len(incidents)}): "
                  f"{', '.join(incidents)}\n")
        out.write("  (reconstruct: python -m distributedarrays_tpu"
                  ".telemetry incident <journal...>)\n")
    alerts = summary.get("alerts") or {}
    if alerts:
        out.write("\nalert transitions:\n")
        for key, n in alerts.items():
            out.write(f"  {key:<40} {n}\n")
    tuning = summary.get("tuning") or []
    if tuning:
        out.write("\ntuning provenance (advisor writes):\n")
        for t in tuning:
            label = t.get("status") or t.get("name") or "?"
            out.write(f"  {label.upper():<12} "
                      f"{t.get('kernel', '?')}[{t.get('key', '?')}]: "
                      f"{t.get('old')} -> {t.get('new')}"
                      + (f"  ({t['finding']})" if t.get("finding")
                         else "") + "\n")
            if t.get("reason"):
                out.write(f"               {t['reason']}\n")
    out.write("\nby category:\n")
    for cat, n in summary["by_category"].items():
        out.write(f"  {cat:<16} {n}\n")
    comm = summary["comm"]
    out.write(f"\ncommunication (estimated): "
              f"{_fmt_bytes(comm['total_bytes'])} over "
              f"{comm['total_ops']} ops")
    if comm.get("traced_bytes") or comm.get("eager_bytes"):
        out.write(f"  (eager {_fmt_bytes(comm.get('eager_bytes', 0))}, "
                  f"traced {_fmt_bytes(comm.get('traced_bytes', 0))})")
    out.write("\n")
    for kind, c in comm["by_kind"].items():
        out.write(f"  {kind:<20} {c['ops']:>6} ops  "
                  f"{_fmt_bytes(c['bytes'])}")
        if "eager_bytes" in c:
            out.write(f"  [eager {_fmt_bytes(c['eager_bytes'])}, "
                      f"traced {_fmt_bytes(c['traced_bytes'])}]")
        out.write("\n")
    spans = summary.get("spans") or {}
    if spans:
        out.write("\nspans (journaled):\n")
        top_spans = sorted(spans.items(),
                           key=lambda kv: -kv[1]["total_s"])[:20]
        for name, s in top_spans:
            out.write(f"  {name:<28} {s['count']:>6} x  "
                      f"{s['total_s']:>10.4f}s  {_fmt_bytes(s['bytes'])}\n")
    fallbacks = summary.get("fallbacks") or {}
    if fallbacks:
        out.write("\ntop fallback keys:\n")
        for key, n in list(fallbacks.items())[:5]:
            out.write(f"  {key:<40} {n}\n")
    out.write("\ntop events:\n")
    top = sorted(summary["by_name"].items(), key=lambda kv: -kv[1])[:20]
    for name, n in top:
        out.write(f"  {name:<40} {n}\n")
