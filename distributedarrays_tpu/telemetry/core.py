"""Telemetry core: the process-wide metrics registry and event journal.

The reference package has no observability at all (SURVEY.md §5:
"Tracing/profiling: none — only commented-out println debugging");
``utils/profiling.py`` wraps the platform profiler but cannot answer
framework-level questions — how many bytes did this workload move, how
many reshards/retraces/fallbacks did it take?  This module is the answer:

- **metrics registry** — process-wide, thread-safe counters, gauges, and
  summary histograms, keyed by name plus optional labels.  When telemetry
  is disabled (``DA_TPU_TELEMETRY=0`` or :func:`disable`) every recording
  call is a single boolean check and an immediate return — no locks, no
  allocation — so instrumentation can stay in hot paths unconditionally.
- **communication accounting** — :func:`record_comm` is the one funnel
  every instrumented communication site goes through (reshards, eager
  transfers, traced collectives, SPMD mailbox sends, multihost gathers).
  It feeds per-kind op/byte counters and the journal.
- **event journal** — an append-only, bounded in-memory buffer of
  structured events with *monotonic* timestamps, mirrored to an
  append-only JSONL file when a journal path is configured
  (``DA_TPU_TELEMETRY_JOURNAL`` or :func:`configure`).  The file is
  created lazily on the first event, so a disabled process never touches
  the filesystem.

Byte numbers are documented **estimates** (payload sizes at the recording
site), not link-level measurements; traced collectives record at *trace*
time (once per compilation), flagged with ``traced=True``.

This module deliberately imports nothing from the rest of the package
(stdlib only), so any layer — layout, darray, ops, parallel, utils — can
import it without cycles.
"""

from __future__ import annotations

import contextvars
import json
import os
import threading
import time
from collections import deque

__all__ = [
    "enabled", "enable", "disable", "configure", "reset",
    "count", "set_gauge", "observe", "event", "record_comm",
    "counter_value", "gauge_value", "comm_bytes", "events",
    "journal_path", "nbytes_of", "report", "dump",
    "register_report_section", "register_reset_hook",
    "begin_incident", "current_incident", "end_incident",
]

_FALSY = ("0", "false", "off", "no")


def _env_enabled() -> bool:
    v = os.environ.get("DA_TPU_TELEMETRY")
    return v is None or v.strip().lower() not in _FALSY


_LOCK = threading.RLock()
_ENABLED: bool = _env_enabled()

_counters: dict[str, float] = {}
_gauges: dict[str, float] = {}
_hists: dict[str, dict] = {}
# comm accounting: kind -> {"ops": n, "bytes": b}
_comm: dict[str, dict] = {}

_EVENT_BUFFER_MAX = 8192
_events: deque = deque(maxlen=_EVENT_BUFFER_MAX)
_events_total = 0          # includes events evicted from the buffer
_once_keys: set = set()    # journal dedup for high-frequency sites

_journal_path: str | None = os.environ.get("DA_TPU_TELEMETRY_JOURNAL") or None
_journal_file = None       # lazily opened append handle
_journal_bytes = 0         # bytes written (or pre-existing) at the path
_journal_max = 0           # size cap, sampled from env at file open
_journal_capped = False    # True only if rotation itself failed (fallback)
_journal_rotations = 0     # completed .1 rotations at the current path

# the process-wide open incident, if any: failure handling spans threads
# (recovery retries, serve dispatch workers, the health sampler), so this
# is plain lock-guarded module state rather than a ContextVar.  Minted at
# the first classified failure and carried through retries the same way
# request trace ids ride through dispatch.
_incident_id: str | None = None
_incident_seq = 0

# one monotonic origin per process so every event timestamp is comparable
_T0 = time.monotonic()

# host identity stamped on every journal event (and postmortem bundle) so
# multihost journals can be merged and re-grouped per host offline.  The
# env override exists for simulated multi-host runs (CI's live-plane gate
# runs two "hosts" as subprocesses of one machine) — a real pod never
# needs it
_HOST = os.environ.get("DA_TPU_TELEMETRY_HOST") or ""
if not _HOST:
    try:
        import socket as _socket
        _HOST = _socket.gethostname() or "unknown"
    except Exception:  # pragma: no cover
        _HOST = "unknown"

# the innermost open tracing span (telemetry/tracing.py) on this
# thread/context — read here so events and comm records are stamped with
# the span they happened under.  A ContextVar, not thread-local: tasks
# inherit it, and fresh threads start clean (no cross-thread parents).
_CURRENT_SPAN: contextvars.ContextVar = contextvars.ContextVar(
    "da_tpu_current_span", default=None)

# the request-scoped trace ids bound to this context (a tuple of strings,
# or None) — written by telemetry/tracing.trace_ctx, read here so journal
# events (and Spans) are stamped with the requests they belong to.  Lives
# in core for the same reason _CURRENT_SPAN does: event() needs it and
# core cannot import tracing.
_TRACE_CTX: contextvars.ContextVar = contextvars.ContextVar(
    "da_tpu_trace_ctx", default=None)

# extension points so sibling modules (tracing) can plug into report() /
# reset() without core importing them (core stays stdlib-only, cycle-free)
_report_sections: dict = {}
_reset_hooks: list = []


def register_report_section(name: str, fn) -> None:
    """Add ``name: fn()`` to every :func:`report` (telemetry-internal)."""
    _report_sections[name] = fn


def register_reset_hook(fn) -> None:
    """Run ``fn()`` on every :func:`reset` (telemetry-internal)."""
    _reset_hooks.append(fn)


def _journal_max_bytes() -> int:
    """Journal file size cap (``DA_TPU_TELEMETRY_JOURNAL_MAX_MB``, default
    64): at the cap the file rotates to ``<path>.1`` (one generation kept)
    and mirroring continues into a fresh file opened with a single
    ``journal.rotated`` marker event — long soaks with the health sampler
    armed keep a bounded recent window instead of going blind.  Sampled
    once per file open (not per write) — reconfigure() to pick up a
    changed value."""
    try:
        mb = float(os.environ.get("DA_TPU_TELEMETRY_JOURNAL_MAX_MB", "64"))
    except ValueError:
        mb = 64.0
    return max(int(mb * 1024 * 1024), 1)


def _key(name: str, labels: dict) -> str:
    """Canonical metric key: ``name`` or ``name{k=v,...}`` (sorted)."""
    if not labels:
        return name
    return name + "{" + ",".join(
        f"{k}={labels[k]}" for k in sorted(labels)) + "}"


# ---------------------------------------------------------------------------
# enable / disable / configure
# ---------------------------------------------------------------------------


def enabled() -> bool:
    """Whether telemetry is recording (env ``DA_TPU_TELEMETRY``, default
    on; overridable at runtime with :func:`enable` / :func:`disable`)."""
    return _ENABLED


def enable() -> None:
    global _ENABLED
    with _LOCK:
        _ENABLED = True


def disable() -> None:
    """Stop recording.  Already-recorded state stays queryable; the
    journal file handle (if open) is closed."""
    global _ENABLED
    with _LOCK:
        _ENABLED = False
        _close_journal_locked()


def configure(journal_path: str | None) -> None:
    """Set (or clear, with ``None``) the JSONL journal path.  The file is
    opened lazily on the next recorded event, in append mode.  Clears any
    size-cap/rotation state from a previous path."""
    global _journal_path, _journal_bytes, _journal_capped, _journal_rotations
    with _LOCK:
        _close_journal_locked()
        _journal_path = journal_path
        _journal_bytes = 0
        _journal_capped = False
        _journal_rotations = 0


def journal_path() -> str | None:
    return _journal_path


def reset() -> None:
    """Clear every metric, the event buffer, and journal dedup state.
    The enabled flag and the configured journal path are kept; an open
    journal file handle is closed (the file itself is left in place)."""
    global _events_total, _journal_bytes, _journal_capped, \
        _journal_rotations, _incident_id
    with _LOCK:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
        _comm.clear()
        _events.clear()
        _once_keys.clear()
        _events_total = 0
        _journal_bytes = 0
        _journal_capped = False
        _journal_rotations = 0
        _incident_id = None
        _close_journal_locked()
        for hook in _reset_hooks:
            hook()


def _close_journal_locked() -> None:
    global _journal_file
    if _journal_file is not None:
        try:
            _journal_file.close()
        except Exception:
            pass
        _journal_file = None


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def count(name: str, n: float = 1, **labels) -> None:
    """Increment counter ``name`` (with optional labels) by ``n``."""
    if not _ENABLED:
        return
    k = _key(name, labels)
    with _LOCK:
        _counters[k] = _counters.get(k, 0) + n


def set_gauge(name: str, value: float, *, journal: bool = False,
              **labels) -> None:
    """Set gauge ``name`` to ``value``.  ``journal=True`` additionally
    records a ``gauge`` journal event — opt in at sites whose *history*
    matters (serve queue depth, admission token levels, elastic live
    devices): the Perfetto export reconstructs counter tracks from these
    events, where the registry alone only keeps the last value."""
    if not _ENABLED:
        return
    k = _key(name, labels)
    with _LOCK:
        _gauges[k] = value
    if journal:
        event("gauge", name, value=value, **labels)


def observe(name: str, value: float, *, buckets=None, **labels) -> None:
    """Record ``value`` into summary histogram ``name`` (count / total /
    min / max; mean derived at report time).

    ``buckets`` (a sorted sequence of upper bounds) upgrades the entry to
    a bucketed histogram: the value lands in the smallest bucket whose
    bound covers it (``+Inf`` above the last).  Bucket counts are stored
    non-cumulative; the Prometheus exporter renders the cumulative
    ``_bucket{le=...}`` series — this is what the per-endpoint serving
    SLO histograms (``da_tpu_serve_slo_*``) ride on."""
    if not _ENABLED:
        return
    k = _key(name, labels)
    with _LOCK:
        h = _hists.get(k)
        if h is None:
            h = _hists[k] = {"count": 1, "total": value,
                             "min": value, "max": value}
        else:
            h["count"] += 1
            h["total"] += value
            if value < h["min"]:
                h["min"] = value
            if value > h["max"]:
                h["max"] = value
        if buckets is not None:
            bk = h.setdefault("buckets", {})
            for b in buckets:
                if value <= b:
                    key = str(float(b))
                    break
            else:
                key = "+Inf"
            bk[key] = bk.get(key, 0) + 1
            if "bounds" not in h:
                h["bounds"] = [float(b) for b in buckets]


def counter_value(name: str, **labels) -> float:
    with _LOCK:
        return _counters.get(_key(name, labels), 0)


def gauge_value(name: str, default=None, **labels):
    with _LOCK:
        return _gauges.get(_key(name, labels), default)


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------


def event(category: str, name: str | None = None, *,
          once_key: str | None = None, **fields) -> None:
    """Append a structured event to the journal.

    ``t`` is seconds since the process's telemetry origin (monotonic —
    safe to order and subtract); ``wall`` is the epoch time for humans.
    ``once_key`` dedups high-frequency sites: only the FIRST event with a
    given key is journaled (counters still see every occurrence).

    Events recorded while a tracing span is open carry its ``span_id``
    (unless the caller already set one) — the nearest *journaled*
    ancestor's, so a journal's span_id references always resolve to a
    span event in the same journal (aggregate-only spans never reach
    it).  Every event also carries the recording thread's ``tid`` — the
    per-thread track key for the Perfetto export."""
    if not _ENABLED:
        return
    global _events_total
    sp = _CURRENT_SPAN.get()
    while sp is not None and not getattr(sp, "journaled", True):
        sp = sp.parent
    with _LOCK:
        if once_key is not None:
            if once_key in _once_keys:
                return
            _once_keys.add(once_key)
        rec = {"seq": _events_total,
               "t": round(time.monotonic() - _T0, 6),
               "wall": round(time.time(), 3),
               "cat": category,
               "tid": threading.get_ident(),
               "host": _HOST,
               "pid": os.getpid()}
        if name is not None:
            rec["name"] = name
        if sp is not None and "span_id" not in fields:
            rec["span_id"] = sp.span_id
        tr = _TRACE_CTX.get()
        if tr and "trace_id" not in fields:
            rec["trace_id"] = list(tr)
        if _incident_id is not None and "incident" not in fields:
            rec["incident"] = _incident_id
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        _events_total += 1
        _events.append(rec)
        _write_journal_locked(rec)


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


def begin_incident(kind: str = "failure") -> str | None:
    """Open (or join) the process-wide incident and return its id.

    Minted at the first classified failure (``inc-<host>-<pid>-<n>``);
    while open, every journal event and flight bundle is stamped with the
    id, so retries, quorum verdicts, restores, shrinks, drains and
    bundles from one causal episode correlate across hosts — the same
    discipline as request trace ids.  Re-entrant: a second classified
    failure inside an open incident joins it (one ``incident/begin``
    event per episode).  Returns ``None`` when telemetry is disabled."""
    global _incident_id, _incident_seq
    if not _ENABLED:
        return None
    with _LOCK:
        if _incident_id is not None:
            return _incident_id
        _incident_seq += 1
        _incident_id = f"inc-{_HOST}-{os.getpid()}-{_incident_seq}"
        inc = _incident_id
    event("incident", "begin", kind=kind)
    return inc


def current_incident() -> str | None:
    """The open incident id, or ``None``."""
    return _incident_id


def end_incident(resolution: str = "resolved") -> None:
    """Close the open incident (no-op if none): one ``incident/end``
    event carrying the id and ``resolution`` (``recovered`` /
    ``minority_exit`` / ``gave_up`` / ...), then stop stamping."""
    global _incident_id
    if _incident_id is None:
        return
    event("incident", "end", resolution=resolution)
    with _LOCK:
        _incident_id = None


def _write_journal_locked(rec: dict) -> None:
    global _journal_file, _journal_bytes, _journal_max, _journal_capped, \
        _events_total, _journal_rotations
    if _journal_path is None or _journal_capped:
        return
    try:
        if _journal_file is None:
            parent = os.path.dirname(_journal_path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            try:
                _journal_bytes = os.path.getsize(_journal_path)
            except OSError:
                _journal_bytes = 0
            _journal_max = _journal_max_bytes()
            _journal_file = open(_journal_path, "a")
        line = json.dumps(rec) + "\n"
        _journal_file.write(line)
        _journal_file.flush()
        _journal_bytes += len(line)
        if _journal_bytes >= _journal_max:
            # size cap reached: rotate the full file to <path>.1 (one
            # generation kept — the previous .1, if any, is replaced) and
            # continue mirroring into a fresh file whose first line is a
            # single journal.rotated marker, so long sampler-armed soaks
            # keep a bounded recent window instead of going blind
            rotated = _journal_bytes
            _close_journal_locked()
            try:
                os.replace(_journal_path, _journal_path + ".1")
            except OSError:
                # rotation impossible (e.g. cross-device, permissions):
                # fall back to the pre-rotation latch — marker in the
                # buffer, file mirroring stops, counters keep recording
                cap = {"seq": _events_total,
                       "t": round(time.monotonic() - _T0, 6),
                       "wall": round(time.time(), 3),
                       "cat": "journal", "name": "capped",
                       "host": _HOST, "pid": os.getpid(),
                       "bytes_written": rotated,
                       "max_bytes": _journal_max}
                _events_total += 1
                _events.append(cap)
                _journal_capped = True
                return
            _journal_rotations += 1
            _journal_bytes = 0
            _journal_file = open(_journal_path, "a")
            marker = {"seq": _events_total,
                      "t": round(time.monotonic() - _T0, 6),
                      "wall": round(time.time(), 3),
                      "cat": "journal", "name": "rotated",
                      "host": _HOST, "pid": os.getpid(),
                      "rotated_to": _journal_path + ".1",
                      "rotation": _journal_rotations,
                      "bytes_rotated": rotated,
                      "max_bytes": _journal_max}
            _events_total += 1
            _events.append(marker)
            mline = json.dumps(marker) + "\n"
            _journal_file.write(mline)
            _journal_file.flush()
            _journal_bytes += len(mline)
    except OSError:
        # telemetry must never take down the workload it observes
        _journal_file = None


def events(category: str | None = None) -> list[dict]:
    """Snapshot of the buffered events (most recent ``_EVENT_BUFFER_MAX``),
    optionally filtered by category."""
    with _LOCK:
        evs = list(_events)
    if category is None:
        return evs
    return [e for e in evs if e.get("cat") == category]


# ---------------------------------------------------------------------------
# communication accounting
# ---------------------------------------------------------------------------


def nbytes_of(x) -> int:
    """Best-effort payload size in bytes: works on numpy/jax arrays AND
    on tracers inside jit/shard_map (shape/dtype are static), on
    bytes-like payloads, and degrades to 0 for unsized objects."""
    try:
        nb = getattr(x, "nbytes", None)
        if isinstance(nb, (int, float)):
            return int(nb)
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            n = 1
            for s in shape:
                n *= int(s)
            import numpy as _np
            return n * _np.dtype(dtype).itemsize
        if isinstance(x, (bytes, bytearray, memoryview)):
            return len(x)
    except Exception:
        pass
    return 0


def record_comm(kind: str, nbytes: int, *, axis=None, op: str | None = None,
                journal: bool = True, once_key: str | None = None,
                **fields) -> None:
    """Account one communication: ``kind`` (reshard / h2d / d2h /
    collective / replicate / spmd_send / multihost_gather / ...),
    estimated payload ``nbytes``, optional mesh ``axis`` and originating
    ``op``.  Feeds ``comm.ops``/``comm.bytes`` per kind and (unless
    ``journal=False``) one journal event under category ``"comm"``.

    When a tracing span is open, the bytes are also attributed to it
    (the span's own-bytes tally; parents see them via child rollups at
    span close) and the journal event carries its ``span_id``."""
    if not _ENABLED:
        return
    nbytes = int(nbytes)
    sp = _CURRENT_SPAN.get()
    with _LOCK:
        c = _comm.get(kind)
        if c is None:
            _comm[kind] = {"ops": 1, "bytes": nbytes}
        else:
            c["ops"] += 1
            c["bytes"] += nbytes
        if sp is not None:
            sp.bytes += nbytes
    if journal:
        ev = dict(fields)
        if axis is not None:
            ev["axis"] = axis
        if op is not None:
            ev["op"] = op
        event("comm", kind, once_key=once_key, bytes=nbytes, **ev)


def comm_bytes(kind: str | None = None) -> int:
    """Total estimated bytes moved (optionally for one kind)."""
    with _LOCK:
        if kind is not None:
            c = _comm.get(kind)
            return int(c["bytes"]) if c else 0
        return int(sum(c["bytes"] for c in _comm.values()))


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------


def report() -> dict:
    """Nested snapshot of everything recorded so far."""
    with _LOCK:
        by_cat: dict[str, int] = {}
        for e in _events:
            by_cat[e["cat"]] = by_cat.get(e["cat"], 0) + 1
        out = {
            "enabled": _ENABLED,
            "counters": dict(_counters),
            "gauges": dict(_gauges),
            "histograms": {
                k: {**h, "mean": h["total"] / h["count"],
                    **({"buckets": dict(h["buckets"])}
                       if "buckets" in h else {})}
                for k, h in _hists.items()
            },
            "comm": {
                "total_bytes": int(sum(c["bytes"] for c in _comm.values())),
                "total_ops": int(sum(c["ops"] for c in _comm.values())),
                "by_kind": {k: dict(v) for k, v in _comm.items()},
            },
            "events": {
                "recorded": _events_total,
                "buffered": len(_events),
                "by_category": by_cat,
                "journal_path": _journal_path,
                "journal_capped": _journal_capped,
                "journal_rotations": _journal_rotations,
            },
            "incident": _incident_id,
        }
    # outside _LOCK: section providers take it themselves (RLock would
    # allow reentry, but holding it across foreign code invites deadlock)
    for name, fn in _report_sections.items():
        try:
            out[name] = fn()
        except Exception:
            out[name] = {"error": "report section failed"}
    return out


def dump(path: str) -> str:
    """Write :func:`report` as indented JSON to ``path``; returns the
    path.  Atomic (tmp + replace), same discipline as autotune.save."""
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report(), f, indent=2, sort_keys=True)
    os.replace(tmp, path)
    return path
