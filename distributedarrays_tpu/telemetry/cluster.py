"""Cluster observatory: cross-host journal merge + causal incident
reconstruction.

PR 13 made the interesting failures *distributed*: a partition incident
leaves its story scattered across per-host journals, flight bundles,
fault history, quorum verdicts, and recovery counters.  This module
reassembles it:

- :func:`merge_journals` aligns per-host journals onto ONE timeline
  (heartbeat-exchange clock offsets from ``parallel/multihost.py`` when
  present as ``multihost/clock`` events, else wall-clock anchors, else a
  first-common-event match), deduplicates shared events, and returns a
  list that ``summarize``/``to_perfetto`` consume directly — a multihost
  run renders as one trace with per-host process tracks.
- :func:`reconstruct_incidents` stitches flight bundles, fault
  injections, ``quorum_assess`` verdicts, recovery attempts, elastic
  shrink/grow epochs, checkpoint restore-source decisions, serve drain
  events and alert transitions into ordered incident reports ("partition
  injected at t=… → minority drained typed → quorum side restored step 4
  peer-first → shrank → retried → converged"), keyed by the incident ids
  the recovery executor mints (``core.begin_incident``) and grouped into
  cross-host episodes when windows overlap.
- :func:`incident_trace` re-exports the merged timeline as Perfetto JSON
  with flow events threading each incident's steps into one arrowed path.

Pure stdlib over plain dicts (like ``summarize``/``export``): journals
pulled off pod workers reconstruct on any machine.  The CLI front-end is
``python -m distributedarrays_tpu.telemetry incident``.
"""

from __future__ import annotations

import json
import os

# direct from-imports: the package re-exports `summarize`/`to_perfetto`
# FUNCTIONS that shadow the module attributes of the same names
from .export import to_perfetto as _to_perfetto
from .summarize import read_journal as _read_journal

__all__ = ["merge_journals", "reconstruct_incidents", "load_bundles",
           "format_incidents", "incident_trace"]

# events these categories emit are part of an incident's causal story
# even when recorded before the incident id was minted (the injection
# itself, quorum checks) — time-window attribution picks them up
_INCIDENT_CATS = ("faults", "multihost", "recovery", "elastic",
                  "checkpoint", "serve", "train", "incident", "alert",
                  "domains")

# seconds of timeline slack around an incident's [begin, end] window for
# attributing unstamped events and bundles (the injection fires just
# before the first classified failure mints the id)
_WINDOW_SLACK_S = 5.0


def _stream_key(e: dict) -> tuple:
    return (str(e.get("host", "")), int(e.get("pid") or 0))


def _wall_anchor(evs: list[dict]) -> float | None:
    """Median of ``wall - t`` over a stream: the epoch time of the
    stream's monotonic origin.  Median, not mean — a single event whose
    wall was recorded across an NTP step must not skew the anchor."""
    deltas = sorted(e["wall"] - e["t"] for e in evs
                    if isinstance(e.get("wall"), (int, float))
                    and isinstance(e.get("t"), (int, float)))
    if not deltas:
        return None
    return deltas[len(deltas) // 2]


def _clock_skews(events: list[dict]) -> dict[tuple[str, str], float]:
    """Directed skew edges from ``multihost/clock`` events:
    ``(recorder_host, peer_host) -> offset_s`` where ``offset_s`` is the
    recorder's wall minus the peer's (recorder ahead by that much).  The
    latest estimate per edge wins."""
    skews: dict[tuple[str, str], float] = {}
    for e in events:
        if e.get("cat") != "multihost" or e.get("name") != "clock":
            continue
        rec_host = str(e.get("host", ""))
        offsets = e.get("offsets")
        if not isinstance(offsets, dict):
            continue
        for info in offsets.values():
            if not isinstance(info, dict):
                continue
            peer = info.get("host")
            off = info.get("offset_s")
            if peer is None or not isinstance(off, (int, float)):
                continue
            skews[(rec_host, str(peer))] = float(off)
    return skews


def _event_fingerprint(e: dict) -> tuple | None:
    """Identity of an event ACROSS hosts, for first-common-event
    alignment: category, name, and the non-meta payload.  Only
    configuration-like events are shared-fate enough to match (two hosts
    journal the same fault plan / domain topology at the same moment)."""
    if (e.get("cat"), e.get("name")) not in (
            ("faults", "configure"), ("domains", "configure"),
            ("multihost", "initialize")):
        return None
    skip = ("seq", "t", "wall", "tid", "host", "pid", "span_id",
            "trace_id", "incident")
    payload = {k: v for k, v in e.items() if k not in skip}
    try:
        return (json.dumps(payload, sort_keys=True, default=str),)
    except (TypeError, ValueError):
        return None


def merge_journals(paths_or_events, *, slack_s: float = _WINDOW_SLACK_S) \
        -> list[dict]:
    """Merge per-host JSONL journals onto one timeline.

    ``paths_or_events``: journal paths (rotated ``<path>.1`` siblings are
    read automatically, oldest first) and/or already-parsed event lists.
    Events are grouped into per-``(host, pid)`` streams, deduplicated on
    ``(host, pid, seq)`` (the same event mirrored into two files — e.g. a
    copied journal — appears once), and each stream's monotonic clock is
    re-based onto the reference stream's (the first stream seen):

    1. **clock offsets** — ``multihost/clock`` events (published by the
       heartbeat exchange, :func:`parallel.multihost.
       exchange_clock_offsets`) give direct skew edges between hosts;
    2. **wall anchors** — otherwise each stream's median ``wall - t``
       places its monotonic origin on the (NTP-disciplined) epoch
       timeline;
    3. **first common event** — with neither (or to refine hosts with no
       clock edge), the earliest configuration event shared by two
       streams (same fault plan / topology payload) is assumed
       simultaneous.

    Returns events sorted by merged ``t`` (seconds from the merged
    origin, the earliest event overall); every event keeps its original
    monotonic stamp as ``t_local``.  The result feeds
    :func:`summarize.summarize` and :func:`export.to_perfetto` (which
    renders one process track per ``(host, pid)``) unchanged.
    """
    streams: dict[tuple, list[dict]] = {}
    seen: set[tuple] = set()
    order: list[tuple] = []
    for src in paths_or_events:
        if isinstance(src, (list, tuple)):
            evs = list(src)
        else:
            evs = []
            rotated = str(src) + ".1"
            if os.path.exists(rotated):
                evs.extend(_read_journal(rotated))
            evs.extend(_read_journal(src))
        for e in evs:
            if not isinstance(e, dict):
                continue
            key = _stream_key(e)
            seq = e.get("seq")
            if seq is not None:
                dk = key + (int(seq),)
                if dk in seen:
                    continue
                seen.add(dk)
            if key not in streams:
                streams[key] = []
                order.append(key)
            streams[key].append(e)
    if not streams:
        return []

    ref = order[0]
    skews = _clock_skews([e for evs in streams.values() for e in evs])
    anchors = {key: _wall_anchor(evs) for key, evs in streams.items()}
    fingerprints: dict[tuple, dict[tuple, float]] = {}
    for key, evs in streams.items():
        fps: dict[tuple, float] = {}
        for e in evs:
            fp = _event_fingerprint(e)
            if fp is not None and isinstance(e.get("t"), (int, float)):
                fps.setdefault(fp, float(e["t"]))
        fingerprints[key] = fps

    ref_host = ref[0]
    ref_anchor = anchors.get(ref) or 0.0

    def _shift(key: tuple) -> float:
        """Seconds to ADD to stream ``key``'s local t to land it on the
        reference stream's local-t scale."""
        if key == ref:
            return 0.0
        host = key[0]
        anchor = anchors.get(key)
        # epoch-based shift first: place both monotonic origins on the
        # wall timeline, then correct the wall clocks' relative skew
        # from a direct clock edge when one exists
        if anchor is not None:
            shift = anchor - ref_anchor
            if host != ref_host:
                if (ref_host, host) in skews:
                    # ref ahead of host by off: host wall + off = ref wall
                    shift += skews[(ref_host, host)]
                elif (host, ref_host) in skews:
                    shift -= skews[(host, ref_host)]
                else:
                    # no clock edge: refine with the earliest shared
                    # configuration event, assumed simultaneous
                    common = set(fingerprints[key]) & \
                        set(fingerprints[ref])
                    if common:
                        fp = min(common,
                                 key=lambda f: fingerprints[ref][f])
                        shift = fingerprints[ref][fp] - \
                            fingerprints[key][fp]
            return shift
        # no wall stamps at all: first-common-event or give up at 0
        common = set(fingerprints[key]) & set(fingerprints[ref])
        if common:
            fp = min(common, key=lambda f: fingerprints[ref][f])
            return fingerprints[ref][fp] - fingerprints[key][fp]
        return 0.0

    shifts = {key: _shift(key) for key in streams}
    merged: list[dict] = []
    for key, evs in streams.items():
        shift = shifts[key]
        for e in evs:
            t = e.get("t")
            if isinstance(t, (int, float)):
                out = dict(e)
                out["t_local"] = t
                out["t"] = round(float(t) + shift, 6)
                merged.append(out)
            else:
                merged.append(dict(e))
    # re-base so the merged origin is the earliest event (negative
    # timestamps confuse trace viewers), then order the timeline
    ts = [e["t"] for e in merged if isinstance(e.get("t"), (int, float))]
    if ts:
        t0 = min(ts)
        if t0 != 0.0:
            for e in merged:
                if isinstance(e.get("t"), (int, float)):
                    e["t"] = round(e["t"] - t0, 6)
    merged.sort(key=lambda e: (e.get("t") if isinstance(
        e.get("t"), (int, float)) else float("inf"),
        str(e.get("host", "")), e.get("seq") or 0))
    return merged


# ---------------------------------------------------------------------------
# incident reconstruction
# ---------------------------------------------------------------------------


def _phrase(e: dict) -> str | None:
    """One human line per causal step; None for events that are not
    steps (spans, comm, gauges...)."""
    cat, name = e.get("cat"), e.get("name")
    if cat == "faults" and name == "fire":
        action = e.get("action", "?")
        site = e.get("site", "?")
        if action == "partition":
            return f"partition injected at {site}"
        return f"fault fired: {action} at {site}"
    if cat == "multihost" and name == "quorum":
        return (f"quorum verdict {e.get('verdict', '?')} "
                f"(side {e.get('side', '?')}, lost {e.get('lost', '?')}) "
                f"on {e.get('host', '?')}")
    if cat == "incident" and name == "begin":
        return f"incident opened ({e.get('kind', '?')})"
    if cat == "incident" and name == "end":
        return f"incident closed: {e.get('resolution', '?')}"
    if cat == "recovery" and name == "failure":
        tail = "retrying" if e.get("retrying") else "not retrying"
        return (f"attempt {e.get('attempt', '?')} failed "
                f"({e.get('verdict', '?')}; {tail})")
    if cat == "recovery" and name == "minority_exit":
        return (f"minority side {e.get('side')} exiting typed "
                f"(lost contact with {e.get('lost')})")
    if cat == "recovery" and name == "recovered":
        return f"recovered after {e.get('attempts', '?')} attempts"
    if cat == "checkpoint" and name == "restore_peer":
        return (f"restored step {e.get('step', '?')} from peer replicas "
                f"(zero disk reads)")
    if cat == "checkpoint" and name == "restore_disk":
        return f"restored step {e.get('step', '?')} from disk"
    if cat == "checkpoint" and name in ("restore_fallback",
                                        "replica_fallback"):
        return f"checkpoint fallback: {name}"
    if cat == "elastic" and name == "shrink":
        dom = f" (domain {e.get('domain')})" if e.get("domain") else ""
        return (f"shrank to {e.get('live', '?')} live devices, moved "
                f"{e.get('moved', '?')} arrays{dom}")
    if cat == "elastic" and name == "grow":
        return f"grew to {e.get('live', '?')} live devices"
    if cat == "elastic" and name == "probe":
        return (f"elastic probe: {e.get('live', '?')} live / "
                f"{e.get('down', '?')} down")
    if cat == "serve" and name == "partition_drain":
        ep = f" [{e.get('endpoint')}]" if e.get("endpoint") else ""
        return f"server drained typed (partition minority){ep}"
    if cat == "serve" and name == "drain":
        return f"server drained ({e.get('depth', 0)} queued)"
    if cat == "train" and name == "reseat":
        return f"trainer re-seated state at step {e.get('step', '?')}"
    if cat == "alert":
        return f"alert {name} {e.get('state', '?')}"
    if cat == "journal" and name == "rotated":
        return None
    return None


def load_bundles(paths) -> list[dict]:
    """Load flight bundles from files and/or directories (every
    ``*.json`` whose ``kind`` is ``da_tpu_postmortem``).  Each bundle
    gains a ``path`` key.  Raises :class:`ValueError` on a bundle whose
    ``schema_version`` is newer than this reader understands — refusing
    a shape we would silently misread beats guessing (missing version =
    v1, still readable)."""
    from . import flight as _flight
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.endswith(".json")))
        else:
            files.append(p)
    bundles = []
    for f in files:
        try:
            with open(f) as fh:
                b = json.load(fh)
        except (OSError, ValueError):
            continue
        if not isinstance(b, dict) or \
                b.get("kind") != "da_tpu_postmortem":
            continue
        version = b.get("schema_version", 1)
        if not isinstance(version, int) or \
                version > _flight.SCHEMA_VERSION:
            raise ValueError(
                f"flight bundle {f} has schema_version {version!r}; this "
                f"reader understands <= {_flight.SCHEMA_VERSION} — "
                f"upgrade distributedarrays_tpu to reconstruct it")
        b["path"] = f
        bundles.append(b)
    return bundles


def reconstruct_incidents(events: list[dict], bundles=(), *,
                          slack_s: float = _WINDOW_SLACK_S) -> dict:
    """Stitch a merged timeline (:func:`merge_journals`) and flight
    bundles into ordered incident reports.

    Incident ids (``inc-<host>-<pid>-<n>``) are per-process; one
    cluster-wide episode (a partition) opens one per side.  Ids whose
    ``[begin, end]`` windows overlap (padded by ``slack_s``) merge into
    one episode; events from incident-relevant categories recorded
    *without* an id inside a window (the injection itself, quorum
    verdicts, drains after the id closed) attach by time + category, and
    bundles attach by their stamped ``incident`` field, else by
    host/pid + wall-clock proximity.

    Returns ``{"incidents": [...], "bundles_total", "bundles_attributed",
    "bundles_unattributed": [...], "unattributed_recovery_events"}`` —
    the last two are the orphan witnesses the CI gate fails on.
    """
    # pass 1: per-id windows from stamped events
    by_id: dict[str, dict] = {}
    for e in events:
        inc = e.get("incident")
        t = e.get("t")
        if not inc or not isinstance(t, (int, float)):
            continue
        w = by_id.setdefault(str(inc), {
            "id": str(inc), "t0": t, "t1": t, "kind": None,
            "resolution": None, "hosts": set(), "events": []})
        w["t0"] = min(w["t0"], t)
        w["t1"] = max(w["t1"], t)
        if e.get("host") is not None:
            w["hosts"].add(str(e["host"]))
        if e.get("cat") == "incident":
            if e.get("name") == "begin" and w["kind"] is None:
                w["kind"] = e.get("kind")
            if e.get("name") == "end":
                w["resolution"] = e.get("resolution")
        w["events"].append(e)

    # pass 2: merge overlapping windows into episodes
    episodes: list[dict] = []
    for w in sorted(by_id.values(), key=lambda w: w["t0"]):
        for ep in episodes:
            if w["t0"] <= ep["t1"] + slack_s and \
                    w["t1"] >= ep["t0"] - slack_s:
                ep["ids"].append(w["id"])
                ep["t0"] = min(ep["t0"], w["t0"])
                ep["t1"] = max(ep["t1"], w["t1"])
                ep["hosts"] |= w["hosts"]
                ep["events"].extend(w["events"])
                if w["kind"]:
                    ep["kinds"].add(w["kind"])
                if w["resolution"]:
                    ep["resolutions"][w["id"]] = w["resolution"]
                break
        else:
            episodes.append({
                "ids": [w["id"]], "t0": w["t0"], "t1": w["t1"],
                "hosts": set(w["hosts"]),
                "kinds": {w["kind"]} if w["kind"] else set(),
                "resolutions": ({w["id"]: w["resolution"]}
                                if w["resolution"] else {}),
                "events": list(w["events"])})

    # pass 3: attach unstamped incident-category events by time window
    claimed = {id(e) for ep in episodes for e in ep["events"]}
    for e in events:
        if id(e) in claimed or e.get("incident"):
            continue
        if e.get("cat") not in _INCIDENT_CATS:
            continue
        t = e.get("t")
        if not isinstance(t, (int, float)):
            continue
        for ep in episodes:
            if ep["t0"] - slack_s <= t <= ep["t1"] + slack_s:
                ep["events"].append(e)
                if e.get("host") is not None:
                    ep["hosts"].add(str(e["host"]))
                break

    # pass 4: attach bundles — stamped incident id first, then
    # host/pid + wall proximity against the episode's own wall range
    bundles = list(bundles)
    unattributed: list[dict] = []
    for b in bundles:
        target = None
        binc = b.get("incident")
        if binc:
            for ep in episodes:
                if str(binc) in ep["ids"]:
                    target = ep
                    break
        if target is None and isinstance(b.get("wall"), (int, float)):
            bkey = (str(b.get("host", "")), int(b.get("pid") or 0))
            for ep in episodes:
                walls = [e["wall"] for e in ep["events"]
                         if isinstance(e.get("wall"), (int, float))
                         and _stream_key(e) == bkey]
                if not walls:
                    walls = [e["wall"] for e in ep["events"]
                             if isinstance(e.get("wall"), (int, float))]
                if walls and min(walls) - slack_s <= b["wall"] \
                        <= max(walls) + slack_s:
                    target = ep
                    break
        if target is not None:
            target.setdefault("bundles", []).append(b)
        else:
            unattributed.append(b)

    # pass 5: render each episode's ordered step list
    out_eps = []
    unattributed_recovery = 0
    for ep in episodes:
        ep["events"].sort(key=lambda e: (
            e.get("t") if isinstance(e.get("t"), (int, float)) else 0.0,
            e.get("seq") or 0))
        steps = []
        for e in ep["events"]:
            phrase = _phrase(e)
            if phrase is None:
                continue
            steps.append({"t": e.get("t"), "host": e.get("host"),
                          "incident": e.get("incident"),
                          "cat": e.get("cat"), "name": e.get("name"),
                          "what": phrase})
        eb = ep.get("bundles", [])
        out_eps.append({
            "ids": ep["ids"],
            "kinds": sorted(k for k in ep["kinds"] if k),
            "t0": round(ep["t0"], 6), "t1": round(ep["t1"], 6),
            "duration_s": round(ep["t1"] - ep["t0"], 6),
            "hosts": sorted(ep["hosts"]),
            "resolutions": dict(ep["resolutions"]),
            "steps": steps,
            "bundles": [{"path": b.get("path"),
                         "reason": b.get("reason"),
                         "classification": b.get("classification"),
                         "host": b.get("host"), "pid": b.get("pid"),
                         "incident": b.get("incident"),
                         "schema_version": b.get("schema_version", 1)}
                        for b in eb],
        })
    # recovery attempts outside any episode are unattributed — with the
    # executor minting ids at the first classified failure this should
    # never happen; a nonzero count means lost correlation
    for e in events:
        if e.get("cat") == "recovery" and e.get("name") == "failure" \
                and not e.get("incident"):
            t = e.get("t")
            inside = any(isinstance(t, (int, float))
                         and ep["t0"] - slack_s <= t <= ep["t1"] + slack_s
                         for ep in episodes)
            if not inside:
                unattributed_recovery += 1
    return {
        "incidents": out_eps,
        "bundles_total": len(bundles),
        "bundles_attributed": len(bundles) - len(unattributed),
        "bundles_unattributed": [b.get("path") or "<in-memory>"
                                 for b in unattributed],
        "unattributed_recovery_events": unattributed_recovery,
        "events_total": len(events),
    }


def format_incidents(report: dict, out) -> None:
    """Render :func:`reconstruct_incidents` as readable text."""
    eps = report.get("incidents", [])
    out.write(f"{len(eps)} incident(s) over {report.get('events_total', 0)}"
              f" events; bundles {report.get('bundles_attributed', 0)}"
              f"/{report.get('bundles_total', 0)} attributed\n")
    for i, ep in enumerate(eps):
        ids = ", ".join(ep["ids"])
        kinds = "/".join(ep["kinds"]) or "?"
        out.write(f"\nincident {i + 1}: {kinds}  [{ids}]\n")
        out.write(f"  window: t={ep['t0']:.3f}s .. {ep['t1']:.3f}s "
                  f"({ep['duration_s']:.3f}s)  "
                  f"hosts: {', '.join(ep['hosts'])}\n")
        if ep["resolutions"]:
            res = ", ".join(f"{k}={v}"
                            for k, v in sorted(ep["resolutions"].items()))
            out.write(f"  resolution: {res}\n")
        for s in ep["steps"]:
            t = s.get("t")
            ts = f"{t:9.3f}s" if isinstance(t, (int, float)) else "        ?"
            out.write(f"  {ts}  [{s.get('host', '?')}] {s['what']}\n")
        for b in ep.get("bundles", []):
            out.write(f"  bundle: {b.get('path')} "
                      f"({b.get('classification')}, host {b.get('host')})\n")
    orphans = report.get("bundles_unattributed", [])
    if orphans:
        out.write(f"\nWARNING: {len(orphans)} orphaned bundle(s): "
                  f"{', '.join(orphans)}\n")
    if report.get("unattributed_recovery_events"):
        out.write(f"WARNING: {report['unattributed_recovery_events']} "
                  f"recovery attempt(s) outside any incident window\n")


def incident_trace(events: list[dict], report: dict | None = None) -> dict:
    """Perfetto JSON for a merged timeline with incident flow events:
    each episode's steps chain together with Chrome flow arrows (one
    flow id per episode), on top of :func:`export.to_perfetto`'s
    per-host process tracks."""
    if report is None:
        report = reconstruct_incidents(events)
    trace = _to_perfetto(events)
    entries = trace["traceEvents"]
    # map (host, pid) -> trace pid the exporter assigned, recomputed the
    # same way (insertion order over the event list)
    procs: dict[tuple, int] = {}
    for e in events:
        key = _stream_key(e)
        if key not in procs:
            procs[key] = len(procs)
    flow_id = 1 << 16         # clear of the request-flow id range
    for ep in report.get("incidents", []):
        steps = [s for s in ep.get("steps", [])
                 if isinstance(s.get("t"), (int, float))]
        if len(steps) < 2:
            continue
        for i, s in enumerate(steps):
            ph = "s" if i == 0 else ("f" if i == len(steps) - 1 else "t")
            key = (str(s.get("host", "")), 0)
            pid = procs.get(key)
            if pid is None:
                # steps carry host but not pid; fall back to the first
                # stream from that host
                pid = next((p for (h, _), p in procs.items()
                            if h == key[0]), 0)
            ev = {"name": "incident", "cat": "incident", "ph": ph,
                  "id": flow_id, "ts": round(s["t"] * 1e6, 3), "dur": 0,
                  "pid": pid, "tid": 0,
                  "args": {"what": s["what"],
                           "ids": ",".join(ep["ids"])}}
            if ph == "f":
                ev["bp"] = "e"
            entries.append(ev)
        flow_id += 1
    return trace
