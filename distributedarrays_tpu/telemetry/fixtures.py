"""Pytest fixture for telemetry-aware tests.

Import into a ``conftest.py`` (or straight into a test module)::

    from distributedarrays_tpu.telemetry.fixtures import telemetry_capture

``telemetry_capture`` gives the test a clean, ENABLED telemetry state
with a tmp-dir journal, and restores the process's prior state (enabled
flag + journal path) afterwards — so telemetry tests cannot leak
configuration into the rest of the suite, and the rest of the suite
cannot pollute a telemetry assertion.
"""

from __future__ import annotations

import pytest

from . import core


@pytest.fixture
def telemetry_capture(tmp_path):
    """Clean enabled telemetry with a journal at ``tmp_path/journal.jsonl``.

    Yields the ``telemetry`` module facade; the journal path is
    ``telemetry.journal_path()``.
    """
    prev_enabled = core.enabled()
    prev_path = core.journal_path()
    core.reset()
    core.configure(str(tmp_path / "journal.jsonl"))
    core.enable()
    try:
        from distributedarrays_tpu import telemetry
        yield telemetry
    finally:
        core.reset()
        core.configure(prev_path)
        if prev_enabled:
            core.enable()
        else:
            core.disable()
