"""Pytest fixture for telemetry-aware tests.

Import into a ``conftest.py`` (or straight into a test module)::

    from distributedarrays_tpu.telemetry.fixtures import telemetry_capture

``telemetry_capture`` gives the test a clean, ENABLED telemetry state
with a tmp-dir journal, and restores the process's prior state (enabled
flag + journal path) afterwards — so telemetry tests cannot leak
configuration into the rest of the suite, and the rest of the suite
cannot pollute a telemetry assertion.

The yielded object proxies the ``telemetry`` module facade and adds
span-coverage helpers, so an op test can assert instrumentation without
parsing the journal::

    def test_my_op_is_traced(telemetry_capture):
        my_op(...)
        telemetry_capture.assert_span("my_op")
        assert telemetry_capture.spans("my_op")[0]["bytes"] > 0
"""

from __future__ import annotations

import pytest

from . import core, memory, tracing


class TelemetryCapture:
    """Module facade plus test-assertion helpers.  Every ``telemetry``
    attribute (``count``, ``events``, ``report``, ...) resolves through
    the proxy unchanged."""

    def __init__(self, module):
        self._module = module

    def __getattr__(self, name):
        return getattr(self._module, name)

    def spans(self, name: str | None = None) -> list[dict]:
        """Finished spans (optionally filtered by name) — see
        ``tracing.spans``."""
        return tracing.spans(name)

    def assert_span(self, name: str, min_count: int = 1) -> list[dict]:
        """Assert at least ``min_count`` spans named ``name`` finished;
        returns the buffered ones (aggregate-only spans count but carry
        no buffered dicts).  Counts come from ``span_stats`` so neither
        buffer eviction nor ``_journal=False`` can hide real coverage;
        the failure message lists what DID run, so a renamed phase is a
        one-glance fix."""
        stats = tracing.span_stats()
        got = stats.get(name, {}).get("count", 0)
        if got < min_count:
            raise AssertionError(
                f"expected >= {min_count} span(s) named {name!r}, "
                f"got {got}; finished span names: {sorted(stats)}")
        return tracing.spans(name)

    def assert_counter(self, name: str, min_count: float = 1,
                       **labels) -> float:
        """Assert counter ``name`` (with optional labels) reached at
        least ``min_count``; returns the observed value.  The failure
        message lists the recorded counter keys, so a renamed metric is
        a one-glance fix — replaces hand-rolled ``counter_value``
        polling in tests."""
        got = core.counter_value(name, **labels)
        if got < min_count:
            with core._LOCK:
                keys = sorted(core._counters)
            raise AssertionError(
                f"expected counter {core._key(name, labels)!r} >= "
                f"{min_count}, got {got}; recorded counters: {keys}")
        return got

    def mem(self) -> dict:
        """Snapshot of the HBM ledger (the ``memory`` report section)."""
        return memory.snapshot()


@pytest.fixture
def telemetry_capture(tmp_path):
    """Clean enabled telemetry with a journal at ``tmp_path/journal.jsonl``.

    Yields a :class:`TelemetryCapture` proxying the ``telemetry`` module
    facade (plus ``spans()`` / ``assert_span()``); the journal path is
    ``telemetry.journal_path()``.
    """
    from . import stream
    prev_enabled = core.enabled()
    prev_path = core.journal_path()
    # stream._reset joins the exporter thread, which may itself be
    # waiting on core._LOCK — so it must run OUTSIDE core.reset's hook
    # list (reset hooks run under the lock), here in plain teardown
    stream._reset()
    core.reset()
    core.configure(str(tmp_path / "journal.jsonl"))
    core.enable()
    try:
        from distributedarrays_tpu import telemetry
        yield TelemetryCapture(telemetry)
    finally:
        stream._reset()
        core.reset()
        core.configure(prev_path)
        if prev_enabled:
            core.enable()
        else:
            core.disable()
