"""Postmortem flight recorder: the black box a crashed run leaves behind.

The journal file is optional (and size-capped); the metrics registry is
in-memory and dies with the process.  When a run crashes — an uncaught
exception out of ``spmd()``/``djit``, a ``CollectiveDivergenceError``, a
stuck process poked with SIGUSR1 — nothing survives to debug from.  This
module fixes that: the telemetry core *already* keeps a fixed-size
in-memory ring of the last journal events (it records even when file
journaling is off or capped), and tracing keeps the open-span registry;
:func:`postmortem` snapshots both, plus the HBM ledger, the lifecycle
registry census (provided by ``distributedarrays_tpu.core`` so this
module stays package-independent), and any divergence events, into ONE
JSON bundle.

Dump triggers:

- :func:`record_crash` — called by the spmd driver, ``djit``, and the
  divergence checker on their failure paths (deduped per exception
  object, capped at ``DA_TPU_FLIGHT_MAX`` bundles per process);
- :func:`postmortem` — on demand;
- SIGUSR1 — :func:`install_sigusr1` (auto-installed on telemetry import
  in the main thread; ``DA_TPU_FLIGHT_SIGUSR1=0`` opts out).

Bundles land in ``DA_TPU_FLIGHT_DIR``, else next to the configured
journal; with neither configured the bundle is kept in memory only
(:func:`last_bundle`) — a library must not scatter files into a cwd it
was never pointed at.  Disabled telemetry (``DA_TPU_TELEMETRY=0``) makes
every trigger a single boolean check.
"""

from __future__ import annotations

import json
import os
import threading
import time
import weakref

from . import core, memory, tracing

__all__ = ["postmortem", "record_crash", "last_bundle",
           "install_sigusr1", "register_census_provider",
           "register_classifier", "crash_bundle_count",
           "SCHEMA_VERSION"]

# bundle schema version, stamped on every bundle so offline readers (the
# incident CLI, external tooling) can refuse shapes they don't
# understand.  v1: unversioned bundles (pre-incident era); v2 adds
# schema_version + the open incident id.
SCHEMA_VERSION = 2

_RING_MAX_ENV = "DA_TPU_FLIGHT_RING"       # bundle ring tail length
_MAX_ENV = "DA_TPU_FLIGHT_MAX"             # bundles per process

_lock = threading.Lock()
# already-bundled errors, keyed by id() with a validator so a dead
# exception's recycled id cannot suppress a new error's bundle, and
# nothing strong-references the exception (pinning its traceback frames
# — and whatever arrays they hold — for the life of the process).
# Python-defined exceptions validate by weakref identity; builtin
# exception types reject weakrefs, so those fall back to a
# (type, message) fingerprint.  Size-bounded by pruning.
_bundled_excs: dict[int, object] = {}   # id -> weakref.ref | fingerprint
_bundles_written = 0
_crash_bundles = 0                      # record_crash attempts that bundled
_last_bundle: dict | None = None
_last_path: str | None = None
_census_provider = None
_classifier = None
_sig_installed = False


def register_census_provider(fn) -> None:
    """Install the lifecycle-registry census callable (``() -> dict``).
    Registered by ``distributedarrays_tpu.core`` at import so the bundle
    can include the live-DArray census without this module importing the
    package (telemetry stays stdlib-only / cycle-free)."""
    global _census_provider
    _census_provider = fn


def register_classifier(fn) -> None:
    """Install the failure classifier (``(exc) -> str`` verdict).
    Registered by ``resilience.recovery`` (same injection pattern as the
    census provider) so every bundle is stamped with the retry verdict
    the recovery executor would act on — the bundle drives the retry
    decision, and offline readers see the same triage."""
    global _classifier
    _classifier = fn


def crash_bundle_count() -> int:
    """Crash bundles assembled so far this process (dedup'd per
    exception object) — the chaos suite's exactly-one-bundle witness."""
    with _lock:
        return _crash_bundles


def _int_env(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _flight_dir() -> str | None:
    d = os.environ.get("DA_TPU_FLIGHT_DIR")
    if d:
        return d
    jp = core.journal_path()
    if jp:
        return os.path.dirname(jp) or "."
    return None


def _exc_info(exc) -> dict | None:
    if exc is None:
        return None
    return {"type": type(exc).__name__,
            "message": str(exc)[:8000],
            "cause": type(exc.__cause__).__name__
            if exc.__cause__ is not None else None}


def snapshot_bundle(reason: str, exc=None) -> dict:
    """Assemble (but do not write) a postmortem bundle."""
    ring = core.events()
    tail = _int_env(_RING_MAX_ENV, 512)
    if len(ring) > tail:
        ring = ring[-tail:]
    try:
        census = _census_provider() if _census_provider is not None else None
    except Exception:
        census = {"error": "census provider failed"}
    try:
        leak = memory.leak_census()
    except Exception:
        leak = {"error": "leak census failed"}
    verdict = None
    if exc is not None and _classifier is not None:
        try:
            verdict = _classifier(exc)
        except Exception:
            verdict = None               # the recorder must never re-crash
    try:
        # the live plane's last drop/lag counters: a postmortem must show
        # whether streamed telemetry was degraded at crash time (counted
        # drops mean the aggregator's view of the final seconds is partial)
        from . import stream as _stream
        stream_stats = _stream.stats()
    except Exception:
        stream_stats = {"armed": False}
    return {
        "kind": "da_tpu_postmortem",
        "schema_version": SCHEMA_VERSION,
        "incident": core.current_incident(),
        "reason": reason,
        "classification": verdict,
        "host": core._HOST,
        "pid": os.getpid(),
        "wall": round(time.time(), 3),
        "t": round(time.monotonic() - core._T0, 6),
        "exception": _exc_info(exc),
        "ring": ring,
        "open_spans": tracing.open_spans(),
        "span_stats": tracing.span_stats(),
        "ledger": memory.snapshot(),
        "ledger_entries": memory.entries(limit=100),
        "registry_census": census,
        "leak_census": leak,
        "divergence": [e for e in ring if e.get("cat") == "divergence"],
        "journal_path": core.journal_path(),
        "stream": stream_stats,
    }


def postmortem(reason: str = "on_demand", exc=None,
               path: str | None = None) -> str | None:
    """Snapshot a bundle and write it as JSON.

    Returns the written path, or ``None`` when telemetry is disabled or
    no destination exists (bundle still kept — :func:`last_bundle`).
    """
    global _bundles_written, _last_bundle, _last_path
    if not core._ENABLED:
        return None
    bundle = snapshot_bundle(reason, exc)
    with _lock:
        _last_bundle = bundle
        if path is None:
            d = _flight_dir()
            if d is not None:
                # reserve the slot under the lock: two threads crashing
                # concurrently must not compute the same bundle path and
                # clobber each other's evidence
                path = os.path.join(
                    d, f"postmortem-{os.getpid()}-{_bundles_written}.json")
                _bundles_written += 1
    if path is None:
        return None
    try:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(bundle, f, indent=2, sort_keys=True, default=str)
        os.replace(tmp, path)
    except OSError:
        return None                  # the recorder must never crash a crash
    with _lock:
        _last_path = path
    core.event("flight", "postmortem", reason=reason, path=path)
    return path


def record_crash(exc, where: str) -> str | None:
    """Crash-path trigger: one bundle per exception object, at most
    ``DA_TPU_FLIGHT_MAX`` (default 8) per process — counted per crash
    *assembly*, so the cap holds in memory-only mode too (no flight dir
    configured).  Exceptions chained from an already-bundled root cause
    are not re-bundled."""
    global _crash_bundles
    if not core._ENABLED:
        return None

    def _fingerprint(e):
        return (type(e).__name__, str(e)[:200])

    def _seen_locked(e) -> bool:
        if e is None:
            return False
        v = _bundled_excs.get(id(e))
        if v is None:
            return False
        if isinstance(v, weakref.ref):
            if v() is e:
                return True
        elif v == _fingerprint(e):
            return True
        del _bundled_excs[id(e)]     # dead entry whose id got recycled
        return False

    with _lock:
        if _crash_bundles >= _int_env(_MAX_ENV, 8):
            return None
        if _seen_locked(exc) or _seen_locked(exc.__cause__):
            return None
        _crash_bundles += 1
        if len(_bundled_excs) > 64:  # bound the dict: dead refs, then FIFO
            for k in [k for k, v in _bundled_excs.items()
                      if isinstance(v, weakref.ref) and v() is None]:
                del _bundled_excs[k]
            while len(_bundled_excs) > 64:
                del _bundled_excs[next(iter(_bundled_excs))]
        try:
            _bundled_excs[id(exc)] = weakref.ref(exc)
        except TypeError:
            _bundled_excs[id(exc)] = _fingerprint(exc)
    return postmortem(f"exception:{where}", exc)


def last_bundle() -> dict | None:
    """The most recent bundle (written or in-memory-only)."""
    with _lock:
        return _last_bundle


def install_sigusr1() -> bool:
    """Install a SIGUSR1 handler dumping a postmortem bundle.  Main
    thread only; returns True when installed.  Chained onto any existing
    non-default handler."""
    global _sig_installed
    if _sig_installed:
        return True
    try:
        import signal
        if threading.current_thread() is not threading.main_thread():
            return False
        sig = getattr(signal, "SIGUSR1", None)
        if sig is None:
            return False
        prev = signal.getsignal(sig)

        def _handler(signum, frame):
            # a signal interrupts the main thread at an arbitrary
            # bytecode — possibly INSIDE a flight._lock critical section
            # (non-reentrant: dumping would self-deadlock) or INSIDE
            # core._LOCK (reentrant, worse: re-entry would interleave a
            # journal line into a half-written one, or snapshot the
            # ledger mid-update).  Skipping the dump is the safe failure
            # mode for both.
            core_owned = getattr(core._LOCK, "_is_owned", lambda: False)()
            if not core_owned and _lock.acquire(blocking=False):
                _lock.release()
                postmortem("sigusr1")
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)

        signal.signal(sig, _handler)
        _sig_installed = True
        return True
    except Exception:
        return False


def _sigusr1_wanted() -> bool:
    v = os.environ.get("DA_TPU_FLIGHT_SIGUSR1")
    return v is None or v.strip().lower() not in core._FALSY


def _reset() -> None:
    global _bundles_written, _crash_bundles, _last_bundle, _last_path
    with _lock:
        _bundled_excs.clear()
        _bundles_written = 0
        _crash_bundles = 0
        _last_bundle = None
        _last_path = None


core.register_reset_hook(_reset)

if core._ENABLED and _sigusr1_wanted():
    install_sigusr1()
