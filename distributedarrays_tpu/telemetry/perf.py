"""Performance observatory: roofline attribution, overlap, critical path.

PR 2's spans say *where* the time went; this module says *why* and
*whether it had to*.  Three pure-function analyses over the journal
(stdlib only, like ``export.py`` — a journal pulled off a pod worker
analyzes on any machine):

- **roofline classification** — hot spans carry analytic cost stamps
  (``flops=``/``bytes_hbm=``/``bytes_ici=`` labels computed from shapes
  at the call site; see :func:`gemm_cost` and friends).  Joined against a
  per-platform peak table (:data:`DEFAULT_PEAKS`; ``DA_TPU_PEAKS``
  env/JSON override) every span occurrence classifies as compute-, HBM-,
  or ICI-bound: the binding resource is the one whose analytic service
  time fills the largest fraction of the measured duration, and that
  fraction is the achieved-vs-roofline number.
- **overlap attribution** — for a span that both communicates and
  computes (a ring GEMM step, an RDMA reshard), the measured duration
  against the analytic comm/compute times bounds how much of the comm
  was hidden: ``dur == t_comm + t_work`` means fully serial, ``dur ==
  max(t_comm, t_work)`` means fully overlapped.  :func:`interval_overlap`
  is the measured twin for timelines that *do* expose comm and compute
  as separate child spans (multi-rank tracks included).
- **critical path** — the chain of spans that determines a root span's
  wall time (gaps attribute to the parent's own work), so "make this
  step faster" starts from the segment that actually gates it.

``python -m distributedarrays_tpu.telemetry doctor RUN.jsonl`` renders
all three as ranked findings; :func:`analyze` is the library entry.
"""

from __future__ import annotations

import json
import os

__all__ = [
    "DEFAULT_PEAKS", "peaks_for", "platform_alias",
    "gemm_cost", "reshard_cost", "attention_cost", "reduce_cost",
    "transfer_cost", "train_step_cost",
    "decode_step_cost", "spmv_cost",
    "span_cost", "classify_occurrence", "classify", "coverage",
    "overlap_stats", "interval_overlap", "timeline_overlap",
    "train_step_overlap", "critical_path", "analyze",
]

PEAKS_ENV = "DA_TPU_PEAKS"

# Per-platform SINGLE-CHIP peaks: dense flops/s (bf16 for TPUs), HBM
# bytes/s, and aggregate ICI bytes/s over one chip's links.  Datasheet
# numbers for the TPUs; the CPU row is a deliberately round
# single-socket default — every number here is a *denominator for a
# fraction*, overridable via DA_TPU_PEAKS (inline JSON or a path to
# one): either a full ``{platform: {...}}`` table merged over the
# defaults, or a single ``{"flops": ...}`` dict applied to whatever
# platform is selected.
#
# Convention: span cost stamps are AGGREGATE volumes over all
# participating devices (2mnk flops for the whole distributed GEMM, the
# plan's total moved bytes, ...), while these peaks are single-chip —
# so a p-way span's roofline fraction reads as achieved share of ONE
# chip's peak (capped at 1).  The binding-resource classification and
# any comparison between spans of the same world size are exact; the
# absolute fraction of a multi-chip span is a conservative lower bound
# on how far it sits from the hardware roofline.
DEFAULT_PEAKS = {
    "tpu-v5e": {"flops": 197e12, "hbm": 819e9, "ici": 200e9},
    "tpu-v5p": {"flops": 459e12, "hbm": 2765e9, "ici": 600e9},
    "cpu": {"flops": 2e11, "hbm": 5e10, "ici": 2e10},
}

_ALIASES = {
    "v5e": "tpu-v5e", "tpu v5e": "tpu-v5e", "tpu v5 lite": "tpu-v5e",
    "tpu-v5litepod": "tpu-v5e", "v5litepod": "tpu-v5e",
    "v5p": "tpu-v5p", "tpu v5p": "tpu-v5p", "tpu v5": "tpu-v5p",
    "cpu": "cpu", "host": "cpu", "interpret": "cpu",
}

_RESOURCES = ("flops", "bytes_hbm", "bytes_ici")
_BOUND = {"flops": "compute", "bytes_hbm": "hbm", "bytes_ici": "ici"}
_PEAK_OF = {"flops": "flops", "bytes_hbm": "hbm", "bytes_ici": "ici"}


def platform_alias(name: str | None) -> str:
    """Normalize a platform/device-kind string to a peak-table key
    (unknown names fall back to ``cpu`` — the conservative denominator)."""
    if not name:
        return "cpu"
    key = str(name).strip().lower()
    if key in DEFAULT_PEAKS:
        return key
    return _ALIASES.get(key, "cpu")


def peaks_for(platform: str | None = None) -> dict:
    """The ``{"flops", "hbm", "ici"}`` peak dict for ``platform``,
    after applying the ``DA_TPU_PEAKS`` override (inline JSON or a path
    to a JSON file; a full per-platform table or a single flat dict)."""
    plat = platform_alias(platform)
    table = {k: dict(v) for k, v in DEFAULT_PEAKS.items()}
    raw = os.environ.get(PEAKS_ENV)
    if raw:
        doc = None
        try:
            doc = json.loads(raw)
        except ValueError:
            try:
                with open(raw) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                doc = None
        if isinstance(doc, dict):
            if any(isinstance(v, dict) for v in doc.values()):
                for k, v in doc.items():
                    if isinstance(v, dict):
                        table.setdefault(platform_alias(k), {}).update(
                            {kk: float(vv) for kk, vv in v.items()})
            else:                       # flat override for this platform
                table.setdefault(plat, {}).update(
                    {kk: float(vv) for kk, vv in doc.items()})
    peaks = dict(DEFAULT_PEAKS["cpu"])
    peaks.update(table.get(plat, {}))
    peaks["platform"] = plat
    return peaks


# ---------------------------------------------------------------------------
# analytic cost stamps (computed from shapes at the instrumented call site)
# ---------------------------------------------------------------------------


def gemm_cost(m: int, n: int, k: int, itemsize: int = 4, *,
              out_itemsize: int | None = None,
              bytes_ici: int = 0) -> dict:
    """Roofline stamp for an ``(m, k) @ (k, n)`` GEMM: ``2mnk`` flops,
    operands read + result written once through HBM, and whatever ICI
    volume the caller's collective plan implies."""
    oi = itemsize if out_itemsize is None else int(out_itemsize)
    return {
        "flops": 2 * int(m) * int(n) * int(k),
        "bytes_hbm": (int(m) * int(k) + int(k) * int(n)) * int(itemsize)
        + int(m) * int(n) * oi,
        "bytes_ici": int(bytes_ici),
    }


def reshard_cost(total_bytes: int, moved_bytes: int) -> dict:
    """Stamp for a reshard: every byte read and rewritten through HBM,
    the plan's *moved* bytes crossing a device boundary, zero flops."""
    return {"flops": 0, "bytes_hbm": 2 * int(total_bytes),
            "bytes_ici": int(moved_bytes)}


def transfer_cost(nbytes: int) -> dict:
    """Stamp for a host<->device transfer (distribute / gather): the
    payload through HBM once; no flops, no ICI."""
    return {"flops": 0, "bytes_hbm": int(nbytes), "bytes_ici": 0}


def attention_cost(s: int, h: int, d: int, itemsize: int = 4, *,
                   p: int = 1, causal: bool = False) -> dict:
    """Stamp for exact attention over a ``(s, h, d)`` q/k/v triple
    sharded over ``p`` ranks: two ``s x s x d`` GEMMs per head (halved
    causal), q/k/v/o through HBM once, and the k/v chunks rotating
    ``p - 1`` ring steps over ICI."""
    fl = 4 * int(s) * int(s) * int(h) * int(d)
    if causal:
        fl //= 2
    kv = 2 * int(s) * int(h) * int(d) * int(itemsize)
    return {
        "flops": fl,
        "bytes_hbm": 4 * int(s) * int(h) * int(d) * int(itemsize),
        "bytes_ici": (int(p) - 1) * kv if p > 1 else 0,
    }


def decode_step_cost(ctx_tokens: int, h: int, d: int,
                     itemsize: int = 4, *, new_tokens: int = 1) -> dict:
    """Stamp for one continuous-batching decode step: ``ctx_tokens``
    total resident context rows across the batch attended by
    ``new_tokens`` single-row queries.  Two row-by-context GEMVs per
    head (``4·ctx·h·d`` flops) against the *entire* K/V working set
    streamed from HBM once plus the new rows written back — arithmetic
    intensity ~0.5 flop/byte at f32, firmly under any roofline ridge,
    which is exactly why the doctor must show decode HBM-bound where
    prefill (:func:`attention_cost`, O(s²) flops over O(s) bytes) shows
    compute-bound."""
    e = int(h) * int(d)
    return {
        "flops": 4 * int(ctx_tokens) * e,
        "bytes_hbm": (2 * int(ctx_tokens) + 3 * int(new_tokens)) * e
        * int(itemsize),
        "bytes_ici": 0,
    }


def spmv_cost(nnz: int, rows: int, itemsize: int = 4, *,
              index_itemsize: int = 4, bytes_ici: int = 0) -> dict:
    """Stamp for a sparse (or stencil) matvec: 2 flops per stored
    nonzero against the nonzeros (values + column indices) streamed
    from HBM once, plus the vector read and result written back.
    Arithmetic intensity ~0.25 flop/byte at f32+int32 — far under any
    ridge point, so the doctor classifies SpMV HBM-bound, or ICI-bound
    once the caller's halo exchange (``bytes_ici``) dominates.  Stencil
    callers pass ``index_itemsize=0`` (the pattern compiles into the
    kernel; only values move)."""
    return {
        "flops": 2 * int(nnz),
        "bytes_hbm": int(nnz) * (int(itemsize) + int(index_itemsize))
        + 2 * int(rows) * int(itemsize),
        "bytes_ici": int(bytes_ici),
    }


def reduce_cost(n_elems: int, itemsize: int = 4, *,
                flops_per_elem: int = 1) -> dict:
    """Stamp for a mapreduce-style sweep: ~1 flop and one HBM read per
    element (map cost unknown — this is the floor, which classifies the
    sweep HBM-bound exactly when it should be)."""
    return {"flops": int(n_elems) * int(flops_per_elem),
            "bytes_hbm": int(n_elems) * int(itemsize), "bytes_ici": 0}


def train_step_cost(n_params: int, p: int, *, flops: float = 0.0,
                    batch_bytes: int = 0, itemsize: int = 4,
                    nslots: int = 2) -> dict:
    """Stamp for one data-parallel ZeRO-1 training step over ``p``
    ranks: the gradient sync is one ring all-gather of the parameter
    shards plus one ring reduce-scatter of the full gradients —
    aggregate ICI volume ``2 (p-1) n_params itemsize`` — and the HBM
    floor is ``3 + 2 nslots`` parameter-vector passes (read params,
    grads and each optimizer moment; write params and each moment —
    7 passes for Adam's two moments, 3 for plain SGD) plus the batch
    read once.  ``flops`` is the task's fwd+bwd estimate (aggregate,
    like every stamp)."""
    n = int(n_params) * int(itemsize)
    return {
        "flops": float(flops),
        "bytes_hbm": (3 + 2 * int(nslots)) * n + int(batch_bytes),
        "bytes_ici": 2 * (int(p) - 1) * n if p > 1 else 0,
    }


# ---------------------------------------------------------------------------
# roofline classification
# ---------------------------------------------------------------------------


def span_cost(span_ev: dict) -> dict | None:
    """The cost stamp on one journal span event (``labels`` holding any
    of ``flops``/``bytes_hbm``/``bytes_ici``), or None when unstamped."""
    labels = span_ev.get("labels") or {}
    out = {}
    for key in _RESOURCES:
        try:
            out[key] = max(float(labels.get(key, 0) or 0), 0.0)
        except (TypeError, ValueError):
            out[key] = 0.0
    if not any(out.values()):
        return None
    return out


def classify_occurrence(span_ev: dict, peaks: dict) -> dict | None:
    """Classify one stamped span occurrence: the binding resource is the
    one whose analytic service time (stamp / peak) fills the largest
    fraction of the measured duration; that fraction is the roofline
    number (capped at 1 — an over-unity estimate means the stamp or the
    peak is off, not that the hardware overperformed)."""
    cost = span_cost(span_ev)
    dur = span_ev.get("dur")
    if cost is None or not dur or dur <= 0:
        return None
    t_est = {}
    achieved = {}
    for key in _RESOURCES:
        peak = float(peaks.get(_PEAK_OF[key], 0) or 0)
        t_est[key] = (cost[key] / peak) if peak > 0 else 0.0
        achieved[key] = cost[key] / dur
    bound_key = max(_RESOURCES, key=lambda k: t_est[k])
    frac = t_est[bound_key] / dur
    occ = {
        "name": span_ev.get("name"),
        "span_id": span_ev.get("span_id"),
        "dur": float(dur),
        "bound": _BOUND[bound_key],
        "roofline_frac": min(round(frac, 4), 1.0),
        "t_est": {k: round(v, 9) for k, v in t_est.items()},
        "achieved": {k: round(v, 3) for k, v in achieved.items()},
        "labels": dict(span_ev.get("labels") or {}),
    }
    if span_ev.get("trace_id"):
        occ["trace_id"] = span_ev["trace_id"]
    return occ


def classify(events: list, peaks: dict | None = None) -> list:
    """Every stamped span occurrence in the journal, classified."""
    peaks = peaks or peaks_for()
    out = []
    for e in events:
        if e.get("cat") != "span":
            continue
        occ = classify_occurrence(e, peaks)
        if occ is not None:
            out.append(occ)
    return out


def _span_forest(events: list) -> tuple[dict, dict, list]:
    """(spans by id, children ids by parent id, root ids) over the
    journal's finished span events."""
    spans = {}
    for e in events:
        if e.get("cat") == "span" and e.get("dur") is not None \
                and e.get("span_id") is not None:
            spans[e["span_id"]] = e
    children: dict = {}
    roots = []
    for sid, e in spans.items():
        pid = e.get("parent_id")
        if pid is not None and pid in spans:
            children.setdefault(pid, []).append(sid)
        else:
            roots.append(sid)
    return spans, children, roots


def coverage(events: list) -> dict:
    """How much of the journal's span wall time is cost-classified.

    Wall = the summed durations of root spans; a root's attributed time
    is its own duration when it carries a cost stamp, else the sum over
    its children (recursively) — a stamped parent covers its subtree, an
    unstamped parent is covered only as far as stamped descendants
    reach."""
    spans, children, roots = _span_forest(events)

    def attributed(sid: int, depth: int = 0) -> float:
        if depth > 256:                 # malformed parent links
            return 0.0
        e = spans[sid]
        if span_cost(e) is not None:
            return float(e["dur"])
        return min(float(e["dur"]),
                   sum(attributed(c, depth + 1)
                       for c in children.get(sid, [])))

    wall = sum(float(spans[r]["dur"]) for r in roots)
    att = sum(attributed(r) for r in roots)
    return {"wall_s": round(wall, 6), "attributed_s": round(att, 6),
            "fraction": round(att / wall, 4) if wall > 0 else 0.0,
            "roots": len(roots), "spans": len(spans)}


# ---------------------------------------------------------------------------
# overlap attribution
# ---------------------------------------------------------------------------


def overlap_stats(span_ev: dict, peaks: dict) -> dict | None:
    """Model-tier overlap for one stamped span that both communicates
    (``bytes_ici > 0``) and works (flops or HBM bytes): with analytic
    comm time ``t_comm`` and work time ``t_work``, a measured duration of
    ``t_comm + t_work`` is fully serial and ``max(t_comm, t_work)`` fully
    overlapped — the fraction of ``t_comm`` hidden under work is
    ``(t_comm + t_work - dur) / t_comm`` clamped into [0, 1].  Reports
    per-step numbers when the span carries a ring size (``ranks`` or
    ``nparts`` label: ``p - 1`` steps)."""
    cost = span_cost(span_ev)
    dur = span_ev.get("dur")
    if cost is None or not dur or dur <= 0 or cost["bytes_ici"] <= 0:
        return None
    ici = float(peaks.get("ici", 0) or 0)
    if ici <= 0:
        return None
    t_comm = cost["bytes_ici"] / ici
    t_work = max(
        cost["flops"] / peaks["flops"] if peaks.get("flops") else 0.0,
        cost["bytes_hbm"] / peaks["hbm"] if peaks.get("hbm") else 0.0)
    overlapped = min(max(t_comm + t_work - dur, 0.0), min(t_comm, t_work))
    unoverlapped = min(max(t_comm - overlapped, 0.0), float(dur))
    labels = span_ev.get("labels") or {}
    steps = None
    for key in ("ranks", "nparts", "p"):
        try:
            p = int(labels.get(key, 0) or 0)
        except (TypeError, ValueError):
            p = 0
        if p >= 2:
            steps = p - 1
            break
    out = {
        "name": span_ev.get("name"),
        "span_id": span_ev.get("span_id"),
        "dur": float(dur),
        "dispatch": labels.get("dispatch"),
        "labels": dict(labels),
        "t_comm": round(t_comm, 9),
        "t_work": round(t_work, 9),
        "overlap_frac": round(overlapped / t_comm, 4) if t_comm else 0.0,
        "unoverlapped_s": round(unoverlapped, 9),
        "unoverlapped_wall_frac": round(unoverlapped / dur, 4),
    }
    if steps:
        out["steps"] = steps
        out["per_step"] = {
            "dur": round(dur / steps, 9),
            "t_comm": round(t_comm / steps, 9),
            "unoverlapped_s": round(unoverlapped / steps, 9),
            "overlap_frac": out["overlap_frac"],
        }
    return out


def _union(intervals: list) -> list:
    """Merge ``(start, end)`` intervals into a disjoint sorted union."""
    ivs = sorted((float(a), float(b)) for a, b in intervals if b > a)
    out: list = []
    for a, b in ivs:
        if out and a <= out[-1][1]:
            out[-1][1] = max(out[-1][1], b)
        else:
            out.append([a, b])
    return [(a, b) for a, b in out]


def _measure(intervals: list) -> float:
    return sum(b - a for a, b in intervals)


def interval_overlap(comm: list, compute: list) -> dict:
    """Measured-tier overlap: ``comm`` and ``compute`` are lists of
    ``(start, end)`` intervals (any thread/rank — skewed multi-rank
    timelines union per class before intersecting).  Returns total comm
    seconds, the seconds of comm overlapped by compute, and the
    fraction."""
    cu, wu = _union(comm), _union(compute)
    ov = 0.0
    i = j = 0
    while i < len(cu) and j < len(wu):
        a = max(cu[i][0], wu[j][0])
        b = min(cu[i][1], wu[j][1])
        if b > a:
            ov += b - a
        if cu[i][1] <= wu[j][1]:
            i += 1
        else:
            j += 1
    total = _measure(cu)
    return {"comm_s": round(total, 9), "overlapped_s": round(ov, 9),
            "unoverlapped_s": round(total - ov, 9),
            "overlap_frac": round(ov / total, 4) if total > 0 else 0.0}


def _span_kind(span_ev: dict) -> str | None:
    """comm/compute classification of one span for the timeline tier:
    an explicit ``kind=`` label wins; else a stamped span with ICI bytes
    and no flops is comm, any other stamped span is compute."""
    labels = span_ev.get("labels") or {}
    kind = labels.get("kind")
    if kind in ("comm", "compute"):
        return kind
    cost = span_cost(span_ev)
    if cost is None:
        return None
    if cost["bytes_ici"] > 0 and cost["flops"] <= 0:
        return "comm"
    return "compute"


def timeline_overlap(events: list) -> list:
    """Measured overlap per *step*: group child spans by parent, split
    them comm/compute (see :func:`_span_kind` — rank-skewed children on
    different threads land in the same step), and intersect the unions.
    Returns one entry per parent that has at least one comm child."""
    spans, children, _ = _span_forest(events)
    out = []
    for pid, kids in sorted(children.items()):
        comm, compute = [], []
        for sid in kids:
            e = spans[sid]
            iv = (float(e.get("start", 0.0)),
                  float(e.get("start", 0.0)) + float(e["dur"]))
            kind = _span_kind(e)
            if kind == "comm":
                comm.append(iv)
            elif kind == "compute":
                compute.append(iv)
        if not comm:
            continue
        parent = spans[pid]
        entry = {"step": parent.get("name"), "span_id": pid,
                 "dur": float(parent["dur"])}
        entry.update(interval_overlap(comm, compute))
        out.append(entry)
    return out


def train_step_overlap(events: list) -> list:
    """Measured grad-sync overlap per *training step*: every
    ``train.step`` span's children split comm/compute (the trainer
    labels ``train.sync`` ``kind=comm`` and ``train.grad``
    ``kind=compute``; rank-skewed children on other threads join the
    same step) and the unions intersect.  One entry per step, in step
    order, carrying the step index / rank count / dispatch labels so
    the doctor can print a per-step trajectory — including steps whose
    sync never overlapped anything (overlap_frac 0.0), which is the
    finding."""
    spans, children, _ = _span_forest(events)
    out = []
    for pid, kids in children.items():
        parent = spans[pid]
        if parent.get("name") != "train.step":
            continue
        comm, compute = [], []
        for sid in kids:
            e = spans[sid]
            iv = (float(e.get("start", 0.0)),
                  float(e.get("start", 0.0)) + float(e["dur"]))
            kind = _span_kind(e)
            if kind == "comm":
                comm.append(iv)
            elif kind == "compute":
                compute.append(iv)
        if not comm:
            continue
        labels = parent.get("labels") or {}
        entry = {"step": labels.get("step"), "span_id": pid,
                 "dur": float(parent["dur"]),
                 "ranks": labels.get("ranks"),
                 "dispatch": labels.get("dispatch")}
        entry.update(interval_overlap(comm, compute))
        out.append(entry)
    def _step_key(e):
        try:
            return (0, int(e["step"]))
        except (TypeError, ValueError):
            return (1, e["span_id"])
    out.sort(key=_step_key)
    return out


# ---------------------------------------------------------------------------
# critical path
# ---------------------------------------------------------------------------

_EPS = 1e-9


def critical_path(events: list, root_span_id: int | None = None) -> list:
    """The chain of span segments that gates a root span's wall time.

    Walks backward from the root's end: at each point the latest-ending
    child interval takes the segment (recursing into its own children);
    gaps with no child running attribute to the parent itself.  Returns
    ``[{"name", "span_id", "self_s"}, ...]`` in timeline order, summing
    to the root's duration.  Default root: the longest root span."""
    spans, children, roots = _span_forest(events)
    if not spans:
        return []
    if root_span_id is None:
        if not roots:
            return []
        root_span_id = max(roots, key=lambda r: spans[r]["dur"])
    if root_span_id not in spans:
        return []

    def seg(acc: list, e: dict, seconds: float) -> None:
        if seconds <= _EPS:
            return
        if acc and acc[-1]["span_id"] == e.get("span_id"):
            acc[-1]["self_s"] += seconds
        else:
            acc.append({"name": e.get("name"),
                        "span_id": e.get("span_id"),
                        "self_s": seconds})

    def walk(sid: int, t_end: float, depth: int = 0) -> list:
        e = spans[sid]
        start = float(e.get("start", 0.0))
        t = min(t_end, start + float(e["dur"]))
        if depth > 64:
            return [{"name": e.get("name"), "span_id": sid,
                     "self_s": max(t - start, 0.0)}]
        kids = [spans[c] for c in children.get(sid, [])]
        segs: list = []                  # built backward, reversed at end
        while t > start + _EPS:
            cands = [k for k in kids
                     if float(k.get("start", 0.0)) < t - _EPS]
            if not cands:
                seg(segs, e, t - start)
                break
            c = max(cands, key=lambda k: min(
                float(k.get("start", 0.0)) + float(k["dur"]), t))
            c_start = float(c.get("start", 0.0))
            c_end = min(c_start + float(c["dur"]), t)
            if c_end < t - _EPS:
                seg(segs, e, t - c_end)   # gap: the parent's own work
            segs.extend(walk(c["span_id"], c_end, depth + 1)[::-1])
            t = c_start
            kids = [k for k in kids if k is not c]
        out: list = []
        for s in segs[::-1]:
            seg(out, {"name": s["name"], "span_id": s["span_id"]},
                s["self_s"])
        return out

    path = walk(root_span_id, float("inf"))
    for s in path:
        s["self_s"] = round(s["self_s"], 9)
    return path


# ---------------------------------------------------------------------------
# the doctor: everything above, as ranked findings
# ---------------------------------------------------------------------------


def _rollup(classified: list) -> dict:
    """Per-name rollup of classified occurrences: count, total seconds,
    the dominant bound class, and the time-weighted roofline fraction."""
    by: dict = {}
    for occ in classified:
        r = by.setdefault(occ["name"], {
            "count": 0, "total_s": 0.0, "frac_weighted": 0.0,
            "bounds": {}})
        r["count"] += 1
        r["total_s"] += occ["dur"]
        r["frac_weighted"] += occ["roofline_frac"] * occ["dur"]
        r["bounds"][occ["bound"]] = r["bounds"].get(occ["bound"], 0) + 1
    out = {}
    for name, r in by.items():
        out[name] = {
            "count": r["count"],
            "total_s": round(r["total_s"], 6),
            "bound": max(r["bounds"], key=r["bounds"].get),
            "roofline_frac": round(r["frac_weighted"] / r["total_s"], 4)
            if r["total_s"] > 0 else 0.0,
        }
    return out


def _action_hint(kind: str, name, labels: dict) -> dict | None:
    """Machine-readable tuning hint for one finding: the autotune
    ``kernel`` namespace, the exact registry ``key`` (from the span's
    ``autotune_key`` / ``dispatch_key`` labels), and a suggested
    ``direction`` — so the advisor consumes structure instead of
    re-parsing detail strings.  ``None`` when the span carries no
    addressable registry key."""
    labels = labels or {}
    if kind == "unoverlapped_comm":
        if labels.get("dispatch") == "rdma" and labels.get("autotune_key"):
            return {"kernel": "rdma_chunks",
                    "key": labels["autotune_key"],
                    "param": "chunks",
                    "direction": "increase",
                    "current": labels.get("rdma_chunks"),
                    "dispatch_key": labels.get("dispatch_key")}
        if labels.get("dispatch_key"):
            return {"kernel": "rdma_dispatch",
                    "key": labels["dispatch_key"],
                    "param": "dispatch",
                    "direction": "compare",
                    "current": labels.get("dispatch")}
        return None
    if kind == "low_roofline":
        if name == "pallas.matmul" and labels.get("autotune_key"):
            return {"kernel": "pallas_matmul",
                    "key": labels["autotune_key"],
                    "param": "block",
                    "direction": "resweep",
                    "shape": labels.get("shape"),
                    "dtype": labels.get("dtype")}
        return None
    return None


def analyze(events: list, peaks: dict | None = None,
            platform: str | None = None) -> dict:
    """The doctor's full report over one journal: coverage, per-name
    roofline rollups, per-occurrence overlap, the critical path of the
    longest root, and ranked findings (each carrying a machine-readable
    ``action`` hint when its span names an autotune registry key)."""
    peaks = peaks or peaks_for(platform)
    classified = classify(events, peaks)
    cov = coverage(events)
    overlaps = [s for s in (
        overlap_stats(e, peaks) for e in events if e.get("cat") == "span")
        if s is not None]
    measured = timeline_overlap(events)
    train_steps = train_step_overlap(events)
    cpath = critical_path(events)
    findings = []
    for ov in overlaps:
        if ov["unoverlapped_s"] <= 0:
            continue
        where = ov["name"]
        if ov.get("dispatch"):
            where += f"[{ov['dispatch']}]"
        findings.append({
            "kind": "unoverlapped_comm",
            "severity_s": ov["unoverlapped_s"],
            "span_id": ov["span_id"],
            "action": _action_hint("unoverlapped_comm", ov["name"],
                                   ov.get("labels")),
            "message": (
                f"{where} spent {ov['unoverlapped_wall_frac']:.0%} of wall "
                f"in unoverlapped ICI ({ov['unoverlapped_s']:.6f}s of "
                f"{ov['dur']:.6f}s; overlap fraction "
                f"{ov['overlap_frac']:.2f}"
                + (f", {ov['steps']} ring steps" if ov.get("steps") else "")
                + ")"),
        })
    for occ in classified:
        slack = occ["dur"] * (1.0 - occ["roofline_frac"])
        if occ["roofline_frac"] < 0.5 and slack > 0:
            findings.append({
                "kind": "low_roofline",
                "severity_s": round(slack, 9),
                "span_id": occ["span_id"],
                "action": _action_hint("low_roofline", occ["name"],
                                       occ.get("labels")),
                "message": (
                    f"{occ['name']} ran at {occ['roofline_frac']:.1%} of "
                    f"the {occ['bound']} roofline "
                    f"({occ['dur']:.6f}s; {slack:.6f}s of headroom)"),
            })
    if cov["fraction"] < 0.9 and cov["wall_s"] > 0:
        findings.append({
            "kind": "coverage_gap",
            "severity_s": round(cov["wall_s"] - cov["attributed_s"], 9),
            "action": None,
            "message": (
                f"only {cov['fraction']:.1%} of {cov['wall_s']:.6f}s span "
                "wall is cost-classified — stamp the missing spans"),
        })
    findings.sort(key=lambda f: -f["severity_s"])
    return {
        "platform": peaks.get("platform", "cpu"),
        "peaks": {k: peaks[k] for k in ("flops", "hbm", "ici")
                  if k in peaks},
        "coverage": cov,
        "by_name": _rollup(classified),
        "classified": classified,
        "overlap": overlaps,
        "measured_overlap": measured,
        "train_steps": train_steps,
        "critical_path": cpath,
        "findings": findings,
    }


def format_analysis(a: dict, out) -> None:
    """Human rendering of :func:`analyze` (the ``doctor`` CLI body)."""
    cov = a["coverage"]
    out.write(f"platform: {a['platform']}  peaks: "
              + "  ".join(f"{k}={v:.3g}" for k, v in a["peaks"].items())
              + "\n")
    out.write(f"coverage: {cov['fraction']:.1%} of {cov['wall_s']:.6f}s "
              f"wall cost-classified ({cov['spans']} spans, "
              f"{cov['roots']} roots)\n")
    if a["by_name"]:
        out.write("\nroofline by span name:\n")
        for name, r in sorted(a["by_name"].items(),
                              key=lambda kv: -kv[1]["total_s"]):
            out.write(f"  {name:<28} {r['count']:>5} x "
                      f"{r['total_s']:>12.6f}s  {r['bound']:<8} "
                      f"{r['roofline_frac']:>6.1%} of roofline\n")
    if a["overlap"]:
        out.write("\ncomm/compute overlap (model tier):\n")
        for ov in sorted(a["overlap"], key=lambda o: -o["unoverlapped_s"]):
            tag = f"[{ov['dispatch']}]" if ov.get("dispatch") else ""
            step = (f"  per-step {ov['per_step']['overlap_frac']:.2f} "
                    f"over {ov['steps']} steps" if ov.get("steps") else "")
            out.write(f"  {ov['name']}{tag:<10} overlap "
                      f"{ov['overlap_frac']:.2f}  unoverlapped "
                      f"{ov['unoverlapped_s']:.6f}s "
                      f"({ov['unoverlapped_wall_frac']:.0%} of wall)"
                      f"{step}\n")
    if a["measured_overlap"]:
        out.write("\ncomm/compute overlap (measured tier):\n")
        for ov in a["measured_overlap"]:
            out.write(f"  {ov['step']:<28} overlap {ov['overlap_frac']:.2f}"
                      f"  unoverlapped {ov['unoverlapped_s']:.6f}s\n")
    if a.get("train_steps"):
        out.write("\ngrad-sync overlap per training step:\n")
        for ov in a["train_steps"]:
            tag = f"[{ov['dispatch']}]" if ov.get("dispatch") else ""
            ranks = f" p={ov['ranks']}" if ov.get("ranks") else ""
            out.write(f"  step {str(ov['step']):<6}{tag}{ranks}  "
                      f"sync {ov['comm_s']:.6f}s  overlap "
                      f"{ov['overlap_frac']:.2f}  unoverlapped "
                      f"{ov['unoverlapped_s']:.6f}s\n")
    if a["critical_path"]:
        out.write("\ncritical path (longest root):\n")
        for s in a["critical_path"]:
            out.write(f"  {s['name']:<28} {s['self_s']:>12.6f}s\n")
    out.write(f"\nfindings ({len(a['findings'])}):\n")
    for i, f in enumerate(a["findings"][:20], 1):
        out.write(f"  {i:>2}. [{f['kind']}] {f['message']}\n")
