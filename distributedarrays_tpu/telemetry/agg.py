"""Live telemetry plane, cluster side: the aggregator service.

Ingests the per-host delta frames published by :mod:`telemetry.stream`
exporters and turns them into one *live* cluster view:

- **one timeline** — per-host event tails are aligned in real time with
  the SAME three-tier clock-offset machinery the post-hoc merge uses
  (:func:`telemetry.cluster.merge_journals` accepts already-parsed event
  lists), so the live ordering matches what an offline
  ``telemetry incident`` merge of the journals would produce;
- **live alerting** — an :class:`alerts.AlertManager` is driven from
  the streamed signals (gauge points with their original wall
  timestamps, counter deltas) instead of the dead journal: the stock
  SLO burn rules fire *while the workload runs* and clear with the same
  hysteresis;
- **scrapeable metrics** — a real Prometheus ``/metrics`` endpoint
  re-exports every host's registry with a ``host`` label plus the
  stream's own health (``da_tpu_stream_dropped_frames`` et al.), and
  ``/healthz`` answers liveness probes;
- **live traces and flames** — ``/trace`` serves the merged timeline as
  a chunked Perfetto download and ``/flame`` the merged collapsed-stack
  profile; ``/snapshot`` feeds the ``telemetry top`` dashboard.

Everything is stdlib (``http.server``); run it in-process
(:func:`serve`) or as a service::

    python -m distributedarrays_tpu.telemetry agg --port 9300

With ``DA_TPU_TELEMETRY=0`` the endpoints refuse cleanly (503) — an
aggregator without telemetry is a contradiction, and the refusal is the
documented, tested behavior rather than an accident.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import alerts, cluster, core, export

__all__ = ["Aggregator", "AggServer", "serve", "live_default_rules"]

# events retained per host stream: bounds aggregator memory; old events
# age out of the live timeline exactly like the exporter's ring drops —
# post-hoc analysis still has the full journals
MAX_EVENTS_PER_HOST = 50_000
# a host with no frame for this long shows as stale in /healthz and top
STALE_AFTER_S = 10.0


class _HostState:
    """Everything the aggregator knows about one ``(host, pid)``."""

    def __init__(self, host: str, pid: int):
        self.host = host
        self.pid = int(pid)
        self.counters: dict[str, float] = {}
        self.gauges: dict[str, float] = {}
        # gauge key -> (wall, value) history points (notes + tick diffs)
        self.points: deque = deque(maxlen=4096)
        self.events: deque = deque(maxlen=MAX_EVENTS_PER_HOST)
        self.flame: dict[str, int] = {}
        self.memory: dict = {}
        self.stream: dict = {}
        self.health: dict | None = None
        self.frames = 0
        self.lost_frames = 0          # transport gaps seen by US
        self.last_frame_seq = -1
        self.last_wall = 0.0

    def key(self) -> str:
        return f"{self.host}:{self.pid}"


class Aggregator:
    """Frame sink + live cluster state.  Thread-safe."""

    def __init__(self, *, rules=None, p99_slo_s: float = 0.5,
                 fast_window_s: float = 60.0, slow_window_s: float = 300.0,
                 step_time_slo_s: float | None = None):
        self._lock = threading.Lock()
        self._hosts: dict[tuple, _HostState] = {}
        self.started_wall = time.time()
        self.frames_ingested = 0
        self.manager = alerts.AlertManager(
            rules if rules is not None else live_default_rules(
                self, p99_slo_s=p99_slo_s, fast_window_s=fast_window_s,
                slow_window_s=slow_window_s,
                step_time_slo_s=step_time_slo_s))

    # -- ingest ------------------------------------------------------------

    def ingest(self, frame: dict) -> dict:
        """Apply one exporter frame; returns a small ack dict."""
        host = str(frame.get("host", "?"))
        pid = int(frame.get("pid", 0))
        key = (host, pid)
        with self._lock:
            hs = self._hosts.get(key)
            if hs is None:
                hs = self._hosts[key] = _HostState(host, pid)
            seq = frame.get("frame_seq")
            if isinstance(seq, int):
                if hs.last_frame_seq >= 0 and seq > hs.last_frame_seq + 1:
                    hs.lost_frames += seq - hs.last_frame_seq - 1
                if seq > hs.last_frame_seq:
                    hs.last_frame_seq = seq
            hs.counters.update(frame.get("counters") or {})
            gauges = frame.get("gauges") or {}
            hs.gauges.update(gauges)
            wall = float(frame.get("wall") or time.time())
            for k, v in gauges.items():
                hs.points.append((wall, k, float(v)))
            for p in frame.get("points") or ():
                try:
                    k, v, w = p[0], float(p[1]), float(p[2])
                except (TypeError, ValueError, IndexError):
                    continue
                hs.points.append((w, k, v))
                hs.gauges[k] = v     # a note is also the latest value
            for e in frame.get("events") or ():
                if isinstance(e, dict):
                    hs.events.append(e)
            for stack, n in (frame.get("flame") or {}).items():
                try:
                    hs.flame[stack] = hs.flame.get(stack, 0) + int(n)
                except (TypeError, ValueError):
                    continue
            if frame.get("memory"):
                hs.memory = frame["memory"]
            if frame.get("stream"):
                hs.stream = frame["stream"]
            if frame.get("health"):
                hs.health = frame["health"]
            hs.frames += 1
            hs.last_wall = wall
            self.frames_ingested += 1
        return {"ok": True, "host": hs.key(), "frames": hs.frames}

    # -- live signal reads (alert rules + dashboard) -----------------------

    def _states(self) -> list[_HostState]:
        with self._lock:
            return list(self._hosts.values())

    def gauge(self, name: str, *, agg: str = "max") -> float | None:
        """The gauge across hosts: ``max`` (worst host, the alerting
        default), ``min``, or ``sum``."""
        vals = []
        for hs in self._states():
            v = hs.gauges.get(name)
            if isinstance(v, (int, float)):
                vals.append(float(v))
        if not vals:
            return None
        if agg == "min":
            return min(vals)
        if agg == "sum":
            return float(sum(vals))
        return max(vals)

    def counter_total(self, name: str) -> float:
        """Sum a counter over all hosts and label sets."""
        prefix = name + "{"
        total = 0.0
        for hs in self._states():
            for k, v in hs.counters.items():
                if k == name or k.startswith(prefix):
                    total += float(v)
        return total

    def recent_points(self, name: str, *, horizon_s: float = 120.0) -> list:
        """``(wall, value)`` points for gauge ``name`` across hosts
        inside the horizon — the burn-window feed."""
        cut = time.time() - horizon_s
        prefix = name + "{"
        out = []
        for hs in self._states():
            for w, k, v in hs.points:
                if w >= cut and (k == name or k.startswith(prefix)):
                    out.append((w, v))
        out.sort()
        return out

    def evaluate(self, now: float | None = None) -> dict:
        """Drive the alert manager on the live stream."""
        return self.manager.evaluate(now)

    # -- merged views ------------------------------------------------------

    def merged_events(self, *, slack_s: float | None = None) -> list[dict]:
        """The live cluster timeline: every host's streamed event tail
        through the SAME three-tier alignment as the post-hoc merge."""
        streams = [list(hs.events) for hs in self._states() if hs.events]
        if not streams:
            return []
        kw = {} if slack_s is None else {"slack_s": slack_s}
        return cluster.merge_journals(streams, **kw)

    def flame_counts(self) -> dict[str, int]:
        merged: dict[str, int] = {}
        for hs in self._states():
            for stack, n in hs.flame.items():
                merged[stack] = merged.get(stack, 0) + n
        return merged

    def open_incidents(self) -> list[str]:
        """Incident ids seen on the stream with a begin and no end."""
        state: dict[str, bool] = {}
        for hs in self._states():
            for e in hs.events:
                if e.get("cat") != "incident":
                    continue
                inc = e.get("incident")
                if not inc:
                    continue
                if e.get("name") == "begin":
                    state.setdefault(inc, True)
                elif e.get("name") == "end":
                    state[inc] = False
        return sorted(i for i, open_ in state.items() if open_)

    # -- exports -----------------------------------------------------------

    def prometheus(self) -> str:
        """The whole cluster in Prometheus text exposition: every host's
        counters/gauges with a ``host`` label, the stream-health gauges
        (``da_tpu_stream_dropped_frames`` ...), and the aggregator's own
        alert gauges."""
        reg: dict = {"counters": {}, "gauges": {}, "histograms": {}}

        def _label(key: str, hs: _HostState) -> str:
            name, _, rest = key.partition("{")
            inner = rest[:-1] if rest.endswith("}") else rest
            parts = [p for p in (inner,) if p]
            parts.append(f"host={hs.key()}")
            return name + "{" + ",".join(parts) + "}"

        states = self._states()
        for hs in states:
            for k, v in hs.counters.items():
                reg["counters"][_label(k, hs)] = v
            for k, v in hs.gauges.items():
                reg["gauges"][_label(k, hs)] = v
            hk = f"host={hs.key()}"
            reg["gauges"][f"stream.dropped_frames{{{hk}}}"] = \
                float(hs.stream.get("frames_dropped", 0) or 0)
            reg["gauges"][f"stream.dropped_events{{{hk}}}"] = \
                float(hs.stream.get("events_dropped", 0) or 0)
            reg["gauges"][f"stream.lost_frames{{{hk}}}"] = \
                float(hs.lost_frames)
            reg["counters"][f"stream.frames{{{hk}}}"] = float(hs.frames)
            mem = hs.memory or {}
            if mem:
                reg["gauges"][f"hbm.live_bytes{{{hk}}}"] = \
                    float(mem.get("live_bytes", 0) or 0)
                reg["gauges"][f"hbm.peak_bytes{{{hk}}}"] = \
                    float(mem.get("peak_bytes", 0) or 0)
        # aggregator-local: firing alerts + totals (no host label)
        for name in self.manager.firing():
            reg["gauges"][f"alert.active{{alert={name}}}"] = 1.0
        reg["gauges"]["agg.hosts"] = float(len(states))
        reg["counters"]["agg.frames_ingested"] = float(self.frames_ingested)
        return export.to_prometheus(reg)

    def snapshot(self) -> dict:
        """The ``telemetry top`` payload: one dict per host plus the
        cluster-level alert/incident state."""
        now = time.time()
        hosts = {}
        for hs in self._states():
            mem = hs.memory or {}
            hosts[hs.key()] = {
                "host": hs.host,
                "pid": hs.pid,
                "age_s": round(max(now - hs.last_wall, 0.0), 3)
                if hs.last_wall else None,
                "stale": bool(hs.last_wall
                              and now - hs.last_wall > STALE_AFTER_S),
                "frames": hs.frames,
                "events": len(hs.events),
                "hbm_live_bytes": mem.get("live_bytes", 0),
                "hbm_peak_bytes": mem.get("peak_bytes", 0),
                "live_devices": hs.gauges.get("elastic.live_devices"),
                "serve_p99_s": hs.gauges.get("serve.request_p99_s"),
                "shed_fraction": self._shed_fraction(hs),
                "train_step_s": hs.gauges.get("train.step_s"),
                "dropped_frames": hs.stream.get("frames_dropped", 0),
                "dropped_events": hs.stream.get("events_dropped", 0),
                "lost_frames": hs.lost_frames,
                "lag_frames": hs.stream.get("lag_frames", 0),
            }
        return {
            "wall": round(now, 3),
            "uptime_s": round(now - self.started_wall, 1),
            "frames_ingested": self.frames_ingested,
            "hosts": hosts,
            "alerts": self.manager.firing(),
            "incidents": self.open_incidents(),
        }

    @staticmethod
    def _shed_fraction(hs: _HostState) -> float | None:
        shed = sub = 0.0
        for k, v in hs.counters.items():
            if k == "serve.shed" or k.startswith("serve.shed{"):
                shed += float(v)
            elif k == "serve.submitted" or \
                    k.startswith("serve.submitted{"):
                sub += float(v)
        if sub <= 0:
            return None
        return round(shed / sub, 4)


def live_default_rules(agg: Aggregator, *, p99_slo_s: float = 0.5,
                       shed_slo: float = 0.1,
                       step_time_slo_s: float | None = None,
                       hbm_budget_bytes: int | None = None,
                       hbm_slo: float = 0.9,
                       min_live_devices: int | None = None,
                       fast_window_s: float = 60.0,
                       slow_window_s: float = 300.0) -> list:
    """:func:`alerts.default_rules` re-aimed at the live stream: the
    same names, thresholds, burn windows and hysteresis, but every
    signal reads the aggregator's merged cross-host state instead of the
    local registry — plus a ``stream_drops`` rule that fires when any
    exporter is losing frames, so degraded observability is itself
    observable."""
    win = {"fast_window_s": fast_window_s, "slow_window_s": slow_window_s}

    def _shed_signal():
        last = {"shed": 0.0, "sub": 0.0}

        def signal():
            shed = agg.counter_total("serve.shed")
            sub = agg.counter_total("serve.submitted")
            d_shed, d_sub = shed - last["shed"], sub - last["sub"]
            last["shed"], last["sub"] = shed, sub
            if d_sub <= 0:
                return None
            return max(d_shed, 0.0) / d_sub
        return signal

    def _drops_signal():
        last = {"n": 0.0}

        def signal():
            total = 0.0
            for hs in agg._states():
                total += float(hs.stream.get("frames_dropped", 0) or 0)
                total += float(hs.lost_frames)
            delta = total - last["n"]
            last["n"] = total
            return max(delta, 0.0)
        return signal

    rules = [
        alerts.AlertRule("serve_p99",
                         lambda: agg.gauge("serve.request_p99_s"),
                         threshold=p99_slo_s, **win,
                         description=f"serve admitted p99 > {p99_slo_s}s "
                                     "on some host (live stream)"),
        alerts.AlertRule("serve_shed", _shed_signal(),
                         threshold=shed_slo, **win,
                         description=f"shed fraction > {shed_slo:.0%} "
                                     "(live stream)"),
        alerts.AlertRule("stream_drops", _drops_signal(),
                         threshold=0.0, **win,
                         description="exporter frames dropped or lost "
                                     "in transit"),
    ]
    if step_time_slo_s is not None:
        rules.append(alerts.AlertRule(
            "train_step_time", lambda: agg.gauge("train.step_s"),
            threshold=step_time_slo_s, **win,
            description=f"train step time > {step_time_slo_s}s "
                        "(live stream)"))
    if hbm_budget_bytes:
        bound = float(hbm_budget_bytes) * hbm_slo

        def _hbm():
            vals = [float((hs.memory or {}).get("live_bytes", 0) or 0)
                    for hs in agg._states()]
            return max(vals) if vals else None
        rules.append(alerts.AlertRule(
            "hbm_live", _hbm, threshold=bound, **win,
            description=f"HBM live bytes > {hbm_slo:.0%} of budget "
                        "on some host"))
    if min_live_devices is not None:
        rules.append(alerts.AlertRule(
            "live_devices",
            lambda: agg.gauge("elastic.live_devices", agg="min"),
            threshold=float(min_live_devices), op="<", **win,
            description=f"live devices < {min_live_devices} "
                        "on some host"))
    return rules


# ---------------------------------------------------------------------------
# HTTP service
# ---------------------------------------------------------------------------


_CHUNK = 64 * 1024


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "da-tpu-agg/1"

    # class attribute injected by AggServer
    agg: Aggregator = None  # type: ignore[assignment]

    def log_message(self, *a):  # noqa: D102 — silence per-request spam
        pass

    def setup(self):
        # track live keep-alive connections on the server so close()
        # can sever them: shutdown() only stops the accept loop, and an
        # exporter holding an HTTP/1.1 connection would otherwise keep
        # feeding a "closed" aggregator through its zombie handler
        super().setup()
        conns = getattr(self.server, "_live_conns", None)
        if conns is not None:
            conns.add(self.connection)

    def finish(self):
        conns = getattr(self.server, "_live_conns", None)
        if conns is not None:
            conns.discard(self.connection)
        super().finish()

    def _refuse_if_disabled(self) -> bool:
        if core.enabled():
            return False
        body = b"telemetry disabled (DA_TPU_TELEMETRY=0)\n"
        self.send_response(503)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
        return True

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        try:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass              # client hung up mid-reply: not our problem

    def do_POST(self):  # noqa: N802 — http.server API
        if self._refuse_if_disabled():
            return
        if self.path.rstrip("/") != "/ingest":
            self._reply(404, b"unknown endpoint\n", "text/plain")
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            frame = json.loads(self.rfile.read(n))
            if not isinstance(frame, dict):
                raise ValueError("frame must be an object")
            ack = self.agg.ingest(frame)
        except (ValueError, KeyError, TypeError) as e:
            self._reply(400, f"bad frame: {e}\n".encode(), "text/plain")
            return
        self._reply(200, json.dumps(ack).encode() + b"\n",
                    "application/json")

    def do_GET(self):  # noqa: N802 — http.server API
        if self._refuse_if_disabled():
            return
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/metrics":
            self._reply(200, self.agg.prometheus().encode(),
                        "text/plain; version=0.0.4")
        elif path == "/healthz":
            snap = self.agg.snapshot()
            body = json.dumps({
                "status": "ok",
                "hosts": len(snap["hosts"]),
                "stale_hosts": sorted(
                    k for k, h in snap["hosts"].items() if h["stale"]),
                "frames_ingested": snap["frames_ingested"],
                "alerts": snap["alerts"],
                "uptime_s": snap["uptime_s"],
            }).encode() + b"\n"
            self._reply(200, body, "application/json")
        elif path == "/snapshot":
            self._reply(200, json.dumps(self.agg.snapshot()).encode()
                        + b"\n", "application/json")
        elif path == "/flame":
            from . import stream as _stream
            body = _stream.collapsed_lines(self.agg.flame_counts())
            self._reply(200, body.encode() + b"\n", "text/plain")
        elif path == "/trace":
            self._send_trace()
        else:
            self._reply(404, b"unknown endpoint\n", "text/plain")

    def _send_trace(self) -> None:
        """The merged live timeline as a *chunked* Perfetto download —
        the trace can be large and is serialized piecewise, so the
        response starts immediately and no Content-Length is needed."""
        trace = export.to_perfetto(self.agg.merged_events())
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        buf = json.dumps(trace).encode()
        for i in range(0, len(buf), _CHUNK):
            chunk = buf[i:i + _CHUNK]
            self.wfile.write(b"%x\r\n" % len(chunk))
            self.wfile.write(chunk)
            self.wfile.write(b"\r\n")
        self.wfile.write(b"0\r\n\r\n")


class AggServer:
    """The aggregator behind a threading HTTP server plus its alert
    evaluation loop.  ``port=0`` binds an ephemeral port (tests)."""

    def __init__(self, aggregator: Aggregator | None = None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 eval_interval_s: float = 0.5, **agg_kwargs):
        self.agg = aggregator or Aggregator(**agg_kwargs)
        handler = type("_BoundHandler", (_Handler,), {"agg": self.agg})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self._httpd._live_conns = set()
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._stop = threading.Event()
        self._eval_interval_s = max(0.05, float(eval_interval_s))
        self._threads = [
            threading.Thread(target=self._httpd.serve_forever,
                             name="da-tpu-agg-http", daemon=True),
            threading.Thread(target=self._eval_loop,
                             name="da-tpu-agg-eval", daemon=True),
        ]

    def _eval_loop(self) -> None:
        while not self._stop.wait(self._eval_interval_s):
            try:
                self.agg.evaluate()
            except Exception:
                pass

    def start(self) -> "AggServer":
        for t in self._threads:
            t.start()
        return self

    def close(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        # sever lingering keep-alive connections so exporters observe
        # the death (and start counting drops) instead of feeding a
        # zombie handler thread
        import socket as _socket
        for conn in list(self._httpd._live_conns):
            try:
                conn.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._httpd._live_conns.clear()

    def __enter__(self) -> "AggServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def serve(*, host: str = "127.0.0.1", port: int = 0, advertise: bool = True,
          **kwargs) -> AggServer:
    """Start an :class:`AggServer` (returned running).  With
    ``advertise`` the URL is published to the multihost coordination KV
    so exporters on other hosts of the same job can discover it without
    per-host configuration."""
    srv = AggServer(host=host, port=port, **kwargs).start()
    if advertise:
        try:
            from ..parallel import multihost as _mh
            _mh.advertise_aggregator(srv.url)
        except Exception:
            pass
    return srv
