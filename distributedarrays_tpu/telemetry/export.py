"""Exporters: the journal/registry in the two lingua-franca formats.

- :func:`to_perfetto` — Chrome trace-event JSON (open at ui.perfetto.dev
  or chrome://tracing): spans as complete ("X") slices, one track per
  recording thread; every other journal event (comm, fallback, autotune,
  ...) as a thread-scoped instant ("i") on the same timeline.
- :func:`to_prometheus` — the metrics registry (a :func:`core.report`
  dict) in Prometheus text exposition format, ``da_tpu_``-prefixed.

Both are pure functions over plain dicts (stdlib only, no JAX), shared
by the ``python -m distributedarrays_tpu.telemetry trace|prom`` CLI
subcommands and by tests — a journal pulled off a pod worker converts on
any machine.
"""

from __future__ import annotations

import re

__all__ = ["to_perfetto", "to_prometheus"]

# journal bookkeeping keys that are not user "args" of an event
_EVENT_META = ("seq", "t", "wall", "cat", "name", "tid", "host", "pid")

# spans labeled rank=N render on synthetic per-rank tracks (tid = base +
# rank) instead of whatever thread happened to run them
_RANK_TRACK_BASE = 1 << 20


def _us(seconds) -> float:
    return round(float(seconds) * 1e6, 3)


def to_perfetto(events, spans=None, pid: int = 0) -> dict:
    """Convert journal ``events`` (list of dicts, e.g. from
    ``summarize.read_journal``) to a trace-event JSON dict.

    ``spans`` defaults to the events with category ``"span"`` (the
    journal mirror of every finished span); pass ``tracing.spans()``
    explicitly to include spans that skipped the journal.  Span ``ts``
    is the span *start*; all other events are instants at their record
    time — the shared monotonic origin makes the two line up.  Every
    entry carries ``ph/ts/dur/pid/tid`` so strict viewers need no
    defaulting.

    Events from more than one ``(host, pid)`` — a merged multihost
    journal (``cluster.merge_journals``) — render as separate process
    tracks: each recorder gets its own trace pid (``pid`` is the base)
    and a ``process_name`` metadata entry, so one trace shows the whole
    cluster with per-host timelines.
    """
    if spans is None:
        spans = [e for e in events if e.get("cat") == "span"]
    rest = [e for e in events if e.get("cat") != "span"]
    trace = []
    threads: dict[tuple[int, int], str] = {}
    procs: dict[tuple[str, int], int] = {}

    def _pid(e) -> int:
        key = (str(e.get("host", "")), int(e.get("pid") or 0))
        p = procs.get(key)
        if p is None:
            p = procs[key] = pid + len(procs)
        return p
    # request flows: spans carrying the same trace id chain together with
    # Chrome flow events (s/t/f), so a serve request's journey — submit,
    # batch dispatch, retries, rank steps — draws as one arrowed path
    flows: dict[str, list] = {}
    for s in spans:
        if s.get("dur") is None:
            continue                       # still-open span snapshot
        spid = _pid(s)
        tid = int(s.get("tid") or 0)
        labels = s.get("labels") or {}
        rank = labels.get("rank")
        if rank is not None:
            # per-rank timelines get their own tracks: SPMD rank spans
            # would otherwise interleave on whatever thread/process tid
            # happened to run them (thread tids are reused across runs;
            # process-backend spans are recorded parent-side)
            try:
                tid = _RANK_TRACK_BASE + int(rank)
                threads.setdefault((spid, tid), f"rank {int(rank)}")
            except (TypeError, ValueError):
                pass
        elif s.get("tname"):
            threads.setdefault((spid, tid), str(s["tname"]))
        args = {k: s[k] for k in ("span_id", "parent_id", "bytes",
                                  "child_bytes", "trace_id")
                if s.get(k) is not None}
        args.update(labels)
        entry = {"name": str(s.get("name", "?")), "cat": "span",
                 "ph": "X", "ts": _us(s.get("start", 0.0)),
                 "dur": _us(s["dur"]), "pid": spid, "tid": tid,
                 "args": args}
        trace.append(entry)
        for t in (s.get("trace_id") or ()):
            flows.setdefault(str(t), []).append(entry)
    # counter-track state: each "C" event's args define ALL series values
    # at that timestamp, so the missing series must be carried forward or
    # the renderer drops its line to zero between samples (per process —
    # merged journals keep one HBM line per host)
    hbm_state: dict[int, list] = {}
    for e in rest:
        epid = _pid(e)
        tid = int(e.get("tid") or 0)
        cat = str(e.get("cat", "?"))
        name = e.get("name")
        args = {k: v for k, v in e.items()
                if k not in _EVENT_META and v is not None}
        if cat == "gauge" and e.get("value") is not None:
            # journaled gauges (serve queue depth, admission token
            # levels, elastic live devices, ...) reconstruct as counter
            # ("C") tracks — one track per gauge name + label set (the
            # span/trace stamps a gauge event happens to carry are
            # provenance, not series identity)
            cname = str(name or "gauge")
            labels = {k: v for k, v in args.items()
                      if k not in ("value", "span_id", "trace_id",
                                   "incident", "t_local")}
            if labels:
                cname += "{" + ",".join(
                    f"{k}={labels[k]}" for k in sorted(labels)) + "}"
            trace.append({"name": cname, "cat": "gauge", "ph": "C",
                          "ts": _us(e.get("t", 0.0)), "dur": 0,
                          "pid": epid, "tid": 0,
                          "args": {"value": e["value"]}})
            continue
        trace.append({"name": f"{cat}/{name}" if name is not None else cat,
                      "cat": cat, "ph": "i", "s": "t",
                      "ts": _us(e.get("t", 0.0)), "dur": 0,
                      "pid": epid, "tid": tid, "args": args})
        if cat == "hbm":
            # counter ("C") track: the HBM ledger as a line under the
            # span timeline — ledger live bytes and transient staging
            # are two series on one counter
            if e.get("live") is not None or \
                    e.get("staging_live") is not None:
                state = hbm_state.setdefault(epid, [0, 0])
                if e.get("live") is not None:
                    state[0] = e["live"]
                if e.get("staging_live") is not None:
                    state[1] = e["staging_live"]
                trace.append({"name": "hbm_bytes", "cat": "hbm",
                              "ph": "C", "ts": _us(e.get("t", 0.0)),
                              "dur": 0, "pid": epid, "tid": 0,
                              "args": {"live": state[0],
                                       "staging": state[1]}})
    for flow_n, (tid_key, entries) in enumerate(sorted(flows.items())):
        if len(entries) < 2:
            continue                  # a flow needs two ends
        entries.sort(key=lambda e: e["ts"])
        for i, entry in enumerate(entries):
            ph = "s" if i == 0 else ("f" if i == len(entries) - 1 else "t")
            ev = {"name": "request", "cat": "trace", "ph": ph,
                  "id": flow_n + 1, "ts": entry["ts"], "dur": 0,
                  "pid": entry["pid"], "tid": entry["tid"],
                  "args": {"trace_id": tid_key}}
            if ph == "f":
                ev["bp"] = "e"        # bind the finish to the slice start
            trace.append(ev)
    for (tpid, tid), tname in sorted(threads.items()):
        trace.append({"name": "thread_name", "ph": "M", "ts": 0, "dur": 0,
                      "pid": tpid, "tid": tid, "args": {"name": tname}})
    if len(procs) > 1:
        # merged multihost journal: name each process track after its
        # recorder so the per-host timelines are identifiable in the UI
        for (host, opid), tpid in sorted(procs.items(),
                                         key=lambda kv: kv[1]):
            trace.append({"name": "process_name", "ph": "M", "ts": 0,
                          "dur": 0, "pid": tpid, "tid": 0,
                          "args": {"name": f"{host or 'host'}:{opid}"}})
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------


# DOTALL: a label VALUE may contain a literal newline (core._key does
# not escape), and a non-matching key would leak the raw newline into
# the metric name and the HELP line — invalid exposition
_KEY_RE = re.compile(r"^(?P<name>[^{]+)(?:\{(?P<labels>.*)\})?$",
                     re.DOTALL)


# label-list splitter: core._key joins "k=v" pairs with "," WITHOUT
# escaping values, so a value may itself contain commas (e.g. fallback
# keys built from tuple reprs: "dfft-host-(2, 2)-...").  Split only on
# commas that start a new "ident=" pair; other commas stay in the value.
_LABEL_SEP_RE = re.compile(r",(?=[a-zA-Z_][a-zA-Z0-9_.]*=)")


def _split_key(key: str) -> tuple[str, dict]:
    """Invert core._key: ``name{k=v,...}`` -> (name, labels)."""
    m = _KEY_RE.match(key)
    if m is None:
        return key, {}
    labels = {}
    raw = m.group("labels")
    if raw:
        for part in _LABEL_SEP_RE.split(raw):
            k, _, v = part.partition("=")
            labels[k] = v
    return m.group("name"), labels


def _metric_name(name: str) -> str:
    return "da_tpu_" + re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _esc(v) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _labels_str(labels: dict) -> str:
    if not labels:
        return ""
    return ("{" + ",".join(f'{re.sub(r"[^a-zA-Z0-9_]", "_", k)}="{_esc(v)}"'
                           for k, v in sorted(labels.items())) + "}")


def _num(v) -> str:
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class _Family:
    def __init__(self, name: str, mtype: str, help_: str):
        self.name, self.mtype, self.help = name, mtype, help_
        self.samples: list[tuple[str, dict, float]] = []

    def add(self, labels: dict, value, suffix: str = ""):
        self.samples.append((suffix, labels, value))

    def lines(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.mtype}"]
        for suffix, labels, value in self.samples:
            out.append(f"{self.name}{suffix}{_labels_str(labels)} "
                       f"{_num(value)}")
        return out


def to_prometheus(registry: dict | None = None) -> str:
    """Render a :func:`core.report` dict (default: the live registry) in
    Prometheus text exposition format.

    Counters become ``da_tpu_<name>_total``, gauges ``da_tpu_<name>``,
    histograms summaries (``_count``/``_sum`` plus ``_min``/``_max``
    gauges); comm accounting and span aggregates get dedicated families
    labeled by kind/span name.  Label sets round-trip from the
    registry's ``name{k=v,...}`` keys.
    """
    if registry is None:
        from . import core
        registry = core.report()
    fams: dict[str, _Family] = {}

    def fam(name, mtype, help_):
        f = fams.get(name)
        if f is None:
            f = fams[name] = _Family(name, mtype, help_)
        return f

    for key, value in sorted(registry.get("counters", {}).items()):
        name, labels = _split_key(key)
        fam(_metric_name(name) + "_total", "counter",
            f"counter {name}").add(labels, value)
    for key, value in sorted(registry.get("gauges", {}).items()):
        name, labels = _split_key(key)
        fam(_metric_name(name), "gauge", f"gauge {name}").add(labels, value)
    for key, h in sorted(registry.get("histograms", {}).items()):
        name, labels = _split_key(key)
        base = _metric_name(name)
        if "buckets" in h:
            # bucketed entry (core.observe(..., buckets=...)): a real
            # Prometheus histogram — cumulative le series + count/sum.
            # The serving SLO families (da_tpu_serve_slo_*) land here.
            f = fam(base, "histogram", f"histogram {name}")
            bounds = sorted(float(b) for b in h.get(
                "bounds", [float(k) for k in h["buckets"] if k != "+Inf"]))
            cum = 0
            for b in bounds:
                cum += int(h["buckets"].get(str(float(b)), 0))
                f.add({**labels, "le": f"{b:g}"}, cum, "_bucket")
            f.add({**labels, "le": "+Inf"}, h.get("count", 0), "_bucket")
            f.add(labels, h.get("count", 0), "_count")
            f.add(labels, h.get("total", 0.0), "_sum")
            continue
        f = fam(base, "summary", f"summary {name}")
        f.add(labels, h.get("count", 0), "_count")
        f.add(labels, h.get("total", 0.0), "_sum")
        fam(base + "_min", "gauge", f"min of {name}").add(
            labels, h.get("min", 0.0))
        fam(base + "_max", "gauge", f"max of {name}").add(
            labels, h.get("max", 0.0))
    comm = registry.get("comm", {})
    for kind, c in sorted(comm.get("by_kind", {}).items()):
        fam("da_tpu_comm_ops_total", "counter",
            "communication operations by kind").add({"kind": kind},
                                                    c.get("ops", 0))
        fam("da_tpu_comm_bytes_total", "counter",
            "estimated communication bytes by kind").add({"kind": kind},
                                                         c.get("bytes", 0))
    for sname, st in sorted(registry.get("spans", {})
                            .get("by_name", {}).items()):
        lbl = {"span": sname}
        fam("da_tpu_span_count_total", "counter",
            "finished spans by name").add(lbl, st.get("count", 0))
        fam("da_tpu_span_seconds_total", "counter",
            "total span wall seconds by name").add(lbl, st.get("total_s", 0))
        fam("da_tpu_span_self_seconds_total", "counter",
            "span self (minus children) seconds by name").add(
                lbl, st.get("self_s", 0))
        fam("da_tpu_span_bytes_total", "counter",
            "comm bytes attributed to spans by name").add(
                lbl, st.get("bytes", 0))
    mem = registry.get("memory", {})
    if mem:
        fam("da_tpu_hbm_live_bytes", "gauge",
            "HBM ledger live bytes").add({"device": "all"},
                                         mem.get("live_bytes", 0))
        fam("da_tpu_hbm_peak_bytes", "gauge",
            "HBM ledger peak bytes").add({"device": "all"},
                                         mem.get("peak_bytes", 0))
        for dev, d in sorted(mem.get("by_device", {}).items()):
            fam("da_tpu_hbm_live_bytes", "gauge",
                "HBM ledger live bytes").add({"device": dev},
                                             d.get("live_bytes", 0))
            fam("da_tpu_hbm_peak_bytes", "gauge",
                "HBM ledger peak bytes").add({"device": dev},
                                             d.get("peak_bytes", 0))
        fam("da_tpu_hbm_tracked_arrays", "gauge",
            "arrays tracked by the HBM ledger").add(
                {}, mem.get("tracked_arrays", 0))
        st = mem.get("staging", {})
        fam("da_tpu_hbm_staging_peak_bytes", "gauge",
            "peak transient staging bytes").add(
                {"tag": "all"}, st.get("peak_bytes", 0))
        for tag, v in sorted(st.get("peak_by_tag", {}).items()):
            fam("da_tpu_hbm_staging_peak_bytes", "gauge",
                "peak transient staging bytes").add({"tag": tag}, v)
    ev = registry.get("events", {})
    if ev:
        fam("da_tpu_events_recorded_total", "counter",
            "journal events recorded").add({}, ev.get("recorded", 0))
    lines: list[str] = []
    for name in sorted(fams):
        lines.extend(fams[name].lines())
    return "\n".join(lines) + "\n" if lines else ""
