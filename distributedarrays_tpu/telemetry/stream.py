"""Live telemetry plane, host side: the streaming exporter.

Everything built before this module is post-hoc — journals are merged
and analyzed after the run exits.  This module makes the same telemetry
*streamable while the workload runs*: a per-host exporter assembles
bounded **delta frames** (changed counters/gauges, the journal-event
tail, HBM-ledger gauges, flame-profile deltas) on its own daemon thread
and ships them to an aggregator (:mod:`telemetry.agg`) over plain HTTP.

Design rules, in order of importance:

1. **The hot path is never touched.**  Recording calls (``count`` /
   ``set_gauge`` / ``event`` / span close) do no streaming work; the
   exporter *pulls* snapshots under ``core._LOCK`` on its own thread.
   The only streaming calls that may appear on warm paths —
   :func:`note` and :func:`poke` — are a single ``is None`` check when
   no exporter is armed (and when telemetry is disabled an exporter can
   never be armed, so ``DA_TPU_TELEMETRY=0`` keeps the one-boolean-check
   discipline).
2. **Streaming never stalls and never backpressures.**  Frames land in
   a bounded ring; a lagging or dead aggregator makes the ring lap and
   the overwritten frames are *counted* (``frames_dropped``), never
   waited on.  Sends use short socket timeouts and a cold-down between
   reconnect attempts.
3. **Drop accounting is explicit.**  Every frame carries the exporter's
   cumulative ``frames_dropped`` / ``events_dropped`` counters, the
   aggregator re-exports them as ``da_tpu_stream_dropped_frames``, and
   :mod:`telemetry.flight` bundles capture them at crash time.

Event tails come from the in-memory ring (same process) or from a
:class:`JournalTailer` following another process's JSONL journal — the
tailer survives size-cap rotation (``journal.rotated``): it drains the
renamed file to EOF before re-opening the fresh one and dedups on the
globally monotonic ``seq``, so rotation mid-stream neither double-ships
nor gaps events.

Continuous profiling rides the same plane: :class:`FlameProfiler`
samples :func:`tracing.open_spans` at a configurable Hz into
collapsed-stack (Brendan Gregg) format; deltas ship in frames and
``python -m distributedarrays_tpu.telemetry flame`` renders them — or
builds the same format post-hoc from a journal's span records
(:func:`collapsed_from_events`).

Arming: :func:`start` explicitly, or export ``DA_TPU_STREAM_AGG=host:port``
before import (the same auto-install pattern as the health sampler) —
the aggregator URL may also come from the multihost coordination KV
(:func:`parallel.multihost.aggregator_endpoint`).
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time

from . import core, tracing

__all__ = [
    "StreamExporter", "JournalTailer", "FlameProfiler",
    "start", "stop", "armed", "stats", "note", "note_health", "poke",
    "collapsed_from_events", "AGG_ENV",
]

AGG_ENV = "DA_TPU_STREAM_AGG"
FRAME_VERSION = 1

# per-frame bounds: a frame is a bounded delta, never "everything since
# the epoch" — a consumer that lagged gets the counters' absolute values
# (self-healing) and an event gap that is COUNTED, not silently absorbed
MAX_EVENTS_PER_FRAME = 2000


def _now_wall() -> float:
    return time.time()


# ---------------------------------------------------------------------------
# frame ring
# ---------------------------------------------------------------------------


class _Ring:
    """Bounded frame ring with explicit drop accounting.

    Single-threaded by design (the exporter thread both pushes assembled
    frames and drains them toward the aggregator), so no lock is needed:
    the ring's job is not cross-thread handoff but *bounded retention* —
    frames the aggregator could not take yet wait here, and when the
    writer laps the reader the oldest frame is overwritten and
    ``dropped`` incremented instead of anyone blocking."""

    def __init__(self, capacity: int):
        self.capacity = max(2, int(capacity))
        self._slots: list = [None] * self.capacity
        self._head = 0          # next write position (monotonic)
        self._tail = 0          # next read position (monotonic)
        self.dropped = 0

    def push(self, frame: dict) -> None:
        if self._head - self._tail >= self.capacity:
            # consumer lagged a full lap: drop the oldest, count it
            self._tail += 1
            self.dropped += 1
        self._slots[self._head % self.capacity] = frame
        self._head += 1

    def peek(self):
        if self._tail >= self._head:
            return None
        return self._slots[self._tail % self.capacity]

    def pop(self) -> None:
        if self._tail < self._head:
            self._slots[self._tail % self.capacity] = None
            self._tail += 1

    def __len__(self) -> int:
        return self._head - self._tail


# ---------------------------------------------------------------------------
# journal tailer (rotation-safe)
# ---------------------------------------------------------------------------


class JournalTailer:
    """Follow a JSONL journal file across size-cap rotations.

    Reads complete lines only (a line the writer is mid-way through is
    left for the next poll), dedups on the journal's globally monotonic
    ``seq``, and handles rotation without double-shipping or gapping:
    when the path's inode no longer matches the open handle (the writer
    renamed the full file to ``<path>.1`` and opened a fresh one), the
    old handle is first drained to EOF — those events exist nowhere else
    once ``.1`` is itself replaced — and only then is the fresh file
    opened from offset 0.  The fresh file begins with the
    ``journal.rotated`` marker whose ``seq`` continues the same sequence,
    so the seq dedup proves continuity; a genuinely missed record (e.g.
    the tailer started late) surfaces as a counted gap in ``dropped``."""

    def __init__(self, path: str, *, from_start: bool = True):
        self.path = str(path)
        self._f = None
        self._ino = None
        self.last_seq = -1
        self.rotations = 0
        self.dropped = 0
        self._from_start = from_start

    def _open(self) -> bool:
        try:
            f = open(self.path, "r")
        except OSError:
            return False
        self._f = f
        try:
            self._ino = os.fstat(f.fileno()).st_ino
        except OSError:
            self._ino = None
        if not self._from_start:
            # intentional skip, not a drop — but seed last_seq from the
            # file's tail so gap accounting stays exact from here on (a
            # record evicted between this open and the first poll counts)
            self._seed_seq_from_tail(f)
            f.seek(0, os.SEEK_END)
            self._from_start = True   # only the very first open skips
        return True

    def _seed_seq_from_tail(self, f) -> None:
        try:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(size - 65536, 0))
            last = None
            for line in f:
                if not line.endswith("\n"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and isinstance(rec.get("seq"),
                                                        int):
                    last = rec["seq"]
            if last is not None:
                self.last_seq = last
        except (OSError, ValueError):
            pass

    def _rotated(self) -> bool:
        """True when ``path`` now names a different file than the open
        handle (the writer rotated)."""
        if self._ino is None:
            return False
        try:
            return os.stat(self.path).st_ino != self._ino
        except OSError:
            return False

    def _read_lines(self, limit: int) -> list[dict]:
        out: list[dict] = []
        f = self._f
        while len(out) < limit:
            pos = f.tell()
            line = f.readline()
            if not line:
                break
            if not line.endswith("\n"):
                # writer mid-line: rewind, retry next poll
                f.seek(pos)
                break
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            seq = rec.get("seq")
            if isinstance(seq, int):
                if seq <= self.last_seq:
                    continue              # overlap (rotation/re-open): dedup
                if self.last_seq >= 0 and seq > self.last_seq + 1:
                    self.dropped += seq - self.last_seq - 1
                self.last_seq = seq
            out.append(rec)
        return out

    def poll(self, max_events: int = MAX_EVENTS_PER_FRAME) -> list[dict]:
        """New complete journal records since the last poll (bounded)."""
        if self._f is None and not self._open():
            return []
        out = self._read_lines(max_events)
        if len(out) < max_events and self._rotated():
            # drain what is left of the renamed generation, then switch
            out.extend(self._read_lines(max_events - len(out)))
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None
            self.rotations += 1
            if self._open():
                out.extend(self._read_lines(max_events - len(out)))
        return out

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None


# ---------------------------------------------------------------------------
# continuous profiling: sampling over open spans
# ---------------------------------------------------------------------------


def _stack_of(sp: dict, by_id: dict) -> str:
    names = [str(sp.get("name", "?"))]
    seen = {sp.get("span_id")}
    parent = by_id.get(sp.get("parent_id"))
    while parent is not None and parent.get("span_id") not in seen:
        seen.add(parent.get("span_id"))
        names.append(str(parent.get("name", "?")))
        parent = by_id.get(parent.get("parent_id"))
    return ";".join(reversed(names))


class FlameProfiler(threading.Thread):
    """Sampling profiler over :func:`tracing.open_spans`.

    At each tick (``hz`` samples/second) every *leaf* open span — one
    with no open child — contributes one sample to its root→leaf stack.
    Samples accumulate as ``{collapsed_stack: count}``; ticks with no
    open span are counted separately (``idle``), so attribution math is
    honest about uninstrumented time.  Zero cost to the sampled threads
    beyond the shared ``core._LOCK`` snapshot."""

    def __init__(self, hz: float = 20.0):
        super().__init__(name="da-tpu-flame", daemon=True)
        self.hz = max(0.5, float(hz))
        self._counts: dict[str, int] = {}
        self._delta: dict[str, int] = {}
        self._lock = threading.Lock()
        self._halt = threading.Event()
        self.samples = 0
        self.idle = 0

    def sample_once(self) -> None:
        sps = tracing.open_spans()
        if not sps:
            self.idle += 1
            return
        by_id = {s.get("span_id"): s for s in sps}
        parents = {s.get("parent_id") for s in sps
                   if s.get("parent_id") in by_id}
        leaves = [s for s in sps if s.get("span_id") not in parents]
        with self._lock:
            for leaf in leaves:
                stack = _stack_of(leaf, by_id)
                self._counts[stack] = self._counts.get(stack, 0) + 1
                self._delta[stack] = self._delta.get(stack, 0) + 1
            self.samples += 1

    def run(self) -> None:  # pragma: no cover — exercised via sample_once
        period = 1.0 / self.hz
        while not self._halt.wait(period):
            try:
                self.sample_once()
            except Exception:
                pass                  # profiling must never kill anything

    def stop(self) -> None:
        self._halt.set()

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def take_delta(self) -> dict[str, int]:
        """Samples accumulated since the last take (ships in frames)."""
        with self._lock:
            d, self._delta = self._delta, {}
            return d

    def collapsed(self) -> str:
        """The accumulated profile in collapsed-stack format
        (``frame;frame;frame count`` per line)."""
        return collapsed_lines(self.counts())


def collapsed_lines(counts: dict) -> str:
    return "\n".join(f"{stack} {int(n)}"
                     for stack, n in sorted(counts.items()) if int(n) > 0)


def collapsed_from_events(events, *, unit_ms: bool = True):
    """Post-hoc flame profile from a journal's finished-span records.

    Returns ``(counts, stats)``: ``counts`` maps each root→leaf stack to
    its **self time in milliseconds** (wall attribution, not samples) —
    a span's self time is its duration minus its journaled children's;
    ``stats`` reports ``attributed_s`` (sum of root-span durations),
    ``wall_s`` (first to last event timestamp) and their ratio, the
    number the live-plane acceptance gate checks (≥90% of wall time
    attributed when the workload runs under spans)."""
    spans = [e for e in events
             if e.get("cat") == "span" and e.get("dur") is not None
             and e.get("span_id") is not None]
    by_id = {s["span_id"]: s for s in spans}
    child_dur: dict = {}
    for s in spans:
        p = s.get("parent_id")
        if p in by_id:
            child_dur[p] = child_dur.get(p, 0.0) + float(s["dur"])
    counts: dict[str, float] = {}
    attributed = 0.0
    for s in spans:
        self_s = max(float(s["dur"]) - child_dur.get(s["span_id"], 0.0),
                     0.0)
        stack = _stack_of(s, by_id)
        counts[stack] = counts.get(stack, 0.0) + \
            (self_s * 1000.0 if unit_ms else self_s)
        if s.get("parent_id") not in by_id:
            attributed += float(s["dur"])
    # a span record's ``t`` is its END stamp; the wall window must open
    # at the earliest span START (t - dur) or the ratio overshoots
    starts, ends = [], []
    for e in events:
        t = e.get("t")
        if not isinstance(t, (int, float)):
            continue
        ends.append(float(t))
        dur = e.get("dur") if e.get("cat") == "span" else None
        starts.append(float(t) - float(dur)
                      if isinstance(dur, (int, float)) else float(t))
    wall = (max(ends) - min(starts)) if starts else 0.0
    stats = {"spans": len(spans), "attributed_s": round(attributed, 6),
             "wall_s": round(wall, 6),
             "attributed_frac": round(attributed / wall, 4) if wall else
             (1.0 if attributed else 0.0)}
    out = {k: int(round(v)) for k, v in counts.items() if round(v) >= 1}
    return out, stats


# ---------------------------------------------------------------------------
# the exporter
# ---------------------------------------------------------------------------


def _parse_url(url: str) -> tuple[str, int, str]:
    """``[http://]host:port[/base]`` -> (host, port, base_path)."""
    u = str(url)
    if "://" in u:
        u = u.split("://", 1)[1]
    base = ""
    if "/" in u:
        u, rest = u.split("/", 1)
        base = "/" + rest.rstrip("/")
    host, _, port = u.rpartition(":")
    return host or "127.0.0.1", int(port), base


class StreamExporter(threading.Thread):
    """Per-host streaming exporter (one daemon thread).

    Every ``interval_s`` it assembles one bounded delta frame — changed
    counters/gauges (absolute values, so a lost frame self-heals),
    eagerly :func:`note`-d gauge points with wall timestamps, the
    journal-event tail (in-memory ring or :class:`JournalTailer`), HBM
    ledger gauges, flame-profile deltas, and its own drop/lag counters —
    pushes it into the bounded ring, and drains the ring toward the
    aggregator with short-timeout POSTs.  A dead aggregator costs
    nothing but counted drops; a revived one gets frames again within
    one reconnect interval."""

    def __init__(self, agg_url: str, *, interval_s: float = 0.5,
                 ring_frames: int = 256, journal: str | None = None,
                 flame_hz: float | None = None,
                 send_timeout_s: float = 1.0,
                 reconnect_s: float = 1.0,
                 heartbeat_every: int = 10):
        super().__init__(name="da-tpu-stream", daemon=True)
        self.agg_host, self.agg_port, self.agg_base = _parse_url(agg_url)
        self.interval_s = max(0.01, float(interval_s))
        self.ring = _Ring(ring_frames)
        self.tailer = JournalTailer(journal) if journal else None
        self.profiler = FlameProfiler(flame_hz) if flame_hz else None
        self.send_timeout_s = float(send_timeout_s)
        self.reconnect_s = float(reconnect_s)
        self.heartbeat_every = max(1, int(heartbeat_every))
        self._halt = threading.Event()
        self._flush = threading.Event()
        self._conn = None
        self._next_try = 0.0
        self._last_counters: dict = {}
        self._last_gauges: dict = {}
        self._last_seq = -1
        self._notes_lock = threading.Lock()
        self._tick_lock = threading.Lock()
        self._notes: list = []
        self._health: dict | None = None
        # cumulative, all monotonic non-decreasing
        self.frame_seq = 0
        self.frames_sent = 0
        self.frames_dropped = 0
        self.events_shipped = 0
        self.events_dropped = 0
        self.send_errors = 0
        self.connected = False
        self._ticks = 0

    # -- hot-path-adjacent entry points (single check when unarmed lives
    # in the module-level wrappers; these are already off the hot path)

    def add_note(self, name: str, value: float, labels: dict) -> None:
        key = core._key(name, labels) if labels else name
        with self._notes_lock:
            self._notes.append([key, float(value), _now_wall()])

    def add_health(self, payload: dict) -> None:
        with self._notes_lock:
            self._health = dict(payload)

    def request_flush(self) -> None:
        self._flush.set()

    # -- frame assembly ----------------------------------------------------

    def _tail_events(self) -> list[dict]:
        if self.tailer is not None:
            evs = self.tailer.poll(MAX_EVENTS_PER_FRAME)
            self.events_dropped = self.tailer.dropped
            return evs
        with core._LOCK:
            # the pending events are a SUFFIX of the ring (seq is
            # globally monotonic): walk from the right and stop at the
            # first already-shipped one, so the lock is held O(pending),
            # not O(ring capacity), per tick
            pending = []
            for e in reversed(core._events):
                if e.get("seq", -1) <= self._last_seq:
                    break
                pending.append(dict(e))
            pending.reverse()
        out = pending[:MAX_EVENTS_PER_FRAME]
        if out:
            first = out[0].get("seq", self._last_seq + 1)
            if self._last_seq >= 0 and first > self._last_seq + 1:
                # the bounded in-memory ring evicted events before we
                # tailed them: a counted gap, never a silent one
                self.events_dropped += first - self._last_seq - 1
            self._last_seq = out[-1].get("seq", self._last_seq)
        return out

    def assemble_frame(self) -> dict | None:
        """One bounded delta frame; None when there is nothing to say
        (a heartbeat frame still goes out every ``heartbeat_every``
        ticks so the aggregator can tell silence from death)."""
        with core._LOCK:
            counters = dict(core._counters)
            gauges = dict(core._gauges)
        c_delta = {k: v for k, v in counters.items()
                   if self._last_counters.get(k) != v}
        g_delta = {k: v for k, v in gauges.items()
                   if self._last_gauges.get(k) != v}
        self._last_counters = counters
        self._last_gauges = gauges
        events = self._tail_events()
        with self._notes_lock:
            points, self._notes = self._notes, []
            health, self._health = self._health, None
        flame = self.profiler.take_delta() if self.profiler else {}
        mem = {}
        try:
            from . import memory as _mem
            mem = {"live_bytes": _mem.live_bytes(),
                   "peak_bytes": _mem.peak_bytes(),
                   "by_device": {str(d): int(v) for d, v in
                                 _mem.live_bytes_by_device().items()}}
        except Exception:
            pass
        self._ticks += 1
        empty = not (c_delta or g_delta or events or points or flame
                     or health)
        if empty and self._ticks % self.heartbeat_every:
            return None
        self.events_shipped += len(events)
        frame = {
            "v": FRAME_VERSION,
            "host": core._HOST,
            "pid": os.getpid(),
            "frame_seq": self.frame_seq,
            "wall": round(_now_wall(), 3),
            "t": round(time.monotonic() - core._T0, 6),
            "counters": c_delta,
            "gauges": g_delta,
            "points": points,
            "events": events,
            "memory": mem,
            "flame": flame,
            "stream": {
                "frames_dropped": self.ring.dropped,
                "events_dropped": self.events_dropped,
                "frames_sent": self.frames_sent,
                "send_errors": self.send_errors,
                "lag_frames": len(self.ring),
            },
        }
        if health:
            frame["health"] = health
        self.frame_seq += 1
        return frame

    # -- transport ---------------------------------------------------------

    def _send(self, frame: dict) -> bool:
        try:
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self.agg_host, self.agg_port,
                    timeout=self.send_timeout_s)
            body = json.dumps(frame).encode()
            self._conn.request("POST", self.agg_base + "/ingest", body,
                               {"Content-Type": "application/json"})
            resp = self._conn.getresponse()
            resp.read()
            ok = 200 <= resp.status < 300
            if not ok:
                raise OSError(f"aggregator returned {resp.status}")
            return True
        except Exception:
            self.send_errors += 1
            self.connected = False
            if self._conn is not None:
                try:
                    self._conn.close()
                except Exception:
                    pass
                self._conn = None
            self._next_try = time.monotonic() + self.reconnect_s
            return False

    def _drain(self) -> None:
        if time.monotonic() < self._next_try:
            return
        while True:
            frame = self.ring.peek()
            if frame is None:
                return
            if not self._send(frame):
                return
            self.connected = True
            self.ring.pop()
            self.frames_sent += 1

    # -- lifecycle ---------------------------------------------------------

    def tick(self) -> None:
        """One assemble+drain cycle (the run loop's body; callable
        directly from tests for determinism).  Serialized against the
        background thread: a manual tick racing the run loop would
        interleave two HTTP requests on the one keep-alive connection
        and corrupt the stream."""
        with self._tick_lock:
            frame = self.assemble_frame()
            if frame is not None:
                self.ring.push(frame)
            self.frames_dropped = self.ring.dropped
            self._drain()

    def run(self) -> None:  # pragma: no cover — exercised via tick()
        if self.profiler is not None:
            self.profiler.start()
        while not self._halt.is_set():
            self._flush.wait(self.interval_s)
            self._flush.clear()
            if self._halt.is_set():
                break
            try:
                self.tick()
            except Exception:
                pass              # streaming must never kill the workload
        # final best-effort flush so short-lived processes still land
        try:
            self.tick()
        except Exception:
            pass
        if self.profiler is not None:
            self.profiler.stop()
        if self.tailer is not None:
            self.tailer.close()
        if self._conn is not None:
            try:
                self._conn.close()
            except Exception:
                pass

    def stop(self, join_s: float = 2.0) -> None:
        self._halt.set()
        self._flush.set()
        if self.is_alive():
            self.join(join_s)

    def stats_dict(self) -> dict:
        return {
            "armed": True,
            "agg": f"{self.agg_host}:{self.agg_port}",
            "connected": self.connected,
            "frames_sent": self.frames_sent,
            "frames_dropped": self.ring.dropped,
            "events_shipped": self.events_shipped,
            "events_dropped": self.events_dropped,
            "send_errors": self.send_errors,
            "lag_frames": len(self.ring),
            "flame_samples": self.profiler.samples if self.profiler else 0,
        }


# ---------------------------------------------------------------------------
# module-level plane control
# ---------------------------------------------------------------------------


_EXPORTER: StreamExporter | None = None
_ARM_LOCK = threading.Lock()


def armed() -> bool:
    """True when a streaming exporter is running in this process."""
    return _EXPORTER is not None


def start(agg_url: str | None = None, *, interval_s: float | None = None,
          journal: str | None = None, flame_hz: float | None = None,
          ring_frames: int | None = None) -> StreamExporter | None:
    """Arm the per-host exporter (idempotent; returns the exporter, or
    ``None`` when telemetry is disabled or no aggregator is known).

    ``agg_url`` defaults to ``DA_TPU_STREAM_AGG``, else the multihost
    coordination KV advertisement (:func:`parallel.multihost.
    aggregator_endpoint`).  ``flame_hz`` defaults to
    ``DA_TPU_FLAME_HZ`` (unset/0 = no continuous profiler)."""
    global _EXPORTER
    if not core.enabled():
        return None
    with _ARM_LOCK:
        if _EXPORTER is not None:
            return _EXPORTER
        if agg_url is None:
            agg_url = os.environ.get(AGG_ENV) or None
        if agg_url is None:
            try:
                from ..parallel import multihost as _mh
                agg_url = _mh.aggregator_endpoint()
            except Exception:
                agg_url = None
        if not agg_url:
            return None
        if interval_s is None:
            interval_s = float(os.environ.get(
                "DA_TPU_STREAM_INTERVAL_S", "0.5"))
        if flame_hz is None:
            try:
                flame_hz = float(os.environ.get("DA_TPU_FLAME_HZ", "0"))
            except ValueError:
                flame_hz = 0.0
        if ring_frames is None:
            ring_frames = int(os.environ.get("DA_TPU_STREAM_RING", "256"))
        exp = StreamExporter(agg_url, interval_s=interval_s,
                             journal=journal,
                             flame_hz=flame_hz or None,
                             ring_frames=ring_frames)
        exp.start()
        _EXPORTER = exp
        core.event("stream", "armed", agg=f"{exp.agg_host}:{exp.agg_port}",
                   interval_s=exp.interval_s,
                   flame_hz=flame_hz or 0)
        return exp


def stop() -> None:
    """Disarm the exporter (no-op when not armed)."""
    global _EXPORTER
    with _ARM_LOCK:
        exp, _EXPORTER = _EXPORTER, None
    if exp is not None:
        exp.stop()


def stats() -> dict:
    """The exporter's live drop/lag counters (``{"armed": False}`` when
    no exporter runs) — captured into flight bundles so a postmortem
    shows whether live telemetry was degraded at crash time."""
    exp = _EXPORTER
    if exp is None:
        return {"armed": False}
    return exp.stats_dict()


def note(name: str, value: float, **labels) -> None:
    """Eagerly publish one gauge point to the live plane.

    Unlike the exporter's tick-sampled registry diff, a note carries its
    own wall timestamp and every update is delivered (not just the last
    value per tick) — the aggregator's burn-rate windows see the full
    history.  Serve and train call this next to their SLO gauges.  A
    single ``is None`` check when no exporter is armed."""
    exp = _EXPORTER
    if exp is None:
        return
    exp.add_note(name, value, labels)


def note_health(payload: dict) -> None:
    """Publish one health-sampler tick to the live plane (the sampler
    calls this so one sampler feeds journal, alerts, AND the stream).
    Single check when unarmed."""
    exp = _EXPORTER
    if exp is None:
        return
    exp.add_health(payload)
    exp.request_flush()


def poke() -> None:
    """Request an immediate frame flush (single check when unarmed)."""
    exp = _EXPORTER
    if exp is None:
        return
    exp.request_flush()


def _maybe_autostart() -> None:
    """Arm at import when ``DA_TPU_STREAM_AGG`` is set (same pattern as
    the health sampler's autostart).  No-op otherwise."""
    if not core.enabled():
        return
    if os.environ.get(AGG_ENV):
        try:
            start()
        except Exception:
            pass


def _reset() -> None:
    """Test hook: disarm and drop module state (joins the exporter, so
    never call from under ``core._LOCK`` — fixtures call it from
    teardown, not from a ``core.reset`` hook)."""
    stop()
