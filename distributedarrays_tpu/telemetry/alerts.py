"""SLO burn-rate alerting + the always-on health sampler.

The observatory so far is *forensic*: journals, bundles and incident
reconstruction tell you what happened after the fact.  This module is
the *while-it-degrades* half:

- **declarative alert rules** (:class:`AlertRule`): a signal callable, a
  breach predicate, and a fast/slow burn-rate window pair — the
  classic multi-window SRE pattern: the FAST window (is a large
  fraction of recent samples breaching?) makes the alert prompt, the
  SLOW window (is the breach sustained?) makes it noise-resistant.
  :func:`default_rules` builds the five stock rules: serve admitted
  p99, shed fraction, train step time, HBM live vs budget, and live
  device count.
- **in-process evaluation** (:class:`AlertManager`): rolling sample
  windows per rule, transitions journaled as typed ``alert`` events
  (``state=firing|cleared`` with the measured burn rates) and mirrored
  to ``alert.active`` gauges — ``da_tpu_alert_active`` in the
  Prometheus export, so a scraper sees exactly what the journal says.
- **the health sampler** (:func:`start_sampler`): a daemon thread
  (``DA_TPU_TELEMETRY_SAMPLE_S``, default OFF) snapshotting HBM live
  bytes, serve queue depth, train step rate and MFU (from PR 11's
  ``train_step_cost`` stamps on ``train.step`` spans) as journaled
  gauges every tick, and driving the alert manager — timelines get data
  *between* spans, and alerts fire without any cooperation from the
  workload.

Disabled telemetry (``DA_TPU_TELEMETRY=0``) keeps the PR 1 discipline:
the sampler never starts, and every evaluation entry point is a single
boolean check.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Callable

from . import core, memory

__all__ = ["AlertRule", "AlertManager", "default_rules",
           "autotune_regressed_rule", "ensure_autotune_rule",
           "start_sampler", "stop_sampler", "sampler_running",
           "SAMPLE_ENV"]

SAMPLE_ENV = "DA_TPU_TELEMETRY_SAMPLE_S"


@dataclasses.dataclass
class AlertRule:
    """One declarative SLO rule.

    ``signal``: ``() -> float | None`` — the current value (None = no
    sample this tick: the windows simply don't advance).  ``breach``:
    value predicate; or leave it None and set ``threshold`` + ``op``
    (``">"``: breaching when value > threshold, ``"<"``: when value <
    threshold — the live-device rule wants "too few").

    ``fast_window_s`` / ``slow_window_s``: the two rolling windows;
    ``fast_burn`` / ``slow_burn``: the breaching-sample fraction each
    window must exceed for the alert to fire.  It clears when the fast
    window's burn falls to half ``fast_burn`` (hysteresis: a boundary
    burn rate must not flap the alert every tick).
    """

    name: str
    signal: Callable[[], float | None]
    threshold: float = 0.0
    op: str = ">"
    breach: Callable[[float], bool] | None = None
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    fast_burn: float = 0.5
    slow_burn: float = 0.1
    description: str = ""

    def is_breach(self, value: float) -> bool:
        if self.breach is not None:
            return bool(self.breach(value))
        if self.op == "<":
            return value < self.threshold
        return value > self.threshold


class AlertManager:
    """Evaluate a rule set over rolling windows; journal transitions.

    Drive it from the health sampler (:func:`start_sampler`) or call
    :meth:`evaluate` from your own loop.  Thread-safe; zero work when
    telemetry is disabled."""

    def __init__(self, rules=()):
        self._lock = threading.Lock()
        self._rules: list[AlertRule] = list(rules)
        # per rule name: deque[(t, breached)], firing flag
        self._windows: dict[str, deque] = {}
        self._firing: dict[str, bool] = {}

    def add(self, rule: AlertRule) -> None:
        with self._lock:
            self._rules.append(rule)

    def rules(self) -> list[AlertRule]:
        with self._lock:
            return list(self._rules)

    def firing(self) -> list[str]:
        """Names of currently-firing alerts."""
        with self._lock:
            return sorted(n for n, f in self._firing.items() if f)

    def reset(self) -> None:
        with self._lock:
            self._windows.clear()
            self._firing.clear()

    @staticmethod
    def _burn(win: deque, now: float, horizon: float) -> tuple[float, int]:
        n = breached = 0
        for t, b in win:
            if now - t <= horizon:
                n += 1
                breached += 1 if b else 0
        return (breached / n if n else 0.0), n

    def evaluate(self, now: float | None = None) -> dict[str, bool]:
        """Sample every rule's signal, advance its windows, and fire /
        clear on burn-rate transitions.  Returns ``{name: firing}``."""
        if not core._ENABLED:
            return {}
        if now is None:
            now = time.monotonic()
        out: dict[str, bool] = {}
        with self._lock:
            rules = list(self._rules)
        for rule in rules:
            try:
                value = rule.signal()
            except Exception:
                value = None             # a broken signal is no sample
            with self._lock:
                win = self._windows.setdefault(rule.name, deque())
                if value is not None:
                    win.append((now, rule.is_breach(float(value))))
                # expire past the slow horizon
                while win and now - win[0][0] > rule.slow_window_s:
                    win.popleft()
                fast, nf = self._burn(win, now, rule.fast_window_s)
                slow, ns = self._burn(win, now, rule.slow_window_s)
                was = self._firing.get(rule.name, False)
                if not was and nf >= 1 and ns >= 1 and \
                        fast >= rule.fast_burn and slow >= rule.slow_burn:
                    firing = True
                elif was and fast <= rule.fast_burn / 2.0:
                    firing = False
                else:
                    firing = was
                self._firing[rule.name] = firing
            if firing != was:
                state = "firing" if firing else "cleared"
                core.count("alerts.transitions", alert=rule.name,
                           state=state)
                core.event("alert", rule.name, state=state,
                           value=value, burn_fast=round(fast, 4),
                           burn_slow=round(slow, 4),
                           threshold=rule.threshold,
                           description=rule.description)
            # gauge on every tick, not just transitions: a scrape between
            # transitions must still see the active set
            core.set_gauge("alert.active", 1.0 if firing else 0.0,
                           alert=rule.name)
            out[rule.name] = firing
        return out


def _counter_total(name: str) -> float:
    """Sum a counter over ALL label sets (``name`` and ``name{...}``)."""
    prefix = name + "{"
    with core._LOCK:
        return sum(v for k, v in core._counters.items()
                   if k == name or k.startswith(prefix))


def _shed_fraction_signal():
    """Incremental shed fraction between evaluations: d(shed)/d(submitted)
    since the last sample — a windowed rate, not the process-lifetime
    average (which would never clear after an incident)."""
    last = {"shed": 0.0, "submitted": 0.0}

    def signal() -> float | None:
        shed = _counter_total("serve.shed")
        sub = _counter_total("serve.submitted")
        d_shed = shed - last["shed"]
        d_sub = sub - last["submitted"]
        last["shed"], last["submitted"] = shed, sub
        if d_sub <= 0:
            return None                  # no traffic: no sample
        return max(d_shed, 0.0) / d_sub
    return signal


def default_rules(*, p99_slo_s: float = 0.5, shed_slo: float = 0.1,
                  step_time_slo_s: float | None = None,
                  hbm_budget_bytes: int | None = None,
                  hbm_slo: float = 0.9,
                  min_live_devices: int | None = None,
                  fast_window_s: float = 60.0,
                  slow_window_s: float = 300.0) -> list[AlertRule]:
    """The five stock rules from the observatory design:

    - ``serve_p99``      — admitted-request rolling p99 over the SLO
      (``serve.request_p99_s`` gauge, published by the server per
      dispatch);
    - ``serve_shed``     — fraction of submissions shed between ticks;
    - ``train_step_time`` — ``train.step_s`` gauge over its SLO (rule
      omitted when ``step_time_slo_s`` is None);
    - ``hbm_live``       — HBM ledger live bytes over ``hbm_slo`` of the
      budget (omitted without a budget; pass the server config's
      ``resolved_hbm_budget()``);
    - ``live_devices``   — ``elastic.live_devices`` gauge UNDER
      ``min_live_devices`` (omitted when None).
    """
    win = {"fast_window_s": fast_window_s, "slow_window_s": slow_window_s}
    rules = [
        AlertRule("serve_p99",
                  lambda: core.gauge_value("serve.request_p99_s"),
                  threshold=p99_slo_s, **win,
                  description=f"serve admitted p99 > {p99_slo_s}s"),
        AlertRule("serve_shed", _shed_fraction_signal(),
                  threshold=shed_slo, **win,
                  description=f"shed fraction > {shed_slo:.0%}"),
    ]
    if step_time_slo_s is not None:
        rules.append(AlertRule(
            "train_step_time",
            lambda: core.gauge_value("train.step_s"),
            threshold=step_time_slo_s, **win,
            description=f"train step time > {step_time_slo_s}s"))
    if hbm_budget_bytes:
        bound = float(hbm_budget_bytes) * hbm_slo
        rules.append(AlertRule(
            "hbm_live", lambda: float(memory.live_bytes()),
            threshold=bound, **win,
            description=f"HBM live bytes > {hbm_slo:.0%} of budget"))
    if min_live_devices is not None:
        rules.append(AlertRule(
            "live_devices",
            lambda: core.gauge_value("elastic.live_devices"),
            threshold=float(min_live_devices), op="<", **win,
            description=f"live devices < {min_live_devices}"))
    return rules


def _rollback_delta_signal():
    """Incremental ``autotune.advisor_rollbacks`` delta between
    evaluations — same windowed-rate pattern as the shed-fraction
    signal: a rollback is an *event*, and the process-lifetime total
    would keep the alert firing forever."""
    last = {"total": _counter_total("autotune.advisor_rollbacks")}

    def signal() -> float | None:
        total = _counter_total("autotune.advisor_rollbacks")
        delta = total - last["total"]
        last["total"] = total
        return max(delta, 0.0)
    return signal


def autotune_regressed_rule(*, fast_window_s: float = 60.0,
                            slow_window_s: float = 300.0) -> AlertRule:
    """A self-tune that regressed under the advisor's micro-probe and was
    rolled back is an *incident*, never a silent slowdown: the rule
    breaches on any new rollback since the previous evaluation (burn
    fractions near zero — one bad tune among healthy ticks must still
    page) and clears once the rollback sample ages out of the fast
    window."""
    return AlertRule(
        "autotune_regressed", _rollback_delta_signal(),
        threshold=0.0, op=">",
        fast_window_s=fast_window_s, slow_window_s=slow_window_s,
        fast_burn=0.01, slow_burn=0.01,
        description="advisor tune regressed under micro-probe; rolled back")


def ensure_autotune_rule(manager: AlertManager | None = None) -> AlertRule:
    """Idempotently register :func:`autotune_regressed_rule` on
    ``manager`` (default: the process-wide manager); returns the rule
    installed there."""
    mgr = manager if manager is not None else _default_manager
    for r in mgr.rules():
        if r.name == "autotune_regressed":
            return r
    rule = autotune_regressed_rule()
    mgr.add(rule)
    return rule


# ---------------------------------------------------------------------------
# the always-on health sampler
# ---------------------------------------------------------------------------


class _HealthSampler(threading.Thread):
    """Daemon thread: one ``sample/health`` journal event + journaled
    gauges per tick, plus one alert-manager evaluation.  Step rate and
    MFU derive from the ``train.step`` span events in the core ring —
    their ``train_step_cost`` flops stamps against the platform peak."""

    def __init__(self, interval_s: float, manager: AlertManager):
        super().__init__(name="da-tpu-health-sampler", daemon=True)
        self.interval_s = max(float(interval_s), 0.05)
        self.manager = manager
        self._stop = threading.Event()
        self._last_seq = -1
        self._peak_flops: float | None = None

    def stop(self) -> None:
        self._stop.set()

    def _train_window(self) -> tuple[int, float, float]:
        """(steps, seconds, flops) from train.step span events recorded
        since the previous tick."""
        steps = 0
        dur = flops = 0.0
        last = self._last_seq
        for e in core.events("span"):
            seq = e.get("seq", -1)
            if seq <= last or e.get("name") != "train.step":
                continue
            self._last_seq = max(self._last_seq, seq)
            steps += 1
            dur += float(e.get("dur") or 0.0)
            labels = e.get("labels") or {}
            try:
                flops += float(labels.get("flops") or 0.0)
            except (TypeError, ValueError):
                pass
        return steps, dur, flops

    def _tick(self) -> None:
        if not core._ENABLED:
            return
        fields: dict = {}
        try:
            live = memory.live_bytes()
            core.set_gauge("health.hbm_live_bytes", float(live),
                           journal=True)
            fields["hbm_live"] = int(live)
        except Exception:
            pass
        depth = core.gauge_value("serve.queue_depth")
        if depth is not None:
            fields["queue_depth"] = depth
        steps, dur, flops = self._train_window()
        if steps:
            rate = steps / self.interval_s
            core.set_gauge("health.step_rate", rate, journal=True)
            fields["step_rate"] = round(rate, 4)
            if flops > 0 and dur > 0:
                if self._peak_flops is None:
                    try:
                        from . import perf as _perf
                        self._peak_flops = float(
                            _perf.peaks_for(None)["flops"])
                    except Exception:
                        self._peak_flops = 0.0
                if self._peak_flops:
                    mfu = min(flops / dur / self._peak_flops, 1.0)
                    core.set_gauge("health.mfu", round(mfu, 6),
                                   journal=True)
                    fields["mfu"] = round(mfu, 6)
        core.event("sample", "health", **fields)
        try:
            self.manager.evaluate()
        except Exception:
            pass                  # the sampler must never kill the host
        # one sampler feeds journal, alerts, AND the live plane: when a
        # streaming exporter is armed the tick's fields go out with the
        # next frame (note_health is a single is-None check otherwise)
        try:
            from . import stream as _stream
            _stream.note_health(dict(fields, t=round(
                time.monotonic() - core._T0, 3)))
        except Exception:
            pass

    def run(self) -> None:  # pragma: no cover — exercised via ticks
        while not self._stop.wait(self.interval_s):
            try:
                self._tick()
            except Exception:
                pass


_sampler: _HealthSampler | None = None
_sampler_lock = threading.Lock()
_default_manager = AlertManager()


def default_manager() -> AlertManager:
    """The process-wide manager the sampler drives; add rules here
    (e.g. ``default_manager().add(rule)``) before or after start."""
    return _default_manager


def sampler_running() -> bool:
    with _sampler_lock:
        return _sampler is not None and _sampler.is_alive()


def start_sampler(interval_s: float | None = None,
                  rules=None) -> bool:
    """Start the health sampler daemon (idempotent).  ``interval_s``
    defaults to ``DA_TPU_TELEMETRY_SAMPLE_S``; with neither set (or
    telemetry disabled) nothing starts and False returns.  ``rules``
    (optional) are added to the default manager first."""
    global _sampler
    if not core._ENABLED:
        return False
    if interval_s is None:
        raw = os.environ.get(SAMPLE_ENV)
        if not raw:
            return False
        try:
            interval_s = float(raw)
        except ValueError:
            return False
    if interval_s <= 0:
        return False
    if rules:
        for r in rules:
            _default_manager.add(r)
    with _sampler_lock:
        if _sampler is not None and _sampler.is_alive():
            return True
        _sampler = _HealthSampler(interval_s, _default_manager)
        _sampler.start()
    core.event("sample", "start", interval_s=interval_s)
    return True


def stop_sampler() -> None:
    global _sampler
    with _sampler_lock:
        s, _sampler = _sampler, None
    if s is not None:
        s.stop()


def _maybe_autostart() -> None:
    """Import-time arm (called from ``telemetry/__init__``): start only
    when the env interval is set — mirrors flight's SIGUSR1 pattern.
    With DA_TPU_TELEMETRY=0 this is one boolean check."""
    if core._ENABLED and os.environ.get(SAMPLE_ENV):
        try:
            start_sampler()
        except Exception:
            pass


def _reset() -> None:
    _default_manager.reset()


core.register_reset_hook(_reset)
