"""Doctor-driven self-tuning: perf findings -> guarded autotune writes.

The observatory diagnoses (``perf.analyze``: unoverlapped_comm /
low_roofline findings over dispatch-labeled spans) but never acted on
what it found — chunk depths, GEMM blocks, and rdma-vs-xla dispatch came
from env knobs and a hand-seeded cache.  This module closes the loop:

- :func:`advise` maps a doctor report to concrete :class:`TuningAction`\\ s
  via a small decision table —

  ===================  ======================  ===========================
  finding              registry target         proposal
  ===================  ======================  ===========================
  unoverlapped_comm    ``rdma_chunks`` entry   double the chunk depth
  (rdma span)          for the span's          (more pieces -> more
                       ``autotune_key``        pipelining, capped at 64)
  dispatch deltas      ``rdma_dispatch``       pin the measured-faster
  (rdma-vs-xla         entry for the span's    dispatch for that shape
  side-by-side)        ``dispatch_key``        class
  low_roofline on      ``pallas_matmul``       re-sweep block candidates
  ``pallas.matmul``    block entry             through ``autotune.sweep``
  ===================  ======================  ===========================

- :func:`apply` executes actions under guard: micro-probe before,
  provenance-stamped registry write (source=advisor, evidence = finding
  kind + measured before-metrics, bounded undo journal), micro-probe
  after, and the pair judged by ``regress.compare`` — a regressing tune
  is rolled back via ``autotune.undo`` and fires the
  ``autotune_regressed`` alert, so a bad self-tune is an incident, never
  a silent slowdown.

Probes are injectable (``probe=``) for deterministic tests; the
``DA_TPU_ADVISE_PROBE_CMD`` env runs a shell command per sample and uses
its wall time (harness validation: CI drives the full CLI loop without
betting on scheduler noise).  Surfaced as ``python -m
distributedarrays_tpu.telemetry advise [--apply|--json]``; every write /
rollback is journaled as an ``autotune`` event for the ``summarize``
tuning-provenance table.  See docs/autotuning.md.
"""

from __future__ import annotations

import dataclasses
import json
import os
import statistics
import subprocess
import time
from typing import Any, Callable

from . import core as _core
from . import regress as _regress

__all__ = ["TuningAction", "advise", "apply", "dispatch_deltas",
           "default_probe", "format_results", "PROBE_METRIC",
           "PROBE_CMD_ENV", "MAX_CHUNKS"]

PROBE_METRIC = "advise_probe_s"
PROBE_CMD_ENV = "DA_TPU_ADVISE_PROBE_CMD"
MAX_CHUNKS = 64          # resolve_chunks' own derived-depth cap
# dispatch preference needs a real measured gap, not scheduler jitter
_DISPATCH_MIN_DELTA = 0.10


@dataclasses.dataclass
class TuningAction:
    """One proposed registry write.

    ``kind``: ``rdma_chunks`` / ``dispatch`` / ``resweep``; ``kernel`` +
    ``key`` address the autotune entry; ``proposed`` is the value to
    write (for ``resweep``, the winner is determined by the sweep at
    apply time and ``candidates`` carries the block list).  ``probe``
    is the spec the default micro-probe rebuilds the workload from
    (shape / dtype / partition info straight off the span labels)."""

    kind: str
    kernel: str
    key: str
    current: Any
    proposed: Any
    finding: str
    evidence: dict
    probe: dict
    candidates: list | None = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if d.get("candidates") is not None:
            d["candidates"] = [list(c) for c in d["candidates"]]
        return d


# ---------------------------------------------------------------------------
# the decision table
# ---------------------------------------------------------------------------


def _as_int(v, default=0) -> int:
    try:
        return int(v)
    except (TypeError, ValueError):
        return default


def _probe_spec(name, labels: dict) -> dict:
    """Micro-probe reconstruction spec off one span's labels."""
    labels = labels or {}
    spec = {"op": name}
    for k in ("shape", "dtype", "src_dim", "dst_dim", "nparts", "ranks",
              "strategy"):
        if labels.get(k) not in (None, ""):
            spec[k] = labels[k]
    return spec


def dispatch_deltas(analysis: dict) -> list[dict]:
    """Rdma-vs-xla side-by-side deltas from the doctor's dispatch-labeled
    overlap stats: for every ``dispatch_key`` observed under BOTH
    dispatches, the mean duration per class and which one measured
    faster.  Spans in one class only yield nothing (no comparison)."""
    by_key: dict[str, dict[str, list]] = {}
    samples: dict[str, dict] = {}
    for ov in analysis.get("overlap") or []:
        labels = ov.get("labels") or {}
        key = labels.get("dispatch_key")
        disp = ov.get("dispatch")
        if not key or disp not in ("rdma", "xla"):
            continue
        by_key.setdefault(key, {}).setdefault(disp, []).append(
            float(ov["dur"]))
        samples.setdefault(key, {})[disp] = ov
    out = []
    for key, sides in by_key.items():
        if "rdma" not in sides or "xla" not in sides:
            continue
        rdma_s = statistics.mean(sides["rdma"])
        xla_s = statistics.mean(sides["xla"])
        slower, faster = max(rdma_s, xla_s), min(rdma_s, xla_s)
        out.append({
            "key": key,
            "rdma_s": round(rdma_s, 9), "xla_s": round(xla_s, 9),
            "n_rdma": len(sides["rdma"]), "n_xla": len(sides["xla"]),
            "faster": "rdma" if rdma_s <= xla_s else "xla",
            "delta_frac": round((slower - faster) / slower, 4)
            if slower > 0 else 0.0,
            "span": samples[key].get(
                "rdma" if rdma_s <= xla_s else "xla"),
        })
    out.sort(key=lambda d: -d["delta_frac"])
    return out


def advise(analysis: dict) -> list[TuningAction]:
    """Map one doctor report (``perf.analyze`` output) to tuning actions
    — at most one per ``(kernel, key)``, worst finding wins.  Pure
    decision logic: nothing is measured or written here."""
    from ..utils import autotune
    actions: dict[tuple, TuningAction] = {}
    overlaps = {ov.get("span_id"): ov
                for ov in analysis.get("overlap") or []}
    classified = {occ.get("span_id"): occ
                  for occ in analysis.get("classified") or []}

    for f in analysis.get("findings") or []:
        hint = f.get("action") or {}
        if f.get("kind") == "unoverlapped_comm" and \
                hint.get("kernel") == "rdma_chunks":
            key = hint["key"]
            if ("rdma_chunks", key) in actions:
                continue
            cur = _as_int(hint.get("current"), 0)
            if cur >= MAX_CHUNKS:
                continue           # already at the depth cap
            proposed = min(max(cur * 2, 2), MAX_CHUNKS)
            if proposed == cur:
                continue
            ov = overlaps.get(f.get("span_id")) or {}
            actions[("rdma_chunks", key)] = TuningAction(
                kind="rdma_chunks", kernel="rdma_chunks", key=key,
                current=autotune.get("rdma_chunks", key),
                proposed=[proposed], finding="unoverlapped_comm",
                evidence={"severity_s": f.get("severity_s"),
                          "overlap_frac": ov.get("overlap_frac"),
                          "unoverlapped_s": ov.get("unoverlapped_s"),
                          "dur_s": ov.get("dur"),
                          "chunks": cur},
                probe=_probe_spec(ov.get("name"), ov.get("labels")))
        elif f.get("kind") == "low_roofline" and \
                hint.get("kernel") == "pallas_matmul":
            key = hint["key"]
            if ("pallas_matmul", key) in actions:
                continue
            shape = hint.get("shape")
            if not shape or len(shape) != 3:
                continue
            occ = classified.get(f.get("span_id")) or {}
            m, k, n = (_as_int(s) for s in shape)
            cands = _block_candidates(m, n, k)
            if not cands:
                continue
            actions[("pallas_matmul", key)] = TuningAction(
                kind="resweep", kernel="pallas_matmul", key=key,
                current=autotune.get("pallas_matmul", key),
                proposed=None, finding="low_roofline",
                evidence={"severity_s": f.get("severity_s"),
                          "roofline_frac": occ.get("roofline_frac"),
                          "bound": occ.get("bound"),
                          "dur_s": occ.get("dur")},
                probe={"op": "pallas.matmul", "shape": shape,
                       "dtype": hint.get("dtype")},
                candidates=cands)

    for d in dispatch_deltas(analysis):
        key = d["key"]
        if ("rdma_dispatch", key) in actions:
            continue
        if d["delta_frac"] < _DISPATCH_MIN_DELTA:
            continue
        cur = autotune.get("rdma_dispatch", key)
        if cur == d["faster"]:
            continue               # already pinned to the winner
        span = d.pop("span", None) or {}
        actions[("rdma_dispatch", key)] = TuningAction(
            kind="dispatch", kernel="rdma_dispatch", key=key,
            current=cur, proposed=d["faster"],
            finding="dispatch_delta", evidence=dict(d),
            probe=_probe_spec(span.get("name"), span.get("labels")))

    out = sorted(actions.values(),
                 key=lambda a: -float(a.evidence.get("severity_s")
                                      or a.evidence.get("delta_frac")
                                      or 0.0))
    for a in out:
        _core.count("autotune.advisor_actions", kind=a.kind)
    return out


def _block_candidates(m: int, n: int, k: int) -> list[tuple]:
    """Bounded, divisor-valid block list for a re-sweep of one GEMM
    shape: per-dim power-of-two divisors around the dims, capped at the
    f32 tile set — small enough for a micro-sweep, wide enough to move
    off a mis-tuned entry."""
    if min(m, n, k) < 1:
        return []

    def divs(dim, cap):
        out, b = [], 1
        while b <= min(dim, cap):
            if dim % b == 0:
                out.append(b)
            b *= 2
        return out[-3:] or [dim]

    cands = []
    for bm in divs(m, 512):
        for bn in divs(n, 512):
            for bk in divs(k, 512):
                cands.append((bm, bn, bk))
    return cands[:24]


# ---------------------------------------------------------------------------
# micro-probes
# ---------------------------------------------------------------------------


def _cmd_probe(action: TuningAction, config=None) -> float:
    """Wall-time a user-supplied shell command (``DA_TPU_ADVISE_PROBE_CMD``)
    — the harness-validation hook: the command sees the action's address
    in its env and the autotune cache via ``DAT_AUTOTUNE_CACHE``."""
    env = dict(os.environ)
    env["DA_TPU_ADVISE_KERNEL"] = action.kernel
    env["DA_TPU_ADVISE_KEY"] = action.key
    env["DA_TPU_ADVISE_CONFIG"] = json.dumps(config)
    t0 = time.perf_counter()
    subprocess.run(os.environ[PROBE_CMD_ENV], shell=True, check=True,
                   env=env, capture_output=True)
    return time.perf_counter() - t0


def _reshard_probe(action: TuningAction, config=None) -> float:
    """Re-run the journaled reshard shape class once, eagerly, and time
    it — registry state at call time (chunk depth, dispatch preference)
    shapes the dispatch exactly like the real workload."""
    import numpy as np

    import distributedarrays_tpu as dat
    spec = action.probe
    shape = tuple(int(s) for s in spec["shape"])
    p = int(spec.get("nparts") or spec.get("ranks") or 2)
    src_dim = int(spec.get("src_dim") or 0)
    dst_dim = int(spec.get("dst_dim") or (1 if len(shape) > 1 else 0))
    dtype = np.dtype(str(spec.get("dtype") or "float32"))
    src_dist = [p if d == src_dim else 1 for d in range(len(shape))]
    dst_dist = [p if d == dst_dim else 1 for d in range(len(shape))]
    x = np.zeros(shape, dtype=dtype)
    E = dat.distribute(x, dist=src_dist)
    F = dat.dzeros(shape, dtype=dtype, dist=dst_dist)
    try:
        t0 = time.perf_counter()
        dat.copyto_(F, E)
        F.garray.block_until_ready()
        return time.perf_counter() - t0
    finally:
        E.close()
        F.close()


def _ring_ag_probe(action: TuningAction, config=None) -> float:
    """Time the overlapped ring GEMM for the journaled shape class."""
    import numpy as np

    import distributedarrays_tpu as dat
    from ..ops import linalg
    spec = action.probe
    m, k, n = (int(s) for s in spec["shape"])
    p = int(spec.get("ranks") or spec.get("nparts") or 2)
    dtype = np.dtype(str(spec.get("dtype") or "float32"))
    A = dat.distribute(np.zeros((m, k), dtype=dtype), dist=[p, 1])
    B = dat.distribute(np.zeros((k, n), dtype=dtype), dist=[p, 1])
    try:
        t0 = time.perf_counter()
        C = linalg._ring_ag_gemm(A, B, dtype)
        C.block_until_ready()
        return time.perf_counter() - t0
    finally:
        A.close()
        B.close()


def _gemm_probe(action: TuningAction, config=None) -> float:
    """Time ``pallas_matmul`` on the finding's shape; ``config`` (a
    candidate block) overrides the registry during a re-sweep."""
    import jax.numpy as jnp

    from ..ops.pallas_gemm import pallas_matmul
    spec = action.probe
    m, k, n = (int(s) for s in spec["shape"])
    dts = spec.get("dtype") or ["float32", "float32"]
    if isinstance(dts, str):
        dts = [dts, dts]
    a = jnp.zeros((m, k), dtype=dts[0])
    b = jnp.zeros((k, n), dtype=dts[1])
    block = tuple(int(x) for x in config) if config else None
    t0 = time.perf_counter()
    pallas_matmul(a, b, block=block).block_until_ready()
    return time.perf_counter() - t0


def default_probe(action: TuningAction, config=None) -> float:
    """One micro-probe sample (seconds) for ``action`` under the CURRENT
    registry state (``config`` only overrides during re-sweep candidate
    timing).  ``DA_TPU_ADVISE_PROBE_CMD`` takes over when set."""
    if os.environ.get(PROBE_CMD_ENV):
        return _cmd_probe(action, config)
    op = str(action.probe.get("op") or "")
    if action.kind == "resweep" or op == "pallas.matmul":
        return _gemm_probe(action, config)
    if op == "matmul.ring_ag":
        return _ring_ag_probe(action, config)
    return _reshard_probe(action, config)


# ---------------------------------------------------------------------------
# guarded apply
# ---------------------------------------------------------------------------


def _samples(probe, action, repeats: int) -> list[float]:
    probe(action)                 # warmup: compile cost is not the tune
    return [float(probe(action)) for _ in range(repeats)]


def apply(actions, *, probe: Callable | None = None, repeats: int = 3,
          mad_k: float = 3.0, rel_floor: float = 0.15,
          persist: bool = False, manager=None,
          evaluate_alerts: bool = True, now: float | None = None) -> list:
    """Execute tuning actions under the rollback guard.

    Per action: micro-probe ``repeats`` samples before, write the
    proposal with advisor provenance (``autotune.record`` — bounded undo
    journal), probe again after, judge the pair with
    ``regress.compare`` (the before samples ARE the baseline series, so
    the verdict inherits the sentinel's noise model).  A ``regression``
    verdict rolls the entry back (``autotune.undo``) and drives the
    ``autotune_regressed`` alert; anything else keeps the tune.  A
    ``resweep`` action first runs ``autotune.sweep`` over its candidate
    blocks (``record_best=False``) to pick the proposal.

    ``probe(action, config=None) -> seconds`` is injectable for
    deterministic tests; default :func:`default_probe`.  ``persist=True``
    writes the registry to the default cache after each decision.
    Returns one result dict per action (``status``: ``applied`` /
    ``rolled_back`` / ``skipped``)."""
    from ..utils import autotune
    from . import alerts
    probe = probe or default_probe
    mgr = manager
    if evaluate_alerts:
        mgr = mgr if mgr is not None else alerts.default_manager()
        alerts.ensure_autotune_rule(mgr)
    results = []
    for action in actions:
        res = action.to_dict()
        try:
            before = _samples(probe, action, repeats)
        except Exception as e:
            res.update(status="skipped",
                       reason=f"probe failed: {type(e).__name__}: {e}")
            _core.count("autotune.advisor_skips", kind=action.kind)
            results.append(res)
            continue
        proposed = action.proposed
        if action.kind == "resweep":
            try:
                proposed, sweep_times = autotune.sweep(
                    action.kernel, action.key, action.candidates,
                    timer=lambda cfg: probe(action, cfg),
                    record_best=False)
                proposed = [int(x) for x in proposed]
                res["sweep_candidates"] = len(sweep_times)
            except Exception as e:
                res.update(status="skipped",
                           reason=f"sweep failed: {type(e).__name__}: {e}")
                _core.count("autotune.advisor_skips", kind=action.kind)
                results.append(res)
                continue
        res["proposed"] = proposed
        if proposed == action.current:
            res.update(status="skipped", reason="already at proposal",
                       before_s=before)
            _core.count("autotune.advisor_skips", kind=action.kind)
            results.append(res)
            continue
        autotune.record(action.kernel, action.key, proposed, provenance={
            "source": "advisor",
            "finding": action.finding,
            "evidence": dict(action.evidence,
                             before_s=[round(s, 9) for s in before]),
            "previous": action.current,
            "ts": time.time(),
        })
        _core.count("autotune.advisor_writes", kind=action.kind)
        try:
            after = _samples(probe, action, repeats)
        except Exception as e:
            # cannot verify: the guarded contract is measure-or-revert
            autotune.undo(action.kernel, action.key)
            res.update(status="rolled_back",
                       reason=f"after-probe failed: "
                              f"{type(e).__name__}: {e}",
                       before_s=before)
            _core.count("autotune.advisor_rollbacks", kind=action.kind)
            results.append(res)
            _journal(action, res)
            if evaluate_alerts:
                mgr.evaluate(now)
            continue
        verdicts = _regress.compare(
            {PROBE_METRIC: statistics.median(after)},
            {PROBE_METRIC: before},
            mad_k=mad_k, rel_floor=rel_floor)
        verdict = verdicts[0] if verdicts else {"status": "ok"}
        res.update(before_s=[round(s, 9) for s in before],
                   after_s=[round(s, 9) for s in after],
                   verdict=verdict)
        if verdict.get("status") == "regression":
            autotune.undo(action.kernel, action.key)
            res["status"] = "rolled_back"
            res["reason"] = (
                f"micro-probe regressed: {verdict['value']:.6g}s vs "
                f"median {verdict['median']:.6g}s (allowed "
                f"{verdict['threshold']:.3g})")
            _core.count("autotune.advisor_rollbacks", kind=action.kind)
        else:
            res["status"] = "applied"
            _core.count("autotune.advisor_applies", kind=action.kind)
        if persist:
            autotune.save_default()
        _journal(action, res)
        if evaluate_alerts:
            mgr.evaluate(now)
        results.append(res)
    return results


def _journal(action: TuningAction, res: dict) -> None:
    if not _core._ENABLED:
        return
    _core.event("autotune", "advise",
                kernel=action.kernel, key=action.key,
                kind=action.kind, finding=action.finding,
                old=action.current, new=res.get("proposed"),
                status=res["status"], reason=res.get("reason"),
                before_s=res.get("before_s"), after_s=res.get("after_s"))


def format_results(actions: list, results: list | None, out) -> None:
    """Human rendering for the ``advise`` CLI: one line per action, with
    apply outcomes when present."""
    if not actions:
        out.write("no tuning actions: the journal shows nothing the "
                  "advisor can address\n")
        return
    by_addr = {(r["kernel"], r["key"]): r for r in results or []}
    for a in actions:
        d = a.to_dict() if isinstance(a, TuningAction) else dict(a)
        r = by_addr.get((d["kernel"], d["key"]))
        status = (r or {}).get("status", "proposed")
        proposed = (r or {}).get("proposed", d.get("proposed"))
        out.write(f"{status.upper():<12} {d['kernel']}[{d['key']}]: "
                  f"{d.get('current')} -> {proposed} "
                  f"({d['finding']})\n")
        ev = d.get("evidence") or {}
        keys = [k for k in ("severity_s", "overlap_frac", "roofline_frac",
                            "delta_frac", "rdma_s", "xla_s") if
                ev.get(k) is not None]
        if keys:
            out.write("             evidence: " +
                      "  ".join(f"{k}={ev[k]:.6g}" for k in keys) + "\n")
        if r and r.get("reason"):
            out.write(f"             {r['reason']}\n")
        if r and r.get("before_s") and r.get("after_s"):
            out.write(
                f"             probe: before median "
                f"{statistics.median(r['before_s']):.6g}s, after median "
                f"{statistics.median(r['after_s']):.6g}s "
                f"(n={len(r['before_s'])})\n")
