"""Noise-aware bench regression sentinel.

The banked trajectory (``BENCH_r*.json`` headline rows; optionally
``BENCH_DETAILS*.json`` label tables) is evidence, not decoration: a
fresh bench run that is significantly slower than the trajectory should
fail loudly instead of silently extending the table.  This module
compares a fresh run's rows against the banked series with thresholds
derived from the trajectory's own noise:

- per metric, the baseline is the **median** of the banked values and
  the spread is the **median absolute deviation** (MAD — robust to the
  single wild round a flaky tunnel produces);
- a fresh value regresses when it is worse than the median by more than
  ``max(mad_k * 1.4826 * MAD, rel_floor * |median|)`` (the 1.4826 factor
  scales MAD to a normal sigma; the relative floor keeps a zero-noise
  trajectory from flagging measurement jitter);
- with fewer than ``min_points`` banked values the noise is unknown and
  only a conservative 50% degradation flags;
- **replayed rows never count** — neither as baseline points nor as a
  fresh measurement (``replayed: true`` from bench.py, or the legacy
  "replayed from the banked table" note) — a replay is the *old* number
  wearing a new timestamp.

Direction is inferred from the metric name (``*_s``, ``*_s_per_iter``,
latency percentiles → lower is better; ``*_gflops``, ``*_tokens_per_s``,
``*_gbps``, ``*_mfu`` → higher); unknown metrics are skipped, never
guessed.  Pure stdlib, shared by ``python -m distributedarrays_tpu
.telemetry regress`` (CI leg + tpu_watch) and tests.
"""

from __future__ import annotations

import glob
import json
import os
import re

__all__ = [
    "direction", "is_replay", "mad", "load_rows", "load_baseline",
    "compare", "format_results",
]

_LOWER_BETTER = re.compile(
    r"(_s|_s_per_iter|_seconds|_latency_s|_p50_s|_p99_s|_ms|"
    r"_iters|_iterations|_residual)$")
_HIGHER_BETTER = re.compile(
    r"(_gflops|_tflops|_gbps|_mfu|_tokens_per_s|_per_s|_rps|"
    r"gflops|tflops)$")
_SKIP = re.compile(
    r"(_error|_rerun_error|_orphan_running|_comm_bytes_est|_hbm_peak_mb|"
    r"_L|_n|_attempts|_attempts_max|_chunks|_block|_sweep|_winner|_path|"
    r"_source|_note|_dispatch|_strategy)$")
# rate units as a mid-name token (the headline metric is
# "gemm_4096_gflops_mixed_precision_bf16pass" — unit in the middle):
# only consulted after both anchored suffix patterns fail, so a
# hypothetical "..._gflops_probe_s" still judges as seconds
_HIGHER_TOKEN = re.compile(
    r"(^|_)(gflops|tflops|gbps|mfu|tokens_per_s|rps)(_|$)")


def direction(metric: str) -> int:
    """-1 when lower is better, +1 when higher is better, 0 unknown."""
    if _SKIP.search(metric):
        return 0
    # rates first: *_tokens_per_s / *_per_s / *_rps end in "_s" too, and
    # a throughput judged lower-is-better would invert every verdict
    if _HIGHER_BETTER.search(metric):
        return 1
    if _LOWER_BETTER.search(metric):
        return -1
    if _HIGHER_TOKEN.search(metric):
        return 1
    return 0


def is_replay(row: dict) -> bool:
    """True when this row is a replay of an older banked measurement."""
    if row.get("replayed") is True:
        return True
    return "replayed from the banked table" in str(row.get("note", ""))


def mad(values: list) -> float:
    """Median absolute deviation (0.0 for fewer than 2 values)."""
    if len(values) < 2:
        return 0.0
    med = _median(values)
    return _median([abs(v - med) for v in values])


def _median(values: list) -> float:
    s = sorted(values)
    n = len(s)
    mid = n // 2
    return float(s[mid]) if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _numeric_items(doc: dict) -> dict:
    out = {}
    for k, v in doc.items():
        if k.startswith("_") or direction(k) == 0:
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        out[k] = float(v)
    return out


def load_rows(path: str) -> dict:
    """Extract ``{metric: value}`` from one file, whatever its shape:

    - a bench stdout row (``{"metric": ..., "value": ...}``),
    - a ``BENCH_r*.json`` wrapper (``{"parsed": {...}}``),
    - a ``BENCH_DETAILS.json`` label table (numeric labels).

    Replayed and errored rows yield nothing (``{}``)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        return {}
    row = doc.get("parsed") if isinstance(doc.get("parsed"), dict) else doc
    if "metric" in row and "value" in row:
        if is_replay(row) or row.get("error") or not row.get("value"):
            return {}
        return {str(row["metric"]): float(row["value"])}
    if is_replay(row):
        return {}
    return _numeric_items(row)


def load_baseline(paths: list) -> dict:
    """``{metric: [values...]}`` over the banked trajectory.  Each entry
    of ``paths`` is a file (loaded via :func:`load_rows`) or a directory
    (every ``BENCH_r*.json`` inside, sorted)."""
    series: dict = {}
    for p in paths:
        files = (sorted(glob.glob(os.path.join(p, "BENCH_r*.json")))
                 if os.path.isdir(p) else [p])
        for f in files:
            try:
                rows = load_rows(f)
            except (OSError, ValueError):
                continue
            for metric, value in rows.items():
                series.setdefault(metric, []).append(value)
    return series


def compare(fresh: dict, baseline: dict, *, mad_k: float = 3.0,
            rel_floor: float = 0.15, min_points: int = 3) -> list:
    """Judge every fresh metric that has a banked series.  Returns one
    dict per judged metric: ``status`` is ``ok`` / ``regression`` /
    ``improved`` / ``skipped``; ``threshold`` is the allowed degradation
    in the metric's own units."""
    results = []
    for metric in sorted(fresh):
        d = direction(metric)
        value = fresh[metric]
        series = baseline.get(metric) or []
        if d == 0:
            continue
        if not series:
            results.append({"metric": metric, "value": value,
                            "status": "skipped",
                            "reason": "no banked baseline"})
            continue
        med = _median(series)
        spread = mad(series)
        if len(series) >= min_points:
            threshold = max(mad_k * 1.4826 * spread,
                            rel_floor * abs(med))
        else:
            threshold = 0.5 * abs(med)
        delta = value - med
        worse = delta if d < 0 else -delta
        status = "ok"
        if worse > threshold:
            status = "regression"
        elif worse < -threshold:
            status = "improved"
        results.append({
            "metric": metric, "value": value, "median": med,
            "mad": spread, "n": len(series), "threshold": threshold,
            "delta": delta, "worse_by": worse, "status": status,
            "direction": "lower_is_better" if d < 0
            else "higher_is_better",
        })
    return results


def format_results(results: list, out, explain: bool = False) -> None:
    """Render compare() verdicts; ``explain`` adds a per-metric baseline
    line (median / MAD / series size / direction / how the threshold was
    derived) so a multi-metric verdict is auditable from the text alone,
    not just the exit code."""
    for r in sorted(results,
                    key=lambda r: (r["status"] != "regression",
                                   -(r.get("worse_by") or 0))):
        if r["status"] == "skipped":
            out.write(f"SKIP  {r['metric']}: {r['reason']}\n")
            continue
        out.write(
            f"{r['status'].upper():<10} {r['metric']}: {r['value']:.6g} "
            f"vs median {r['median']:.6g} over {r['n']} banked runs "
            f"(MAD {r['mad']:.3g}, allowed degradation "
            f"{r['threshold']:.3g})\n")
        if explain:
            arrow = ("lower is better"
                     if r.get("direction") == "lower_is_better"
                     else "higher is better")
            rule = ("max(mad_k*1.4826*MAD, rel_floor*|median|)"
                    if r["n"] >= 3 else
                    "conservative 50% of |median| (fewer than 3 points)")
            out.write(
                f"          baseline: median {r['median']:.6g}, "
                f"MAD {r['mad']:.3g} over n={r['n']}; {arrow}; "
                f"delta {r['delta']:+.6g} (worse_by {r['worse_by']:.6g}); "
                f"threshold = {rule}\n")
