"""HBM memory ledger: who holds how many device bytes, and since when.

PR 4's reshard planner promises *bounded staging memory* and its
``_BufShare`` co-ownership makes "who owns these bytes" non-trivial; the
lifecycle registry (``core.registry()`` / ``d_closeall()``) proves arrays
are *closed* but says nothing about the resource those invariants
protect.  This module is the accounting layer between the two: every
DArray's device buffer is tracked from creation through rebind/reshard/
mutation to ``close()``/finalizer, with

- **per-device live-byte gauges and peak watermarks** — physical bytes
  (sum over addressable shards, so replication and blocked padding cost
  what they actually cost in HBM), not logical array sizes;
- **shared-ownership awareness** — a buffer co-owned through a
  ``_BufShare`` token is counted ONCE and released only when the last
  owner closes, mirroring the runtime semantics exactly;
- **allocation-site attribution** — the creating span plus a truncated
  stack per entry (``DA_TPU_TELEMETRY_MEMSTACK=0`` turns the stack
  capture off; ``DA_TPU_TELEMETRY=0`` turns the whole ledger off and
  every hook collapses to a single boolean check);
- **staging accounting** — :func:`staging` brackets transient buffers
  (the reshard planner's per-chunk staging pieces), so the
  ``DA_TPU_RESHARD_CHUNK_MB`` bound is *observed*, not assumed;
- **:func:`leak_census`** — diffs the ledger against
  ``jax.live_arrays()`` and classifies bytes as ledger-tracked /
  untracked-foreign / deleted-but-registered.

Surfaced as the ``memory`` section of :func:`core.report`, as
``da_tpu_hbm_*`` gauges in ``to_prometheus``, as a counter ("C") track in
``to_perfetto``, and via ``python -m distributedarrays_tpu.telemetry mem``.

Like the rest of the telemetry core this module imports nothing from the
rest of the package (stdlib only; ``leak_census`` imports jax lazily),
so any layer can import it without cycles.
"""

from __future__ import annotations

import itertools
import os
import sys
import time
import traceback
import weakref

from . import core

__all__ = [
    "track", "untrack", "share", "sample",
    "live_bytes", "live_bytes_by_device", "peak_bytes", "reset_peak",
    "tracked_count",
    "staging", "staging_peak", "snapshot", "entries", "leak_census",
]

_STACK_DEPTH = 5


def _stack_enabled() -> bool:
    v = os.environ.get("DA_TPU_TELEMETRY_MEMSTACK")
    return v is None or v.strip().lower() not in core._FALSY


class _Entry:
    """One tracked device buffer.  ``owners`` is the set of DArray ids
    co-owning it (>1 after a ``_BufShare`` join); bytes are freed when
    the LAST owner leaves."""

    __slots__ = ("eid", "owners", "nbytes", "per_dev", "site", "span",
                 "stack", "buf_ref", "buf_id", "t")

    def to_dict(self) -> dict:
        # the stack is stored as raw FrameSummary objects (no line-text
        # lookup, no string formatting on the allocation path) and only
        # rendered here, when someone actually inspects the entry
        stack = None
        if self.stack:
            stack = [f"{os.path.basename(fr.filename)}:{fr.lineno}:"
                     f"{fr.name}" for fr in reversed(self.stack)]
        return {"owners": [list(o) if isinstance(o, tuple) else o
                           for o in sorted(self.owners)],
                "nbytes": self.nbytes,
                "per_device": {str(k): v for k, v in self.per_dev.items()},
                "site": self.site, "span": self.span, "stack": stack,
                "age_s": round(time.monotonic() - self.t, 3)}


_ids = itertools.count(1)          # CPython-atomic
_entries: dict[int, _Entry] = {}   # eid -> entry
_by_owner: dict = {}               # owner id -> eid
_by_buf: dict[int, int] = {}       # id(buf) -> eid (weakref-validated)
_live_total = 0
_peak_total = 0
_live_dev: dict = {}               # device id -> live bytes
_peak_dev: dict = {}               # device id -> peak bytes
_staging_live = 0
_staging_peak = 0
_staging_peak_tag: dict[str, int] = {}


def _shard_bytes(buf) -> tuple[dict, int]:
    """Physical per-device byte map of a (possibly sharded, possibly
    replicated) device buffer — duck-typed so this module never imports
    jax.  Falls back to the logical size on one pseudo-device when shard
    introspection is unavailable."""
    per: dict = {}
    total = 0
    try:
        shards = buf.addressable_shards
    except Exception:
        shards = None
    if shards:
        try:
            for s in shards:
                dev = getattr(getattr(s, "device", None), "id", -1)
                nb = int(getattr(getattr(s, "data", None), "nbytes", 0) or 0)
                per[dev] = per.get(dev, 0) + nb
                total += nb
            return per, total
        except Exception:
            per, total = {}, 0
    nb = core.nbytes_of(buf)
    return ({-1: nb} if nb else {}), nb


def _capture_site():
    sp = core._CURRENT_SPAN.get()
    span = sp.name if sp is not None else None
    stack = None
    if _stack_enabled():
        try:
            # lookup_lines=False: no linecache file reads on the hot
            # path; frames are formatted lazily in _Entry.to_dict
            stack = list(traceback.StackSummary.extract(
                traceback.walk_stack(sys._getframe(2)),
                limit=_STACK_DEPTH, lookup_lines=False))
        except Exception:
            stack = None
    return span, stack


def _add_locked(per: dict, total: int) -> None:
    global _live_total, _peak_total
    _live_total += total
    if _live_total > _peak_total:
        _peak_total = _live_total
    for dev, nb in per.items():
        v = _live_dev.get(dev, 0) + nb
        _live_dev[dev] = v
        if v > _peak_dev.get(dev, 0):
            _peak_dev[dev] = v


def _sub_locked(per: dict, total: int) -> None:
    global _live_total
    _live_total -= total
    for dev, nb in per.items():
        v = _live_dev.get(dev, 0) - nb
        if v <= 0:
            _live_dev.pop(dev, None)
        else:
            _live_dev[dev] = v


def _drop_owner_locked(owner):
    """Remove ``owner`` from its entry; returns the freed entry (bytes
    subtracted) when the owner was the last holder, else None."""
    eid = _by_owner.pop(owner, None)
    if eid is None:
        return None
    e = _entries.get(eid)
    if e is None:
        return None
    e.owners.discard(owner)
    if e.owners:
        return None
    del _entries[eid]
    if _by_buf.get(e.buf_id) == eid:
        del _by_buf[e.buf_id]
    _sub_locked(e.per_dev, e.nbytes)
    return e


# ---------------------------------------------------------------------------
# lifecycle hooks (called from darray.py)
# ---------------------------------------------------------------------------


def track(owner, buf, *, site: str | None = None) -> None:
    """Attribute ``buf``'s device bytes to DArray ``owner``.  Re-tracking
    an owner (rebind) releases its previous entry first.  If ``buf`` is
    already a tracked entry's buffer (identity-checked through the
    entry's weakref), the owner JOINS that entry instead of allocating a
    duplicate — so handing a buffer from one DArray to another (aligned
    ``samedist``, ``map_localparts_into``) never double-counts it, not
    even transiently: the peak watermark only ever sees real HBM."""
    if not core._ENABLED:
        return
    per, total = _shard_bytes(buf)
    span, stack = _capture_site()
    try:
        ref = weakref.ref(buf)
    except TypeError:
        ref = None
    with core._LOCK:
        jeid = _by_buf.get(id(buf))
        je = _entries.get(jeid) if jeid is not None else None
        if je is not None and (je.buf_ref is None
                               or je.buf_ref() is not buf):
            je = None                # stale id: a dead buffer's address
        if je is not None:
            if _by_owner.get(owner) != jeid:
                _drop_owner_locked(owner)
                je.owners.add(owner)
                _by_owner[owner] = jeid
            live = _live_total
        else:
            e = _Entry()
            e.eid = next(_ids)
            e.owners = {owner}
            e.nbytes = total
            e.per_dev = per
            e.site = site
            e.span = span
            e.stack = stack
            e.buf_ref = ref
            e.buf_id = id(buf)
            e.t = time.monotonic()
            _drop_owner_locked(owner)
            _entries[e.eid] = e
            _by_owner[owner] = e.eid
            if ref is not None:
                _by_buf[id(buf)] = e.eid
            live = _live_total + total
            _add_locked(per, total)
    if je is not None:
        core.event("hbm", "share", owner=str(owner), bytes=total,
                   live=live, site=site)
    else:
        core.event("hbm", "alloc", owner=str(owner), bytes=total,
                   live=live, site=site)


def untrack(owner) -> None:
    """Owner released its buffer (close / finalizer / wrapper release).
    Frees the entry's bytes only when ``owner`` was the last holder.
    Always runs (even with telemetry disabled) so the ledger can drain
    after a mid-run ``disable()`` — a no-op dict probe when empty."""
    if not _by_owner:
        return
    with core._LOCK:
        freed = _drop_owner_locked(owner)
        live = _live_total
    if freed is not None and core._ENABLED:
        core.event("hbm", "free", owner=str(owner), bytes=freed.nbytes,
                   live=live, site=freed.site)


def share(src_owner, dst_owner) -> None:
    """``dst_owner`` now co-owns ``src_owner``'s buffer (a ``_BufShare``
    group formed).  ``dst_owner``'s own entry — the double-count from its
    constructor tracking the shared buffer — is dissolved; the group's
    bytes stay counted once, on the shared entry."""
    if not core._ENABLED and not _by_owner:
        return
    with core._LOCK:
        seid = _by_owner.get(src_owner)
        if seid is None:
            return                       # source untracked: nothing to join
        if _by_owner.get(dst_owner) != seid:
            _drop_owner_locked(dst_owner)
        e = _entries.get(seid)
        if e is not None:
            e.owners.add(dst_owner)
            _by_owner[dst_owner] = seid


def sample(tag: str) -> None:
    """Journal one ``hbm``/``sample`` point (current live bytes) — used
    at phase boundaries (checkpoint save/restore) so the Perfetto HBM
    counter track shows them even when no alloc/free lands exactly
    there."""
    if not core._ENABLED:
        return
    with core._LOCK:
        live = _live_total
    core.event("hbm", "sample", tag=tag, live=live)


# ---------------------------------------------------------------------------
# staging (transient buffers: reshard chunks, checkpoint encode)
# ---------------------------------------------------------------------------


class staging:
    """Context manager bracketing a transient allocation of ``nbytes``
    (estimated, per device): feeds the staging live gauge and per-tag
    peak watermarks, so chunked-reshard staging is *observed* against
    its ``DA_TPU_RESHARD_CHUNK_MB`` budget."""

    __slots__ = ("_tag", "_nbytes", "_on")

    def __init__(self, tag: str, nbytes: int):
        self._tag = tag
        self._nbytes = int(nbytes)
        self._on = False

    def __enter__(self):
        if not core._ENABLED:            # the single-boolean disabled path
            return self
        self._on = True
        global _staging_live, _staging_peak
        with core._LOCK:
            _staging_live += self._nbytes
            if _staging_live > _staging_peak:
                _staging_peak = _staging_live
            tp = _staging_peak_tag.get(self._tag, 0)
            if _staging_live > tp:
                _staging_peak_tag[self._tag] = _staging_live
            live = _staging_live
        core.event("hbm", "staging", tag=self._tag, bytes=self._nbytes,
                   staging_live=live)
        return self

    def __exit__(self, *exc):
        if self._on:
            global _staging_live
            with core._LOCK:
                _staging_live -= self._nbytes
        return False


def staging_peak(tag: str | None = None) -> int:
    with core._LOCK:
        if tag is None:
            return _staging_peak
        return _staging_peak_tag.get(tag, 0)


# ---------------------------------------------------------------------------
# queries
# ---------------------------------------------------------------------------


def live_bytes(device=None) -> int:
    with core._LOCK:
        if device is None:
            return _live_total
        return _live_dev.get(device, 0)


def live_bytes_by_device() -> dict:
    """Per-device live-byte map (device id -> bytes) — the elastic
    manager's witness that a shrunk device's HBM actually drained."""
    with core._LOCK:
        return dict(_live_dev)


def peak_bytes(device=None) -> int:
    with core._LOCK:
        if device is None:
            return _peak_total
        return _peak_dev.get(device, 0)


def reset_peak() -> None:
    """Reset every peak watermark (total, per-device, staging) to the
    current live level — the per-bench-config watermark reset."""
    global _peak_total, _staging_peak
    with core._LOCK:
        _peak_total = _live_total
        _peak_dev.clear()
        _peak_dev.update(_live_dev)
        _staging_peak = _staging_live
        _staging_peak_tag.clear()


def tracked_count() -> int:
    with core._LOCK:
        return len(_entries)


def entries(limit: int | None = None) -> list[dict]:
    """Snapshot of the tracked entries (largest first), for bundles and
    debugging."""
    with core._LOCK:
        es = sorted(_entries.values(), key=lambda e: -e.nbytes)
        if limit is not None:
            es = es[:limit]
        return [e.to_dict() for e in es]


def snapshot() -> dict:
    """The ``memory`` section of :func:`core.report`."""
    with core._LOCK:
        sites: dict[str, dict] = {}
        for e in _entries.values():
            key = e.span or e.site or "?"
            s = sites.setdefault(key, {"bytes": 0, "count": 0})
            s["bytes"] += e.nbytes
            s["count"] += 1
        return {
            "live_bytes": _live_total,
            "peak_bytes": _peak_total,
            "tracked_arrays": len(_entries),
            "owners": len(_by_owner),
            "by_device": {str(d): {"live_bytes": _live_dev.get(d, 0),
                                   "peak_bytes": _peak_dev.get(d, 0)}
                          for d in sorted(set(_live_dev) | set(_peak_dev),
                                          key=str)},
            "staging": {"live_bytes": _staging_live,
                        "peak_bytes": _staging_peak,
                        "peak_by_tag": dict(sorted(
                            _staging_peak_tag.items()))},
            "top_sites": sorted(
                ([k, v["bytes"], v["count"]] for k, v in sites.items()),
                key=lambda kv: -kv[1])[:10],
        }


def leak_census() -> dict:
    """Diff the ledger against ``jax.live_arrays()``.

    - ``ledger_tracked`` — live jax buffers the ledger knows about;
    - ``untracked_foreign`` — live jax buffers with no ledger entry
      (raw jnp temporaries, jit constants, user arrays);
    - ``deleted_but_registered`` — ledger entries whose buffer is gone
      (deleted or collected) without the owner releasing — the
      lifecycle-hygiene violations this census exists to catch.
    """
    with core._LOCK:
        es = list(_entries.values())
    live_tracked_ids = set()
    stale_bytes = stale_count = 0
    for e in es:
        buf = e.buf_ref() if e.buf_ref is not None else None
        deleted = buf is None
        if buf is not None:
            try:
                deleted = bool(buf.is_deleted())
            except Exception:
                deleted = False
        if deleted:
            stale_bytes += e.nbytes
            stale_count += 1
        else:
            live_tracked_ids.add(id(buf))
    tracked_b = tracked_n = foreign_b = foreign_n = 0
    arrays_seen = None
    try:
        import jax
        arrays_seen = [a for a in jax.live_arrays()
                       if not getattr(a, "is_deleted", lambda: False)()]
    except Exception:
        arrays_seen = None
    if arrays_seen is not None:
        for a in arrays_seen:
            _, nb = _shard_bytes(a)
            if id(a) in live_tracked_ids:
                tracked_b += nb
                tracked_n += 1
            else:
                foreign_b += nb
                foreign_n += 1
    return {
        "ledger_tracked": {"bytes": tracked_b, "count": tracked_n},
        "untracked_foreign": {"bytes": foreign_b, "count": foreign_n},
        "deleted_but_registered": {"bytes": stale_bytes,
                                   "count": stale_count},
        "jax_live_arrays": None if arrays_seen is None
        else len(arrays_seen),
    }


def _reset() -> None:
    global _live_total, _peak_total, _staging_live, _staging_peak
    with core._LOCK:
        _entries.clear()
        _by_owner.clear()
        _by_buf.clear()
        _live_dev.clear()
        _peak_dev.clear()
        _staging_peak_tag.clear()
        _live_total = _peak_total = 0
        _staging_live = _staging_peak = 0


core.register_report_section("memory", snapshot)
core.register_reset_hook(_reset)
