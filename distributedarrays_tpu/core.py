"""Object lifecycle: IDs, the live-array registry, and the release protocol.

TPU-native counterpart of /root/reference/src/core.jl.  The reference needs a
distributed GC — a creator-side ref set (core.jl:30-52), an all-nodes registry
of id → WeakRef (core.jl:1-28) and a finalizer-driven release fan-out
(core.jl:67-105) — because chunks live in remote worker processes.  Under
single-controller JAX the controller owns every buffer handle, so lifecycle
collapses to: Python refcounting + ``jax.Array.delete()`` to drop HBM eagerly.
We keep the same *observable* surface for parity and for the leak-checking
test discipline (reference test/runtests.jl:28-37, test/darray.jl:1079-1086):

- ``next_did()``       — atomic id generation (core.jl:55-65)
- ``registry()``       — id → weakref of every live DArray
- ``close(d)``         — eager release of d's device buffers (core.jl:92-105)
- ``d_closeall()``     — close every DArray created here (core.jl:95-103)
- ``refcount_report`` / ``check_leaks`` helpers for tests
"""

from __future__ import annotations

import itertools
import threading
import weakref

from . import telemetry as _tm

__all__ = ["next_did", "d_closeall", "close", "registry", "live_ids",
           "live_arrays", "procs"]

_id_counter = itertools.count(1)
_id_lock = threading.Lock()

# thread-local SPMD rank: 0 on the controller thread, set per-task by
# parallel.spmd (the reference's `myid()` analog for localpart addressing)
_rank_tls = threading.local()


def current_rank() -> int:
    return getattr(_rank_tls, "rank", 0)

# id -> weakref.ref(DArray).  Mirrors the reference REGISTRY (core.jl:1-28);
# the lock mirrors its ReentrantLock discipline — and must genuinely be
# reentrant here: the flight recorder's SIGUSR1 handler snapshots the
# registry census on whatever thread the signal interrupts, possibly one
# already inside register/unregister/d_closeall.
_registry: dict[tuple[int, int], "weakref.ref"] = {}
_registry_lock = threading.RLock()


def next_did() -> tuple[int, int]:
    """Fresh DArray id ``(controller_pid, seq)``.

    The reference returns ``(myid(), atomic_add!(DID))`` (core.jl:55-65); the
    single controller is always pid 0 here, kept as a tuple for parity.
    """
    with _id_lock:
        return (0, next(_id_counter))


def register(d) -> None:
    with _registry_lock:
        _registry[d.id] = weakref.ref(d)


def unregister(did) -> None:
    with _registry_lock:
        _registry.pop(did, None)


def registry() -> dict:
    """Snapshot of the live registry (for tests / leak checks)."""
    with _registry_lock:
        return {k: v for k, v in _registry.items() if v() is not None}


def live_ids() -> list[tuple[int, int]]:
    return sorted(registry().keys())


def live_arrays() -> list:
    """Strong references to every live registered DArray, id-ordered —
    the iteration surface the elastic device-set manager re-lays-out
    over (a weakref snapshot would let arrays die mid-re-layout)."""
    snap = registry()
    return [d for d in (snap[k]() for k in sorted(snap)) if d is not None]


def close(d) -> None:
    """Eagerly release ``d``'s device buffers (reference ``Base.close(d)``,
    core.jl:105; release fan-out core.jl:68-84 becomes a local delete)."""
    d._close()


def d_closeall() -> None:
    """Close every live DArray (reference ``d_closeall``, core.jl:95-103).

    The registry is cleared BEFORE the close loop, so a ``_close()`` that
    raises must not strand the remaining (now-unregistered) arrays with
    their HBM pinned: every array is closed regardless, the FIRST error
    is re-raised at the end, and the whole sweep is journaled as one
    ``lifecycle``/``closeall`` event with the closed count and the bytes
    the HBM ledger saw drain."""
    with _registry_lock:
        refs = list(_registry.values())
        _registry.clear()
    live0 = _tm.memory.live_bytes() if _tm.enabled() else 0
    first: BaseException | None = None
    closed = failed = 0
    for r in refs:
        d = r()
        if d is None:
            continue
        try:
            d._close(_unregister=False)
            closed += 1
        except BaseException as e:  # noqa: BLE001 — re-raised below
            failed += 1
            if first is None:
                first = e
    if _tm.enabled():
        _tm.event("lifecycle", "closeall", closed=closed, errors=failed,
                  freed_bytes=max(live0 - _tm.memory.live_bytes(), 0))
    if first is not None:
        raise first


def procs(d):
    """Process/rank grid of ``d`` (reference ``procs(::DArray)``, core.jl:112)."""
    return d.pids


def _registry_census() -> dict:
    """Live-registry snapshot for flight-recorder bundles: how many
    arrays were open at crash time, and which (id/type/dims/closed)."""
    snap = registry()
    items = []
    for did in sorted(snap):
        d = snap[did]()
        if d is None:
            continue
        items.append({"id": list(did), "type": type(d).__name__,
                      "dims": [int(x) for x in getattr(d, "dims", ()) or ()],
                      "closed": bool(getattr(d, "_closed", False))})
    return {"live": len(items), "arrays": items[:200]}


# telemetry stays package-independent: the bundle's registry census is
# injected from here instead of imported from there
_tm.flight.register_census_provider(_registry_census)


# ---------------------------------------------------------------------------
# Scalar-indexing guard (reference darray.jl:637-648, exported `allowscalar`)
# ---------------------------------------------------------------------------

_allowscalar = threading.local()


def allowscalar(flag: bool | None = None):
    """Get/set whether scalar ``getindex``/``setindex`` on a DArray is allowed.

    Mirrors /root/reference/src/darray.jl:641-645.  Scalar reads gather one
    element from device to host — a performance trap the tests ban globally
    (reference test/runtests.jl:5-7).  Usable as a context manager::

        with allowscalar(True):
            x = d[3, 4]
    """
    if flag is None:
        return getattr(_allowscalar, "flag", False)
    return _AllowScalar(flag)


class _AllowScalar:
    def __init__(self, flag: bool):
        self._prev = getattr(_allowscalar, "flag", False)
        _allowscalar.flag = bool(flag)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _allowscalar.flag = self._prev
        return False

    def __bool__(self):
        return getattr(_allowscalar, "flag", False)


def _scalar_indexing_allowed():
    if not getattr(_allowscalar, "flag", False):
        raise RuntimeError(
            "scalar indexing of a DArray is disabled; it gathers one element "
            "per call from device HBM. Use allowscalar(True) (context manager) "
            "to permit it explicitly. [reference darray.jl:638-640]"
        )
