"""MPI-style SPMD programming mode.

TPU-native counterpart of /root/reference/src/spmd.jl (260 LoC).  The
reference gives each worker a RemoteChannel, a demux task routing
``(ctxt_id, typ, from, data, tag)`` tuples into per-context channels
(spmd.jl:72-98), out-of-order buffering for unexpected messages
(spmd.jl:126-143), and collectives built from send/recv (159-231).

Design split for TPU:

- **This module** is the *dynamic* half: fully general tagged send/recv
  between ranks, contexts with context-local storage, barrier/bcast/
  scatter/gather — runs host-side, one Python task (thread) per rank under
  the single controller.  Message passing is in-memory mailbox matching,
  which preserves the reference's semantics (tags, out-of-order buffering,
  any pattern, any payload) exactly — there is no TCP to emulate.
- ``parallel.collectives`` is the *static* half: communication patterns
  known at trace time (ring shifts, halo exchange, all-to-all) compile to
  ``shard_map`` + ``lax.ppermute``/``psum``/``all_to_all`` over ICI — that
  is the path where the reference's send/recv ring programs (e.g.
  test/spmd.jl:90-101, the stencil in docs/src/index.md:160-181) belong on
  TPU, and what the benchmarks exercise.

Inside ``spmd(f, ...)`` each rank task sees ``myid()`` (its rank) and
DArray ``localpart`` resolves against that rank, mirroring how reference
SPMD closures address their chunk.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Sequence

import numpy as np

from .. import core
from .. import layout as L
from .. import telemetry as _tm
from ..analysis import divergence as _dv
from ..resilience import faults as _fl

__all__ = [
    "spmd", "spmd_async", "sendto", "recvfrom", "recvfrom_any", "barrier",
    "bcast", "scatter", "gather_spmd", "context", "context_local_storage",
    "myid", "nprocs", "SPMDContext", "close_context",
]

_TIMEOUT_ENV = "DA_TPU_SPMD_TIMEOUT"
_DEFAULT_TIMEOUT = 60.0  # seconds; a stuck collective fails loudly, not forever


def _default_timeout() -> float:
    """The receive-timeout default: ``DA_TPU_SPMD_TIMEOUT`` seconds when
    set (resilience tests shrink it to trip fast; pod jobs with slow DCN
    raise it), else 60s.  Read per call so a test can flip the env
    without reimporting; both the thread and process backends resolve
    their ``timeout=None`` defaults through here."""
    try:
        return float(os.environ.get(_TIMEOUT_ENV, _DEFAULT_TIMEOUT))
    except ValueError:
        return _DEFAULT_TIMEOUT


_PEER_ABORT = "SPMD peer task failed; aborting receive"


def _record_crash(exc) -> None:
    """Flight-recorder trigger on the spmd failure paths: a crashed run
    leaves one postmortem bundle (ring + open spans + HBM ledger +
    registry census).  Single boolean check when telemetry is off; the
    recorder must never mask the real error."""
    if _tm.enabled():
        try:
            _tm.flight.record_crash(exc, where="spmd")
        except Exception:
            pass


def _scan_stash(msgs: list, match: Callable[[tuple], bool]):
    """Pop and return the first stashed message satisfying ``match``
    (out-of-order buffering, reference spmd.jl:126-143), else None.
    Shared by the thread mailbox and the process backend's queue view."""
    for i, m in enumerate(msgs):
        if match(m):
            return msgs.pop(i)
    return None


def _timeout_source(timeout: float) -> str:
    """Where the effective receive timeout came from — named honestly:
    the env var is credited only when it actually produced this value
    (an explicit ``timeout=`` argument overrides it, and an unparsable
    value silently falls back to the default)."""
    configured = os.environ.get(_TIMEOUT_ENV)
    if configured is not None:
        try:
            if float(configured) == timeout:
                return f"{_TIMEOUT_ENV}={configured}"
        except ValueError:
            if timeout == _DEFAULT_TIMEOUT:
                return (f"{_TIMEOUT_ENV}={configured!r} invalid, using "
                        f"default {_DEFAULT_TIMEOUT:g}s")
        return "explicit timeout argument"
    if timeout == _DEFAULT_TIMEOUT:
        return f"default {_DEFAULT_TIMEOUT:g}s; set {_TIMEOUT_ENV}"
    return "explicit timeout argument"


def _receive_timeout(timeout: float, msgs: list,
                     tag: Any = None) -> TimeoutError:
    return TimeoutError(
        f"SPMD receive timed out after {timeout}s "
        f"({_timeout_source(timeout)}) blocked on tag={tag!r} "
        f"(pending: {[(m[0], m[1], m[3]) for m in msgs[:8]]})")


class _Mailbox:
    """Per-(context, rank) message store with tag/type/source matching and
    out-of-order buffering (reference spmd.jl:126-143: unexpected messages
    are stashed and re-examined)."""

    def __init__(self):
        self._msgs: list[tuple] = []          # (typ, from_pid, data, tag)
        self._cond = threading.Condition()

    def put(self, msg: tuple):
        with self._cond:
            self._msgs.append(msg)
            self._cond.notify_all()

    def take(self, match: Callable[[tuple], bool], failed: "threading.Event",
             timeout: float, tag: Any = None):
        # span: the drain wait is where SPMD programs spend their blocked
        # time — aggregate-only (_journal=False: a chatty ring would emit
        # thousands of journal lines), visible in span_stats()/report()
        with _tm.span("spmd.mailbox.drain", _journal=False):
            deadline = time.monotonic() + timeout
            with self._cond:
                while True:
                    m = _scan_stash(self._msgs, match)
                    if m is not None:
                        return m
                    if failed.is_set():
                        raise RuntimeError(_PEER_ABORT)
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise _receive_timeout(timeout, self._msgs, tag)
                    self._cond.wait(min(remaining, 0.1))


class SPMDContext:
    """Execution context: isolates message traffic and carries per-rank local
    storage (reference SPMDContext, spmd.jl:18-35; storage spmd.jl:59-64)."""

    def __init__(self, pids: Sequence[int] | None = None):
        self.id = core.next_did()
        self.pids = [int(p) for p in (pids if pids is not None else L.all_ranks())]
        self.store: dict[int, dict] = {p: {} for p in self.pids}
        self._mailboxes: dict[int, _Mailbox] = {p: _Mailbox() for p in self.pids}
        self._barrier_gen: dict[int, int] = {p: 0 for p in self.pids}
        self._failed = threading.Event()
        self._release_gen = 0
        self._proc_state = None   # process backend's persistent queues
        # per-run collective-divergence checker (DA_TPU_CHECK_DIVERGENCE=1,
        # thread backend); installed/cleared by spmd()
        self._divergence = None

    def mailbox(self, pid: int) -> _Mailbox:
        try:
            return self._mailboxes[pid]
        except KeyError:
            raise ValueError(f"rank {pid} is not in context {self.id} "
                             f"(pids={self.pids})") from None

    def close(self):
        """Free message state (reference delete_ctxt_id broadcast,
        spmd.jl:30-35,256-258)."""
        self._mailboxes = {p: _Mailbox() for p in self.pids}
        self.store = {p: {} for p in self.pids}
        self._drop_proc_state()

    def _reset_comm(self):
        """Drain in-flight messages and resynchronize barrier generations
        after a failed run, keeping per-rank storage.  Without this an
        explicit context is poisoned: stale messages satisfy future
        receives and diverged barrier generations deadlock the next run."""
        self._mailboxes = {p: _Mailbox() for p in self.pids}
        self._barrier_gen = {p: 0 for p in self.pids}
        self._failed = threading.Event()
        self._drop_proc_state()

    def _drop_proc_state(self):
        """Drop the process backend's cross-run leftover messages (set
        lazily by spmd_process.run_spmd_process) — the process-mode
        analog of replacing the thread mailboxes above."""
        self._proc_state = None


_CONTEXTS_LOCK = threading.Lock()
_CONTEXTS: dict = {}

_tls = threading.local()


def context(pids: Sequence[int] | None = None) -> SPMDContext:
    """Create an explicit SPMD context (reference context(), spmd.jl:59-61)."""
    c = SPMDContext(pids)
    with _CONTEXTS_LOCK:
        _CONTEXTS[c.id] = c
    return c


def close_context(c: SPMDContext):
    with _CONTEXTS_LOCK:
        _CONTEXTS.pop(c.id, None)
    c.close()


def _current() -> tuple[SPMDContext, int]:
    ctx = getattr(_tls, "ctxt", None)
    if ctx is None:
        raise RuntimeError(
            "not inside an spmd() run — sendto/recvfrom/barrier/... are only "
            "meaningful within spmd(f, ...) (reference spmd.jl:118)")
    return ctx, core.current_rank()


def myid() -> int:
    """Rank of the calling SPMD task (reference myid())."""
    return core.current_rank()


def nprocs() -> int:
    ctx = getattr(_tls, "ctxt", None)
    return len(ctx.pids) if ctx is not None else L.nranks()


def context_local_storage() -> dict:
    """This rank's per-context dict, persistent across spmd() runs on the
    same explicit context (reference context_local_storage, spmd.jl:62-64)."""
    ctx, rank = _current()
    return ctx.store[rank]


# ---------------------------------------------------------------------------
# point-to-point
# ---------------------------------------------------------------------------


def sendto(pid: int, data: Any, tag: Any = None):
    """Async send to ``pid`` (reference sendto, spmd.jl:145-147)."""
    ctx, rank = _current()
    # per-send byte accounting (estimate: array payloads report nbytes,
    # unsized Python objects report 0); journal dedup'd per direction so
    # a chatty ring program cannot flood the journal.  enabled() guard:
    # this is the SPMD hot path, disabled mode must not even build the
    # key strings
    if _tm.enabled():
        _tm.record_comm("spmd_send", _tm.nbytes_of(data), op="sendto",
                        once_key=f"spmd_send:{rank}->{pid}",
                        src=rank, dst=pid)
    ctx.mailbox(pid).put(("sendto", rank, data, tag))


def recvfrom(pid: int, tag: Any = None, timeout: float | None = None):
    """Blocking receive of a message from ``pid`` with matching ``tag``
    (reference recvfrom, spmd.jl:149-151).  Out-of-order messages stay
    buffered until their matching receive.  ``timeout`` defaults to
    ``DA_TPU_SPMD_TIMEOUT`` (60s unset)."""
    ctx, rank = _current()
    if timeout is None:
        timeout = _default_timeout()
    m = ctx.mailbox(rank).take(
        lambda m: m[0] == "sendto" and m[1] == pid and m[3] == tag,
        ctx._failed, timeout, tag=tag)
    _tm.count("spmd.recv")
    return m[2]


def recvfrom_any(tag: Any = None, timeout: float | None = None):
    """Receive from whichever rank sends first; returns ``(from_pid, data)``
    (reference recvfrom_any, spmd.jl:153-157)."""
    ctx, rank = _current()
    if timeout is None:
        timeout = _default_timeout()
    m = ctx.mailbox(rank).take(
        lambda m: m[0] == "sendto" and m[3] == tag, ctx._failed, timeout,
        tag=tag)
    _tm.count("spmd.recv")
    return m[1], m[2]


# ---------------------------------------------------------------------------
# collectives (reference spmd.jl:159-231)
# ---------------------------------------------------------------------------


def _dv_note(ctx, rank: int, op: str, detail: str) -> None:
    """Record an eager collective with the run's divergence checker (no-op
    unless DA_TPU_CHECK_DIVERGENCE armed this run).  Raises
    CollectiveDivergenceError in the issuing rank's task on mismatch.
    getattr: the process backend's _RunContext duck-types SPMDContext and
    is never instrumented (checking is thread-backend only)."""
    ck = getattr(ctx, "_divergence", None)
    if ck is not None:
        ck.record(rank, op, detail)


def barrier(tag: Any = None, timeout: float | None = None):
    """All-to-all barrier with double-barrier protection via per-rank
    generation counters (reference barrier, spmd.jl:159-184)."""
    ctx, rank = _current()
    _fl.check("spmd.collective", op="barrier", rank=rank)
    _dv_note(ctx, rank, "barrier", f"tag={tag!r}")
    _tm.count("spmd.barrier")
    if timeout is None:
        timeout = _default_timeout()
    gen = ctx._barrier_gen[rank]
    ctx._barrier_gen[rank] = gen + 1
    btag = ("barrier", gen, tag)
    for p in ctx.pids:
        ctx.mailbox(p).put(("barrier", rank, None, btag))
    for p in ctx.pids:
        ctx.mailbox(rank).take(
            lambda m, p=p: m[0] == "barrier" and m[1] == p and m[3] == btag,
            ctx._failed, timeout, tag=btag)


def _check_root(ctx, root):
    if root not in ctx.pids:
        raise ValueError(f"root {root} is not in context pids {ctx.pids}")


def bcast(data: Any, root: int, tag: Any = None,
          timeout: float | None = None):
    """Broadcast from ``root`` to every rank (reference bcast,
    spmd.jl:186-196)."""
    ctx, rank = _current()
    _check_root(ctx, root)
    _fl.check("spmd.collective", op="bcast", rank=rank)
    if timeout is None:
        timeout = _default_timeout()
    # payload signature excluded: only root's data participates (non-root
    # ranks conventionally pass None), so shapes legitimately differ
    _dv_note(ctx, rank, "bcast", f"root={root}, tag={tag!r}")
    btag = ("bcast", tag)
    if rank == root:
        if _tm.enabled():
            _tm.record_comm("spmd_send",
                            _tm.nbytes_of(data) * (len(ctx.pids) - 1),
                            op="bcast", once_key=f"spmd_send:bcast:{root}",
                            src=root)
        for p in ctx.pids:
            if p != root:
                ctx.mailbox(p).put(("sendto", root, data, btag))
        return data
    m = ctx.mailbox(rank).take(
        lambda m: m[0] == "sendto" and m[1] == root and m[3] == btag,
        ctx._failed, timeout, tag=btag)
    return m[2]


def scatter(x, root: int, tag: Any = None, timeout: float | None = None):
    """Split ``x`` evenly across ranks from ``root`` (reference scatter,
    spmd.jl:198-212; equal division is asserted like the reference's
    ``@assert rem(length(x), length(pids)) == 0``)."""
    ctx, rank = _current()
    _check_root(ctx, root)
    _fl.check("spmd.collective", op="scatter", rank=rank)
    if timeout is None:
        timeout = _default_timeout()
    _dv_note(ctx, rank, "scatter", f"root={root}, tag={tag!r}")
    stag = ("scatter", tag)
    if rank == root:
        n = len(x)
        if n % len(ctx.pids) != 0:
            raise ValueError(
                f"scatter: length {n} not divisible by {len(ctx.pids)} ranks")
        per = n // len(ctx.pids)
        if _tm.enabled():
            _tm.record_comm("spmd_send", _tm.nbytes_of(x), op="scatter",
                            once_key=f"spmd_send:scatter:{root}", src=root)
        mine = None
        for i, p in enumerate(ctx.pids):
            part = x[i * per:(i + 1) * per]
            if p == rank:
                mine = part
            else:
                ctx.mailbox(p).put(("sendto", root, part, stag))
        return mine
    m = ctx.mailbox(rank).take(
        lambda m: m[0] == "sendto" and m[1] == root and m[3] == stag,
        ctx._failed, timeout, tag=stag)
    return m[2]


def gather_spmd(x, root: int, tag: Any = None,
                timeout: float | None = None):
    """Collect one value per rank at ``root``, pid-ordered (reference gather,
    spmd.jl:214-231).  Returns the list on root, None elsewhere."""
    ctx, rank = _current()
    _check_root(ctx, root)
    _fl.check("spmd.collective", op="gather_spmd", rank=rank)
    if timeout is None:
        timeout = _default_timeout()
    _dv_note(ctx, rank, "gather_spmd",
             f"root={root}, tag={tag!r}, "
             f"payload={_dv.payload_signature(x)}")
    gtag = ("gather", tag)
    if rank != root:
        if _tm.enabled():
            _tm.record_comm("spmd_send", _tm.nbytes_of(x), op="gather",
                            once_key=f"spmd_send:gather:{rank}->{root}",
                            src=rank, dst=root)
        ctx.mailbox(root).put(("sendto", rank, x, gtag))
        return None
    out = {}
    out[rank] = x
    for p in ctx.pids:
        if p == root:
            continue
        m = ctx.mailbox(rank).take(
            lambda m, p=p: m[0] == "sendto" and m[1] == p and m[3] == gtag,
            ctx._failed, timeout, tag=gtag)
        out[p] = m[2]
    return [out[p] for p in ctx.pids]


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


@_tm.traced(name="spmd.run")
def spmd(f: Callable, *args, pids: Sequence[int] | None = None,
         context: SPMDContext | None = None, timeout: float = 300.0,
         backend: str = "thread"):
    """Run ``f(*args)`` once per rank, concurrently (reference spmd driver,
    spmd.jl:233-254).

    Each rank runs in its own task with ``myid()`` set, an implicit fresh
    context unless an explicit one is passed (implicit contexts are cleared
    after the run, like the reference's ``clear_ctxt`` path), and DArray
    arguments resolve ``localpart()`` against the task's rank.  Returns the
    per-rank return values, pid-ordered.

    ``backend="process"`` forks one OS process per rank (the reference's
    addprocs worker model, runtests.jl:10-13): pure-Python rank compute
    runs GIL-free, messages/results/storage cross process boundaries (and
    must be picklable), and context storage is merged back after the run.
    Host-side compute only — see parallel/spmd_process.py.
    """
    implicit = context is None
    ctx = SPMDContext(pids) if implicit else context
    if pids is not None and not implicit and list(pids) != ctx.pids:
        raise ValueError("pids disagree with explicit context's pids")
    _tm.count("spmd.runs", backend=backend)
    if _tm.enabled():
        # the @traced spmd.run span opened without knowing the backend
        # or rank count — stamp them now (per-call labels on the span)
        _tm.annotate(backend=backend, ranks=len(ctx.pids))
        _tm.event("spmd", "run", backend=backend, ranks=len(ctx.pids),
                  once_key=f"spmd:run:{backend}:{len(ctx.pids)}")
    checker = None
    if _dv.checking():
        if backend == "thread":
            checker = _dv.DivergenceChecker(ctx.pids,
                                            on_mismatch=ctx._failed.set)
        else:
            # the stderr warning is one-shot and easily lost — journal a
            # typed event + counter so the doctor and incident
            # reconstruction can see the coverage gap (this run was NOT
            # divergence-checked, even though the env var says it was)
            _tm.count("analysis.divergence_unchecked", backend=backend)
            if _tm.enabled():
                _tm.event("divergence", "unchecked_backend",
                          backend=backend, ranks=len(ctx.pids),
                          once_key=f"divergence:unchecked:{backend}")
            from ..utils.debug import warn_once
            warn_once("divergence:process-backend",
                      "DA_TPU_CHECK_DIVERGENCE is set but the process "
                      "backend is not instrumented; collective-divergence "
                      "checking only covers backend='thread'")
    ctx._divergence = checker
    if backend == "process":
        from .spmd_process import run_spmd_process
        try:
            res = run_spmd_process(f, args, ctx, timeout)
        except BaseException as e:
            _record_crash(e)
            if not implicit:
                ctx._reset_comm()    # same post-failure hygiene as threads
            raise
        finally:
            if implicit:
                ctx.close()
        return [res[p] for p in ctx.pids]
    if backend != "thread":
        raise ValueError(f"unknown spmd backend {backend!r} "
                         "(expected 'thread' or 'process')")
    dirty = False
    try:
        results = _fanout_thread_ranks(ctx, f, args, timeout, checker)
    except BaseException:
        # failed or timed-out run: drain stale messages and resync
        # barrier generations so an explicit context stays usable
        dirty = True
        raise
    finally:
        ctx._divergence = None
        if implicit:
            ctx.close()
        elif dirty:
            ctx._reset_comm()
    return [results[p] for p in ctx.pids]


def _fanout_thread_ranks(ctx: SPMDContext, f: Callable, args: tuple,
                         timeout: float, checker) -> dict[int, Any]:
    """The thread backend's rank fan-out, extracted from the driver so the
    blocking :func:`spmd` and the async dispatch path share one engine:
    one daemon thread per rank, a single shared deadline, peer-abort
    wakeups, and root-cause error aggregation.  Returns ``{rank:
    result}``; raises (after recording a flight bundle) on any failure."""
    results: dict[int, Any] = {}
    errors: dict[int, BaseException] = {}
    # request-trace propagation: contextvars do not cross thread starts,
    # so capture the caller's trace ids here and rebind inside each rank
    # task — a serve request's id reaches its rank steps (and the spans/
    # events they record) without touching the span parent isolation
    # (fresh threads still root their own span timelines)
    trace_ids = _tm.current_trace_ids()

    def run(rank: int):
        core._rank_tls.rank = rank
        _tls.ctxt = ctx
        if trace_ids:
            _tm.tracing.bind_trace_ids(trace_ids)
        try:
            # deterministic chaos: an armed fault plan can kill/hang this
            # rank at task start — the thread-backend "host death" site
            _fl.check("spmd.rank", rank=rank, backend="thread")
            # per-rank step span: a fresh thread has no contextvar parent,
            # so rank timelines are independent root spans (one Perfetto
            # track per rank thread)
            with _tm.span("spmd.step", rank=rank):
                results[rank] = f(*args)
            if checker is not None:
                # clean completion: peers mid-collective beyond this rank's
                # final count can never be matched — fail fast, don't let
                # them wait out the receive timeout
                checker.finish(rank)
        except BaseException as e:  # noqa: BLE001 — propagated to caller
            errors[rank] = e
            ctx._failed.set()
        finally:
            core._rank_tls.rank = 0
            _tls.ctxt = None

    threads = [threading.Thread(target=run, args=(p,), name=f"spmd-{p}",
                                daemon=True) for p in ctx.pids]
    for t in threads:
        t.start()
    # one shared deadline: the documented timeout bounds the whole run, not
    # each join (nranks sequential joins would multiply the worst case)
    deadline = time.monotonic() + timeout
    for t in threads:
        t.join(max(0.0, deadline - time.monotonic()))
        if t.is_alive():
            ctx._failed.set()      # wake blocked receivers
            for t2 in threads:
                t2.join(5)
            err = TimeoutError(
                f"spmd task {t.name} did not finish in {timeout}s")
            _record_crash(err)
            raise err
    if errors:
        def _secondary(e):
            # failures that are consequences, not causes: peer aborts,
            # receive timeouts, and the divergence error itself
            return ((isinstance(e, RuntimeError)
                     and "peer task failed" in str(e))
                    or isinstance(e, (TimeoutError,
                                      _dv.CollectiveDivergenceError)))
        if (checker is not None and checker.error is not None
                and all(_secondary(e) for e in errors.values())):
            # the divergence IS the root cause: every other failure is a
            # peer abort/timeout it triggered.  Raise it directly so the
            # per-rank sequence diff reaches the caller unwrapped.
            _record_crash(checker.error)
            raise checker.error
        # prefer the root-cause failure over secondary "peer failed" aborts
        primary = [(r, e) for r, e in sorted(errors.items())
                   if not (isinstance(e, RuntimeError)
                           and "peer task failed" in str(e))]
        rank, err = primary[0] if primary else sorted(errors.items())[0]
        _record_crash(err)
        raise RuntimeError(
            f"spmd task on rank {rank} failed ({len(errors)} total failures)"
        ) from err
    if checker is not None:
        checker.verify()   # backstop: identical sequences end to end
    return results


# ---------------------------------------------------------------------------
# async dispatch
# ---------------------------------------------------------------------------

_DISPATCHERS_ENV = "DA_TPU_SPMD_DISPATCHERS"
_dispatch_pool = None
_dispatch_lock = threading.Lock()


def _dispatcher():
    """The shared async-dispatch pool (daemon threads; size
    ``DA_TPU_SPMD_DISPATCHERS``, default 4).  Lazy: a process that only
    ever calls blocking :func:`spmd` never creates it."""
    global _dispatch_pool
    if _dispatch_pool is None:
        from concurrent.futures import ThreadPoolExecutor
        with _dispatch_lock:
            if _dispatch_pool is None:
                try:
                    n = int(os.environ.get(_DISPATCHERS_ENV, "4"))
                except ValueError:
                    n = 4
                _dispatch_pool = ThreadPoolExecutor(
                    max_workers=max(1, n),
                    thread_name_prefix="spmd-dispatch")
    return _dispatch_pool


def spmd_async(f: Callable, *args, pids: Sequence[int] | None = None,
               context: SPMDContext | None = None, timeout: float = 300.0,
               backend: str = "thread"):
    """Asynchronous :func:`spmd`: enqueue the run on the shared dispatch
    pool and return a ``concurrent.futures.Future`` resolving to the
    pid-ordered per-rank results (or raising exactly what ``spmd`` would).

    This is the async half of the serving refactor: dispatchers overlap
    independent runs (up to ``DA_TPU_SPMD_DISPATCHERS`` concurrently)
    instead of the caller blocking through each eager fan-out — the
    serving executor and any pipelined workload submit here.  Runs on
    the same explicit ``context`` are NOT serialized by this function;
    overlapping them has the same semantics as overlapping threads did.
    """
    return _dispatcher().submit(
        lambda: spmd(f, *args, pids=pids, context=context, timeout=timeout,
                     backend=backend))
