"""Multi-host (DCN) support: the distributed communication backend's
cross-host half.

The reference scales by adding Julia worker processes over TCP
(`addprocs`, Distributed stdlib — SURVEY.md §2 "Distributed communication
backend").  The TPU-native equivalent is one JAX *controller per host*
coordinating through ``jax.distributed``: inside a jitted program,
cross-host communication is the same XLA collectives as cross-chip — they
ride ICI within a slice and DCN across slices, chosen by the compiler from
the mesh topology.  Nothing else in this framework changes for multi-host:
every op is expressed against a ``Mesh``, so a mesh built from global
devices makes DArrays span hosts.

On a single-host environment these helpers degrade gracefully (process
count 1), so the same program runs everywhere — the multi-host analog of
the reference running its full test suite on local `addprocs` workers.
"""

from __future__ import annotations

import os
import time

import numpy as np

import jax
from jax.sharding import Mesh

from .. import layout as L
from .. import telemetry as _tm

__all__ = ["initialize", "global_mesh", "process_info", "sync_hosts",
           "host_local_slice", "gather_global", "heartbeat",
           "down_peer_processes", "quorum_assess",
           "exchange_clock_offsets", "advertise_aggregator",
           "aggregator_endpoint", "advertise_exporter"]


def _init_timeout_kw(initialization_timeout_s: int | None) -> dict:
    """Bounded coordinator startup: an explicit timeout wins, else
    ``DA_TPU_MH_INIT_TIMEOUT_S``, else jax's default (300 s).  A cluster
    whose coordinator never comes up must fail with a diagnosable
    timeout, not hang the job (or a test harness) indefinitely."""
    if initialization_timeout_s is None:
        env = os.environ.get("DA_TPU_MH_INIT_TIMEOUT_S")
        if env:
            try:
                initialization_timeout_s = int(float(env))
            except ValueError:
                initialization_timeout_s = None
    if initialization_timeout_s is None:
        return {}
    return {"initialization_timeout": max(1, int(initialization_timeout_s))}


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None,
               initialization_timeout_s: int | None = None) -> None:
    """Join the multi-host job (wraps ``jax.distributed.initialize``).

    With no arguments, attempts the standard auto-detecting initialization
    (TPU pod metadata / cluster env); if no cluster is detected the call
    degrades to a single-process no-op, so the same program runs on a
    laptop and a pod.  After joining, ``jax.devices()`` is the *global*
    device list and meshes built from it span hosts.

    ``initialization_timeout_s`` (or ``DA_TPU_MH_INIT_TIMEOUT_S``) bounds
    the coordinator handshake — past it the runtime raises instead of
    waiting forever on a coordinator that never started.
    """
    kw = _init_timeout_kw(initialization_timeout_s)
    if num_processes is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id, **kw)
        _tm.event("multihost", "initialize",
                  num_processes=num_processes, process_id=process_id)
        return
    try:
        jax.distributed.initialize(**kw)
        _tm.event("multihost", "initialize", auto=True)
    except ValueError as e:
        # Degrade to single-process mode ONLY for the "nothing configured"
        # signature: auto-detection found no cluster, so initialize() had
        # no coordinator_address to use (a ValueError raised before any
        # connection attempt).  Real join failures (unreachable
        # coordinator, timeout, double init) are RuntimeErrors and must
        # surface — a pod job silently running single-process is the worst
        # failure mode.
        if "coordinator_address" in str(e):
            return
        raise


def _kv_client():
    """The ``jax.distributed`` coordination-service KV client, or None
    when single-process / not initialized — the heartbeat helpers
    degrade to no-ops so the same program runs on a laptop and a pod."""
    try:
        if jax.process_count() <= 1:
            return None
        from jax._src import distributed as _dist  # pragma: no cover
        return getattr(_dist.global_state, "client", None)  # pragma: no cover
    except Exception:
        return None


_HB_PREFIX = "dat/heartbeat/"
_CLOCK_PREFIX = "dat/clock/"
_AGG_KEY = "dat/telemetry/agg"
_EXPORTER_PREFIX = "dat/telemetry/exporter/"


def advertise_aggregator(url: str) -> bool:
    """Publish the live-telemetry aggregator's URL to the coordination
    KV so every host's streaming exporter (:mod:`telemetry.stream`) can
    discover it without per-host configuration — the same KV the
    heartbeat rides.  Returns False (no-op) single-process."""
    client = _kv_client()
    if client is None:
        return False
    try:  # pragma: no cover — needs a real multi-controller job
        client.key_value_set(_AGG_KEY, str(url), allow_overwrite=True)
        return True
    except Exception:  # pragma: no cover
        return False


def aggregator_endpoint() -> str | None:
    """The advertised aggregator URL from the coordination KV, or None
    (single-process, nothing advertised, or client unavailable) — the
    exporter's discovery fallback when ``DA_TPU_STREAM_AGG`` is unset."""
    client = _kv_client()
    if client is None:
        return None
    try:  # pragma: no cover — needs a real multi-controller job
        raw = client.key_value_try_get(_AGG_KEY)
        return str(raw) if raw else None
    except Exception:  # pragma: no cover
        return None


def advertise_exporter() -> bool:
    """Register this process's armed streaming exporter in the KV
    (``dat/telemetry/exporter/<idx>`` -> ``"<host>:<pid> <epoch>"``) so
    an operator can enumerate which hosts are publishing to the live
    plane.  Returns False (no-op) single-process or when unarmed."""
    client = _kv_client()
    if client is None:
        return False
    try:  # pragma: no cover — needs a real multi-controller job
        from ..telemetry import stream as _stream
        if not _stream.armed():
            return False
        client.key_value_set(
            f"{_EXPORTER_PREFIX}{jax.process_index()}",
            f"{_tm.core._HOST}:{os.getpid()} {time.time():.3f}",
            allow_overwrite=True)
        return True
    except Exception:  # pragma: no cover
        return False


def heartbeat() -> bool:
    """Publish this controller process's liveness timestamp to the
    coordination service's KV store.  Call it periodically (the elastic
    manager's probe loop does); peers read it via
    :func:`down_peer_processes`.  The same write doubles as this host's
    clock sample for :func:`exchange_clock_offsets` (value format:
    ``"<epoch> <hostname>"``; a bare epoch from older writers still
    parses).  Returns False (no-op) single-process or when the
    distributed client is unavailable."""
    client = _kv_client()
    if client is None:
        return False
    try:  # pragma: no cover — needs a real multi-controller job
        now = time.time()
        client.key_value_set(f"{_HB_PREFIX}{jax.process_index()}",
                             f"{now:.3f}", allow_overwrite=True)
        client.key_value_set(f"{_CLOCK_PREFIX}{jax.process_index()}",
                             f"{now:.6f} {_tm.core._HOST}",
                             allow_overwrite=True)
        return True
    except Exception:  # pragma: no cover
        return False


def exchange_clock_offsets(journal: bool = True) -> dict[int, dict]:
    """Estimate this host's wall-clock skew against every heartbeating
    peer: ``{peer_process: {"offset_s": mine - theirs, "host": name}}``.

    Offsets ride the same coordination-service KV as the heartbeat (each
    :func:`heartbeat` publishes a ``dat/clock/<idx>`` sample); a read of
    a peer's last sample against our clock bounds the skew to within one
    heartbeat period — coarse, but enough for the offline journal merge
    (``telemetry.cluster.merge_journals``) to align per-host timelines.
    With ``journal=True`` the estimate lands as one ``multihost/clock``
    event, which is exactly what the merger looks for.  Single-process
    (or no distributed client): empty dict, nothing journaled."""
    client = _kv_client()
    if client is None:
        return {}
    offsets: dict[int, dict] = {}
    me = jax.process_index()  # pragma: no cover — needs real multi-host
    for p in range(jax.process_count()):  # pragma: no cover
        if p == me:
            continue
        try:
            raw = client.key_value_try_get(f"{_CLOCK_PREFIX}{p}")
            if not raw:
                continue
            parts = str(raw).split(None, 1)
            theirs = float(parts[0])
            host = parts[1].strip() if len(parts) > 1 else f"process-{p}"
            offsets[p] = {"offset_s": round(time.time() - theirs, 6),
                          "host": host}
        except Exception:
            continue           # an unreadable peer sample is no estimate
    if offsets and journal and _tm.enabled():  # pragma: no cover
        _tm.event("multihost", "clock", process_index=me,
                  offsets={str(k): v for k, v in offsets.items()})
    return offsets  # pragma: no cover


def down_peer_processes(stale_s: float = 30.0) -> set[int]:
    """Peer controller process indices whose heartbeat is absent or older
    than ``stale_s`` — the multihost half of the elastic manager's REAL
    health signal.  Single-process (or no distributed client): empty set,
    nothing is ever reported down from here."""
    client = _kv_client()
    if client is None:
        return set()
    down: set[int] = set()
    me = jax.process_index()  # pragma: no cover — needs real multi-host
    for p in range(jax.process_count()):  # pragma: no cover
        if p == me:
            continue
        try:
            raw = client.key_value_try_get(f"{_HB_PREFIX}{p}")
        except Exception as e:
            # only an ABSENT key is evidence of a dead peer; a transport/
            # client error says nothing about the peer and must not down
            # the whole fleet in one hiccup epoch
            if "NOT_FOUND" in str(e).upper().replace(" ", "_"):
                down.add(p)
            continue
        try:
            if not raw or time.time() - float(raw) > stale_s:
                down.add(p)
        except ValueError:
            down.add(p)        # unparsable heartbeat = no heartbeat
    return down  # pragma: no cover


def quorum_assess(stale_s: float = 30.0) -> dict:
    """This controller's partition verdict over the failure-domain
    topology: ``{"verdict": "healthy"|"quorum"|"minority", "side",
    "lost", "reason"}``.

    Two evidence sources, simulated first (so chaos runs are
    deterministic): an armed ``partition`` fault's
    ``faults.partition_state()``, else the real heartbeat census
    (:func:`down_peer_processes` over the coordination-service KV).  The
    decision itself is ``domains.majority_side``: the side holding a
    strict majority of the expected ranks continues; a 50/50 tie breaks
    toward the coordinator's side; and because a strict majority wins
    regardless, a partition that swallows the coordinator still leaves
    the majority running (coordinator-loss fallback).  Healthy (no
    partition evidence) short-circuits — this is cheap enough for every
    elastic probe epoch.
    """
    from ..resilience import domains as _dom
    from ..resilience import faults as _fl
    topo = _dom.topology()
    expected = topo.ranks()
    st = _fl.partition_state()
    if st is not None:
        q = _dom.majority_side(st["groups"], st["observer"],
                               expected_total=len(expected))
        out = {**q, "reason": "injected partition (fault plan)"}
    else:
        down_procs = down_peer_processes(stale_s=stale_s)
        if not down_procs:
            out = {"verdict": "healthy", "side": list(expected),
                   "lost": [], "reason": "no partition evidence"}
        else:  # pragma: no cover — needs a real multi-controller job
            # heartbeat census: my side is every process still
            # heartbeating (me included); the far side is the stale set.
            # Rank granularity comes from the device→process map.
            stale = set(down_procs)
            mine, lost = [], []
            for i, dev in enumerate(jax.devices()):
                (lost if getattr(dev, "process_index", 0) in stale
                 else mine).append(i)
            q = _dom.majority_side([mine, lost], mine[0] if mine else 0,
                                   expected_total=len(expected))
            reason = "heartbeat census"
            if 0 in stale:
                reason += " (coordinator process lost)"
            out = {**q, "reason": reason}
    _tm.count("multihost.quorum_checks", verdict=out["verdict"])
    if out["verdict"] != "healthy" and _tm.enabled():
        # cold path: only journaled while partitioned
        _tm.event("multihost", "quorum", verdict=out["verdict"],
                  side=len(out["side"]), lost=len(out["lost"]),
                  reason=out["reason"])
    return out


def process_info() -> dict:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def global_mesh(shape: tuple[int, ...], axis_names: tuple[str, ...]) -> Mesh:
    """Mesh over ALL global devices.  Axis order should put the
    fastest-communicating axes (tensor/sequence parallel) within a host's
    slice so their collectives ride ICI, and the slowest (data parallel)
    across hosts on DCN — the scaling-book layout recipe."""
    devs = np.asarray(jax.devices(), dtype=object)
    if int(np.prod(shape)) != devs.size:
        raise ValueError(f"mesh shape {shape} != {devs.size} global devices")
    return Mesh(devs.reshape(shape), axis_names)


def sync_hosts(name: str = "sync") -> None:
    """Barrier across controller processes (host-side, for program phases;
    in-program synchronization is a collective, not this)."""
    if jax.process_count() > 1:  # pragma: no cover - needs real multi-host
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


def gather_global(d) -> np.ndarray:
    """Host numpy copy of a DArray (or jax.Array) that may SPAN controller
    processes — the multi-controller analog of the reference's ``Array(d)``
    gather (darray.jl:211-224), which pulls every remote chunk to the
    caller.

    EVERY process must call this (SPMD discipline); every branch predicate
    is process-independent so no process can wander into a collective
    alone.  Three cases: data on this process only → direct fetch; data
    spanning processes → one compiled replication program (an XLA
    all-gather over DCN+ICI); data owned by a process SUBSET → the owners
    fetch locally and a host-level allgather (the ``jax.distributed``
    client's CPU collective) hands the bytes to everyone else."""
    arr = d.garray if hasattr(d, "garray") else d
    if jax.process_count() == 1:
        return np.asarray(arr)
    # cross-host gather: every non-owning process receives the full array
    # over DCN (replication program and/or host-level allgather)
    if _tm.enabled():
        _tm.record_comm("multihost_gather", _tm.nbytes_of(arr),
                        op="gather_global", shape=list(np.shape(arr)))
    procs_of = sorted({dev.process_index for dev in arr.sharding.device_set})
    me = jax.process_index()
    if len(procs_of) > 1:
        # every owning process joins the compiled replication; with data
        # spanning all processes this is fully symmetric
        from jax.sharding import NamedSharding, PartitionSpec
        from ..darray import _resharder
        if me in procs_of:
            # _resharder is lru_cached on the sharding — no per-call retrace
            rep = _resharder(NamedSharding(
                arr.sharding.mesh, PartitionSpec()))(arr)
            val = np.asarray(rep.addressable_data(0))
        else:
            val = np.zeros(arr.shape, np.dtype(arr.dtype))
    elif me in procs_of:
        val = np.asarray(arr)                    # sole owner: local fetch
    else:
        val = np.zeros(arr.shape, np.dtype(arr.dtype))
    if len(procs_of) < jax.process_count():
        from jax.experimental import multihost_utils
        out = multihost_utils.process_allgather(val)
        val = np.asarray(out[procs_of[0]])
    return val


def host_local_slice(d) -> list:
    """The chunks of ``d`` owned by this host's local devices (the
    multi-controller analog of ``localpart``)."""
    local = {dev.id for dev in jax.local_devices()}
    out = []
    for pid in [int(p) for p in d.pids.flat]:
        dev = jax.devices()[pid]
        if dev.id in local:
            out.append((pid, d.localpart(pid)))
    return out
