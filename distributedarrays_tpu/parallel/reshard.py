"""Layout-aware reshard planner: chunked collective redistribution.

Every redistribution in the framework used to be one whole-array
``jax.device_put``: correct, but it moves (and peaks at) the FULL logical
array even when the two layouts share most of their bytes.  "Memory-
efficient array redistribution through portable collective communication"
(arXiv:2112.01075) shows that any reshard decomposes into a short sequence
of all-to-all / all-gather / dynamic-slice stages whose peak per-device
memory is bounded by src-shard + dst-shard + one staging chunk; DrJAX
(arXiv:2403.07128) shows that keeping that movement inside one compiled
program is what makes it scale.  This module is that planner:

1. **Plan** (:func:`plan_reshard`) — pure metadata.  The chunk-intersection
   transfer plan between a source and destination layout is block algebra
   on cut vectors (``layout.cut_intersections``): which global regions
   must cross a device boundary, and therefore how many bytes the reshard
   *has* to move (``moved_bytes`` — the (p-1)/p fraction for an even
   repartition, 0 for a pure relabeling).  Plans are ``lru_cache``d on
   ``(shape, itemsize, src sharding, dst sharding)`` exactly the way the
   identity resharder caches on sharding alone, so a hot loop resharding
   the same layout pair replans nothing (``reshard.plan_requests`` vs
   ``reshard.plan_builds`` counters expose the hit rate).

2. **Lower** (:func:`reshard`) — divisible single-axis repartitions become
   ONE compiled shard_map program over a canonical 1-D mesh, built from
   ``parallel.collectives.pall_to_all``/``pgather`` (the same collectives
   fft.py uses for its repartitions), **chunked along the largest eligible
   axis** so the staging buffer stays bounded by
   ``DA_TPU_RESHARD_CHUNK_MB`` (default 64) instead of the whole shard:

   - shard dim *i* → shard dim *j*:  tiled ``all_to_all`` per chunk;
   - shard dim *i* → replicated:     tiled ``all_gather`` per chunk;
   - replicated → shard dim *j*:     a local ``dynamic_slice`` (no comm).

3. **Fall back** — non-divisible, replicated-uneven, multi-dim-grid, and
   device-set-changing moves keep the ``device_put`` path (compiled
   identity program when the device set is unchanged).  Either way the
   chosen strategy is recorded via a ``reshard``/``plan`` journal event
   and as the ``strategy`` label of the ``reshard`` span, so Perfetto and
   ``telemetry summarize`` attribute bytes per strategy.

``dalint`` rule DAL007 flags direct cross-sharding ``jax.device_put`` on
DArray buffers outside this module, so new code routes through here.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import math
import os

import numpy as np

import jax
from jax import lax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import layout as L
from .. import telemetry as _tm
from ..telemetry import perf as _perf
from ..resilience import faults as _fl
from .collectives import pall_to_all, pgather, shard_map_compat

__all__ = ["ReshardPlan", "plan_reshard", "reshard", "plan_stats",
           "layout_of_sharding"]


_CHUNK_MB_ENV = "DA_TPU_RESHARD_CHUNK_MB"

# cross-product cap: a plan is metadata, not a workload — layouts whose
# intersection grid exceeds this fall back to the whole-array estimate
_MAX_PLAN_REGIONS = 65536


def _chunk_target_bytes() -> int:
    try:
        mb = float(os.environ.get(_CHUNK_MB_ENV, "64"))
    except ValueError:
        mb = 64.0
    return max(int(mb * 1024 * 1024), 1)


# ---------------------------------------------------------------------------
# plan metadata
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    """The transfer plan between two layouts — pure metadata, hashable.

    ``moved_bytes`` is the number of bytes that must cross a device
    boundary (summed over receiving devices), from the chunk-intersection
    algebra; ``total_bytes`` the logical array size.  ``strategy`` is one
    of ``noop`` (same sharding object), ``all_to_all`` / ``all_gather`` /
    ``local_slice`` (the compiled single-collective lowerings), or
    ``device_put`` (fallback; ``reason`` says why)."""

    strategy: str
    shape: tuple
    itemsize: int
    moved_bytes: int
    total_bytes: int
    src_dim: int | None = None
    dst_dim: int | None = None
    nparts: int = 1
    ranks: tuple = ()
    chunk_axis: int | None = None
    nchunks: int = 1
    reason: str = ""

    @property
    def collective(self) -> bool:
        return self.strategy in ("all_to_all", "all_gather", "local_slice")


def layout_of_sharding(sharding, shape):
    """The (cuts, owners) layout a sharding implies for ``shape``:
    per-dim cut vectors of the physical shard grid, and a dict mapping
    each block's grid coordinates to the sorted tuple of device ranks
    holding it (>1 entry = replication along some mesh axis)."""
    m = sharding.devices_indices_map(tuple(int(s) for s in shape))
    starts: list[set] = [set([0]) for _ in shape]
    for idx in m.values():
        for d, sl in enumerate(idx):
            starts[d].add(int(sl.start or 0))
    cuts = [sorted(s) + [int(n)] for s, n in zip(starts, shape)]
    owners: dict[tuple, list] = {}
    for dev, idx in m.items():
        ci = tuple(cuts[d].index(int(sl.start or 0))
                   for d, sl in enumerate(idx))
        owners.setdefault(ci, []).append(int(dev.id))
    return cuts, {k: tuple(sorted(v)) for k, v in owners.items()}


def _moved_elems(shape, src_cuts, src_owners, dst_cuts, dst_owners) -> int:
    """Elements that must cross a device boundary: for every region in the
    N-D chunk-intersection grid, count it once per destination device that
    does not already hold it."""
    per_dim = [L.cut_intersections(sc, dc)
               for sc, dc in zip(src_cuts, dst_cuts)]
    nregions = math.prod(len(o) for o in per_dim) if per_dim else 1
    if nregions > _MAX_PLAN_REGIONS:
        raise ValueError(f"plan too large: {nregions} regions")
    moved = 0
    for combo in itertools.product(*per_dim):
        n = 1
        for (_ai, _bi, lo, hi) in combo:
            n *= (hi - lo)
        sci = tuple(c[0] for c in combo)
        dci = tuple(c[1] for c in combo)
        sown = src_owners.get(sci, ())
        for dv in dst_owners.get(dci, ()):
            if dv not in sown:
                moved += n
    return moved


def _grid_of(cuts) -> tuple[int, ...]:
    return tuple(len(c) - 1 for c in cuts)


def _uniform(cuts) -> bool:
    sizes = np.diff(np.asarray(cuts, dtype=np.int64))
    return sizes.size == 0 or len(set(sizes.tolist())) == 1


def _singleton_rank_order(owners, grid, dim):
    """The per-block owner ranks of a layout sharded on exactly one dim,
    in block order — None if any block is replicated/multi-owned."""
    order = []
    for k in range(grid[dim]):
        ci = tuple(k if d == dim else 0 for d in range(len(grid)))
        own = owners.get(ci, ())
        if len(own) != 1:
            return None
        order.append(own[0])
    return tuple(order)


def _smallest_divisor_at_least(n: int, k: int) -> int:
    """Smallest divisor of ``n`` that is >= ``k`` (``n`` itself at worst)."""
    if k <= 1:
        return 1
    for d in range(k, n + 1):
        if n % d == 0:
            return d
    return n


def _pick_chunking(shape, itemsize, src_dim, dst_dim, p, strategy,
                   chunk_target):
    """(chunk_axis, nchunks): chunk along the largest eligible axis so one
    staging piece stays under ``chunk_target`` bytes per device.  For
    all_to_all the dst dim itself is eligible (the kernel pre-slices so
    tiled chunks land in dst-block order); the src/concat dim never is
    (its chunk results would interleave)."""
    local_bytes = math.prod(shape) * itemsize // max(p, 1)
    want = -(-local_bytes // chunk_target)          # ceil
    if want <= 1:
        return None, 1
    cands = []
    for d in range(len(shape)):
        if d == src_dim:
            continue
        if d == dst_dim:
            if strategy != "all_to_all":
                continue
            units = shape[d] // p
        else:
            units = shape[d]
        if units > 1:
            cands.append((units, d))
    if not cands:
        return None, 1
    units, axis = max(cands)
    return axis, _smallest_divisor_at_least(units, min(want, units))


@functools.lru_cache(maxsize=512)
def _plan_cached(shape, itemsize, src_sharding, dst_sharding,
                 chunk_target) -> ReshardPlan:
    # lru-miss body: once per distinct layout pair — the cold path the
    # plan-cache counters track
    _tm.count("reshard.plan_builds")
    plan = _build_plan(shape, itemsize, src_sharding, dst_sharding,
                       chunk_target)
    if _tm.enabled():
        _tm.event("reshard", "plan", strategy=plan.strategy,
                  shape=list(shape), moved_bytes=plan.moved_bytes,
                  total_bytes=plan.total_bytes, nparts=plan.nparts,
                  nchunks=plan.nchunks, reason=plan.reason)
    return plan


def _build_plan(shape, itemsize, src, dst, chunk_target) -> ReshardPlan:
    total = math.prod(shape) * itemsize if shape else itemsize

    def fallback(reason, moved=None):
        return ReshardPlan("device_put", shape, itemsize,
                           total if moved is None else moved, total,
                           reason=reason)

    if src == dst:
        return ReshardPlan("noop", shape, itemsize, 0, total)
    try:
        s_cuts, s_own = layout_of_sharding(src, shape)
        d_cuts, d_own = layout_of_sharding(dst, shape)
        moved = _moved_elems(shape, s_cuts, s_own, d_cuts, d_own) * itemsize
    except Exception as e:                           # introspection failed
        return fallback(f"opaque layouts ({type(e).__name__})")
    s_ranks_all = {r for own in s_own.values() for r in own}
    d_ranks_all = {r for own in d_own.values() for r in own}
    if s_ranks_all != d_ranks_all:
        return fallback("device sets differ", moved)
    s_grid, d_grid = _grid_of(s_cuts), _grid_of(d_cuts)
    s_sh = [d for d, g in enumerate(s_grid) if g > 1]
    d_sh = [d for d, g in enumerate(d_grid) if g > 1]
    if len(s_sh) > 1 or len(d_sh) > 1:
        return fallback("multi-dim chunk grid", moved)
    if not _uniform(s_cuts[s_sh[0]] if s_sh else [0]) or \
            (s_sh and shape[s_sh[0]] % s_grid[s_sh[0]]):
        return fallback("uneven source shards", moved)
    if not _uniform(d_cuts[d_sh[0]] if d_sh else [0]) or \
            (d_sh and shape[d_sh[0]] % d_grid[d_sh[0]]):
        return fallback("uneven destination shards", moved)

    if s_sh and d_sh:
        i, j = s_sh[0], d_sh[0]
        p = s_grid[i]
        if i == j or d_grid[j] != p:
            return fallback("incompatible repartition widths", moved)
        src_order = _singleton_rank_order(s_own, s_grid, i)
        dst_order = _singleton_rank_order(d_own, d_grid, j)
        if src_order is None or dst_order is None or src_order != dst_order:
            return fallback("replicated blocks or rank order differs", moved)
        if shape[j] % p:
            return fallback("dst dim not divisible", moved)
        ca, nc = _pick_chunking(shape, itemsize, i, j, p, "all_to_all",
                                chunk_target)
        return ReshardPlan("all_to_all", shape, itemsize, moved, total,
                           src_dim=i, dst_dim=j, nparts=p, ranks=src_order,
                           chunk_axis=ca, nchunks=nc)
    if s_sh and not d_sh:
        i = s_sh[0]
        p = s_grid[i]
        src_order = _singleton_rank_order(s_own, s_grid, i)
        if src_order is None:
            return fallback("replicated source blocks", moved)
        ca, nc = _pick_chunking(shape, itemsize, i, None, p, "all_gather",
                                chunk_target)
        return ReshardPlan("all_gather", shape, itemsize, moved, total,
                           src_dim=i, dst_dim=None, nparts=p,
                           ranks=src_order, chunk_axis=ca, nchunks=nc)
    if d_sh and not s_sh:
        j = d_sh[0]
        p = d_grid[j]
        dst_order = _singleton_rank_order(d_own, d_grid, j)
        if dst_order is None:
            return fallback("replicated destination blocks", moved)
        # every dst device must already hold the (replicated) source
        src_everywhere = all(set(dst_order) <= set(own)
                             for own in s_own.values())
        if not src_everywhere:
            return fallback("source not replicated on dst devices", moved)
        return ReshardPlan("local_slice", shape, itemsize, 0, total,
                           src_dim=None, dst_dim=j, nparts=p,
                           ranks=dst_order)
    if moved == 0:
        # same placement under a different sharding object: device_put is
        # a zero-copy relabel
        return fallback("placement-equal", moved=0)
    return fallback("no sharded dims on either side", moved)


def plan_reshard(x, dst_sharding, *, src_sharding=None,
                 itemsize=None) -> ReshardPlan:
    """The transfer plan for moving ``x`` (a jax.Array, or a shape tuple
    with ``src_sharding``/``itemsize`` given) onto ``dst_sharding``.
    Cached per layout pair; pure metadata — nothing moves."""
    if hasattr(x, "sharding"):
        shape = tuple(int(s) for s in x.shape)
        src_sharding = x.sharding
        itemsize = int(np.dtype(x.dtype).itemsize)
    else:
        shape = tuple(int(s) for s in x)
        if src_sharding is None or itemsize is None:
            raise ValueError("shape-form plan_reshard needs src_sharding "
                             "and itemsize")
    _tm.count("reshard.plan_requests")
    return _plan_cached(shape, int(itemsize), src_sharding, dst_sharding,
                        _chunk_target_bytes())


def plan_stats() -> dict:
    """Plan-cache statistics (hits/misses/size) — the `_resharder`-style
    lru the tentpole caches plans in."""
    ci = _plan_cached.cache_info()
    return {"hits": ci.hits, "misses": ci.misses, "size": ci.currsize}


# ---------------------------------------------------------------------------
# compiled lowering
# ---------------------------------------------------------------------------


def _spec_for(dim, ndim, axis):
    if dim is None:
        return P()
    return P(*[axis if d == dim else None for d in range(ndim)])


@functools.lru_cache(maxsize=512)
def _collective_jit(mesh, strategy, ndim, src_dim, dst_dim, p,
                    chunk_axis, nchunks, rdma=None):
    """ONE compiled shard_map program for a planned single-axis
    repartition, chunked so each collective stages at most 1/nchunks of
    the local shard.  With ``rdma`` set (``"compiled"``/``"interpret"``,
    from :func:`ops.pallas_collectives.rdma_mode`) the inner exchange is
    the Pallas RDMA ring kernel instead of the XLA collective: chunk
    DMAs land directly at their output offsets (no XLA-level staging
    loop needed — the kernel double-buffers internally), overlapping
    wire time with the slice/concat work."""
    _tm.count("jit.builds", fn="reshard_collective")
    # cold path: lru-miss body, once per distinct planned program
    _tm.event("jit", "build", fn="reshard_collective",  # dalint: disable=DAL003
              strategy=strategy, nchunks=nchunks, rdma=str(rdma))
    axis = mesh.axis_names[0]
    in_spec = _spec_for(src_dim, ndim, axis)
    out_spec = _spec_for(dst_dim, ndim, axis) if strategy != "all_gather" \
        else P(*([None] * ndim))

    def kernel(x):
        if rdma and strategy in ("all_to_all", "all_gather"):
            from ..ops import pallas_collectives as _pc
            interp = rdma == "interpret"
            if strategy == "all_to_all":
                return _pc.ring_all_to_all(x, axis, split_dim=dst_dim,
                                           concat_dim=src_dim,
                                           interpret=interp)
            return _pc.ring_all_gather(x, axis, dim=src_dim,
                                       interpret=interp)
        if strategy == "all_to_all":
            if nchunks <= 1:
                return pall_to_all(x, axis, split_dim=dst_dim,
                                   concat_dim=src_dim)
            if chunk_axis == dst_dim:
                # pre-slice so each chunk's tiled all_to_all lands every
                # rank the k-th contiguous slice of ITS dst block — plain
                # chunking along the split dim would interleave ranks
                jp = x.shape[dst_dim] // p
                step = jp // nchunks
                outs = []
                for k in range(nchunks):
                    piece = jnp.concatenate(
                        [lax.slice_in_dim(x, r * jp + k * step,
                                          r * jp + (k + 1) * step,
                                          axis=dst_dim)
                         for r in range(p)], axis=dst_dim)
                    outs.append(pall_to_all(piece, axis, split_dim=dst_dim,
                                            concat_dim=src_dim))
                return jnp.concatenate(outs, axis=dst_dim)
            step = x.shape[chunk_axis] // nchunks
            outs = [pall_to_all(
                lax.slice_in_dim(x, k * step, (k + 1) * step,
                                 axis=chunk_axis),
                axis, split_dim=dst_dim, concat_dim=src_dim)
                for k in range(nchunks)]
            return jnp.concatenate(outs, axis=chunk_axis)
        if strategy == "all_gather":
            if nchunks <= 1:
                return pgather(x, axis, tiled=True, dim=src_dim)
            step = x.shape[chunk_axis] // nchunks
            outs = [pgather(
                lax.slice_in_dim(x, k * step, (k + 1) * step,
                                 axis=chunk_axis),
                axis, tiled=True, dim=src_dim)
                for k in range(nchunks)]
            return jnp.concatenate(outs, axis=chunk_axis)
        # local_slice: replicated -> sharded, zero communication
        r = lax.axis_index(axis)
        blk = x.shape[dst_dim] // p
        return lax.dynamic_slice_in_dim(x, r * blk, blk, axis=dst_dim)

    # pallas_call has no shard_map replication rule: the RDMA variant
    # must opt out of the check (the XLA variant keeps it)
    return jax.jit(shard_map_compat(kernel, mesh, in_spec, out_spec,
                                    check=False if rdma else None))


def _run_collective(x, dst_sharding, plan: ReshardPlan, rdma=None):
    mesh = L.mesh_for(list(plan.ranks), (plan.nparts,))
    fn = _collective_jit(mesh, plan.strategy, len(plan.shape),
                         plan.src_dim, plan.dst_dim, plan.nparts,
                         plan.chunk_axis, plan.nchunks, rdma)
    y = fn(x)
    if y.sharding != dst_sharding:
        # equivalent placement under the caller's sharding object —
        # zero-copy relabel
        y = jax.device_put(y, dst_sharding)
    return y


@functools.lru_cache(maxsize=None)
def _resharder(sharding):
    """Compiled identity program placing its input under ``sharding`` —
    the fallback mover (and the multi-controller-legal one: XLA inserts
    the DCN/ICI collective; eager device_put cannot cross hosts)."""
    _tm.count("jit.builds", fn="resharder")
    # cold path: lru-miss body, once per distinct target sharding
    _tm.event("jit", "build", fn="resharder",  # dalint: disable=DAL003
              to=str(sharding))
    return jax.jit(lambda x: x, out_shardings=sharding)


def _device_put_path(x, dst_sharding):
    if getattr(x, "size", 1) == 0:
        # XLA rejects out_shardings on zero-element results; device_put
        # places them fine
        return jax.device_put(x, dst_sharding)
    if isinstance(x, jax.Array) and \
            not getattr(dst_sharding, "is_fully_addressable", True) and \
            getattr(x.sharding, "device_set", None) == \
            dst_sharding.device_set:
        # process-spanning move: eager device_put cannot cross hosts —
        # the compiled identity program can (XLA inserts the collective)
        return _resharder(dst_sharding)(x)
    return jax.device_put(x, dst_sharding)


def reshard(x, dst_sharding, *, op: str = "reshard",
            plan: ReshardPlan | None = None):
    """Move ``x`` onto ``dst_sharding`` via the planned strategy.

    The single funnel for cross-sharding data movement (DAL007): plans
    are cached per layout pair, divisible single-axis repartitions run as
    one compiled chunked-collective program, everything else takes the
    ``device_put`` path.  Telemetry: a ``reshard`` span labeled with the
    strategy, and comm bytes = the plan's *moved* bytes (what must cross
    a device boundary), not the whole array."""
    if getattr(x, "sharding", None) == dst_sharding:
        return x
    if plan is None:
        plan = plan_reshard(x, dst_sharding)
    if plan.strategy == "noop":
        return x
    # RDMA dispatch decided eagerly so the compiled program is keyed on
    # it (flipping DA_TPU_RDMA re-jits) and the span says which path ran
    rdma = None
    rdma_chunks = 0
    chunks_src = ""
    autotune_key = ""
    dispatch_key = ""
    dispatch_src = ""
    if plan.collective and plan.strategy in ("all_to_all", "all_gather"):
        from ..ops import pallas_collectives as _pc
        rdma = _pc.rdma_mode()
        dtype_str = str(getattr(x, "dtype", "float32"))
        # per-shape-class dispatch preference (advisor-written
        # "rdma_dispatch" entry); an explicit DA_TPU_RDMA env wins inside
        # resolve_dispatch, and a preference can only demote to XLA — it
        # never conjures RDMA on a platform rdma_mode rejected
        dispatch_key = _pc.dispatch_key_for(
            "reshard", plan.strategy, *plan.shape, dtype_str, plan.nparts)
        pref, dispatch_src = _pc.resolve_dispatch(dispatch_key)
        if pref == "xla":
            rdma = None
        if rdma and plan.strategy == "all_to_all":
            lshape = tuple(s // plan.nparts if d == plan.src_dim else s
                           for d, s in enumerate(plan.shape))
            # the kernel concats along the plan's src dim; clamping here
            # keeps span/bench provenance equal to the depth it runs
            rdma_chunks, chunks_src = _pc.a2a_chunks_for(
                lshape, dtype_str, plan.nparts, plan.src_dim)
            # the exact "rdma_chunks" registry key this depth resolved
            # under — the advisor addresses its writes by this label
            autotune_key = _pc.a2a_chunks_key(lshape, dtype_str,
                                              plan.nparts)
    with _tm.span("reshard", op=op, strategy=plan.strategy,
                  dispatch="rdma" if rdma else "xla",
                  rdma_chunks=rdma_chunks, rdma_chunks_source=chunks_src,
                  autotune_key=autotune_key, dispatch_key=dispatch_key,
                  dispatch_source=dispatch_src,
                  shape=list(plan.shape),
                  dtype=str(getattr(x, "dtype", "float32")),
                  src_dim=plan.src_dim, dst_dim=plan.dst_dim,
                  nparts=plan.nparts,
                  # analytic cost stamp (telemetry.perf): every byte
                  # read + rewritten through HBM, the plan's MOVED bytes
                  # crossing a device boundary over ICI, zero flops —
                  # the doctor classifies each occurrence against the
                  # platform roofline from these
                  **_perf.reshard_cost(plan.total_bytes,
                                       plan.moved_bytes)):
        if plan.collective:
            # chaos site: an armed fault plan can abort the planned
            # collective here — mid-reshard, before any chunk moves, so
            # the source buffer is still intact for the retry
            _fl.check("reshard.chunk", strategy=plan.strategy, op=op)
            try:
                # staging high-water: one chunk piece of the local shard
                # is what the chunked lowering stages per device.  This
                # is PLAN-DERIVED (XLA's internal staging buffers are not
                # jax-observable) — it audits the chunking the planner
                # actually chose (nchunks) against the
                # DA_TPU_RESHARD_CHUNK_MB budget, catching selection
                # regressions, not compiled-program memory use
                local = plan.total_bytes // max(plan.nparts, 1)
                piece = -(-local // max(plan.nchunks, 1))
                if rdma and plan.strategy == "all_to_all":
                    # the RDMA ring lands chunk DMAs at their final
                    # output offsets; what stages per device is one
                    # in-flight chunk window, not an XLA concat buffer
                    piece = min(piece,
                                -(-local // max(rdma_chunks, 1)))
                with _tm.memory.staging(f"reshard.{plan.strategy}", piece):
                    out = _run_collective(x, dst_sharding, plan, rdma)
                if _tm.enabled():
                    _tm.record_comm("reshard", plan.moved_bytes, op=op,
                                    strategy=plan.strategy,
                                    dispatch="rdma" if rdma else "xla",
                                    shape=list(plan.shape))
                return out
            except Exception as e:
                # the compiled path must never cost correctness; fall
                # through to device_put, loudly once per signature
                _tm.count("reshard.collective_fallbacks")
                from ..utils.debug import warn_once
                warn_once(
                    f"reshard:{plan.strategy}:{type(e).__name__}",
                    f"reshard: compiled {plan.strategy} lowering failed "
                    f"({type(e).__name__}: {e}); falling back to "
                    f"device_put")
        if _tm.enabled():
            _tm.record_comm("reshard", plan.moved_bytes, op=op,
                            strategy="device_put", shape=list(plan.shape))
        return _device_put_path(x, dst_sharding)
