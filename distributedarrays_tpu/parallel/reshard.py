"""Layout-aware reshard planner: chunked collective redistribution.

Every redistribution in the framework used to be one whole-array
``jax.device_put``: correct, but it moves (and peaks at) the FULL logical
array even when the two layouts share most of their bytes.  "Memory-
efficient array redistribution through portable collective communication"
(arXiv:2112.01075) shows that any reshard decomposes into a short sequence
of all-to-all / all-gather / dynamic-slice stages whose peak per-device
memory is bounded by src-shard + dst-shard + one staging chunk; DrJAX
(arXiv:2403.07128) shows that keeping that movement inside one compiled
program is what makes it scale.  This module is that planner:

1. **Plan** (:func:`plan_reshard`) — pure metadata.  The chunk-intersection
   transfer plan between a source and destination layout is block algebra
   on cut vectors (``layout.cut_intersections``): which global regions
   must cross a device boundary, and therefore how many bytes the reshard
   *has* to move (``moved_bytes`` — the (p-1)/p fraction for an even
   repartition, 0 for a pure relabeling).  Plans are ``lru_cache``d on
   ``(shape, itemsize, src sharding, dst sharding)`` exactly the way the
   identity resharder caches on sharding alone, so a hot loop resharding
   the same layout pair replans nothing (``reshard.plan_requests`` vs
   ``reshard.plan_builds`` counters expose the hit rate).

2. **Lower** (:func:`reshard`) — divisible single-axis repartitions become
   ONE compiled shard_map program over a canonical 1-D mesh, built from
   ``parallel.collectives.pall_to_all``/``pgather`` (the same collectives
   fft.py uses for its repartitions), **chunked along the largest eligible
   axis** so the staging buffer stays bounded by
   ``DA_TPU_RESHARD_CHUNK_MB`` (default 64) instead of the whole shard:

   - shard dim *i* → shard dim *j*:  tiled ``all_to_all`` per chunk;
   - shard dim *i* → replicated:     tiled ``all_gather`` per chunk;
   - replicated → shard dim *j*:     a local ``dynamic_slice`` (no comm).

3. **Lower the general case** — moves no single collective covers
   (multi-axis repartitions, mesh-axis transposes, partial replication)
   factorize over a *common refinement* of the two device grids
   (arXiv 2112.01075): the owner maps are digitized into a mixed-radix
   mesh whose axes each carry ONE per-axis collective — an
   ``all_to_all`` for an axis moving between array dims, an
   ``all_gather`` for an axis leaving, a local dynamic-slice for an axis
   appearing — composed as one compiled shard_map *chain* (strategy
   ``chain``).  Start-aligned ceil-uneven layouts ride the same chain
   between a comm-free pad and slice-back; device-set-shrinking moves
   whose destination is replicated enough gather collectively on the
   source mesh first (``gather_put``).  The chain planner is
   topology-aware: each mesh axis is classified intra- vs cross-domain
   against ``resilience.domains`` and the plan/span carry
   ``intra_bytes``/``cross_bytes``, with intra-domain exchanges
   scheduled first.

4. **Fall back** — whatever remains takes the ``device_put`` path
   (compiled identity program when the device set is unchanged), counted
   under ``reshard.collective_fallbacks`` with a canonical ``reason=``
   label (uneven | multi_axis | device_set | dtype | shape | runtime).
   Either way the chosen strategy is recorded via a ``reshard``/``plan``
   journal event and as the ``strategy`` label of the ``reshard`` span,
   so Perfetto and ``telemetry summarize`` attribute bytes per strategy.

``dalint`` rule DAL007 flags direct cross-sharding ``jax.device_put`` on
DArray buffers outside this module, so new code routes through here.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import math
import os

import numpy as np

import jax
from jax import lax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import layout as L
from .. import telemetry as _tm
from ..telemetry import perf as _perf
from ..resilience import faults as _fl
from .collectives import pall_to_all, pgather, shard_map_compat

__all__ = ["ReshardPlan", "plan_reshard", "reshard", "plan_stats",
           "layout_of_sharding"]


_CHUNK_MB_ENV = "DA_TPU_RESHARD_CHUNK_MB"

# cross-product cap: a plan is metadata, not a workload — layouts whose
# intersection grid exceeds this fall back to the whole-array estimate
_MAX_PLAN_REGIONS = 65536


def _chunk_target_bytes() -> int:
    try:
        mb = float(os.environ.get(_CHUNK_MB_ENV, "64"))
    except ValueError:
        mb = 64.0
    return max(int(mb * 1024 * 1024), 1)


# ---------------------------------------------------------------------------
# plan metadata
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    """The transfer plan between two layouts — pure metadata, hashable.

    ``moved_bytes`` is the number of bytes that must cross a device
    boundary (summed over receiving devices), from the chunk-intersection
    algebra; ``total_bytes`` the logical array size.  ``strategy`` is one
    of ``noop`` (same sharding object), ``all_to_all`` / ``all_gather`` /
    ``local_slice`` (the compiled single-collective lowerings), ``chain``
    / ``gather_put`` (the general per-axis collective chain over the
    refined mesh — see the module docstring), or ``device_put``
    (fallback; ``reason`` says why).

    Chain plans also carry: ``mesh_shape`` (refined mesh axis sizes,
    major→minor over the canonical rank order ``ranks``), ``src_comp`` /
    ``dst_comp`` (per array dim, the mesh-axis indices sharding it,
    major→minor), ``steps`` (the scheduled per-axis ops, each
    ``(kind, axis, q, src_dim, dst_dim, chunk_axis, nchunks,
    moved_bytes)``), ``pad_shape`` (ceil-uneven layouts: the even analog
    the chain runs on, between a comm-free pad and slice-back),
    ``staging_bytes`` (the worst step's staging piece) and the
    topology split ``intra_bytes``/``cross_bytes``."""

    strategy: str
    shape: tuple
    itemsize: int
    moved_bytes: int
    total_bytes: int
    src_dim: int | None = None
    dst_dim: int | None = None
    nparts: int = 1
    ranks: tuple = ()
    chunk_axis: int | None = None
    nchunks: int = 1
    reason: str = ""
    steps: tuple = ()
    mesh_shape: tuple = ()
    src_comp: tuple = ()
    dst_comp: tuple = ()
    pad_shape: tuple = ()
    staging_bytes: int = 0
    intra_bytes: int = 0
    cross_bytes: int = 0

    @property
    def collective(self) -> bool:
        return self.strategy in ("all_to_all", "all_gather", "local_slice",
                                 "chain", "gather_put")


def layout_of_sharding(sharding, shape):
    """The (cuts, owners) layout a sharding implies for ``shape``:
    per-dim cut vectors of the physical shard grid, and a dict mapping
    each block's grid coordinates to the sorted tuple of device ranks
    holding it (>1 entry = replication along some mesh axis)."""
    m = sharding.devices_indices_map(tuple(int(s) for s in shape))
    starts: list[set] = [set([0]) for _ in shape]
    for idx in m.values():
        for d, sl in enumerate(idx):
            starts[d].add(int(sl.start or 0))
    cuts = [sorted(s) + [int(n)] for s, n in zip(starts, shape)]
    owners: dict[tuple, list] = {}
    for dev, idx in m.items():
        ci = tuple(cuts[d].index(int(sl.start or 0))
                   for d, sl in enumerate(idx))
        owners.setdefault(ci, []).append(int(dev.id))
    return cuts, {k: tuple(sorted(v)) for k, v in owners.items()}


def _moved_elems(shape, src_cuts, src_owners, dst_cuts, dst_owners) -> int:
    """Elements that must cross a device boundary: for every region in the
    N-D chunk-intersection grid, count it once per destination device that
    does not already hold it."""
    per_dim = [L.cut_intersections(sc, dc)
               for sc, dc in zip(src_cuts, dst_cuts)]
    nregions = math.prod(len(o) for o in per_dim) if per_dim else 1
    if nregions > _MAX_PLAN_REGIONS:
        raise ValueError(f"plan too large: {nregions} regions")
    moved = 0
    for combo in itertools.product(*per_dim):
        n = 1
        for (_ai, _bi, lo, hi) in combo:
            n *= (hi - lo)
        sci = tuple(c[0] for c in combo)
        dci = tuple(c[1] for c in combo)
        sown = src_owners.get(sci, ())
        for dv in dst_owners.get(dci, ()):
            if dv not in sown:
                moved += n
    return moved


def _grid_of(cuts) -> tuple[int, ...]:
    return tuple(len(c) - 1 for c in cuts)


def _uniform(cuts) -> bool:
    sizes = np.diff(np.asarray(cuts, dtype=np.int64))
    return sizes.size == 0 or len(set(sizes.tolist())) == 1


def _singleton_rank_order(owners, grid, dim):
    """The per-block owner ranks of a layout sharded on exactly one dim,
    in block order — None if any block is replicated/multi-owned."""
    order = []
    for k in range(grid[dim]):
        ci = tuple(k if d == dim else 0 for d in range(len(grid)))
        own = owners.get(ci, ())
        if len(own) != 1:
            return None
        order.append(own[0])
    return tuple(order)


def _smallest_divisor_at_least(n: int, k: int) -> int:
    """Smallest divisor of ``n`` that is >= ``k`` (``n`` itself at worst)."""
    if k <= 1:
        return 1
    for d in range(k, n + 1):
        if n % d == 0:
            return d
    return n


def _pick_chunking(shape, itemsize, src_dim, dst_dim, p, strategy,
                   chunk_target):
    """(chunk_axis, nchunks): chunk along the largest eligible axis so one
    staging piece stays under ``chunk_target`` bytes per device.  For
    all_to_all the dst dim itself is eligible (the kernel pre-slices so
    tiled chunks land in dst-block order); the src/concat dim never is
    (its chunk results would interleave)."""
    local_bytes = math.prod(shape) * itemsize // max(p, 1)
    want = -(-local_bytes // chunk_target)          # ceil
    if want <= 1:
        return None, 1
    cands = []
    for d in range(len(shape)):
        if d == src_dim:
            continue
        if d == dst_dim:
            if strategy != "all_to_all":
                continue
            units = shape[d] // p
        else:
            units = shape[d]
        if units > 1:
            cands.append((units, d))
    if not cands:
        return None, 1
    units, axis = max(cands)
    return axis, _smallest_divisor_at_least(units, min(want, units))


# ---------------------------------------------------------------------------
# general lowering: mixed-radix factorization → per-axis collective chain
# ---------------------------------------------------------------------------
#
# arXiv 2112.01075: any even redistribution factorizes over a common
# refinement of the two layouts' device grids.  We recover that refinement
# from the owner maps alone: flatten whichever side covers every rank
# exactly once (row-major over its grid) into a canonical rank order, then
# split that order's mixed radix until BOTH sides' block coordinates are
# per-digit linear functions of the rank index.  Each refined digit is one
# mesh axis, each side becomes a composite PartitionSpec over those axes,
# and the move is a short schedule of per-axis collectives.  Order is
# forced by contiguity: a dim's factors leave minor-first and arrive
# major-first, so every concat/slice touches contiguous blocks.

_MAX_CHAIN_RANKS = 4096


def _linear_weight(vals):
    """The weight w when ``vals`` is v ↦ v*w (w may be 0) — else None."""
    w = vals[1] if len(vals) > 1 else 0
    return w if all(v == k * w for k, v in enumerate(vals)) else None


def _side_coords(own, pos, nranks):
    """Per-rank block coordinates in canonical order; None unless every
    rank owns exactly one block."""
    out = [None] * nranks
    for ci, ranks in own.items():
        for r in ranks:
            c = pos.get(r)
            if c is None or out[c] is not None:
                return None
            out[c] = ci
    return None if any(v is None for v in out) else out


def _digitize(ndim, s_grid, s_own, d_grid, d_own):
    """``(canon_ranks, digit_sizes, strides, src_comp, dst_comp)`` — the
    common mixed-radix refinement of the two owner maps — or None when no
    such factorization exists (rank-order mismatch, replication on both
    sides, non-radix block assignment)."""
    ranks = sorted({r for o in s_own.values() for r in o})
    nr = len(ranks)
    if nr > _MAX_CHAIN_RANKS or nr < 2:
        return None
    ps = math.prod(s_grid) if s_grid else 1
    pd = math.prod(d_grid) if d_grid else 1
    if ps == nr:
        canon_grid, canon_own = s_grid, s_own
    elif pd == nr:
        canon_grid, canon_own = d_grid, d_own
    else:                    # replication on BOTH sides: no full flatten
        return None
    canon = []
    for coords in itertools.product(*(range(g) for g in canon_grid)):
        o = canon_own.get(coords, ())
        if len(o) != 1:
            return None
        canon.append(o[0])
    pos = {r: i for i, r in enumerate(canon)}
    if len(pos) != nr:
        return None
    scoord = _side_coords(s_own, pos, nr)
    dcoord = _side_coords(d_own, pos, nr)
    if scoord is None or dcoord is None:
        return None
    if any(scoord[0]) or any(dcoord[0]):     # not start-aligned
        return None
    digits = []                              # (size, stride), major→minor
    stride = nr
    for g in canon_grid:
        stride //= g
        if g > 1:
            digits.append((g, stride))
    for coord in (scoord, dcoord):
        for d in range(ndim):
            k = 0
            while k < len(digits):
                q, t = digits[k]
                vals = [coord[v * t][d] for v in range(q)]
                if _linear_weight(vals) is not None:
                    k += 1
                    continue
                for a in range(2, q):        # split into (q//a, a)
                    if q % a:
                        continue
                    if all(vals[v] == vals[(v // a) * a] + vals[v % a]
                           for v in range(q)):
                        digits[k:k + 1] = [(q // a, t * a), (a, t)]
                        break
                else:
                    return None
    comps = []
    for coord in (scoord, dcoord):
        wmap = {}                            # digit -> (dim, weight)
        for m, (q, t) in enumerate(digits):
            hot = [d for d in range(ndim) if coord[t][d]]
            if len(hot) > 1:                 # one digit, two dims: not a
                return None                  # valid block grid
            if hot:
                wmap[m] = (hot[0], coord[t][hot[0]])
        comp = []
        for d in range(ndim):
            mine = sorted((w, m) for m, (dd, w) in wmap.items() if dd == d)
            exp = 1
            for w, m in mine:                # minor → major: exact radix
                if w != exp:
                    return None
                exp *= digits[m][0]
            comp.append(tuple(m for _w, m in reversed(mine)))
        for c in range(nr):                  # exhaustive: every rank's
            for d in range(ndim):            # block decomposes exactly
                v = sum(((c // digits[m][1]) % digits[m][0]) * wmap[m][1]
                        for m in comp[d])
                if v != coord[c][d]:
                    return None
        comps.append(tuple(comp))
    sizes = tuple(q for q, _t in digits)
    strides = tuple(t for _q, t in digits)
    return tuple(canon), sizes, strides, comps[0], comps[1]


def _digit_cross_domain(canon, q, t):
    """True when some sub-group along this digit spans failure domains —
    an exchange along it rides the DCN, not fast intra-domain links."""
    try:
        from ..resilience import domains as _dom
        topo = _dom.topology()
    except Exception:
        return False

    def dom(r):
        try:
            return topo.domain_of(r)
        except KeyError:
            return ("uncovered", r)

    nr = len(canon)
    for base in range(nr):
        if (base // t) % q:
            continue                         # not a group anchor
        if len({dom(canon[base + v * t]) for v in range(q)}) > 1:
            return True
    return False


def _schedule_chain(sizes, src_comp, dst_comp, cross):
    """Ordered ``(kind, digit, src_dim, dst_dim)`` ops transforming the
    source composites into the destination composites.  When several
    exchanges are simultaneously legal, intra-domain ones go first (the
    hierarchical tier: fast links early, the cross-domain residue
    coalesces into the fewest late exchanges)."""
    state = [list(c) for c in src_comp]
    target = [list(c) for c in dst_comp]
    loc = {m: (j, k) for j, c in enumerate(dst_comp)
           for k, m in enumerate(c)}
    ops = []
    for _ in range(4 * len(sizes) + 4):
        if state == target:
            return ops
        cands = []
        for i, st in enumerate(state):
            if not st:
                continue
            m = st[-1]
            at = loc.get(m)
            if at is not None:
                j, k = at
                if j != i and len(state[j]) == k and \
                        state[j] == target[j][:k]:
                    cands.append((cross.get(m, False), i,
                                  ("a2a", m, i, j)))
        if cands:
            op = min(cands)[2]
            _kind, m, i, j = op
            state[i].pop()
            state[j].append(m)
            ops.append(op)
            continue
        placed = {m for st in state for m in st}
        progressed = False
        for j, tg in enumerate(target):
            k = len(state[j])
            if k < len(tg) and state[j] == tg[:k] and tg[k] not in placed:
                ops.append(("slice", tg[k], None, j))
                state[j].append(tg[k])
                progressed = True
                break
        if progressed:
            continue
        # unblock first: if some digit could a2a into dim j but j's tail
        # holds extra digits past the correct prefix, gathering j's tail
        # enables the cheaper exchange (gather+a2a beats gather+gather
        # for a mesh-axis transpose)
        for i, st in enumerate(state):
            if not st:
                continue
            at = loc.get(st[-1])
            if at is None:
                continue
            j, k = at
            if j != i and len(state[j]) > k and \
                    state[j][:k] == target[j][:k]:
                ops.append(("gather", state[j][-1], j, None))
                state[j].pop()
                progressed = True
                break
        if progressed:
            continue
        for i, st in enumerate(state):
            if st and st != target[i][:len(st)]:
                ops.append(("gather", st[-1], i, None))
                st.pop()
                progressed = True
                break
        if not progressed:
            return None
    return None


def _pick_step_chunking(local, itemsize, concat_dim, split_dim, q,
                        chunk_target):
    """(chunk_axis, nchunks) for one chain step — :func:`_pick_chunking`
    over the step's evolving LOCAL shape.  -1 = unchunked."""
    lbytes = math.prod(local) * itemsize
    want = -(-lbytes // chunk_target)
    if want <= 1:
        return -1, 1
    cands = []
    for d in range(len(local)):
        if d == concat_dim:
            continue
        units = local[d] // q if d == split_dim else local[d]
        if units > 1:
            cands.append((units, d))
    if not cands:
        return -1, 1
    units, axis = max(cands)
    return axis, _smallest_divisor_at_least(units, min(want, units))


def _chain_steps(shape, itemsize, sizes, strides, src_comp, ops, canon,
                 cross, chunk_target):
    """Resolve scheduled ops into executable steps with per-step
    chunking, moved bytes, the staging high-water, and the intra/cross
    domain byte split."""
    nr = len(canon)
    local = [shape[d] // math.prod([sizes[m] for m in src_comp[d]] or [1])
             for d in range(len(shape))]
    steps = []
    moved = staging = intra = crossb = 0
    for kind, m, i, j in ops:
        q = sizes[m]
        lelems = math.prod(local) if local else 1
        ca, nc, mstep, stg = -1, 1, 0, 0
        if kind == "a2a":
            ca, nc = _pick_step_chunking(local, itemsize, i, j, q,
                                         chunk_target)
            mstep = nr * (lelems - lelems // q) * itemsize
            local[i] *= q
            local[j] //= q
            stg = -(-(lelems * itemsize) // max(nc, 1))
        elif kind == "gather":
            # the transient is the GATHERED output (q x the input), so
            # both the chunk count and the staging watermark budget
            # against the post-gather local shape
            local[i] *= q
            ca, nc = _pick_step_chunking(local, itemsize, i, None, q,
                                         chunk_target)
            mstep = nr * lelems * (q - 1) * itemsize
            stg = -(-(lelems * q * itemsize) // max(nc, 1))
        else:                                # slice: no comm, no staging
            local[j] //= q
        moved += mstep
        staging = max(staging, stg)
        if cross.get(m, False) and kind != "slice":
            crossb += mstep
        else:
            intra += mstep
        steps.append((kind, m, q, -1 if i is None else i,
                      -1 if j is None else j, ca, nc, mstep))
    return tuple(steps), moved, staging, intra, crossb


def _try_chain(shape, itemsize, s_grid, s_own, d_grid, d_own, total,
               chunk_target, pad_shape=()):
    """A ``chain`` plan for the even general case (on ``pad_shape``, the
    even analog, when the real layouts are ceil-uneven) — None when the
    layouts don't share a mixed-radix refinement."""
    work = tuple(pad_shape) or tuple(shape)
    dig = _digitize(len(work), s_grid, s_own, d_grid, d_own)
    if dig is None:
        return None
    canon, sizes, strides, src_comp, dst_comp = dig
    if not sizes:
        return None
    for comp in (src_comp, dst_comp):
        for d in range(len(work)):
            if work[d] % math.prod([sizes[m] for m in comp[d]] or [1]):
                return None
    cross = {m: _digit_cross_domain(canon, sizes[m], strides[m])
             for m in range(len(sizes))}
    ops = _schedule_chain(sizes, src_comp, dst_comp, cross)
    if not ops:
        return None
    steps, moved, staging, intra, crossb = _chain_steps(
        work, itemsize, sizes, strides, src_comp, ops, canon, cross,
        chunk_target)
    return ReshardPlan("chain", tuple(shape), itemsize, moved, total,
                       nparts=len(canon), ranks=canon,
                       nchunks=max(s[6] for s in steps),
                       steps=steps, mesh_shape=sizes, src_comp=src_comp,
                       dst_comp=dst_comp,
                       pad_shape=tuple(pad_shape)
                       if tuple(pad_shape) != tuple(shape) else (),
                       staging_bytes=staging, intra_bytes=intra,
                       cross_bytes=crossb)


def _try_pad_chain(shape, itemsize, s_cuts, s_own, d_cuts, d_own, total,
                   chunk_target):
    """Start-aligned ceil-uneven layouts whose per-dim pads agree: run
    the even chain on the padded analog between a comm-free pad and
    slice-back (ceil cuts put every pad byte on the trailing shard)."""
    pad = []
    for d, n in enumerate(shape):
        need = None
        for cuts in (s_cuts[d], d_cuts[d]):
            g = len(cuts) - 1
            if g <= 1:
                continue
            c = cuts[1] - cuts[0]
            if c <= 0 or list(cuts) != [min(k * c, n) for k in range(g + 1)]:
                return None                  # not start-aligned ceil cuts
            want = g * c
            if need is None:
                need = want
            elif need != want:
                return None                  # the sides' pads disagree
        pad.append(need if need is not None else n)
    if tuple(pad) == tuple(shape):
        return None                          # actually even: not ours
    return _try_chain(shape, itemsize, _grid_of(s_cuts), s_own,
                      _grid_of(d_cuts), d_own, total, chunk_target,
                      pad_shape=tuple(pad))


def _try_gather_put(shape, itemsize, s_grid, s_own, d_own, total,
                    chunk_target):
    """Device-set-shrinking moves (elastic re-layout): when the
    destination is replicated enough — fewer blocks than ranks, the
    signature of ``layout.sharding_for``'s divisibility rule after an
    uneven shrink — gather collectively ON the source mesh, then
    restrict to the survivors with a comm-free device_put (every
    survivor already holds the bytes)."""
    s_ranks = sorted({r for o in s_own.values() for r in o})
    d_ranks = {r for o in d_own.values() for r in o}
    if not d_ranks < set(s_ranks):
        return None
    if len(d_own) >= len(d_ranks):
        return None                  # properly sharded: device_put wins
    ndim = len(shape)
    rep_own = {tuple([0] * ndim): tuple(s_ranks)}
    plan = _try_chain(shape, itemsize, s_grid, s_own,
                      tuple([1] * ndim), rep_own, total, chunk_target)
    if plan is None:
        return None
    return dataclasses.replace(plan, strategy="gather_put")


@functools.lru_cache(maxsize=512)
def _plan_cached(shape, itemsize, src_sharding, dst_sharding,
                 chunk_target) -> ReshardPlan:
    # lru-miss body: once per distinct layout pair — the cold path the
    # plan-cache counters track
    _tm.count("reshard.plan_builds")
    plan = _build_plan(shape, itemsize, src_sharding, dst_sharding,
                       chunk_target)
    if _tm.enabled():
        _tm.event("reshard", "plan", strategy=plan.strategy,
                  shape=list(shape), moved_bytes=plan.moved_bytes,
                  total_bytes=plan.total_bytes, nparts=plan.nparts,
                  nchunks=plan.nchunks, reason=plan.reason)
    return plan


def _build_plan(shape, itemsize, src, dst, chunk_target) -> ReshardPlan:
    total = math.prod(shape) * itemsize if shape else itemsize

    def fallback(reason, moved=None):
        return ReshardPlan("device_put", shape, itemsize,
                           total if moved is None else moved, total,
                           reason=reason)

    if src == dst:
        return ReshardPlan("noop", shape, itemsize, 0, total)
    try:
        s_cuts, s_own = layout_of_sharding(src, shape)
        d_cuts, d_own = layout_of_sharding(dst, shape)
        moved = _moved_elems(shape, s_cuts, s_own, d_cuts, d_own) * itemsize
    except Exception as e:                           # introspection failed
        return fallback(f"opaque layouts ({type(e).__name__})")
    s_ranks_all = {r for own in s_own.values() for r in own}
    d_ranks_all = {r for own in d_own.values() for r in own}
    s_grid, d_grid = _grid_of(s_cuts), _grid_of(d_cuts)
    # uniform start-0/end-n cuts are automatically divisible
    even = all(_uniform(c) for c in s_cuts) and \
        all(_uniform(c) for c in d_cuts)
    if s_ranks_all != d_ranks_all:
        if even and d_ranks_all < s_ranks_all:
            gp = _try_gather_put(shape, itemsize, s_grid, s_own, d_own,
                                 total, chunk_target)
            if gp is not None:
                return gp
        return fallback("device sets differ", moved)
    if not even:
        pc = _try_pad_chain(shape, itemsize, s_cuts, s_own, d_cuts, d_own,
                            total, chunk_target)
        if pc is not None:
            return pc
        if any(not _uniform(c) for c in s_cuts):
            return fallback("uneven source shards", moved)
        return fallback("uneven destination shards", moved)
    s_sh = [d for d, g in enumerate(s_grid) if g > 1]
    d_sh = [d for d, g in enumerate(d_grid) if g > 1]

    why = None
    if len(s_sh) > 1 or len(d_sh) > 1:
        why = "multi-dim chunk grid"
    elif s_sh and d_sh:
        i, j = s_sh[0], d_sh[0]
        p = s_grid[i]
        if i == j or d_grid[j] != p:
            why = "incompatible repartition widths"
        else:
            src_order = _singleton_rank_order(s_own, s_grid, i)
            dst_order = _singleton_rank_order(d_own, d_grid, j)
            if src_order is None or dst_order is None or \
                    src_order != dst_order:
                why = "replicated blocks or rank order differs"
            else:
                ca, nc = _pick_chunking(shape, itemsize, i, j, p,
                                        "all_to_all", chunk_target)
                return ReshardPlan("all_to_all", shape, itemsize, moved,
                                   total, src_dim=i, dst_dim=j, nparts=p,
                                   ranks=src_order, chunk_axis=ca,
                                   nchunks=nc)
    elif s_sh:
        i = s_sh[0]
        p = s_grid[i]
        src_order = _singleton_rank_order(s_own, s_grid, i)
        if src_order is None:
            why = "replicated source blocks"
        else:
            ca, nc = _pick_chunking(shape, itemsize, i, None, p,
                                    "all_gather", chunk_target)
            return ReshardPlan("all_gather", shape, itemsize, moved, total,
                               src_dim=i, dst_dim=None, nparts=p,
                               ranks=src_order, chunk_axis=ca, nchunks=nc)
    elif d_sh:
        j = d_sh[0]
        p = d_grid[j]
        dst_order = _singleton_rank_order(d_own, d_grid, j)
        if dst_order is None:
            why = "replicated destination blocks"
        else:
            # every dst device must already hold the (replicated) source
            src_everywhere = all(set(dst_order) <= set(own)
                                 for own in s_own.values())
            if not src_everywhere:
                why = "source not replicated on dst devices"
            else:
                return ReshardPlan("local_slice", shape, itemsize, 0,
                                   total, src_dim=None, dst_dim=j,
                                   nparts=p, ranks=dst_order)
    elif moved == 0:
        # same placement under a different sharding object: device_put is
        # a zero-copy relabel
        return fallback("placement-equal", moved=0)
    else:
        why = "no sharded dims on either side"
    # the single-collective fast paths passed: the general chain covers
    # multi-axis repartitions, mesh-axis transposes and partial
    # replication over a common mixed-radix refinement
    ch = _try_chain(shape, itemsize, s_grid, s_own, d_grid, d_own, total,
                    chunk_target)
    if ch is not None:
        return ch
    return fallback(why, moved)


def plan_reshard(x, dst_sharding, *, src_sharding=None,
                 itemsize=None) -> ReshardPlan:
    """The transfer plan for moving ``x`` (a jax.Array, or a shape tuple
    with ``src_sharding``/``itemsize`` given) onto ``dst_sharding``.
    Cached per layout pair; pure metadata — nothing moves."""
    if hasattr(x, "sharding"):
        shape = tuple(int(s) for s in x.shape)
        src_sharding = x.sharding
        try:
            itemsize = int(np.dtype(x.dtype).itemsize)
        except TypeError:
            # extended dtypes (PRNG keys) have no numpy itemsize; the
            # collective lowerings can't slice them anyway — plan the
            # counted device_put directly (bytes in element units)
            n = math.prod(shape) if shape else 1
            return ReshardPlan("device_put", shape, 1, n, n,
                               reason="extended dtype")
    else:
        shape = tuple(int(s) for s in x)
        if src_sharding is None or itemsize is None:
            raise ValueError("shape-form plan_reshard needs src_sharding "
                             "and itemsize")
    _tm.count("reshard.plan_requests")
    return _plan_cached(shape, int(itemsize), src_sharding, dst_sharding,
                        _chunk_target_bytes())


def plan_stats() -> dict:
    """Plan-cache statistics (hits/misses/size) — the `_resharder`-style
    lru the tentpole caches plans in."""
    ci = _plan_cached.cache_info()
    return {"hits": ci.hits, "misses": ci.misses, "size": ci.currsize}


# ---------------------------------------------------------------------------
# compiled lowering
# ---------------------------------------------------------------------------


def _spec_for(dim, ndim, axis):
    if dim is None:
        return P()
    return P(*[axis if d == dim else None for d in range(ndim)])


def _a2a_chunked(x, axis, split_dim, concat_dim, p, chunk_axis, nchunks):
    """Tiled all_to_all, chunked so one staging piece stays bounded.
    Chunking along the split dim pre-slices so each chunk's tiled
    exchange lands every rank the k-th contiguous slice of ITS dst block
    — plain chunking along the split dim would interleave ranks."""
    if nchunks <= 1:
        return pall_to_all(x, axis, split_dim=split_dim,
                           concat_dim=concat_dim)
    if chunk_axis == split_dim:
        jp = x.shape[split_dim] // p
        step = jp // nchunks
        outs = []
        for k in range(nchunks):
            piece = jnp.concatenate(
                [lax.slice_in_dim(x, r * jp + k * step,
                                  r * jp + (k + 1) * step,
                                  axis=split_dim)
                 for r in range(p)], axis=split_dim)
            outs.append(pall_to_all(piece, axis, split_dim=split_dim,
                                    concat_dim=concat_dim))
        return jnp.concatenate(outs, axis=split_dim)
    step = x.shape[chunk_axis] // nchunks
    outs = [pall_to_all(
        lax.slice_in_dim(x, k * step, (k + 1) * step, axis=chunk_axis),
        axis, split_dim=split_dim, concat_dim=concat_dim)
        for k in range(nchunks)]
    return jnp.concatenate(outs, axis=chunk_axis)


def _gather_chunked(x, axis, dim, chunk_axis, nchunks):
    """Tiled all_gather along ``dim``, chunked along ``chunk_axis``."""
    if nchunks <= 1:
        return pgather(x, axis, tiled=True, dim=dim)
    step = x.shape[chunk_axis] // nchunks
    outs = [pgather(
        lax.slice_in_dim(x, k * step, (k + 1) * step, axis=chunk_axis),
        axis, tiled=True, dim=dim)
        for k in range(nchunks)]
    return jnp.concatenate(outs, axis=chunk_axis)


@functools.lru_cache(maxsize=512)
def _collective_jit(mesh, strategy, ndim, src_dim, dst_dim, p,
                    chunk_axis, nchunks, rdma=None):
    """ONE compiled shard_map program for a planned single-axis
    repartition, chunked so each collective stages at most 1/nchunks of
    the local shard.  With ``rdma`` set (``"compiled"``/``"interpret"``,
    from :func:`ops.pallas_collectives.rdma_mode`) the inner exchange is
    the Pallas RDMA ring kernel instead of the XLA collective: chunk
    DMAs land directly at their output offsets (no XLA-level staging
    loop needed — the kernel double-buffers internally), overlapping
    wire time with the slice/concat work."""
    _tm.count("jit.builds", fn="reshard_collective")
    # cold path: lru-miss body, once per distinct planned program
    _tm.event("jit", "build", fn="reshard_collective",  # dalint: disable=DAL003
              strategy=strategy, nchunks=nchunks, rdma=str(rdma))
    axis = mesh.axis_names[0]
    in_spec = _spec_for(src_dim, ndim, axis)
    out_spec = _spec_for(dst_dim, ndim, axis) if strategy != "all_gather" \
        else P(*([None] * ndim))

    def kernel(x):
        if rdma and strategy in ("all_to_all", "all_gather"):
            from ..ops import pallas_collectives as _pc
            interp = rdma == "interpret"
            if strategy == "all_to_all":
                return _pc.ring_all_to_all(x, axis, split_dim=dst_dim,
                                           concat_dim=src_dim,
                                           interpret=interp)
            return _pc.ring_all_gather(x, axis, dim=src_dim,
                                       interpret=interp)
        if strategy == "all_to_all":
            return _a2a_chunked(x, axis, dst_dim, src_dim, p, chunk_axis,
                                nchunks)
        if strategy == "all_gather":
            return _gather_chunked(x, axis, src_dim, chunk_axis, nchunks)
        # local_slice: replicated -> sharded, zero communication
        r = lax.axis_index(axis)
        blk = x.shape[dst_dim] // p
        return lax.dynamic_slice_in_dim(x, r * blk, blk, axis=dst_dim)

    # pallas_call has no shard_map replication rule: the RDMA variant
    # must opt out of the check (the XLA variant keeps it)
    return jax.jit(shard_map_compat(kernel, mesh, in_spec, out_spec,
                                    check=False if rdma else None))


def _run_collective(x, dst_sharding, plan: ReshardPlan, rdma=None):
    mesh = L.mesh_for(list(plan.ranks), (plan.nparts,))
    fn = _collective_jit(mesh, plan.strategy, len(plan.shape),
                         plan.src_dim, plan.dst_dim, plan.nparts,
                         plan.chunk_axis, plan.nchunks, rdma)
    y = fn(x)
    if y.sharding != dst_sharding:
        # equivalent placement under the caller's sharding object —
        # zero-copy relabel
        y = jax.device_put(y, dst_sharding)
    return y


def _comp_spec(comp, ndim):
    """PartitionSpec from per-dim mesh-axis composites (indices into the
    refined mesh's ``d{i}`` axis names, major→minor)."""
    entries = []
    for d in range(ndim):
        c = comp[d] if d < len(comp) else ()
        if not c:
            entries.append(None)
        elif len(c) == 1:
            entries.append(f"d{c[0]}")
        else:
            entries.append(tuple(f"d{m}" for m in c))
    return P(*entries)


@functools.lru_cache(maxsize=512)
def _chain_jit(mesh, ndim, src_comp, dst_comp, steps, rdma=None):
    """ONE compiled shard_map program running a planned per-axis
    collective chain over the refined device mesh — the general lowering
    (arXiv 2112.01075's per-axis decomposition).  With ``rdma`` set the
    a2a/gather steps ride the Pallas RDMA ring kernels with
    mesh-coordinate device ids (``mesh_axes``) when the mesh is
    multi-axis; interpret mode demotes multi-axis arming to the lax
    fallback inside the kernel, so CPU runs stay correct."""
    _tm.count("jit.builds", fn="reshard_chain")
    # cold path: lru-miss body, once per distinct planned chain
    _tm.event("jit", "build", fn="reshard_chain",  # dalint: disable=DAL003
              steps=len(steps), rdma=str(rdma))
    in_spec = _comp_spec(src_comp, ndim)
    out_spec = _comp_spec(dst_comp, ndim)
    names = mesh.axis_names
    mesh_axes = tuple(names) if len(names) > 1 else None

    def kernel(x):
        from ..ops import pallas_collectives as _pc
        for kind, m, q, i, j, ca, nc in (s[:7] for s in steps):
            name = f"d{m}"
            if kind == "a2a":
                if rdma:
                    x = _pc.ring_all_to_all(
                        x, name, split_dim=j, concat_dim=i,
                        interpret=rdma == "interpret",
                        mesh_axes=mesh_axes)
                else:
                    x = _a2a_chunked(x, name, j, i, q,
                                     ca if ca >= 0 else None, nc)
            elif kind == "gather":
                if rdma:
                    x = _pc.ring_all_gather(
                        x, name, dim=i, interpret=rdma == "interpret",
                        mesh_axes=mesh_axes)
                else:
                    x = _gather_chunked(x, name, i,
                                        ca if ca >= 0 else None, nc)
            else:                            # slice: local, no comm
                r = lax.axis_index(name)
                blk = x.shape[j] // q
                x = lax.dynamic_slice_in_dim(x, r * blk, blk, axis=j)
        return x

    # composite specs + optional pallas_call inside: opt out of the
    # replication check (multi-axis inference has no rule for either)
    return jax.jit(shard_map_compat(kernel, mesh, in_spec, out_spec,
                                    check=False))


@functools.lru_cache(maxsize=256)
def _pad_jit(mesh, src_comp, shape, pad_shape):
    """Compiled ceil-pad: grow each uneven dim to its even analog under
    the same placement — ceil cuts put every pad byte on the trailing
    shard, so nothing crosses a device."""
    _tm.count("jit.builds", fn="reshard_pad")
    widths = tuple((0, p - s) for s, p in zip(shape, pad_shape))
    out = NamedSharding(mesh, _comp_spec(src_comp, len(shape)))
    return jax.jit(lambda x: jnp.pad(x, widths), out_shardings=out)


@functools.lru_cache(maxsize=256)
def _slice_back_jit(dst_sharding, shape):
    """Compiled slice from the even analog back to the logical extent,
    placed under the caller's (ceil-uneven) destination sharding."""
    _tm.count("jit.builds", fn="reshard_slice")
    idx = tuple(slice(0, s) for s in shape)
    return jax.jit(lambda y: y[idx], out_shardings=dst_sharding)


def _run_chain(x, dst_sharding, plan: ReshardPlan, rdma=None):
    mesh = L.mesh_for(list(plan.ranks), plan.mesh_shape)
    ndim = len(plan.shape)
    if plan.pad_shape:
        x = _pad_jit(mesh, plan.src_comp, plan.shape, plan.pad_shape)(x)
    fn = _chain_jit(mesh, ndim, plan.src_comp, plan.dst_comp, plan.steps,
                    rdma)
    y = fn(x)
    if plan.pad_shape:
        return _slice_back_jit(dst_sharding, plan.shape)(y)
    if plan.strategy == "gather_put":
        # restrict the now-replicated buffer to the survivor subset —
        # comm-free: every destination device already holds the bytes
        return _device_put_path(y, dst_sharding)
    if y.sharding != dst_sharding:
        y = jax.device_put(y, dst_sharding)
    return y


@functools.lru_cache(maxsize=None)
def _resharder(sharding):
    """Compiled identity program placing its input under ``sharding`` —
    the fallback mover (and the multi-controller-legal one: XLA inserts
    the DCN/ICI collective; eager device_put cannot cross hosts)."""
    _tm.count("jit.builds", fn="resharder")
    # cold path: lru-miss body, once per distinct target sharding
    _tm.event("jit", "build", fn="resharder",  # dalint: disable=DAL003
              to=str(sharding))
    return jax.jit(lambda x: x, out_shardings=sharding)


def _device_put_path(x, dst_sharding):
    if getattr(x, "size", 1) == 0:
        # XLA rejects out_shardings on zero-element results; device_put
        # places them fine
        return jax.device_put(x, dst_sharding)
    if isinstance(x, jax.Array) and \
            not getattr(dst_sharding, "is_fully_addressable", True) and \
            getattr(x.sharding, "device_set", None) == \
            dst_sharding.device_set:
        # process-spanning move: eager device_put cannot cross hosts —
        # the compiled identity program can (XLA inserts the collective)
        return _resharder(dst_sharding)(x)
    return jax.device_put(x, dst_sharding)


def _fallback_reason(reason: str) -> str:
    """Canonical residue class for the ``reason=`` label on
    ``reshard.collective_fallbacks`` — why a move still falls back
    (uneven | multi_axis | device_set | dtype | shape)."""
    r = reason.lower()
    if "uneven" in r or "divisible" in r:
        return "uneven"
    if "device set" in r or "not replicated on dst" in r:
        return "device_set"
    if "dtype" in r:
        return "dtype"
    if "multi-dim" in r or "incompatible" in r or "rank order" in r \
            or "replicated" in r:
        return "multi_axis"
    return "shape"


def reshard(x, dst_sharding, *, op: str = "reshard",
            plan: ReshardPlan | None = None):
    """Move ``x`` onto ``dst_sharding`` via the planned strategy.

    The single funnel for cross-sharding data movement (DAL007): plans
    are cached per layout pair, divisible single-axis repartitions run as
    one compiled chunked-collective program, the general case runs the
    per-axis collective chain over the refined mesh, and the residue
    takes the ``device_put`` path (counted, with a canonical ``reason=``
    label).  Telemetry: a ``reshard`` span labeled with the strategy and
    the plan's ``intra_bytes``/``cross_bytes`` domain split, and comm
    bytes = the plan's *moved* bytes (what must cross a device
    boundary), not the whole array."""
    if getattr(x, "sharding", None) == dst_sharding:
        return x
    if plan is None:
        plan = plan_reshard(x, dst_sharding)
    if plan.strategy == "noop":
        return x
    if plan.collective:
        try:
            ext = jax.dtypes.issubdtype(getattr(x, "dtype", None),
                                        jax.dtypes.extended)
        except Exception:
            ext = False
        if ext:
            # extended dtypes (PRNG key arrays) have no collective
            # lowering — planned from shardings alone, gated on dtype here
            plan = dataclasses.replace(plan, strategy="device_put",
                                       reason="extended dtype")
    # RDMA dispatch decided eagerly so the compiled program is keyed on
    # it (flipping DA_TPU_RDMA re-jits) and the span says which path ran
    rdma = None
    rdma_chunks = 0
    chunks_src = ""
    autotune_key = ""
    dispatch_key = ""
    dispatch_src = ""
    if plan.steps and any(s[0] != "slice" for s in plan.steps):
        # chain steps ride the ring kernels when the platform arms them
        # (mesh-coordinate addressing on multi-axis meshes); slices-only
        # chains are local and need no dispatch decision
        from ..ops import pallas_collectives as _pc
        rdma = _pc.rdma_mode()
    elif plan.collective and plan.strategy in ("all_to_all", "all_gather"):
        from ..ops import pallas_collectives as _pc
        rdma = _pc.rdma_mode()
        dtype_str = str(getattr(x, "dtype", "float32"))
        # per-shape-class dispatch preference (advisor-written
        # "rdma_dispatch" entry); an explicit DA_TPU_RDMA env wins inside
        # resolve_dispatch, and a preference can only demote to XLA — it
        # never conjures RDMA on a platform rdma_mode rejected
        dispatch_key = _pc.dispatch_key_for(
            "reshard", plan.strategy, *plan.shape, dtype_str, plan.nparts)
        pref, dispatch_src = _pc.resolve_dispatch(dispatch_key)
        if pref == "xla":
            rdma = None
        if rdma and plan.strategy == "all_to_all":
            lshape = tuple(s // plan.nparts if d == plan.src_dim else s
                           for d, s in enumerate(plan.shape))
            # the kernel concats along the plan's src dim; clamping here
            # keeps span/bench provenance equal to the depth it runs
            rdma_chunks, chunks_src = _pc.a2a_chunks_for(
                lshape, dtype_str, plan.nparts, plan.src_dim)
            # the exact "rdma_chunks" registry key this depth resolved
            # under — the advisor addresses its writes by this label
            autotune_key = _pc.a2a_chunks_key(lshape, dtype_str,
                                              plan.nparts)
    with _tm.span("reshard", op=op, strategy=plan.strategy,
                  dispatch="rdma" if rdma else "xla",
                  rdma_chunks=rdma_chunks, rdma_chunks_source=chunks_src,
                  autotune_key=autotune_key, dispatch_key=dispatch_key,
                  dispatch_source=dispatch_src,
                  shape=list(plan.shape),
                  dtype=str(getattr(x, "dtype", "float32")),
                  src_dim=plan.src_dim, dst_dim=plan.dst_dim,
                  nparts=plan.nparts, nsteps=len(plan.steps),
                  # hierarchical-tier provenance: how many of the moved
                  # bytes stay on fast intra-domain links vs cross the DCN
                  intra_bytes=plan.intra_bytes,
                  cross_bytes=plan.cross_bytes,
                  # analytic cost stamp (telemetry.perf): every byte
                  # read + rewritten through HBM, the plan's MOVED bytes
                  # crossing a device boundary over ICI, zero flops —
                  # the doctor classifies each occurrence against the
                  # platform roofline from these
                  **_perf.reshard_cost(plan.total_bytes,
                                       plan.moved_bytes)):
        if plan.collective:
            # chaos site: an armed fault plan can abort the planned
            # collective here — mid-reshard, before any chunk moves, so
            # the source buffer is still intact for the retry
            _fl.check("reshard.chunk", strategy=plan.strategy, op=op)
            try:
                # staging high-water: one chunk piece of the local shard
                # is what the chunked lowering stages per device.  This
                # is PLAN-DERIVED (XLA's internal staging buffers are not
                # jax-observable) — it audits the chunking the planner
                # actually chose (nchunks) against the
                # DA_TPU_RESHARD_CHUNK_MB budget, catching selection
                # regressions, not compiled-program memory use
                local = plan.total_bytes // max(plan.nparts, 1)
                piece = -(-local // max(plan.nchunks, 1))
                if plan.staging_bytes:
                    # chain: the planner pre-computed the worst step's
                    # staging piece over the evolving local shape
                    piece = plan.staging_bytes
                if rdma and plan.strategy == "all_to_all":
                    # the RDMA ring lands chunk DMAs at their final
                    # output offsets; what stages per device is one
                    # in-flight chunk window, not an XLA concat buffer
                    piece = min(piece,
                                -(-local // max(rdma_chunks, 1)))
                with _tm.memory.staging(f"reshard.{plan.strategy}", piece):
                    if plan.steps:
                        out = _run_chain(x, dst_sharding, plan, rdma)
                    else:
                        out = _run_collective(x, dst_sharding, plan, rdma)
                if _tm.enabled():
                    _tm.record_comm("reshard", plan.moved_bytes, op=op,
                                    strategy=plan.strategy,
                                    dispatch="rdma" if rdma else "xla",
                                    shape=list(plan.shape))
                return out
            except Exception as e:
                # the compiled path must never cost correctness; fall
                # through to device_put, loudly once per signature
                _tm.count("reshard.collective_fallbacks", reason="runtime")
                from ..utils.debug import warn_once
                warn_once(
                    f"reshard:{plan.strategy}:{type(e).__name__}",
                    f"reshard: compiled {plan.strategy} lowering failed "
                    f"({type(e).__name__}: {e}); falling back to "
                    f"device_put")
        if plan.strategy == "device_put" and plan.moved_bytes:
            # the residue the advisor targets: why does this move still
            # fall back?  (placement-equal relabels move nothing and are
            # not a residue)
            _tm.count("reshard.collective_fallbacks",
                      reason=_fallback_reason(plan.reason))
        if _tm.enabled():
            _tm.record_comm("reshard", plan.moved_bytes, op=op,
                            strategy="device_put", shape=list(plan.shape))
        return _device_put_path(x, dst_sharding)
