"""Process backend for the dynamic SPMD mode.

``spmd(f, ..., backend="process")`` runs each rank in a real forked OS
process instead of a thread — the moral equivalent of the reference's
``addprocs`` worker processes (/root/reference/test/runtests.jl:10-13):
pure-Python compute inside ranks runs GIL-free in parallel, and rank
isolation is process isolation.

Design (mirrors spmd_mode's thread semantics exactly):

- **fork per run**: children inherit ``f``, its closure, and the context
  snapshot without pickling (the reference ships closures to workers via
  Serialization; fork is the single-host equivalent).  Only *returned*
  values, *messages*, and *context storage write-back* cross process
  boundaries and must be picklable.
- **mailboxes** are per-rank ``multiprocessing.Queue`` inboxes plus a
  rank-local stash, giving the same tagged matching with out-of-order
  buffering as the thread backend's ``_Mailbox`` (reference
  spmd.jl:126-143).  Messages sent but not received in a run persist as
  parent-held per-rank leftover lists on the SPMDContext (receivable in
  the next run, like the thread mailboxes) — parked in parent memory,
  not in queue buffers, because a pipe is bounded and a parked message
  would wedge the sender's feeder thread.
- **failure propagation**: a shared ``multiprocessing.Event``; blocked
  receivers poll it and abort, like the thread backend's ``ctx._failed``.
- **context storage**: each child inherits ``ctx.store`` at fork and
  sends its rank's dict back with its result; the parent merges it into
  the explicit context so ``context_local_storage`` persists across runs
  (storage values must be picklable in this backend).

Host-side compute only: do not touch jax device state inside ranks — the
forked children share the parent's runtime handles.  Device work belongs
to the compiled half (``parallel.collectives``).  Requires the ``fork``
start method (POSIX).
"""

from __future__ import annotations

import os
import time
import traceback
from typing import Any, Callable

__all__ = ["run_spmd_process"]


class _QueueMailbox:
    """Child-side view of one rank's inbox: the shared mp.Queue plus the
    rank-local out-of-order stash.  Only the owning rank calls take()."""

    def __init__(self, queue, stash: list):
        self._q = queue
        self._stash = stash

    def put(self, msg: tuple):
        self._q.put(msg)

    def take(self, match: Callable[[tuple], bool], failed, timeout: float,
             tag=None):
        import queue as queue_mod
        from .spmd_mode import _PEER_ABORT, _receive_timeout, _scan_stash
        deadline = time.monotonic() + timeout
        while True:
            m = _scan_stash(self._stash, match)
            if m is not None:
                return m
            if failed.is_set():
                raise RuntimeError(_PEER_ABORT)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise _receive_timeout(timeout, self._stash, tag)
            try:
                self._stash.append(self._q.get(timeout=min(remaining, 0.1)))
            except queue_mod.Empty:
                pass


class _RunContext:
    """Per-child stand-in for SPMDContext: same attribute surface as the
    pieces sendto/recvfrom/barrier/... touch (mailbox, pids, store,
    _barrier_gen, _failed)."""

    def __init__(self, ctx_id, pids, queues, store, failed, stash):
        self.id = ctx_id
        self.pids = list(pids)
        self.store = store
        self._queues = queues
        self._stash: list[tuple] = stash
        self._barrier_gen = {p: 0 for p in self.pids}
        self._failed = failed

    def mailbox(self, pid: int) -> _QueueMailbox:
        try:
            return _QueueMailbox(self._queues[pid], self._stash)
        except KeyError:
            raise ValueError(f"rank {pid} is not in context {self.id} "
                             f"(pids={self.pids})") from None


def run_spmd_process(f: Callable, args: tuple, ctx, timeout: float):
    """Execute one spmd() run on the process backend.

    ``ctx`` is the caller's SPMDContext (thread-backend object); its pids
    and storage snapshot are used, and each rank's storage dict is merged
    back after a successful run.  Returns ``{rank: result}`` or raises
    like the thread driver.

    Telemetry note: counters mutated INSIDE forked children live in the
    child's copy-on-write memory and die with it — rank-side sends on
    this backend are therefore accounted at the parent level (one event
    per run plus the result/leftover payload bytes shipped back), not
    per-message.
    """
    import multiprocessing as mp

    from .. import telemetry as _tm

    try:
        mpctx = mp.get_context("fork")
    except ValueError:  # pragma: no cover — non-POSIX
        raise RuntimeError(
            "backend='process' needs the fork start method (POSIX only); "
            "use the default thread backend") from None

    # Cross-run message persistence (thread-backend parity: a message sent
    # in one run is receivable in the next on the same explicit context)
    # lives in PARENT memory as per-rank leftover lists, not in queue
    # buffers: a pipe is bounded, so parking messages there deadlocks the
    # sender's feeder thread when nobody drains.  Children inherit their
    # leftover stash via fork; unconsumed messages ship back with the
    # result; the parent drains reported ranks' queues for late sends.
    if ctx._proc_state is None:
        ctx._proc_state = {"leftover": {p: [] for p in ctx.pids}}
    leftover = ctx._proc_state["leftover"]
    queues = {p: mpctx.Queue() for p in ctx.pids}
    result_q = mpctx.Queue()
    failed = mpctx.Event()

    from .. import core
    from ..resilience import faults as _fl
    from . import spmd_mode

    # fault decisions happen HERE, parent-side, so the plan's per-spec
    # invocation counters persist across retries (a counter bumped inside
    # a forked child dies with it); only the ACTION runs in the child —
    # a raise ships home as a rank failure, an "exit" dies unreported
    dooms = {p: _fl.decide("spmd.rank", rank=p, backend="process")
             for p in ctx.pids} if _fl.active() else {}

    def child(rank: int):
        rctx = _RunContext(ctx.id, ctx.pids, queues, ctx.store, failed,
                           list(leftover[rank]))
        core._rank_tls.rank = rank
        spmd_mode._tls.ctxt = rctx
        os.environ["DA_TPU_FAULT_CHILD"] = "1"   # arms the "exit" action
        # graceful-shutdown signal: a SIGTERM (forwarded by the parent, or
        # delivered directly by a process-group kill) raises INSIDE the
        # rank's compute, so the child drains its inbox and reports a
        # clear "received SIGTERM" failure instead of dying mid-collective
        # and leaving its peers to a cryptic receive timeout.  After fork
        # the forked thread IS the child's main thread, so installing the
        # handler here is legal.
        import signal

        def _on_sigterm(signum, frame):
            raise RuntimeError(
                f"SPMD worker rank {rank} received SIGTERM: draining and "
                "reporting before exit")

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except (ValueError, OSError):  # pragma: no cover — exotic platform
            pass
        try:
            # the rank's step interval, measured in-child against the
            # fork-inherited telemetry origin (_tc._T0) and shipped home
            # with the result: counters bumped in a forked child die with
            # it, so the parent records the span — both backends produce
            # rank-labeled spmd.step spans this way
            from ..telemetry import core as _tc
            step_t0 = time.monotonic()
            span_rec = {"start": step_t0 - _tc._T0, "dur": 0.0,
                        "ok": True}
            try:
                _fl.act(dooms.get(rank),
                        {"rank": rank, "backend": "process"})
                r = f(*args)
                span_rec["dur"] = time.monotonic() - step_t0
                status = (rank, "ok", r, rctx.store.get(rank, {}))
            except BaseException as e:  # noqa: BLE001 — shipped to parent
                span_rec["dur"] = time.monotonic() - step_t0
                span_rec["ok"] = False
                failed.set()
                # mark peer-abort secondaries structurally so the parent
                # needn't string-match user tracebacks
                secondary = (isinstance(e, RuntimeError)
                             and str(e) == spmd_mode._PEER_ABORT)
                status = (rank, "err", (secondary,
                          f"{type(e).__name__}: {e}\n"
                          f"{''.join(traceback.format_exception(e))}"),
                          None)
            # drain the inbox so unconsumed messages ride home with the
            # result (and so peers' feeder threads blocked on this pipe
            # get unblocked); matching ignores order, so re-stashing
            # cannot change which message a tagged receive resolves to
            import queue as queue_mod
            try:
                while True:
                    rctx._stash.append(queues[rank].get_nowait())
            except queue_mod.Empty:
                pass
            result_q.put(status + (rctx._stash, span_rec))
        finally:
            # mp.Queue.put hands off to a feeder thread; flush every queue
            # this child wrote (messages AND result) before the hard exit,
            # or buffered items silently vanish with the process
            for q in list(queues.values()) + [result_q]:
                q.close()
                q.join_thread()
            os._exit(0)  # skip atexit/teardown of inherited runtime state

    procs = [mpctx.Process(target=child, args=(p,), name=f"spmd-{p}",
                           daemon=True) for p in ctx.pids]
    import warnings
    with warnings.catch_warnings():
        # CPython warns that forking a multithreaded (jax) process may
        # deadlock; the module docstring documents the host-compute-only
        # contract that makes this safe, so don't re-warn per run
        warnings.filterwarnings(
            "ignore", message=".*fork.*", category=DeprecationWarning)
        warnings.filterwarnings(
            "ignore", message=".*fork.*", category=RuntimeWarning)
        for p in procs:
            p.start()

    # forward SIGTERM to the children for the run's duration so a
    # controller shutdown (systemd stop, k8s preemption) drains workers
    # gracefully: each child's handler raises and reports home instead of
    # the whole run wedging into a receive timeout.  signal.signal is
    # main-thread-only; from a dispatcher thread we skip installation — a
    # process-group SIGTERM still reaches the children directly, where
    # their own handlers take over.
    import signal
    import threading as _threading
    _prev_sigterm = None
    _sigterm_installed = False

    def _forward_sigterm(signum, frame):
        for pr in procs:
            if pr.is_alive() and pr.pid:
                try:
                    os.kill(pr.pid, signal.SIGTERM)
                except ProcessLookupError:  # pragma: no cover — just exited
                    pass
        if callable(_prev_sigterm):
            _prev_sigterm(signum, frame)

    if _threading.current_thread() is _threading.main_thread():
        try:
            _prev_sigterm = signal.signal(signal.SIGTERM, _forward_sigterm)
            _sigterm_installed = True
        except (ValueError, OSError):  # pragma: no cover — exotic platform
            pass

    import queue as queue_mod
    results: dict[int, Any] = {}
    stores: dict[int, dict] = {}
    errors: dict[int, str] = {}

    def drain(ranks, bound_s: float = 5.0):
        # pull late-sent messages out of exited ranks' inboxes into the
        # parent-held leftovers — this is also what unblocks a laggard
        # sender's feeder thread stuck on a full pipe to a dead peer.
        # Bounded via a helper thread: get_nowait's recv can block
        # indefinitely on a PARTIAL frame (a sender killed mid-write), and
        # the parent must never wedge on per-run garbage.
        ranks = [p for p in ranks if not queues[p].empty()]
        if not ranks:       # nothing buffered: skip the helper-thread spin
            return

        def _pull():
            for p in ranks:
                try:
                    while True:
                        leftover[p].append(queues[p].get_nowait())
                except queue_mod.Empty:
                    pass

        import threading
        t = threading.Thread(target=_pull, daemon=True)
        t.start()
        t.join(bound_s)

    deadline = time.monotonic() + timeout
    try:
        while len(results) + len(errors) < len(ctx.pids):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                failed.set()
                raise TimeoutError(
                    f"spmd process run did not finish in {timeout}s "
                    f"(completed ranks: {sorted(results)})")
            try:
                (rank, status, payload, store, stash,
                 span_rec) = result_q.get(timeout=min(remaining, 0.2))
            except queue_mod.Empty:
                drain(set(results) | set(errors))
                dead = [p for p, pr in zip(ctx.pids, procs)
                        if not pr.is_alive() and p not in results
                        and p not in errors]
                if dead and result_q.empty():
                    failed.set()
                    raise RuntimeError(
                        f"spmd process rank(s) {dead} died without "
                        "reporting (non-picklable result/storage, or the "
                        "child crashed)")
                continue
            leftover[rank] = list(stash)
            if _tm.enabled() and isinstance(span_rec, dict):
                # the child-measured rank step, recorded parent-side:
                # rank-labeled like the thread backend's spmd.step, so
                # per-rank timelines separate into their own Perfetto
                # tracks on this backend too
                _tm.record_external_span(
                    "spmd.step", span_rec.get("start", 0.0),
                    span_rec.get("dur", 0.0),
                    labels={"rank": rank, "backend": "process"},
                    tname=f"spmd-{rank}",
                    error=not span_rec.get("ok", True))
            if status == "ok":
                results[rank] = payload
                stores[rank] = store
            else:
                errors[rank] = payload
    finally:
        if _sigterm_installed:
            try:
                # a None previous disposition (handler installed by
                # non-Python code) cannot be re-installed; fall back to
                # SIG_DFL rather than abort the finally's child cleanup
                signal.signal(signal.SIGTERM,
                              _prev_sigterm if _prev_sigterm is not None
                              else signal.SIG_DFL)
            except (ValueError, OSError, TypeError):  # pragma: no cover
                pass
        # drain BEFORE joining: a child whose feeder is mid-write into a
        # dead peer's full pipe can only finish (and exit) once the parent
        # consumes that pipe; terminating it instead would truncate the
        # frame and poison the queue
        drain(ctx.pids)
        for pr in procs:
            pr.join(5)
        drain(ctx.pids)          # anything flushed while joining
        for pr in procs:
            if pr.is_alive():  # pragma: no cover — stuck child
                pr.terminate()
        for q in list(queues.values()) + [result_q]:
            q.close()
            q.cancel_join_thread()
        # successful ranks keep their storage writes even when a peer
        # failed (thread backend mutates ctx.store live; mirror that)
        for rank, st in stores.items():
            ctx.store[rank] = st

    if _tm.enabled():
        _tm.event("spmd", "process_run", ranks=len(ctx.pids),
                  ok=len(results), failed=len(errors),
                  once_key=f"spmd:process_run:{len(ctx.pids)}")
        _tm.record_comm("spmd_process_result",
                        sum(_tm.nbytes_of(v) for v in results.values()),
                        op="run_spmd_process", journal=False)

    if errors:
        # prefer root-cause failures over structurally-marked peer aborts
        primary = [(r, t) for r, (sec, t) in sorted(errors.items())
                   if not sec]
        rank, err = (primary if primary
                     else [(r, t) for r, (_, t) in sorted(errors.items())])[0]
        raise RuntimeError(
            f"spmd task on rank {rank} failed ({len(errors)} total "
            f"failures); child traceback:\n{err}")
    return results
