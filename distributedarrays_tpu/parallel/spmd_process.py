"""Process backend for the dynamic SPMD mode.

``spmd(f, ..., backend="process")`` runs each rank in a real forked OS
process instead of a thread — the moral equivalent of the reference's
``addprocs`` worker processes (/root/reference/test/runtests.jl:10-13):
pure-Python compute inside ranks runs GIL-free in parallel, and rank
isolation is process isolation.

Design (mirrors spmd_mode's thread semantics exactly):

- **fork per run**: children inherit ``f``, its closure, and the context
  snapshot without pickling (the reference ships closures to workers via
  Serialization; fork is the single-host equivalent).  Only *returned*
  values, *messages*, and *context storage write-back* cross process
  boundaries and must be picklable.
- **mailboxes** are per-rank ``multiprocessing.Queue`` inboxes plus a
  rank-local stash, giving the same tagged matching with out-of-order
  buffering as the thread backend's ``_Mailbox`` (reference
  spmd.jl:126-143).  The inboxes live on the SPMDContext and persist
  across runs (a message sent but not received in one run is receivable
  in the next, like the thread mailboxes); unconsumed stashed messages
  are re-queued when a rank exits.
- **failure propagation**: a shared ``multiprocessing.Event``; blocked
  receivers poll it and abort, like the thread backend's ``ctx._failed``.
- **context storage**: each child inherits ``ctx.store`` at fork and
  sends its rank's dict back with its result; the parent merges it into
  the explicit context so ``context_local_storage`` persists across runs
  (storage values must be picklable in this backend).

Host-side compute only: do not touch jax device state inside ranks — the
forked children share the parent's runtime handles.  Device work belongs
to the compiled half (``parallel.collectives``).  Requires the ``fork``
start method (POSIX).
"""

from __future__ import annotations

import os
import time
import traceback
from typing import Any, Callable

__all__ = ["run_spmd_process"]


class _QueueMailbox:
    """Child-side view of one rank's inbox: the shared mp.Queue plus the
    rank-local out-of-order stash.  Only the owning rank calls take()."""

    def __init__(self, queue, stash: list):
        self._q = queue
        self._stash = stash

    def put(self, msg: tuple):
        self._q.put(msg)

    def take(self, match: Callable[[tuple], bool], failed, timeout: float):
        import queue as queue_mod
        from .spmd_mode import _PEER_ABORT, _receive_timeout, _scan_stash
        deadline = time.monotonic() + timeout
        while True:
            m = _scan_stash(self._stash, match)
            if m is not None:
                return m
            if failed.is_set():
                raise RuntimeError(_PEER_ABORT)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise _receive_timeout(timeout, self._stash)
            try:
                self._stash.append(self._q.get(timeout=min(remaining, 0.1)))
            except queue_mod.Empty:
                pass


class _RunContext:
    """Per-child stand-in for SPMDContext: same attribute surface as the
    pieces sendto/recvfrom/barrier/... touch (mailbox, pids, store,
    _barrier_gen, _failed)."""

    def __init__(self, ctx_id, pids, queues, store, failed):
        self.id = ctx_id
        self.pids = list(pids)
        self.store = store
        self._queues = queues
        self._stash: list[tuple] = []
        self._barrier_gen = {p: 0 for p in self.pids}
        self._failed = failed

    def mailbox(self, pid: int) -> _QueueMailbox:
        try:
            return _QueueMailbox(self._queues[pid], self._stash)
        except KeyError:
            raise ValueError(f"rank {pid} is not in context {self.id} "
                             f"(pids={self.pids})") from None


def run_spmd_process(f: Callable, args: tuple, ctx, timeout: float):
    """Execute one spmd() run on the process backend.

    ``ctx`` is the caller's SPMDContext (thread-backend object); its pids
    and storage snapshot are used, and each rank's storage dict is merged
    back after a successful run.  Returns ``{rank: result}`` or raises
    like the thread driver.
    """
    import multiprocessing as mp

    try:
        mpctx = mp.get_context("fork")
    except ValueError:  # pragma: no cover — non-POSIX
        raise RuntimeError(
            "backend='process' needs the fork start method (POSIX only); "
            "use the default thread backend") from None

    # per-rank inboxes persist on the context across runs (thread-backend
    # parity: a message sent in one run is receivable in the next on the
    # same explicit context); _reset_comm/close releases them
    if ctx._proc_state is None:
        ctx._proc_state = {"queues": {p: mpctx.Queue() for p in ctx.pids}}
    queues = ctx._proc_state["queues"]
    result_q = mpctx.Queue()
    failed = mpctx.Event()

    from .. import core
    from . import spmd_mode

    def child(rank: int):
        rctx = _RunContext(ctx.id, ctx.pids, queues, ctx.store, failed)
        core._rank_tls.rank = rank
        spmd_mode._tls.ctxt = rctx
        try:
            try:
                r = f(*args)
                result_q.put((rank, "ok", r, rctx.store.get(rank, {})))
            except BaseException as e:  # noqa: BLE001 — shipped to parent
                failed.set()
                # mark peer-abort secondaries structurally so the parent
                # needn't string-match user tracebacks
                secondary = (isinstance(e, RuntimeError)
                             and str(e) == spmd_mode._PEER_ABORT)
                result_q.put((rank, "err", (secondary,
                              f"{type(e).__name__}: {e}\n"
                              f"{''.join(traceback.format_exception(e))}"),
                              None))
        finally:
            # messages pulled into the stash but not consumed go back to
            # this rank's inbox so they stay receivable next run (matching
            # ignores order, so re-queueing cannot change which message a
            # given tagged receive resolves to — only FIFO among identical
            # (typ, from, tag) duplicates could shift, post-failure, where
            # _reset_comm drains everything anyway)
            for m in rctx._stash:
                queues[rank].put(m)
            # mp.Queue.put hands off to a feeder thread; flush every queue
            # this child wrote (messages AND result) before the hard exit,
            # or buffered items silently vanish with the process
            for q in list(queues.values()) + [result_q]:
                q.close()
                q.join_thread()
            os._exit(0)  # skip atexit/teardown of inherited runtime state

    procs = [mpctx.Process(target=child, args=(p,), name=f"spmd-{p}",
                           daemon=True) for p in ctx.pids]
    import warnings
    with warnings.catch_warnings():
        # CPython warns that forking a multithreaded (jax) process may
        # deadlock; the module docstring documents the host-compute-only
        # contract that makes this safe, so don't re-warn per run
        warnings.filterwarnings(
            "ignore", message=".*fork.*", category=DeprecationWarning)
        warnings.filterwarnings(
            "ignore", message=".*fork.*", category=RuntimeWarning)
        for p in procs:
            p.start()

    import queue as queue_mod
    results: dict[int, Any] = {}
    stores: dict[int, dict] = {}
    errors: dict[int, str] = {}
    deadline = time.monotonic() + timeout
    try:
        while len(results) + len(errors) < len(ctx.pids):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                failed.set()
                raise TimeoutError(
                    f"spmd process run did not finish in {timeout}s "
                    f"(completed ranks: {sorted(results)})")
            try:
                rank, status, payload, store = result_q.get(
                    timeout=min(remaining, 0.2))
            except queue_mod.Empty:
                dead = [p for p, pr in zip(ctx.pids, procs)
                        if not pr.is_alive() and p not in results
                        and p not in errors]
                if dead and result_q.empty():
                    failed.set()
                    raise RuntimeError(
                        f"spmd process rank(s) {dead} died without "
                        "reporting (non-picklable result/storage, or the "
                        "child crashed)")
                continue
            if status == "ok":
                results[rank] = payload
                stores[rank] = store
            else:
                errors[rank] = payload
    finally:
        for pr in procs:
            pr.join(5)
            if pr.is_alive():  # pragma: no cover — stuck child
                pr.terminate()
        # the message queues belong to the context (released by
        # _reset_comm/close); only the per-run result queue dies here
        result_q.close()
        result_q.cancel_join_thread()

    if errors:
        # prefer root-cause failures over structurally-marked peer aborts
        primary = [(r, t) for r, (sec, t) in sorted(errors.items())
                   if not sec]
        rank, err = (primary if primary
                     else [(r, t) for r, (_, t) in sorted(errors.items())])[0]
        raise RuntimeError(
            f"spmd task on rank {rank} failed ({len(errors)} total "
            f"failures); child traceback:\n{err}")
    for rank, st in stores.items():
        ctx.store[rank] = st
    return results
