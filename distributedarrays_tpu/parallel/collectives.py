"""Traced SPMD collectives: the static-pattern half of the SPMD layer.

Where the reference builds ring shifts, halo exchanges, and reductions out
of eager ``sendto``/``recvfrom`` over TCP channels (spmd.jl:145-231; ring
program test/spmd.jl:90-101; stencil docs/src/index.md:160-181), the
TPU-native design compiles the *pattern* once: programs written against
these helpers run under ``jax.shard_map`` over a device mesh, and every
communication lowers to an XLA collective on ICI:

- ``pshift``           — ring neighbor shift       → ``lax.ppermute``
- ``halo_exchange``    — stencil boundary exchange → two ``lax.ppermute``
- ``pbarrier``         — sync point                → ``lax.psum`` of 1
- ``pbcast``           — root broadcast            → masked ``lax.psum``
- ``pgather``          — concat over ranks         → ``lax.all_gather``
- ``preduce``          — all-reduce                → ``lax.psum``/``pmax``…
- ``pall_to_all``      — repartition               → ``lax.all_to_all``

This is exactly the substrate of ring attention / context parallelism
(SURVEY.md §5: "long-context"): a sequence-sharded array ring-shifting
blocks while accumulating is ``pshift`` in a ``lax.fori_loop``.

``run_spmd`` wraps a function into a jitted shard_map program over a mesh —
the compiled analog of the reference's ``spmd(f, ...)`` driver.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import layout as L
from .. import telemetry as _tm

__all__ = [
    "spmd_mesh", "run_spmd", "shard_map_compat", "pshift", "halo_exchange",
    "pbarrier", "pbcast", "pgather", "preduce", "pall_to_all", "axis_rank",
    "axis_size",
]


def shard_map_compat(f: Callable, mesh: Mesh, in_specs, out_specs,
                     check: bool | None = None):
    """``shard_map`` across jax versions: the stable ``jax.shard_map``
    (``check_vma=``) when present, else the 0.4.x experimental API
    (``jax.experimental.shard_map.shard_map``, ``check_rep=``).  Every
    shard_map construction in the package goes through here so a jax
    upgrade/downgrade is a one-site change.  ``check=None`` keeps the
    library's own default (the replication/VMA check stays ON for call
    sites that never opted out of it)."""
    kw = {}
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        if check is not None:
            kw["check_vma"] = check
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as _esm
    if check is not None:
        kw["check_rep"] = check
    return _esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **kw)


def _rec(kind: str, x, axis: str, **fields) -> None:
    """Trace-time communication accounting for the compiled collectives.

    These helpers execute inside ``shard_map`` tracing, so the recording
    happens ONCE PER TRACE (compilation), not per device step — flagged
    ``traced=True`` in the journal.  ``x`` is the per-rank block; its
    static shape/dtype give the per-rank payload estimate."""
    if _tm.enabled():
        _tm.record_comm(kind, _tm.nbytes_of(x), axis=axis, traced=True,
                        once_key=f"collective:{kind}:{axis}:{fields}",
                        **fields)


def spmd_mesh(n: int | None = None, axis: str = "p") -> Mesh:
    """A 1-D mesh over the first ``n`` device ranks (default: all)."""
    n = L.nranks() if n is None else int(n)
    return L.mesh_for(list(range(n)), (n,)) if axis == "d0" else \
        Mesh(np.asarray(jax.devices()[:n], dtype=object).reshape(n), (axis,))


def run_spmd(f: Callable, mesh: Mesh, in_specs, out_specs,
             check_vma: bool = False):
    """Compile ``f`` as one SPMD program over ``mesh`` (jit ∘ shard_map).

    The traced analog of the reference's ``spmd(f, args...)`` driver
    (spmd.jl:233-254): every rank runs the same ``f`` on its shard; inside,
    collectives from this module communicate over the mesh axes.
    """
    _tm.count("op.run_spmd")
    # cold path: program construction, not the per-step execution
    _tm.event("jit", "build", fn="run_spmd",  # dalint: disable=DAL003
              once_key=f"run_spmd:{getattr(f, '__name__', f)!s}:"
                       f"{tuple(mesh.shape.items())}")
    return jax.jit(shard_map_compat(f, mesh, in_specs, out_specs,
                                    check=check_vma))


def axis_rank(axis: str):
    """This rank's index along a mesh axis (reference myid() analog)."""
    return lax.axis_index(axis)


def axis_size(axis: str):
    """Static size of a mesh axis from inside a traced program.  Version
    compat: ``lax.axis_size`` when present (new jax), else the 0.4.x
    ``jax.core.axis_frame`` (which returns the size directly)."""
    sz = getattr(lax, "axis_size", None)
    if sz is not None:
        return sz(axis)
    import jax.core as _jc
    return _jc.axis_frame(axis)


def pshift(x, axis: str, shift: int = 1, wrap: bool = True):
    """Ring/neighbor shift along a mesh axis: rank i receives rank
    ``i - shift``'s block (reference: the sendto/recvfrom ring,
    test/spmd.jl:90-101 → one ``lax.ppermute`` over ICI).

    With ``wrap=False`` ranks at the boundary receive zeros.
    """
    n = axis_size(axis)
    if wrap:
        perm = [(i, (i + shift) % n) for i in range(n)]
    else:
        perm = [(i, i + shift) for i in range(n) if 0 <= i + shift < n]
    _rec("ppermute", x, axis, op="pshift", shift=shift)
    return lax.ppermute(x, axis, perm)


def halo_exchange(x, axis: str, halo: int = 1, dim: int = 0,
                  wrap: bool = False):
    """Exchange ``halo``-wide boundary slabs with both mesh-axis neighbors.

    Returns ``(lo, hi)``: the slab arriving from the previous rank (to
    prepend) and from the next rank (to append) along local dim ``dim``.
    This is the 5-point-stencil / Game-of-Life pattern the reference builds
    with eager sends (docs/src/index.md:160-181) — here two ppermutes that
    ride ICI, fused into the surrounding jitted program.
    """
    idx_lo = [slice(None)] * x.ndim
    idx_lo[dim] = slice(0, halo)
    idx_hi = [slice(None)] * x.ndim
    idx_hi[dim] = slice(x.shape[dim] - halo, x.shape[dim])
    # my top slab goes to my previous neighbor (arrives as their `hi`);
    # my bottom slab goes to my next neighbor (arrives as their `lo`)
    hi = pshift(x[tuple(idx_lo)], axis, shift=-1, wrap=wrap)
    lo = pshift(x[tuple(idx_hi)], axis, shift=+1, wrap=wrap)
    return lo, hi


def halo_exchange_2d(x, axes: tuple[str, str], halo: int = 1,
                     wrap: bool = False):
    """Full 2-D halo exchange including corners.

    ``x`` is this rank's (m, n) block on a 2-D mesh ``axes = (row_axis,
    col_axis)``.  Returns the (m + 2h, n + 2h) block padded with the
    neighbors' boundary data (zeros at the global edge when ``wrap`` is
    False).  Corners arrive correctly because the column exchange runs on
    the already row-extended block — the standard two-phase scheme, four
    ``ppermute``s total.
    """
    row_axis, col_axis = axes
    # phase 1: exchange rows along the row axis
    lo, hi = halo_exchange(x, row_axis, halo=halo, dim=0, wrap=wrap)
    xr = jnp.concatenate([lo, x, hi], axis=0)          # (m + 2h, n)
    # phase 2: exchange columns of the extended block along the col axis
    lo2, hi2 = halo_exchange(xr, col_axis, halo=halo, dim=1, wrap=wrap)
    return jnp.concatenate([lo2, xr, hi2], axis=1)     # (m + 2h, n + 2h)


def pbarrier(axis: str):
    """Synchronization point: all ranks must reach it before any proceeds
    (reference barrier, spmd.jl:159-184).  In a compiled SPMD program this
    is a collective dependency — a psum of 1."""
    _rec("psum", jnp.ones((), jnp.int32), axis, op="pbarrier")
    return lax.psum(jnp.ones((), jnp.int32), axis)


def pbcast(x, axis: str, root: int = 0):
    """Every rank gets root's block (reference bcast, spmd.jl:186-196):
    mask + all-reduce, which XLA lowers to an ICI broadcast."""
    me = lax.axis_index(axis)
    masked = jnp.where(me == root, x, jnp.zeros_like(x))
    _rec("psum", x, axis, op="pbcast", root=root)
    return lax.psum(masked, axis)


def pgather(x, axis: str, tiled: bool = False, dim: int = 0):
    """Concatenate every rank's block, pid-ordered (reference gather,
    spmd.jl:214-231) → ``lax.all_gather``.  ``dim`` picks the local axis
    the blocks stack along (the reshard planner gathers along the
    previously-sharded dim, not always dim 0)."""
    _rec("all_gather", x, axis, op="pgather")
    return lax.all_gather(x, axis, axis=dim, tiled=tiled)


_PREDUCERS = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin,
              "mean": lax.pmean}


def preduce(x, axis: str, op: str = "sum"):
    """All-reduce over a mesh axis (two-phase mapreduce analog,
    mapreduce.jl:29-35, but over ICI)."""
    _rec("psum" if op in ("sum", "mean") else f"p{op}", x, axis,
         op="preduce")
    return _PREDUCERS[op](x, axis)


def pall_to_all(x, axis: str, split_dim: int, concat_dim: int,
                tiled: bool = True):
    """All-to-all repartition (the scatter phase of the reference's sample
    sort, sort.jl:24-55) → ``lax.all_to_all``."""
    _rec("all_to_all", x, axis, op="pall_to_all")
    return lax.all_to_all(x, axis, split_axis=split_dim,
                          concat_axis=concat_dim, tiled=tiled)
