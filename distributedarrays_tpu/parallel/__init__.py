from . import collectives, multihost, reshard, spmd_mode  # noqa: F401
from .collectives import (axis_rank, axis_size, halo_exchange, pall_to_all,
                          pbarrier, pbcast, pgather, preduce, pshift,
                          run_spmd, spmd_mesh)
from .spmd_mode import (SPMDContext, barrier, bcast, close_context, context,
                   context_local_storage, gather_spmd, myid, nprocs,
                   recvfrom, recvfrom_any, scatter, sendto, spmd, spmd_async)
