"""Flat-vector optimizers for the data-parallel trainer.

The trainer (:mod:`.trainer`) keeps model parameters as ONE flat f32
vector sharded over the data-parallel ranks (the ZeRO-1 layout: each
rank owns — and updates — only its slice of the parameters and of every
optimizer moment).  Optimizers here are therefore *elementwise* pure
functions over flat slices: the update at index ``i`` depends only on
``p[i]``, ``g[i]`` and the moments at ``i``, so the exact same code is
correct on a full vector, a shard, or a padded shard (padding rows carry
zero gradients and provably stay zero — see :meth:`Optimizer.update`).

Two members cover the repo's training workloads: plain/momentum SGD and
Adam.  Hyperparameters live on the (hashable, frozen) spec so a trainer
program cache can key on them.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax.numpy as jnp

__all__ = ["Optimizer", "sgd", "adam"]


@dataclasses.dataclass(frozen=True)
class Optimizer:
    """One optimizer spec: ``kind`` ∈ {"sgd", "adam"} plus hyperparams.

    ``nslots`` moment buffers ride next to the parameter vector (same
    shape, same sharding): 0 for plain SGD, 1 for momentum SGD, 2 for
    Adam.  :meth:`update` is traced inside the trainer's shard_map
    program; :meth:`init_slots` runs on the host at state creation.
    """

    kind: str = "adam"
    lr: float = 1e-3
    momentum: float = 0.0        # sgd only
    b1: float = 0.9              # adam
    b2: float = 0.999            # adam
    eps: float = 1e-8            # adam

    def __post_init__(self):
        if self.kind not in ("sgd", "adam"):
            raise ValueError(f"unknown optimizer kind {self.kind!r} "
                             "(use 'sgd' or 'adam')")

    @property
    def nslots(self) -> int:
        if self.kind == "adam":
            return 2
        return 1 if self.momentum else 0

    def init_slots(self, n: int) -> tuple:
        """Zero moment vectors for an ``n``-element parameter slice."""
        return tuple(np.zeros(n, dtype=np.float32)
                     for _ in range(self.nslots))

    def update(self, t, p, g, slots: tuple) -> tuple:
        """One elementwise step: ``(p, *slots), g -> (p', *slots')``.

        ``t`` is the 1-based step number (traced scalar — Adam's bias
        correction; a retraced program per step would defeat the jit
        cache).  A zero gradient is a provable fixed point for every
        member (Adam: m=v=0 ⇒ update 0/(0+eps)=0), which is what makes
        the trainer's shard padding safe.
        """
        lr = jnp.float32(self.lr)
        if self.kind == "sgd":
            if not self.momentum:
                return (p - lr * g,)
            (m,) = slots
            m2 = jnp.float32(self.momentum) * m + g
            return p - lr * m2, m2
        m, v = slots
        b1, b2 = jnp.float32(self.b1), jnp.float32(self.b2)
        t = t.astype(jnp.float32)
        m2 = b1 * m + (1.0 - b1) * g
        v2 = b2 * v + (1.0 - b2) * jnp.square(g)
        mhat = m2 / (1.0 - jnp.power(b1, t))
        vhat = v2 / (1.0 - jnp.power(b2, t))
        return (p - lr * mhat / (jnp.sqrt(vhat) + jnp.float32(self.eps)),
                m2, v2)


def sgd(lr: float = 1e-2, momentum: float = 0.0) -> Optimizer:
    """Plain (or momentum) SGD over the flat parameter vector."""
    return Optimizer(kind="sgd", lr=lr, momentum=momentum)


def adam(lr: float = 1e-3, b1: float = 0.9, b2: float = 0.999,
         eps: float = 1e-8) -> Optimizer:
    """Adam with bias correction; two sharded moment vectors."""
    return Optimizer(kind="adam", lr=lr, b1=b1, b2=b2, eps=eps)
