"""Chaos-survivable data-parallel trainer over sharded DArrays.

The workload half of ROADMAP item 1: SGD/Adam training whose every
moving part is owned by the subsystems the previous PRs built, so one
long-running stateful job finally exercises them together —

- **State lives in DArrays.**  The parameters are ONE flat f32 vector
  (ZeRO-1 layout), sharded over the data-parallel ranks together with
  every optimizer moment; each epoch's batch is sharded the same way via
  ``distribute``.  Because they are ordinary registered DArrays,
  ``elastic.shrink()`` re-lays parameters, optimizer state AND batch
  shards onto the survivors through the reshard planner — the trainer
  adds no relocation code of its own.
- **Gradient sync rides the PR 8 ring kernels.**  Inside one
  ``jit(shard_map)`` program per rank count: ``ring_all_gather`` fans the
  parameter shards out, ``jax.grad`` runs per rank on the local batch
  shard, and ``ring_reduce_scatter`` returns each rank exactly its slice
  of the summed gradient (both kernels fall back to the bit-equivalent
  ``lax`` collectives off-TPU, so the program is identical on the CPU
  test mesh).
- **Every step runs under ``recovery.run_with_recovery``** with a
  per-step wall-clock deadline (``RetryPolicy.max_elapsed_s``).  A
  device-loss verdict restores the last published checkpoint
  (integrity-verified — a corrupt shard quarantines and falls back),
  shrinks onto survivors, and deterministically recomputes from the
  restored step; the rewind also discards now-stale later checkpoints
  (``CheckpointManager.discard_from``) so no future restore can
  resurrect the abandoned timeline.
- **Straggler detection**: completed step durations feed a rolling
  p99-derived budget; a step that exceeds it triggers an elastic health
  probe, and a probe-confirmed dead rank raises :class:`DeadRankError`
  (classified ``device_loss``) BEFORE the step's update is applied — the
  recovery path then handles it like any other device loss.

Fault-injection sites ``train.step`` (top of every step) and
``grad.sync`` (between the per-rank gradient program and the sync/update
program) make the whole arc deterministically chaos-testable; see
``tests/test_train.py`` for the acceptance soak.
"""

from __future__ import annotations

import collections
import math
import time
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import layout as L
from .. import telemetry as _tm
from ..telemetry import stream as _tstream
from ..darray import distribute
from ..parallel.collectives import shard_map_compat
from ..resilience import elastic, faults, recovery
from .optim import Optimizer, adam
from .tasks import TrainTask

__all__ = ["Trainer", "StragglerDetector", "DeadRankError", "fit_result"]


class DeadRankError(RuntimeError):
    """A straggler probe confirmed a rank's device is gone.  The message
    carries the ``device lost`` fingerprint so ``recovery.classify``
    reaches the ``device_loss`` verdict (restore + shrink + retry)."""

    def __init__(self, ranks, budget_s: float, dur_s: float):
        self.ranks = sorted(int(r) for r in ranks)
        super().__init__(
            f"straggler probe confirmed rank(s) {self.ranks} device lost "
            f"(step took {dur_s:.3f}s against a {budget_s:.3f}s rolling "
            f"p99 budget)")


class StragglerDetector:
    """Rolling p99-derived per-step wall-clock budget.

    ``observe(dur)`` returns True when ``dur`` exceeded the budget in
    force *before* this step (so one slow step cannot raise its own
    bar), then folds the duration into the window.  No budget exists
    until ``warmup`` steps have completed — the first steps pay jit
    compilation and must not trip the detector."""

    def __init__(self, factor: float = 3.0, min_budget_s: float = 0.25,
                 warmup: int = 4, window: int = 64):
        self.factor = float(factor)
        self.min_budget_s = float(min_budget_s)
        self.warmup = int(warmup)
        self._durs: collections.deque = collections.deque(maxlen=window)

    def budget(self) -> float | None:
        """The current budget in seconds, or None during warmup."""
        if len(self._durs) < self.warmup:
            return None
        s = sorted(self._durs)
        p99 = s[min(len(s) - 1, math.ceil(0.99 * len(s)) - 1)]
        return max(self.min_budget_s, self.factor * p99)

    def observe(self, dur_s: float) -> bool:
        b = self.budget()
        exceeded = b is not None and dur_s > b
        self._durs.append(float(dur_s))
        return exceeded


class Trainer:
    """Data-parallel trainer over sharded DArrays (module docstring).

    ``ckpt_dir=None`` trains without durable state (recovery retries
    from live state); with a directory, a ``CheckpointManager`` publishes
    integrity-verified steps every ``save_every`` steps and recovery
    restores through it.  ``async_save`` defaults to False because the
    chaos acceptance needs the published-step set at fault time to be a
    pure function of the step index — flip it on when replay determinism
    is not required.

    ``ranks`` pins the device set (intersected with the elastic live
    set each attempt); default is whatever ``elastic.manager()`` reports
    live.
    """

    def __init__(self, task: TrainTask, optimizer: Optimizer | None = None,
                 ckpt_dir=None, save_every: int = 0,
                 step_deadline_s: float | None = None,
                 policy: recovery.RetryPolicy | None = None,
                 straggler: StragglerDetector | None = None,
                 ranks: Sequence[int] | None = None,
                 seed: int = 0, async_save: bool = False,
                 max_to_keep: int | None = None,
                 peer_replicas: bool = False):
        self.task = task
        self.opt = optimizer or adam()
        self.save_every = int(save_every)
        self.step_deadline_s = step_deadline_s
        self.straggler = straggler or StragglerDetector()
        self._policy = policy
        self._pin_ranks = [int(r) for r in ranks] if ranks else None
        self.seed = int(seed)
        self._mgr = None
        if ckpt_dir is not None:
            from ..utils.checkpoint import CheckpointManager, \
                PeerReplicaStore
            # peer_replicas: every published step is also replicated into
            # buddy-rank memory (cross-failure-domain placement), and a
            # device-loss/partition restore pulls from there first — zero
            # disk reads when a whole host's shards die
            self._mgr = CheckpointManager(
                ckpt_dir, async_save=async_save, max_to_keep=max_to_keep,
                replicas=PeerReplicaStore() if peer_replicas else None)
        self._step = 0
        self._losses: dict[int, float] = {}
        self._state: dict | None = None       # name -> DArray, + "spec"
        self._spec = None                     # (treedef, shapes, size P)
        self._batch = None                    # (step, [DArrays], w DArray)
        self._progs: dict = {}
        self._dispatch: dict = {}             # program key -> "rdma"|"xla"
        self._closed = False

    # -- flat parameter vector ---------------------------------------------

    def _flatten_init(self):
        params = self.task.init_params(jax.random.PRNGKey(self.seed))
        leaves, treedef = jax.tree_util.tree_flatten(params)
        shapes = [tuple(int(s) for s in np.shape(lf)) for lf in leaves]
        flat = np.concatenate(
            [np.asarray(lf, dtype=np.float32).ravel() for lf in leaves]) \
            if leaves else np.zeros(0, np.float32)
        self._spec = (treedef, shapes, int(flat.size))
        return flat

    def _unflatten(self, flat):
        """Rebuild the params pytree from a flat (traced) vector —
        static offsets, so this is free at run time."""
        treedef, shapes, _ = self._spec
        leaves, off = [], 0
        for shp in shapes:
            n = int(np.prod(shp)) if shp else 1
            leaves.append(jnp.reshape(flat[off:off + n], shp))
            off += n
        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- device set / state ------------------------------------------------

    def _ranks_now(self) -> list[int]:
        live = elastic.manager().live_ranks()
        if self._pin_ranks is not None:
            pinned = [r for r in self._pin_ranks if r in live]
            if not pinned:
                # the pin is a hard boundary: training must never
                # silently migrate onto devices the caller excluded
                raise RuntimeError(
                    f"trainer: no pinned rank of {self._pin_ranks} is "
                    f"live (live set: {live})")
            return pinned
        if not live:
            raise RuntimeError("trainer: no live devices remain")
        return live

    def _state_names(self) -> list[str]:
        return ["pflat"] + [f"m{i}" for i in range(self.opt.nslots)]

    def _ensure_state(self):
        if self._state is not None:
            return
        if self._mgr is not None and self._mgr.steps():
            # resume: adopt the latest verified checkpoint (corrupt steps
            # quarantine + fall back inside restore())
            self._adopt(self._mgr.restore())
            return
        flat = self._flatten_init()
        ranks = self._ranks_now()
        p = len(ranks)
        self._state = {"pflat": distribute(flat, procs=ranks, dist=[p])}
        for i, slot in enumerate(self.opt.init_slots(flat.size)):
            self._state[f"m{i}"] = distribute(slot, procs=ranks, dist=[p])  # dalint: disable=DAL006 — long-lived optimizer state, closed by _close_state()

    def _adopt(self, tree: dict):
        """Re-seat state from a restored checkpoint tree (recovery's
        ``restore_fn`` and the resume path): close the current DArrays,
        take the restored ones, rewind the step counter, truncate the
        loss record, and discard now-stale later checkpoints so the
        abandoned timeline can never be restored."""
        if self._spec is None:
            # spec is derived from the task, not the checkpoint; build it
            # once (also reseeds nothing: init params are discarded)
            self._flatten_init()
        names = self._state_names()
        missing = [k for k in names if k not in tree]
        extra = [k for k in tree
                 if k not in names and k != "step" and hasattr(tree[k],
                                                               "close")]
        if missing:
            # a checkpoint written with a different optimizer: fail
            # diagnosably — and close every restored DArray first, or
            # the registered buffers leak for the process lifetime
            for k, v in tree.items():
                if hasattr(v, "close"):
                    v.close()
            raise ValueError(
                f"checkpoint step {tree.get('step')} is missing optimizer "
                f"state {missing} (this trainer expects {names}); it was "
                f"written with a different optimizer configuration")
        self._close_state()
        self._state = {k: tree[k] for k in names}
        # surplus restored state (a checkpoint written with MORE slots)
        # is closed, not silently leaked
        for k in extra:
            tree[k].close()
        self._step = int(tree["step"])
        self._losses = {k: v for k, v in self._losses.items()
                        if k < self._step}
        if self._mgr is not None:
            self._mgr.discard_from(self._step + 1)
        _tm.count("train.reseats")
        if _tm.enabled():
            # cold path: a re-seat is one per recovery, not per step
            _tm.event("train", "reseat", step=self._step)

    def _close_state(self):
        if self._state:
            for d in self._state.values():
                try:
                    d.close()
                except Exception:  # noqa: BLE001 — already-closed is fine
                    pass
        self._state = None

    def _close_batch(self):
        if self._batch is not None:
            for d in self._batch[1]:
                try:
                    d.close()
                except Exception:  # noqa: BLE001 — already-closed is fine
                    pass
            self._batch = None

    # -- per-rank-count compiled programs ----------------------------------

    def _programs(self, ranks: tuple, ppad: int, bshapes: tuple,
                  bdtypes: tuple, b_real: int):
        key = (ranks, ppad, bshapes, bdtypes, b_real, self.opt)
        progs = self._progs.get(key)
        if progs is not None:
            return progs + (key, False)
        p = len(ranks)
        mesh = L.mesh_for(list(ranks), (p,))
        ax = mesh.axis_names[0]
        n_params = self._spec[2]
        from ..ops.pallas_collectives import (ring_all_gather,
                                              ring_reduce_scatter)

        def grad_prog(pfl, w, *batch):
            # fan the parameter shards out (ring AG on TPU, lax
            # all_gather fallback elsewhere), per-rank grad on the local
            # batch shard; the returned grad is this rank's FULL-length
            # gradient, stacked so the sync program can ring it
            full = ring_all_gather(pfl, ax, dim=0)

            def lf(flat):
                return self.task.loss_sum(
                    self._unflatten(flat[:n_params]), batch, w)

            loss, g = jax.value_and_grad(lf)(full)
            return g[None], loss[None]

        bspecs = tuple(P(ax, *([None] * (len(s) - 1))) for s in bshapes)
        grad_fn = jax.jit(shard_map_compat(
            grad_prog, mesh, in_specs=(P(ax), P(ax)) + bspecs,
            out_specs=(P(ax, None), P(ax)), check=False))

        def sync_prog(t, gstack, pfl, *slots):
            g = gstack[0]
            # each rank ends with its own slice of the globally-summed
            # gradient (ring RS on TPU, psum_scatter fallback) — the
            # ZeRO-1 sync — then updates only its parameter/moment slice
            gs = ring_reduce_scatter(g, ax, dim=0) / jnp.float32(b_real)
            return self.opt.update(t, pfl, gs, slots)

        nst = self.opt.nslots
        sync_fn = jax.jit(shard_map_compat(
            sync_prog, mesh,
            in_specs=(P(),) + (P(ax, None),) + (P(ax),) * (1 + nst),
            out_specs=(P(ax),) * (1 + nst), check=False))
        progs = (grad_fn, sync_fn)
        self._progs[key] = progs
        _tm.count("train.program_builds")
        if _tm.enabled():
            # cold path: one build per (rank count, shapes) combination
            _tm.event("train", "program_build", ranks=p, ppad=ppad)
        return progs + (key, True)

    # -- batch pipeline ----------------------------------------------------

    def _batch_for(self, step: int, ranks: list[int]):
        """The step's batch as DArrays sharded over ``ranks`` (padded to
        a rank-divisible global size; weight-0 rows are inert in
        ``loss_sum``).  Returns ``(darrays, b_real)``.  Reused across
        retry attempts of the same step — and because the DArrays are
        registered, an ``elastic.shrink()`` between attempts re-lays
        THEM onto survivors too."""
        p = len(ranks)
        cur = self._batch
        if cur is not None and cur[0] == (step, tuple(ranks)):
            return cur[1], cur[2]
        self._close_batch()
        leaves = self.task.batch(step)
        b = int(np.shape(leaves[0])[0])
        bpad = -(-b // p) * p
        darrs = []
        for x in leaves:
            x = np.asarray(x)
            if bpad != b:
                pad = np.zeros((bpad - b,) + x.shape[1:], x.dtype)
                x = np.concatenate([x, pad])
            darrs.append(distribute(x, procs=ranks,  # dalint: disable=DAL006 — the step's batch shards, closed by _close_batch() on the next step/close
                                    dist=[p] + [1] * (x.ndim - 1)))
        w = np.zeros(bpad, np.float32)
        w[:b] = 1.0
        darrs.append(distribute(w, procs=ranks, dist=[p]))
        self._batch = ((step, tuple(ranks)), darrs, b)
        return darrs, b

    # -- one recoverable step ----------------------------------------------

    def _attempt_step(self):
        n = self._step
        ranks = self._ranks_now()
        # state must live on the step's rank set before any program sees
        # it: after a device-loss recovery, elastic.shrink() already
        # re-laid the restored arrays onto the survivors, but a resume
        # onto a pinned/changed rank set reaches here with the saved
        # layout — route it through the same reshard planner
        for d in self._state.values():
            if sorted({int(x) for x in d.pids.flat}) != sorted(ranks):
                elastic.relayout(d, ranks)
        p = len(ranks)
        n_params = self._spec[2]
        ppad = -(-n_params // p) * p
        batch_darrs, b_real = self._batch_for(n, ranks)
        *bleaves, wq = [d.garray for d in batch_darrs]
        b_pad = int(bleaves[0].shape[0])
        bshapes = tuple(tuple(int(s) for s in x.shape) for x in bleaves)
        bdtypes = tuple(str(x.dtype) for x in bleaves)
        grad_fn, sync_fn, progkey, fresh_build = self._programs(
            tuple(ranks), ppad, bshapes, bdtypes, b_real)

        epoch = n // self.save_every if self.save_every else 0
        with _tm.span("train.step", step=n, ranks=p):
            if _tm.enabled():
                from ..telemetry import perf as _perf
                _tm.annotate(**_perf.train_step_cost(
                    n_params=ppad, p=p,
                    flops=float(self.task.step_flops(b_pad)),
                    batch_bytes=sum(int(x.nbytes) for x in bleaves),
                    nslots=self.opt.nslots))
            t0 = time.monotonic()
            # chaos site: the top of every step — the "host dies
            # mid-epoch" injection point (a hang here counts against the
            # straggler budget: the clock is already running)
            faults.check("train.step", step=n, epoch=epoch)
            pfl = jnp.pad(self._state["pflat"].garray,
                          (0, ppad - n_params))
            slots = [jnp.pad(self._state[f"m{i}"].garray,
                             (0, ppad - n_params))
                     for i in range(self.opt.nslots)]
            # the dispatch label must reflect the path the ring kernels
            # ACTUALLY took (per-kernel gates — VMEM, divisibility —
            # can fall back to lax even with RDMA armed): on the
            # program's first execution (its trace) the kernels bump
            # the dispatch counter once per compilation, so the delta
            # over the build step is the truth; later steps reuse it
            rd0 = _dispatch_rdma_count() if fresh_build else 0
            with _tm.span("train.grad", step=n, kind="compute"):
                gstack, lsums = grad_fn(pfl, wq, *bleaves)
                jax.block_until_ready(lsums)
            # chaos site: between per-rank grads and the sync program —
            # the gradient exchange is where a ring peer's death lands
            faults.check("grad.sync", step=n)
            with _tm.span("train.sync", step=n, kind="comm"):
                outs = sync_fn(jnp.int32(n + 1), gstack, pfl, *slots)
                jax.block_until_ready(outs)
            if fresh_build:
                self._dispatch[progkey] = \
                    "rdma" if _dispatch_rdma_count() > rd0 else "xla"
            _tm.annotate(dispatch=self._dispatch.get(progkey, "xla"))
            dur = time.monotonic() - t0
            # last step wall time as a gauge: the alerts module's
            # train_step_time burn-rate rule samples it between spans
            _tm.set_gauge("train.step_s", round(dur, 6))
            # live plane: per-step timing points for the aggregator's
            # train_step_time burn windows (single check when unarmed)
            _tstream.note("train.step_s", round(dur, 6))
            # straggler gate BEFORE the update is applied: a confirmed
            # dead rank must abort the step so the recovery retry
            # (restore + shrink) recomputes it — never double-applies
            # it.  A step that paid a fresh program build neither feeds
            # nor is judged by the rolling window — compile time is not
            # steady-state step time, and one such outlier would inflate
            # the p99 budget for the whole window
            if not fresh_build and self.straggler.observe(dur):
                _tm.count("train.stragglers")
                if _tm.enabled():
                    # cold path: an exceeded budget is exceptional
                    _tm.event("train", "straggler", step=n,
                              dur=round(dur, 6))
                probe = elastic.manager().probe()
                dead = set(probe["down"]) & set(ranks)
                if dead:
                    raise DeadRankError(dead, self.straggler.budget()
                                        or 0.0, dur)
            loss = float(np.asarray(lsums, np.float32).sum()
                         / np.float32(b_real))
            new_p, *new_slots = outs
            # write-back stays on device: __setitem__ at-sets the slice
            # straight from the program's output arrays — a host
            # round-trip of the full state here would dominate the step
            self._state["pflat"][:] = new_p[:n_params]
            for i, s in enumerate(new_slots):
                self._state[f"m{i}"][:] = s[:n_params]
        self._losses[n] = loss
        self._step = n + 1
        if self._mgr is not None and self.save_every and \
                self._step % self.save_every == 0:
            self._mgr.save(self._step, self._ckpt_tree())
        return loss

    def _ckpt_tree(self):
        return {"step": self._step,
                **{k: self._state[k] for k in self._state_names()}}

    def _step_policy(self) -> recovery.RetryPolicy:
        if self._policy is not None:
            pol = self._policy
        else:
            pol = recovery.RetryPolicy()
        if self.step_deadline_s is not None and \
                pol.max_elapsed_s is None:
            import dataclasses as _dc
            pol = _dc.replace(pol, max_elapsed_s=self.step_deadline_s)
        return pol

    # -- public API --------------------------------------------------------

    def fit(self, steps: int) -> dict:
        """Train to ``steps`` total optimizer steps (resuming from the
        current/restored step), each step under the recovery executor.

        Returns ``{"losses", "start", "steps", "resumed_from"}``:
        ``losses[i]`` is the final loss of step ``start + i`` —
        ``start`` is 0 for a fresh run (a mid-run recovery rewound and
        re-recorded the recomputed steps in place), and the restored
        step for a trainer resumed from a checkpoint (it has no record
        of the earlier steps)."""
        if self._closed:
            raise RuntimeError("trainer is closed")
        self._ensure_state()
        first = self._step
        restore_fn = self._adopt if self._mgr is not None else None
        try:
            while self._step < int(steps):
                recovery.run_with_recovery(
                    self._attempt_step, policy=self._step_policy(),
                    checkpoints=self._mgr, restore_fn=restore_fn)
        finally:
            self._close_batch()
        if self._mgr is not None:
            self._mgr.wait()
        # a fresh trainer resumed from step S has no record before S; a
        # mid-run rewind re-records the recomputed steps in place
        start = min(self._losses) if self._losses else int(steps)
        return {"losses": [self._losses[i]
                           for i in range(start, int(steps))],
                "start": start, "steps": self._step,
                "resumed_from": first}

    def step_once(self) -> float:
        """One recovered step (the bench hook)."""
        if self._closed:
            raise RuntimeError("trainer is closed")
        self._ensure_state()
        restore_fn = self._adopt if self._mgr is not None else None
        return recovery.run_with_recovery(
            self._attempt_step, policy=self._step_policy(),
            checkpoints=self._mgr, restore_fn=restore_fn)

    @property
    def step(self) -> int:
        return self._step

    def losses(self) -> dict:
        """Per-step final loss record (post-resume values win)."""
        return dict(self._losses)

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._close_batch()
        self._close_state()
        if self._mgr is not None:
            self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _dispatch_rdma_count() -> int:
    """Total RDMA-path dispatches of the trainer's two ring kernels —
    ``_record_dispatch`` bumps these once per compilation, so a delta
    across a program's first execution witnesses the path actually
    taken (gates included), not merely the armed mode."""
    return sum(_tm.counter_value("pallas_collectives.dispatch",
                                 op=op, path="rdma")
               for op in ("ring_all_gather", "ring_reduce_scatter"))


def fit_result(losses: list, from_step: int = 0) -> list:
    """The loss trajectory from a resume point (test/bench helper)."""
    return list(losses[from_step:])
