"""Training tasks: the model-side contract the trainer drives.

A :class:`TrainTask` bundles what the :class:`~.trainer.Trainer` needs
and nothing else:

- ``init_params(key) -> pytree`` of f32 leaves,
- ``loss_sum(params, batch, w) -> scalar`` — the **weighted sum** of
  per-example losses over one *local* batch shard (``w`` is the
  per-example weight vector: 1.0 for real examples, 0.0 for the padding
  rows the trainer appends to make the global batch divisible by the
  rank count).  Summing locally and ``psum``-ing globally keeps the
  global loss/gradient exactly independent of how the batch is split,
  which is what the chaos test's bit-identical-resume acceptance rides
  on.
- ``batch(step) -> tuple of host arrays`` — the deterministic data
  pipeline: the same step index must yield the same batch on every
  (re-)run, or a recovery retry could never reproduce the trajectory.
- ``step_flops(batch_size)`` — analytic fwd+bwd flops for the perf
  doctor's ``train.step`` stamps (0.0 when unknown).

The two constructors reuse the existing model layer rather than define
new networks: :func:`mlp_task` trains :mod:`..models.mlp`'s network on a
fixed random teacher, :func:`transformer_task` trains
:mod:`..models.transformer`'s decoder on next-token prediction.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["TrainTask", "mlp_task", "transformer_task"]


@dataclasses.dataclass(frozen=True)
class TrainTask:
    """The trainer's model-side contract (see module docstring)."""

    name: str
    batch_size: int
    init_params: Callable
    loss_sum: Callable            # (params, batch_tuple, w) -> scalar sum
    batch: Callable               # (step) -> tuple of host np arrays
    step_flops: Callable = lambda batch_size: 0.0


def _mix_rng(seed: int, step: int) -> np.random.Generator:
    """Per-(task-seed, step) host RNG: plain integer mixing (hash() is
    process-salted, which would break cross-process replay)."""
    return np.random.default_rng((seed * 1_000_003 + step * 8_191)
                                 & 0x7FFFFFFF)


def mlp_task(sizes: Sequence[int] = (16, 32, 32, 4),
             batch_size: int = 56, seed: int = 0) -> TrainTask:
    """Regression on a fixed random teacher with the mesh-sharded MLP
    (:mod:`..models.mlp` — its ``forward`` is reused verbatim; only the
    per-example weighting is new).  ``batch_size=56`` divides both 8 and
    7 ranks, so a shrink from the default CPU mesh needs no re-padding.
    """
    from ..models import mlp
    sizes = tuple(int(s) for s in sizes)
    teacher = np.random.default_rng(seed + 7).standard_normal(
        (sizes[0], sizes[-1])).astype(np.float32) / np.sqrt(sizes[0])

    def init_params(key):
        return mlp.init_params(key, sizes, dtype=jnp.float32)

    def loss_sum(params, batch, w):
        x, y = batch
        pred = mlp.forward(params, x)
        per_ex = jnp.mean(jnp.square(pred - y), axis=-1)   # (B_local,)
        return jnp.sum(per_ex * w)

    def batch(step):
        rng = _mix_rng(seed, step)
        x = rng.standard_normal((batch_size, sizes[0])).astype(np.float32)
        y = np.tanh(x @ teacher).astype(np.float32)
        return x, y

    def step_flops(bsz):
        # fwd GEMMs: 2*B*in*out per layer; bwd ≈ 2x fwd
        fwd = sum(2.0 * bsz * a * b for a, b in zip(sizes, sizes[1:]))
        return 3.0 * fwd

    return TrainTask(name=f"mlp{ 'x'.join(map(str, sizes)) }",
                     batch_size=batch_size, init_params=init_params,
                     loss_sum=loss_sum, batch=batch,
                     step_flops=step_flops)


def transformer_task(vocab: int = 64, dim: int = 32, heads: int = 2,
                     layers: int = 1, seq: int = 16,
                     batch_size: int = 56, seed: int = 0) -> TrainTask:
    """Next-token prediction with the decoder from
    :mod:`..models.transformer` (its ``Config``/``init_params``/
    ``forward`` are reused; the per-example token-mean cross-entropy here
    replaces its batch-mean ``loss_fn`` so padding rows can carry zero
    weight)."""
    from ..models import transformer as tr
    cfg = tr.Config(vocab=vocab, dim=dim, heads=heads, layers=layers,
                    max_seq=seq, dtype=jnp.float32)

    def init_params(key):
        # f32 master weights: the trainer's flat vector (and the
        # bit-identical-resume acceptance) is f32 end to end
        return tr.init_params(key, cfg)

    def loss_sum(params, batch, w):
        (tokens,) = batch
        logits = tr.forward(params, tokens[:, :-1], cfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        tgt = tokens[:, 1:]
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        per_ex = jnp.mean(nll, axis=-1)                    # (B_local,)
        return jnp.sum(per_ex * w)

    def batch(step):
        # learnable data: each example is a modular counting sequence
        # from a random offset — next-token prediction has an exact
        # answer, so the loss trajectory visibly descends in a few steps
        rng = _mix_rng(seed, step)
        offs = rng.integers(0, vocab, size=(batch_size, 1), dtype=np.int64)
        toks = (offs + np.arange(seq + 1)) % vocab
        return (toks.astype(np.int32),)

    def step_flops(bsz):
        # dominant GEMMs per token: qkv+proj (8*dim^2) + ffn
        # (2*4*dim^2*2) per layer, + the vocab head; fwd+bwd ≈ 3x fwd
        per_tok = layers * (8.0 * dim * dim + 16.0 * dim * dim) \
            + 2.0 * dim * vocab
        return 3.0 * bsz * seq * per_tok

    return TrainTask(name=f"transformer_d{dim}", batch_size=batch_size,
                     init_params=init_params, loss_sum=loss_sum,
                     batch=batch, step_flops=step_flops)
