"""Fault-tolerant elastic training over sharded DArrays.

``Trainer`` runs data-parallel SGD/Adam with ZeRO-1 sharded state,
ring-collective gradient sync, per-step recovery deadlines, straggler
detection, and integrity-verified checkpoint resume — see
:mod:`.trainer` and docs/training.md.
"""

from .optim import Optimizer, adam, sgd
from .tasks import TrainTask, mlp_task, transformer_task
from .trainer import DeadRankError, StragglerDetector, Trainer

__all__ = [
    "Trainer", "StragglerDetector", "DeadRankError",
    "Optimizer", "adam", "sgd",
    "TrainTask", "mlp_task", "transformer_task",
]
