"""ctypes bindings for the native host runtime (native/chunkcopy.cpp).

The C++ side parallelizes the strided chunk↔global copies that the host
paths of the framework perform around device scatters (DArray-from-init
assembly, ``from_chunks``, checkpoint restore).  The library is
compiled on first use with the system g++ into ``build/`` and bound via
ctypes; every caller has a pure-numpy fallback, so the framework works
identically without a toolchain — the native path is a performance tier,
not a dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path

import numpy as np

__all__ = ["available", "assemble", "scatter_chunks", "worth_using"]

_REPO = Path(__file__).resolve().parents[2]
_SRC = _REPO / "native" / "chunkcopy.cpp"
_BUILD = _REPO / "build"
_SO = _BUILD / "libchunkcopy.so"

_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if (not _SO.exists()
                    or (_SRC.exists()
                        and _SO.stat().st_mtime < _SRC.stat().st_mtime)):
                _BUILD.mkdir(exist_ok=True)
                # compile to a process-unique temp path and atomically
                # rename, so concurrent processes never dlopen a
                # half-written .so
                tmp = _SO.with_suffix(f".{os.getpid()}.tmp.so")
                subprocess.run(  # dalint: disable=DAL008 — one-shot native build; the lock exists precisely to make every caller wait for the .so
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     "-o", str(tmp), str(_SRC)],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, _SO)
            try:
                lib = ctypes.CDLL(str(_SO))
            except OSError:
                # racing writer may have just replaced the file; retry once
                lib = ctypes.CDLL(str(_SO))
            lib.chunk_copy.restype = ctypes.c_int
            lib.chunk_copy.argtypes = [
                ctypes.c_char_p,                      # dst
                ctypes.POINTER(ctypes.c_int64),       # dst_dims
                ctypes.c_int,                         # ndim
                ctypes.POINTER(ctypes.c_char_p),      # chunks
                ctypes.POINTER(ctypes.c_int64),       # shapes
                ctypes.POINTER(ctypes.c_int64),       # offsets
                ctypes.c_int64,                       # n_chunks
                ctypes.c_int64,                       # itemsize
                ctypes.c_int,                         # scatter
                ctypes.c_int,                         # n_threads
            ]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def worth_using(total_bytes: int, n_chunks: int) -> bool:
    """Engage the native path only where thread-parallel copies can win:
    multi-core hosts moving enough data to amortize the ctypes marshalling.
    On a single-core host numpy's serial memcpy is already bandwidth-bound
    and the native path is pure overhead."""
    return (available() and (os.cpu_count() or 1) > 1
            and n_chunks > 1 and total_bytes >= 32 * 1024 * 1024)


def _call(dst: np.ndarray, chunks, offsets, scatter: bool,
          n_threads: int | None) -> bool:
    lib = _load()
    if lib is None:
        return False
    if not dst.flags.c_contiguous or dst.dtype.hasobject:
        return False
    for c, o in zip(chunks, offsets):
        if not (isinstance(c, np.ndarray) and c.flags.c_contiguous
                and c.dtype == dst.dtype and c.ndim == dst.ndim):
            return False
        # bounds: the C side memcpys blindly; a bad region must fail the
        # same way the numpy fallback does, not corrupt the heap
        for d in range(dst.ndim):
            if o[d] < 0 or o[d] + c.shape[d] > dst.shape[d]:
                raise ValueError(
                    f"chunk at offset {tuple(o)} with shape {c.shape} "
                    f"exceeds destination dims {dst.shape}")
    n = len(chunks)
    if n == 0:
        return True
    nd = dst.ndim
    dims_arr = (ctypes.c_int64 * max(nd, 1))(*(dst.shape or (1,)))
    ptr_arr = (ctypes.c_char_p * n)()
    for i, c in enumerate(chunks):
        ptr_arr[i] = ctypes.cast(ctypes.c_void_p(c.ctypes.data),
                                 ctypes.c_char_p)
    shp = (ctypes.c_int64 * (n * max(nd, 1)))()
    off = (ctypes.c_int64 * (n * max(nd, 1)))()
    for i, (c, o) in enumerate(zip(chunks, offsets)):
        for d in range(nd):
            shp[i * nd + d] = c.shape[d]
            off[i * nd + d] = o[d]
    if n_threads is None:
        n_threads = min(n, os.cpu_count() or 1)
    rc = lib.chunk_copy(
        dst.ctypes.data_as(ctypes.c_char_p), dims_arr, nd,
        ptr_arr, shp, off, n, dst.dtype.itemsize, int(scatter),
        int(n_threads))
    return rc == 0


def assemble(dst: np.ndarray, chunks, offsets, n_threads=None) -> np.ndarray:
    """Copy contiguous row-major ``chunks`` into ``dst`` at elementwise
    ``offsets`` (one origin tuple per chunk).  Falls back to numpy slicing
    when the native library is unavailable or inputs are non-contiguous."""
    if not _call(dst, list(chunks), list(offsets), scatter=False,
                 n_threads=n_threads):
        for c, o in zip(chunks, offsets):
            sl = tuple(slice(o[d], o[d] + c.shape[d]) for d in range(dst.ndim))
            dst[sl] = c
    return dst


def scatter_chunks(src: np.ndarray, shapes, offsets, n_threads=None) -> list:
    """Slice ``src`` apart into freshly-allocated contiguous chunks of the
    given shapes at the given origins (inverse of assemble)."""
    chunks = [np.empty(tuple(s), dtype=src.dtype) for s in shapes]
    if not _call(src, chunks, list(offsets), scatter=True,
                 n_threads=n_threads):
        for c, o in zip(chunks, offsets):
            sl = tuple(slice(o[d], o[d] + c.shape[d]) for d in range(src.ndim))
            c[...] = src[sl]
    return chunks
