"""Invariant checking and debugging aids.

The reference's "race detection" is defensive: every shared registry is
lock-guarded and CI runs bounds-checked (SURVEY.md §5; core.jl:2-6,
spmd.jl:39-53, runtests.jl:12).  This framework keeps those defenses (all
registries and mailboxes are lock-guarded, mailbox receives time out
loudly) and adds an explicit invariant checker, usable in tests or
sprinkled into long-running jobs:

- ``validate(d)`` — asserts the full DArray layout contract: cuts are
  monotone and tile the dims, indices agree with cuts, the pid grid
  matches the chunk grid, the payload's shape/dtype/devices are
  consistent, and the registry knows the array.
- ``check_all()`` — validates every live DArray in the registry.
"""

from __future__ import annotations

import threading
import warnings

import numpy as np

from .. import core
from .. import layout as L
from .. import telemetry
from ..darray import DArray

__all__ = ["validate", "check_all", "warn_once"]

_warned: set = set()
_warned_lock = threading.Lock()


def warn_once(key: str, msg: str, stacklevel: int = 3) -> None:
    """Emit ``msg`` as a RuntimeWarning the FIRST time ``key`` is seen in
    this process.  Used by ops that take a documented fallback path (e.g.
    shard_map → host loop) so the degradation is visible exactly once
    instead of silently eating performance (VERDICT round-2 item 7).

    Every call is additionally COUNTED (telemetry ``fallback.hits`` with
    the site key as a label) and the first occurrence per key is
    journaled under category ``"fallback"`` — so degradations are
    queryable after the fact (``telemetry.report()``), not just visible
    once on stderr."""
    telemetry.count("fallback.hits", key=key)
    with _warned_lock:
        if key in _warned:
            return
        _warned.add(key)
    telemetry.event("fallback", key, message=msg)
    warnings.warn(msg, RuntimeWarning, stacklevel=stacklevel)


def _check(cond: bool, msg: str) -> None:
    # explicit raise (not `assert`) so the checker still works under
    # `python -O`, where asserts are compiled out
    if not cond:
        raise AssertionError(msg)


def validate(d: DArray) -> None:
    """Raise AssertionError with a precise message on any broken layout
    invariant of ``d``."""
    _check(not d._closed, f"{d.id}: closed DArray")
    _check(d.id in core.registry(), f"{d.id}: missing from registry")
    nd = len(d.dims)
    _check(len(d.cuts) == nd, f"{d.id}: {len(d.cuts)} cut vectors, {nd} dims")
    for dim, c in enumerate(d.cuts):
        _check(c[0] == 0 and c[-1] == d.dims[dim],
               f"{d.id}: cuts[{dim}]={c} do not span [0, {d.dims[dim]}]")
        _check(all(a <= b for a, b in zip(c, c[1:])),
               f"{d.id}: cuts[{dim}]={c} not monotone")
        _check(len(c) == d.pids.shape[dim] + 1,
               f"{d.id}: cuts[{dim}] has {len(c)} entries for "
               f"{d.pids.shape[dim]} chunks")
    _check(d.indices.shape == d.pids.shape,
           f"{d.id}: indices grid {d.indices.shape} != pid grid {d.pids.shape}")
    for ci in np.ndindex(*d.pids.shape):
        idx = d.indices[ci]
        for dim in range(nd):
            want = range(d.cuts[dim][ci[dim]], d.cuts[dim][ci[dim] + 1])
            _check(idx[dim] == want,
                   f"{d.id}: indices[{ci}][{dim}]={idx[dim]} != "
                   f"cuts-derived {want}")
    g = d.garray
    _check(tuple(g.shape) == d.dims,
           f"{d.id}: payload shape {g.shape} != dims {d.dims}")
    navail = L.nranks()
    for p in d.pids.flat:
        _check(0 <= int(p) < navail, f"{d.id}: rank {p} out of range")


def check_all() -> int:
    """Validate every live DArray; returns how many were checked."""
    n = 0
    for ref in core.registry().values():
        d = ref()
        if isinstance(d, DArray) and not d._closed:
            validate(d)
            n += 1
    return n
