"""Tracing / profiling utilities.

The reference has no tracing subsystem (SURVEY.md §5: "Tracing/profiling:
none — only commented-out println debugging", spmd.jl:122,136).  On TPU we
get a real profiler from the platform; this module wraps it in the
framework's terms:

- ``trace(dir)`` — context manager capturing a JAX/XLA profile (viewable
  in Perfetto / TensorBoard) around any block of DArray operations.
- ``annotate(name)`` — named trace spans for host-side phases.
- ``op_timer()`` — lightweight wall-clock accounting of eager ops with
  marginal-cost support (see bench.py for the tunnel caveat).

Framework-level accounting (byte counts, reshard/fallback/retrace
counters, the event journal, hierarchical spans) lives in
``distributedarrays_tpu.telemetry`` — this module is the deep-dive tier
on top, and both hooks are REBASED on telemetry spans: ``annotate(name)``
opens one telemetry span AND one ``jax.profiler.TraceAnnotation``, so a
single annotation shows the phase on the XLA/Perfetto profile timeline
and in the framework journal (with comm-byte attribution); ``OpTimer``
times through the same span machinery (keeping its local totals and the
``optimer.<name>`` histograms).  Profiler captures are journaled so a
telemetry report names the trace directories that cover it.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

from .. import telemetry as _tm

__all__ = ["trace", "annotate", "OpTimer"]


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a JAX profiler trace of the enclosed block.

    View with `tensorboard --logdir <dir>` or ui.perfetto.dev.
    """
    # cold path: bounds a whole profiler capture session
    _tm.event("profile", "trace_start", dir=str(log_dir))  # dalint: disable=DAL003
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        _tm.event("profile", "trace_stop", dir=str(log_dir))  # dalint: disable=DAL003


@contextlib.contextmanager
def annotate(name: str):
    """Named span on BOTH timelines: the XLA profiler trace
    (``jax.profiler.TraceAnnotation``) and the framework journal (a
    telemetry span — comm/events inside are attributed to it).  One
    annotation, both views."""
    with _tm.span(name, src="annotate"):
        with jax.profiler.TraceAnnotation(name):
            yield


class OpTimer:
    """Accumulating wall-clock timer for host-side phases.

    >>> t = OpTimer()
    >>> with t("distribute"): d = distribute(A)
    >>> t.report()
    """

    def __init__(self):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)

    @contextlib.contextmanager
    def __call__(self, name: str):
        t0 = time.perf_counter()
        try:
            # a real telemetry span (not just a histogram sample): the
            # phase nests under whatever span is open, shows up in the
            # Perfetto export, and owns the comm bytes it causes
            with _tm.span(name, src="optimer"):
                yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] += dt
            self.counts[name] += 1
            # mirror into the process-wide registry so OpTimer totals show
            # up in telemetry.report() next to the comm/fallback counters
            _tm.observe(f"optimer.{name}", dt)

    def report(self) -> dict:
        return {k: {"total_s": self.totals[k], "calls": self.counts[k],
                    "mean_s": self.totals[k] / self.counts[k]}
                for k in sorted(self.totals)}
