"""Checkpoint / resume for distributed arrays.

The reference has **no** checkpoint subsystem (SURVEY.md §5: "Checkpoint /
resume: none") — serializing a DArray over Julia's wire just moves ids
(serialize.jl:1-42).  A complete TPU framework needs durable state, so this
module provides it natively:

``save(path, tree)`` / ``load(path)`` checkpoint any pytree containing
DArrays, DDatas, jax.Arrays, numpy arrays, and plain Python values.
DArrays round-trip **with their layout**: dims, chunk grid, cuts and rank
assignment are restored exactly, and shard placement happens at load time
through the same sharding machinery as construction (one device_put
scatter per array).  Storage is a JSON-metadata file plus either a
self-contained ``.npz`` (default) or an Orbax PyTree store
(``save(..., store="orbax")`` — the chunked, multi-host-capable tier);
the layout-metadata format is shared, so both stores restore identically.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any

import numpy as np

import jax

from ..darray import DArray, DData, distribute

__all__ = ["save", "load"]

_META = "dartpu_meta.json"
_ARRS = "arrays.npz"
_ORBAX = "orbax_store"


def _encode(tree, arrays: dict):
    """Recursively replace array-ish leaves with tagged placeholders."""
    if isinstance(tree, DArray):
        key = f"a{len(arrays)}"
        arrays[key] = np.asarray(tree)
        return {"__dartpu__": "DArray", "key": key,
                "procs": [int(p) for p in tree.pids.flat],
                "dist": list(tree.pids.shape),
                "cuts": [list(c) for c in tree.cuts]}
    if isinstance(tree, DData):
        parts = tree.gather()
        enc_parts = [_encode(p, arrays) for p in parts]
        return {"__dartpu__": "DData", "parts": enc_parts,
                "pids": [int(p) for p in tree.pids]}
    if isinstance(tree, (jax.Array, np.ndarray)):
        key = f"a{len(arrays)}"
        host = np.asarray(tree)
        entry = {"__dartpu__": "ndarray", "key": key,
                 "jax": isinstance(tree, jax.Array)}
        import ml_dtypes
        if host.dtype.kind == "V" and hasattr(ml_dtypes, host.dtype.name):
            # ml_dtypes (bfloat16, fp8, ...) don't survive npz round-trips;
            # store raw bytes + the dtype name and re-view at load.
            # (structured void dtypes fall through — npz handles those.)
            entry["mldtype"] = host.dtype.name
            entry["shape"] = list(host.shape)
            host = np.frombuffer(host.tobytes(), dtype=np.uint8)
        arrays[key] = host
        return entry
    if isinstance(tree, dict):
        if all(isinstance(k, str) for k in tree) and \
                not any(k in ("__dartpu__", "__dartpu_store__")
                        for k in tree):
            return {k: _encode(v, arrays) for k, v in tree.items()}
        # non-string keys round-trip via an item-pair encoding (plain JSON
        # would silently stringify them)
        return {"__dartpu__": "dict",
                "items": [[_encode(k, arrays), _encode(v, arrays)]
                          for k, v in tree.items()]}
    if isinstance(tree, (list, tuple)):
        enc = [_encode(v, arrays) for v in tree]
        return {"__dartpu__": "tuple", "items": enc} \
            if isinstance(tree, tuple) else enc
    if isinstance(tree, bool) or tree is None or isinstance(tree, str):
        return tree
    if isinstance(tree, np.generic):
        # preserve the numpy scalar type (float() would corrupt int64/bool_)
        return {"__dartpu__": "npscalar", "dtype": str(tree.dtype),
                "v": tree.item()}
    if isinstance(tree, (int, float)):
        return tree
    raise TypeError(f"cannot checkpoint leaf of type {type(tree)}")


def _restore_darray(tree, arrays):
    host = arrays[tree["key"]]
    procs, dist = tree["procs"], tree["dist"]
    navail = len(jax.devices())
    if any(p >= navail for p in procs):
        import warnings
        warnings.warn(
            f"checkpoint was written on {max(procs) + 1}+ devices but only "
            f"{navail} are available; restoring with the default layout")
        return distribute(host)
    cuts = tree.get("cuts")
    if cuts is not None:
        # restore the exact (possibly uneven / non-default) chunk layout:
        # the saved host array is already assembled, so wrap it directly —
        # one device_put, no chunk split/reassemble round-trip
        from ..darray import darray_from_cuts
        return darray_from_cuts(host, procs, cuts)
    return distribute(host, procs=procs, dist=dist)


def _decode(tree, arrays):
    if isinstance(tree, dict):
        tag = tree.get("__dartpu__")
        if tag == "DArray":
            return _restore_darray(tree, arrays)
        if tag == "npscalar":
            return np.dtype(tree["dtype"]).type(tree["v"])
        if tag == "dict":
            return {_decode(k, arrays): _decode(v, arrays)
                    for k, v in tree["items"]}
        if tag == "ndarray":
            host = arrays[tree["key"]]
            if "mldtype" in tree:
                import ml_dtypes
                dt = np.dtype(getattr(ml_dtypes, tree["mldtype"]))
                host = np.frombuffer(host.tobytes(), dtype=dt).reshape(
                    tree["shape"]).copy()   # frombuffer views are read-only
            return jax.numpy.asarray(host) if tree["jax"] else host
        if tag == "DData":
            from ..darray import DData as _DData
            parts = [_decode(p, arrays) for p in tree["parts"]]
            return _DData(dict(zip(tree["pids"], parts)), tree["pids"])
        if tag == "tuple":
            return tuple(_decode(v, arrays) for v in tree["items"])
        return {k: _decode(v, arrays) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_decode(v, arrays) for v in tree]
    return tree


def save(path: str | os.PathLike, tree: Any, store: str = "npz") -> None:
    """Checkpoint a pytree (DArrays keep their layout metadata).

    ``store``: "npz" (default — single self-contained file pair) or
    "orbax" (Orbax PyTree store: chunked/ocdbt on-disk format, the
    multi-host-capable tier).  The layout metadata format is identical, so
    the two stores are feature-equivalent for restores on one host.
    """
    if store not in ("npz", "orbax"):
        # validate before any side effect (no stray directories/encodes)
        raise ValueError(f"unknown store {store!r} (use 'npz' or 'orbax')")
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    arrays: dict[str, np.ndarray] = {}
    meta = _encode(tree, arrays)
    if store == "orbax" and arrays:
        import orbax.checkpoint as ocp
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save((path / _ORBAX).resolve(), arrays, force=True)
    elif store == "npz":
        np.savez(path / _ARRS, **arrays)
    # (orbax with no array leaves: nothing to store; load mirrors this)
    meta_doc = {"__dartpu_store__": store, "tree": meta}
    (path / _META).write_text(json.dumps(meta_doc))


def load(path: str | os.PathLike) -> Any:
    """Restore a checkpoint (either store); DArrays are re-distributed onto
    their saved chunk grids (default relayout with a warning when fewer
    devices are available than at save time)."""
    path = Path(path)
    meta_doc = json.loads((path / _META).read_text())
    # positive new-format detection: the sentinel key can never be produced
    # by _encode (user dicts containing it are item-pair encoded)
    if isinstance(meta_doc, dict) and "__dartpu_store__" in meta_doc:
        store, meta = meta_doc["__dartpu_store__"], meta_doc["tree"]
    else:                                  # pre-store-field checkpoints
        store, meta = "npz", meta_doc
    if store == "orbax":
        if (path / _ORBAX).exists():
            import orbax.checkpoint as ocp
            with ocp.PyTreeCheckpointer() as ckptr:
                arrays = ckptr.restore((path / _ORBAX).resolve())
        else:                              # array-free checkpoint
            arrays = {}
    else:
        with np.load(path / _ARRS) as z:
            arrays = {k: z[k] for k in z.files}
    return _decode(meta, arrays)
