"""Checkpoint / resume for distributed arrays.

The reference has **no** checkpoint subsystem (SURVEY.md §5: "Checkpoint /
resume: none") — serializing a DArray over Julia's wire just moves ids
(serialize.jl:1-42).  A complete TPU framework needs durable state, so this
module provides it natively:

``save(path, tree)`` / ``load(path)`` checkpoint any pytree containing
DArrays, DDatas, jax.Arrays, numpy arrays, and plain Python values.
DArrays round-trip **with their layout**: dims, chunk grid, cuts and rank
assignment are restored exactly, and shard placement happens at load time
through the same sharding machinery as construction (one device_put
scatter per array).  Storage is a JSON-metadata file plus either a
self-contained ``.npz`` (default) or an Orbax PyTree store
(``save(..., store="orbax")`` — the chunked, multi-host-capable tier);
the layout-metadata format is shared, so both stores restore identically.

``CheckpointManager`` adds the training-loop tier on top: stepped
checkpoints under one directory, **async** saves (device→host snapshot
happens synchronously at ``save()``; serialization and disk IO run on a
background thread so the train loop isn't stalled), atomic publication
(write to a hidden temp dir, rename into place), and ``max_to_keep``
rotation of completed steps.

**Integrity:** every payload array's CRC32 is recorded in the step
metadata at save time and re-verified on restore; a mismatch raises
:class:`CheckpointIntegrityError`.  ``CheckpointManager.restore()``
treats a corrupt step exactly like a partially-published one — it
quarantines the bad step directory (renamed to ``.quarantine_step_*``,
so it never counts as restorable again), journals a
``restore_fallback``, and falls back to the previous verified step.
The ``checkpoint.read`` fault site (action ``corrupt``) flips payload
bytes deterministically so this whole path is chaos-testable.

**Peer replicas:** pass ``replicas=PeerReplicaStore()`` to the manager
and every published step is ALSO replicated chunk-by-chunk into buddy
ranks' memory — each chunk's buddy in a *different* failure domain
(``resilience.domains.buddy_map``), CRC-stamped.  ``restore()`` then
tries the peer replica first and falls back to disk, so a
device-loss/partition recovery runs at interconnect speed and a whole
host's shards survive its loss with zero disk reads (witnessed by the
``checkpoint.disk_reads`` vs ``checkpoint.restore_source`` counters).
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import zlib
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path
from typing import Any

import numpy as np

import jax

from .. import telemetry as _tm
from ..darray import DArray, DData, distribute

__all__ = ["save", "load", "CheckpointManager", "CheckpointIntegrityError",
           "PeerReplicaStore", "PeerReplicaUnavailable"]

_META = "dartpu_meta.json"
_ARRS = "arrays.npz"
_ORBAX = "orbax_store"


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint payload array failed its recorded CRC32 check —
    bytes on disk (or the read path) are corrupt.  ``path`` is the
    checkpoint directory, ``keys`` the failing payload keys."""

    def __init__(self, path, keys: list):
        self.path = str(path)
        self.keys = list(keys)
        super().__init__(
            f"checkpoint {self.path} failed integrity verification: "
            f"payload CRC32 mismatch on {self.keys}")


def _crc_map(arrays: dict) -> dict:
    """Per-payload CRC32 over the at-rest host bytes — the integrity
    metadata stored next to the tree (one pass per array; checkpoint IO
    dominates)."""
    return {k: int(zlib.crc32(np.ascontiguousarray(v).tobytes()))
            for k, v in arrays.items()}


def _verify_integrity(path, meta_doc: dict, arrays: dict) -> None:
    """Check every payload array against the CRC32s recorded at save
    time.  Pre-integrity checkpoints (no ``integrity`` section) pass
    unverified; a key recorded but missing from the payload counts as a
    mismatch (a vanished shard is corruption, not absence)."""
    integ = meta_doc.get("integrity") if isinstance(meta_doc, dict) else None
    if not integ or not isinstance(integ.get("crc32"), dict):
        return
    bad = []
    for key, want in integ["crc32"].items():
        arr = arrays.get(key)
        if arr is None or int(zlib.crc32(
                np.ascontiguousarray(arr).tobytes())) != int(want):
            bad.append(key)
    if bad:
        _tm.count("checkpoint.integrity_failures")
        if _tm.enabled():
            # cold path: a corrupt checkpoint is exceptional by definition
            _tm.event("checkpoint", "integrity_failure", path=str(path),
                      keys=",".join(sorted(bad)[:8]))
        raise CheckpointIntegrityError(path, sorted(bad))


def _encode(tree, arrays: dict, copy: bool = False):
    """Recursively replace array-ish leaves with tagged placeholders.

    ``copy=True`` decouples plain numpy leaves from caller-owned buffers
    (async checkpointing); device-sourced leaves (DArray/jax.Array) already
    materialize fresh host arrays and are never re-copied."""
    if isinstance(tree, DArray):
        key = f"a{len(arrays)}"
        # probe the at-rest physical buffer (`_data`), NOT `.garray` —
        # for padded layouts garray runs the compiled unpad program, and
        # the addressability answer is the same
        if getattr(tree._data.sharding, "is_fully_addressable", True):
            arrays[key] = np.asarray(tree)
        else:
            # multi-controller: the array spans processes — assemble via
            # the DCN gather (every process calls save in SPMD style,
            # like the reference's master-side checkpoint gather)
            from ..parallel.multihost import gather_global
            arrays[key] = gather_global(tree)
        return {"__dartpu__": "DArray", "key": key,
                "procs": [int(p) for p in tree.pids.flat],
                "dist": list(tree.pids.shape),
                "cuts": [list(c) for c in tree.cuts]}
    if isinstance(tree, DData):
        parts = tree.gather()
        enc_parts = [_encode(p, arrays, copy) for p in parts]
        return {"__dartpu__": "DData", "parts": enc_parts,
                "pids": [int(p) for p in tree.pids]}
    if isinstance(tree, (jax.Array, np.ndarray)):
        key = f"a{len(arrays)}"
        host = np.asarray(tree)
        if copy and host is tree:   # numpy leaf aliasing caller memory
            host = host.copy()
        entry = {"__dartpu__": "ndarray", "key": key,
                 "jax": isinstance(tree, jax.Array)}
        import ml_dtypes
        if host.dtype.kind == "V" and hasattr(ml_dtypes, host.dtype.name):
            # ml_dtypes (bfloat16, fp8, ...) don't survive npz round-trips;
            # store raw bytes + the dtype name and re-view at load.
            # (structured void dtypes fall through — npz handles those.)
            entry["mldtype"] = host.dtype.name
            entry["shape"] = list(host.shape)
            host = np.frombuffer(host.tobytes(), dtype=np.uint8)
        arrays[key] = host
        return entry
    if isinstance(tree, dict):
        if all(isinstance(k, str) for k in tree) and \
                not any(k in ("__dartpu__", "__dartpu_store__")
                        for k in tree):
            return {k: _encode(v, arrays, copy) for k, v in tree.items()}
        # non-string keys round-trip via an item-pair encoding (plain JSON
        # would silently stringify them)
        return {"__dartpu__": "dict",
                "items": [[_encode(k, arrays, copy),
                           _encode(v, arrays, copy)]
                          for k, v in tree.items()]}
    if isinstance(tree, (list, tuple)):
        enc = [_encode(v, arrays, copy) for v in tree]
        return {"__dartpu__": "tuple", "items": enc} \
            if isinstance(tree, tuple) else enc
    if isinstance(tree, bool) or tree is None or isinstance(tree, str):
        return tree
    if isinstance(tree, np.generic):
        # preserve the numpy scalar type (float() would corrupt int64/bool_)
        return {"__dartpu__": "npscalar", "dtype": str(tree.dtype),
                "v": tree.item()}
    if isinstance(tree, (int, float)):
        return tree
    raise TypeError(f"cannot checkpoint leaf of type {type(tree)}")


def _restore_darray(tree, arrays):
    host = arrays[tree["key"]]
    procs, dist = tree["procs"], tree["dist"]
    navail = len(jax.devices())
    if any(p >= navail for p in procs):
        import warnings
        warnings.warn(
            f"checkpoint was written on {max(procs) + 1}+ devices but only "
            f"{navail} are available; restoring with the default layout")
        return distribute(host)
    cuts = tree.get("cuts")
    if cuts is not None:
        # restore the exact (possibly uneven / non-default) chunk layout:
        # the saved host array is already assembled, so wrap it directly —
        # one device_put, no chunk split/reassemble round-trip
        from ..darray import darray_from_cuts
        return darray_from_cuts(host, procs, cuts)
    return distribute(host, procs=procs, dist=dist)


def _decode(tree, arrays):
    if isinstance(tree, dict):
        tag = tree.get("__dartpu__")
        if tag == "DArray":
            return _restore_darray(tree, arrays)
        if tag == "npscalar":
            return np.dtype(tree["dtype"]).type(tree["v"])
        if tag == "dict":
            return {_decode(k, arrays): _decode(v, arrays)
                    for k, v in tree["items"]}
        if tag == "ndarray":
            host = arrays[tree["key"]]
            if "mldtype" in tree:
                import ml_dtypes
                dt = np.dtype(getattr(ml_dtypes, tree["mldtype"]))
                host = np.frombuffer(host.tobytes(), dtype=dt).reshape(
                    tree["shape"]).copy()   # frombuffer views are read-only
            return jax.numpy.asarray(host) if tree["jax"] else host
        if tag == "DData":
            from ..darray import DData as _DData
            parts = [_decode(p, arrays) for p in tree["parts"]]
            return _DData(dict(zip(tree["pids"], parts)), tree["pids"])
        if tag == "tuple":
            return tuple(_decode(v, arrays) for v in tree["items"])
        return {k: _decode(v, arrays) for k, v in tree.items()}
    if isinstance(tree, list):
        return [_decode(v, arrays) for v in tree]
    return tree


def _read_faults(path, store: str, arrays: dict) -> dict:
    """The ``checkpoint.read`` injection site: a fired ``corrupt`` spec
    flips payload bytes (seeded — :func:`faults.corrupt_arrays`); any
    other action runs normally (``raise``/``device_loss``/``hang`` model
    a failing storage read)."""
    from ..resilience import faults as _fl
    spec = _fl.decide("checkpoint.read", store=store, path=str(path))
    if spec is None:
        return arrays
    if spec.action == "corrupt":
        return _fl.corrupt_arrays(spec, arrays)
    _fl.act(spec, {"store": store, "path": str(path)})
    return arrays


def save(path: str | os.PathLike, tree: Any, store: str = "npz") -> None:
    """Checkpoint a pytree (DArrays keep their layout metadata).

    ``store``: "npz" (default — single self-contained file pair) or
    "orbax" (Orbax PyTree store: chunked/ocdbt on-disk format, the
    multi-host-capable tier).  The layout metadata format is identical, so
    the two stores are feature-equivalent for restores on one host.
    """
    if store not in ("npz", "orbax"):
        # validate before any side effect (no stray directories/encodes)
        raise ValueError(f"unknown store {store!r} (use 'npz' or 'orbax')")
    with _tm.span("checkpoint.save", store=store):
        # cold path: checkpoint I/O dominates the event cost
        _tm.event("checkpoint", "save_start", path=str(path),  # dalint: disable=DAL003
                  store=store)
        arrays: dict[str, np.ndarray] = {}
        with _tm.span("checkpoint.save.encode", _journal=False):
            meta = _encode(tree, arrays)
        if _tm.enabled():
            from ..telemetry import perf as _perf
            # cost stamp on the checkpoint.save span: payload bytes
            # through the host once (disk I/O rides the HBM column)
            _tm.annotate(**_perf.transfer_cost(
                sum(a.nbytes for a in arrays.values())))
        with _tm.span("checkpoint.save.write", _journal=False):
            _write_store(Path(path), meta, arrays, store)
        _tm.count("checkpoint.saves")
        # cold path: checkpoint I/O dominates the event cost
        _tm.event("checkpoint", "save_end", path=str(path),  # dalint: disable=DAL003
                  store=store, arrays=len(arrays),
                  bytes=int(sum(a.nbytes for a in arrays.values())))
        # HBM-ledger phase boundary on the Perfetto counter track
        _tm.memory.sample("checkpoint.save")


def load(path: str | os.PathLike) -> Any:
    """Restore a checkpoint (either store); DArrays are re-distributed onto
    their saved chunk grids (default relayout with a warning when fewer
    devices are available than at save time)."""
    path = Path(path)
    with _tm.span("checkpoint.restore"):
        # the zero-disk-reads witness for peer-replica restores: every
        # on-disk load counts here, a replica fetch never reaches this
        _tm.count("checkpoint.disk_reads")
        # cold path: checkpoint I/O dominates the event cost
        _tm.event("checkpoint", "restore_start", path=str(path))  # dalint: disable=DAL003
        meta_doc = json.loads((path / _META).read_text())
        # positive new-format detection: the sentinel key can never be
        # produced by _encode (user dicts containing it are item-pair
        # encoded)
        if isinstance(meta_doc, dict) and "__dartpu_store__" in meta_doc:
            store, meta = meta_doc["__dartpu_store__"], meta_doc["tree"]
        else:                                  # pre-store-field checkpoints
            store, meta = "npz", meta_doc
        with _tm.span("checkpoint.restore.read", _journal=False):
            if store == "orbax":
                if (path / _ORBAX).exists():
                    import orbax.checkpoint as ocp
                    with ocp.PyTreeCheckpointer() as ckptr:
                        arrays = ckptr.restore((path / _ORBAX).resolve())
                else:                          # array-free checkpoint
                    arrays = {}
            else:
                with np.load(path / _ARRS) as z:
                    arrays = {k: z[k] for k in z.files}
        # chaos site: an armed plan can corrupt (or fail) the payload
        # read — byte flips applied HERE, before verification, so the
        # integrity check is what catches them, exactly like real disk
        # rot would be caught
        arrays = _read_faults(path, store, arrays)
        _verify_integrity(path, meta_doc, arrays)
        with _tm.span("checkpoint.restore.decode", _journal=False):
            out = _decode(meta, arrays)
        if _tm.enabled():
            from ..telemetry import perf as _perf
            # cost stamp mirroring save's: restored payload bytes
            _tm.annotate(**_perf.transfer_cost(
                sum(a.nbytes for a in arrays.values())))
        _tm.count("checkpoint.restores")
        # cold path: checkpoint I/O dominates the event cost
        _tm.event("checkpoint", "restore_end", path=str(path),  # dalint: disable=DAL003
                  store=store, arrays=len(arrays),
                  bytes=int(sum(a.nbytes for a in arrays.values())))
        # HBM-ledger phase boundary on the Perfetto counter track
        _tm.memory.sample("checkpoint.restore")
        return out


def _write_store(path: Path, meta, arrays, store: str) -> None:
    """Serialize one already-encoded checkpoint into ``path`` (the single
    body behind both save() and CheckpointManager publication).

    The metadata file is written LAST: its presence is the publish
    marker, so an interruption between the payload write and here leaves
    a *partial* directory that ``CheckpointManager.steps()`` ignores and
    ``restore()`` falls back past."""
    path.mkdir(parents=True, exist_ok=True)
    if store == "orbax" and arrays:
        import orbax.checkpoint as ocp
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save((path / _ORBAX).resolve(), arrays, force=True)
    elif store == "npz":
        np.savez(path / _ARRS, **arrays)
    # chaos site: an armed fault plan can kill the write here — payload
    # on disk, publish marker absent — the "interrupted checkpoint"
    # failure the restore fallback must survive
    from ..resilience import faults as _fl
    _fl.check("checkpoint.write", store=store)
    # (orbax with no array leaves: nothing to store; load mirrors this)
    (path / _META).write_text(
        json.dumps({"__dartpu_store__": store, "tree": meta,
                    "integrity": {"algo": "crc32",
                                  "crc32": _crc_map(arrays)}}))


class PeerReplicaUnavailable(RuntimeError):
    """No live rank holds a needed replica chunk — both its owner and
    its buddy holder are down (e.g. a partition took two domains at
    once).  The restore path falls back to disk past this."""

    def __init__(self, step: int, key: str, chunk: int,
                 owner: int, holder: int):
        self.step, self.key, self.chunk = int(step), str(key), int(chunk)
        super().__init__(
            f"peer replica for step {step} chunk {key}[{chunk}] is gone: "
            f"owner rank {owner} and holder rank {holder} are both down")


def _darray_entries(meta) -> dict:
    """Every encoded-DArray placeholder in a checkpoint tree, by payload
    key — the chunk layout (procs/dist/cuts) peer replication shards by."""
    out: dict = {}

    def walk(t):
        if isinstance(t, dict):
            if t.get("__dartpu__") == "DArray":
                out[t["key"]] = t
                return
            for v in t.values():
                walk(v)
        elif isinstance(t, list):
            for v in t:
                walk(v)
    walk(meta)
    return out


def _chunk_slices(entry: dict) -> list:
    """Per-block ``(owner_rank, index_slices)`` for one encoded DArray,
    in the block grid's row-major order — the unit peer replication
    copies, exactly the bytes that rank's device held."""
    grid = tuple(int(x) for x in entry["dist"])
    procs = [int(p) for p in entry["procs"]]
    cuts = entry["cuts"]
    out = []
    for j, owner in enumerate(procs):
        coords = np.unravel_index(j, grid) if grid else ()
        sl = tuple(slice(int(cuts[d][c]), int(cuts[d][c + 1]))
                   for d, c in enumerate(coords))
        out.append((owner, sl))
    return out


class PeerReplicaStore:
    """In-memory peer replicas of checkpoint payloads, placed by failure
    domain.

    The single-controller model of per-host RAM replication: at publish
    time every payload chunk is copied into its owner rank's *buddy*
    rank (``resilience.domains.buddy_map`` — a different failure domain
    whenever two domains are live), CRC-stamped per chunk.  A later
    :meth:`fetch` reassembles the step from chunks whose owner is still
    live ("local") or whose holder is ("peer" — the over-the-wire pull),
    so a whole domain's loss costs zero disk reads; only when BOTH sides
    of a chunk are down does the restore fall back to disk.  On a real
    multi-controller deployment the same placement map drives RDMA copies
    between hosts; the store's accounting (owner/holder/CRC per chunk) is
    identical.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # step -> {"meta": tree, "keys": {key: (shape, dtype)},
        #          "chunks": {(key, j): {owner, holder, data, crc, slices}}}
        self._steps: dict[int, dict] = {}

    # -- placement ---------------------------------------------------------

    def put(self, step: int, meta, arrays: dict,
            live_ranks=None) -> dict:
        """Replicate one encoded checkpoint into buddy memory.  Returns
        ``{"chunks": n, "bytes": n, "cross_domain": bool}``."""
        from ..resilience import domains as _dm
        if live_ranks is None:
            from ..resilience import elastic as _el
            live_ranks = _el.manager().live_ranks()
        live = sorted({int(r) for r in live_ranks})
        bmap = _dm.buddy_map(live)
        dents = _darray_entries(meta)
        chunks: dict = {}
        keys: dict = {}
        total = 0
        for key, arr in arrays.items():
            host = np.ascontiguousarray(arr)
            keys[key] = (tuple(host.shape), host.dtype.str)
            if key in dents:
                parts = _chunk_slices(dents[key])
            else:
                # plain (replicated) leaf: one chunk, conceptually owned
                # by the first live rank's host
                parts = [(live[0] if live else 0,
                          tuple(slice(0, n) for n in host.shape))]
            for j, (owner, sl) in enumerate(parts):
                data = host[sl].tobytes()
                total += len(data)
                chunks[(key, j)] = {
                    "owner": int(owner),
                    "holder": int(bmap.get(int(owner), int(owner))),
                    "data": data,
                    "crc": int(zlib.crc32(data)),
                    "slices": [(s.start, s.stop) for s in sl],
                }
        # a JSON round-trip decouples the stored tree from caller-owned
        # (and possibly later-mutated) metadata structures
        rec = {"meta": json.loads(json.dumps(meta)), "keys": keys,
               "chunks": chunks}
        with self._lock:
            self._steps[int(step)] = rec
        _tm.count("checkpoint.replications")
        if _tm.enabled():
            # cold path: one event per replicated step
            _tm.event("checkpoint", "replicate", step=int(step),
                      chunks=len(chunks), bytes=total,
                      cross_domain=_dm.is_cross_domain(bmap))
        return {"chunks": len(chunks), "bytes": total,
                "cross_domain": _dm.is_cross_domain(bmap)}

    # -- retrieval ---------------------------------------------------------

    def fetch(self, step: int, live_ranks=None):
        """Reassemble ``(meta, arrays, info)`` for ``step`` from replica
        chunks reachable through live ranks.  Raises ``KeyError`` when
        the step was never replicated, :class:`PeerReplicaUnavailable`
        when a chunk's owner AND holder are both down, and
        :class:`CheckpointIntegrityError` on a per-chunk CRC mismatch."""
        with self._lock:
            rec = self._steps.get(int(step))
            if rec is None:
                raise KeyError(f"no peer replica for step {step}")
        if live_ranks is None:
            from ..resilience import elastic as _el
            live_ranks = _el.manager().live_ranks()
        live = {int(r) for r in live_ranks}
        arrays: dict[str, np.ndarray] = {}
        for key, (shape, dstr) in rec["keys"].items():
            arrays[key] = np.empty(shape, dtype=np.dtype(dstr))
        n_local = n_peer = 0
        bad: list[str] = []
        for (key, j), ch in rec["chunks"].items():
            if ch["owner"] in live:
                n_local += 1
            elif ch["holder"] in live:
                n_peer += 1
            else:
                raise PeerReplicaUnavailable(step, key, j, ch["owner"],
                                             ch["holder"])
            if int(zlib.crc32(ch["data"])) != ch["crc"]:
                bad.append(key)
                continue
            sl = tuple(slice(a, b) for a, b in ch["slices"])
            dst = arrays[key]
            cshape = tuple(b - a for a, b in ch["slices"])
            dst[sl] = np.frombuffer(
                ch["data"], dtype=dst.dtype).reshape(cshape)
        if bad:
            _tm.count("checkpoint.integrity_failures")
            raise CheckpointIntegrityError(f"<peer replica step {step}>",
                                           sorted(set(bad)))
        if n_peer:
            _tm.count("checkpoint.peer_fetches", n=n_peer)
        info = {"local_chunks": n_local, "peer_chunks": n_peer}
        if _tm.enabled():
            # cold path: one event per replica restore
            _tm.event("checkpoint", "replica_fetch", step=int(step),
                      **info)
        return rec["meta"], arrays, info

    # -- inventory ---------------------------------------------------------

    def steps(self) -> list[int]:
        with self._lock:
            return sorted(self._steps)

    def drop(self, step: int) -> None:
        with self._lock:
            self._steps.pop(int(step), None)

    def drop_from(self, step: int) -> list[int]:
        with self._lock:
            dropped = sorted(s for s in self._steps if s >= int(step))
            for s in dropped:
                del self._steps[s]
        return dropped

    def clear(self) -> None:
        with self._lock:
            self._steps.clear()


class CheckpointManager:
    """Stepped checkpoints with async save and ``max_to_keep`` rotation.

    The reference has no checkpoint subsystem at all (SURVEY.md §5); this
    is the training-loop tier a TPU framework needs.  Usage::

        with CheckpointManager(dir, max_to_keep=3) as mgr:
            for step in range(...):
                ...
                mgr.save(step, {"params": params, "opt": opt_state})
        state = CheckpointManager(dir).restore()        # latest step

    ``save`` snapshots device state to host *synchronously* (so the train
    loop may mutate/donate its arrays immediately) and hands
    serialization + disk IO to one background thread; steps are written
    to a hidden temp directory and renamed into place, so readers never
    observe a partial checkpoint, and a crash mid-save leaves the
    previous steps intact.  Rotation deletes the oldest completed steps
    beyond ``max_to_keep`` after each successful save.
    """

    _STEP = "step_{:08d}"

    def __init__(self, directory: str | os.PathLike,
                 max_to_keep: int | None = 3, async_save: bool = True,
                 keep_quarantined: int | None = 4,
                 replicas: PeerReplicaStore | None = None):
        if max_to_keep is not None and max_to_keep < 1:
            raise ValueError(f"max_to_keep must be >= 1, got {max_to_keep}")
        if keep_quarantined is not None and keep_quarantined < 0:
            raise ValueError(f"keep_quarantined must be >= 0, got "
                             f"{keep_quarantined}")
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        # quarantined (corrupt) step dirs kept for forensics; older ones
        # are reaped during save so they cannot accumulate forever
        # (None = keep all)
        self.keep_quarantined = keep_quarantined
        # peer replica tier: replicate each published step into buddy
        # memory and restore from there first (None = disk only)
        self._replicas = replicas
        self._async = bool(async_save)
        self._pool = (ThreadPoolExecutor(max_workers=1,
                                         thread_name_prefix="ckpt")
                      if self._async else None)
        self._pending: dict[int, Any] = {}   # step -> in-flight future
        self._lock = threading.Lock()

    # -- inventory ---------------------------------------------------------

    def steps(self) -> list[int]:
        """Completed (published) step numbers, ascending."""
        out = []
        for p in self.directory.iterdir():
            name = p.name
            if p.is_dir() and name.startswith("step_") and \
                    name[5:].isdigit() and (p / _META).exists():
                out.append(int(name[5:]))
        return sorted(out)

    def latest_step(self) -> int | None:
        s = self.steps()
        return s[-1] if s else None

    def _step_dir(self, step: int) -> Path:
        return self.directory / self._STEP.format(step)

    # -- save --------------------------------------------------------------

    def save(self, step: int, tree: Any, store: str = "npz") -> None:
        """Checkpoint ``tree`` as ``step``.  Device→host transfer happens
        before this returns; IO happens in the background (async mode)."""
        if store not in ("npz", "orbax"):
            raise ValueError(f"unknown store {store!r} (use 'npz'/'orbax')")
        with self._lock:
            self._reap(wait=False)  # dalint: disable=DAL008 — wait=False reaps only done() futures; result() returns immediately
            # pending/reserved steps count as existing: a duplicate racing
            # an in-flight (or concurrently-encoding) save must get this
            # ValueError, not a later os.replace failure from the
            # background thread — so the step is RESERVED here, inside the
            # same lock section as the check
            if step in self.steps() or step in self._pending:
                raise ValueError(f"step {step} already exists in "
                                 f"{self.directory}")
            self._pending[step] = None          # reservation
        try:
            arrays: dict[str, np.ndarray] = {}
            # copy=True decouples plain numpy leaves from caller-owned
            # buffers (device leaves already materialize fresh host arrays)
            meta = _encode(tree, arrays, copy=True)
            if self._pool is None:
                self._publish(step, meta, arrays, store)
                with self._lock:
                    self._pending.pop(step, None)
                return
            with self._lock:
                self._pending[step] = self._pool.submit(
                    self._publish, step, meta, arrays, store)
        except BaseException:
            with self._lock:
                self._pending.pop(step, None)
            raise

    def _publish(self, step: int, meta, arrays, store: str) -> None:
        # peer replication FIRST (it is memory-speed; the disk write
        # dominates), so a crash mid-write still leaves the in-memory
        # replica restorable.  Best-effort: a replication failure must
        # never lose the durable tier.
        if self._replicas is not None:
            try:
                self._replicas.put(step, meta, arrays)
            except Exception as e:  # noqa: BLE001 — disk tier still publishes
                _tm.count("checkpoint.replication_failures")
                if _tm.enabled():
                    # cold path: a failed replication is exceptional
                    _tm.event("checkpoint", "replication_failure",
                              step=step,
                              error=f"{type(e).__name__}: {str(e)[:200]}")
        final = self._step_dir(step)
        tmp = self.directory / f".tmp_{self._STEP.format(step)}"
        if tmp.exists():
            shutil.rmtree(tmp)
        _write_store(tmp, meta, arrays, store)
        os.replace(tmp, final)
        # event from the background save thread — the journal is
        # thread-safe, and the publish time is the phase worth seeing
        _tm.count("checkpoint.saves")
        # cold path: the atomic-publish rename dominates the event cost
        _tm.event("checkpoint", "publish", step=step, store=store,  # dalint: disable=DAL003
                  arrays=len(arrays),
                  bytes=int(sum(a.nbytes for a in arrays.values())))
        self._rotate()
        self._reap_quarantine()

    def _rotate(self) -> None:
        if self.max_to_keep is None:
            return
        done = self.steps()
        for s in done[:max(0, len(done) - self.max_to_keep)]:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
            if self._replicas is not None:
                self._replicas.drop(s)
        if self._replicas is not None:
            # replica-only steps (disk write failed) rotate on the same
            # census, or the memory tier would grow unboundedly
            reps = self._replicas.steps()
            for s in reps[:max(0, len(reps) - self.max_to_keep)]:
                self._replicas.drop(s)

    def _reap_quarantine(self) -> None:
        """Bound the ``.quarantine_step_*`` forensic stash: keep the
        newest ``keep_quarantined`` (by step, which the zero-padded name
        sorts), reap the rest oldest-first, journaling each reap."""
        if self.keep_quarantined is None:
            return
        quarantined = sorted(
            p for p in self.directory.iterdir()
            if p.is_dir() and p.name.startswith(".quarantine_step_"))
        for p in quarantined[:max(0,
                                  len(quarantined) - self.keep_quarantined)]:
            shutil.rmtree(p, ignore_errors=True)
            _tm.count("checkpoint.quarantine_reaps")
            if _tm.enabled():
                # cold path: reaping is rarer than quarantining
                _tm.event("checkpoint", "quarantine_reap", path=p.name)

    def _reap(self, wait: bool) -> None:
        still, first_exc = {}, None
        for step, fut in self._pending.items():
            if fut is None:          # reserved by a save() mid-encode
                still[step] = fut
            elif fut.done() or wait:
                try:
                    fut.result()
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    # the failed future must still leave _pending, or the
                    # manager wedges: every later call would re-raise this
                    # and the step could never be retried
                    if first_exc is None:
                        first_exc = e
            else:
                still[step] = fut
        self._pending = still
        if first_exc is not None:
            raise first_exc

    # -- restore / lifecycle ----------------------------------------------

    def _restore_replica(self, step: int):
        """Try the peer-replica tier for one step.  Returns the decoded
        tree, or None when no replica exists / the replica cannot serve
        (chunk owners+holders all down, CRC mismatch) — the caller falls
        back to disk.  A CRC-bad replica is evicted like a quarantined
        disk step (the bytes are provably wrong forever)."""
        if self._replicas is None:
            return None
        try:
            meta, arrays, info = self._replicas.fetch(step)
            out = _decode(meta, arrays)
        except KeyError:
            return None                      # never replicated: not a fault
        except Exception as e:  # noqa: BLE001 — disk tier is the fallback
            _tm.count("checkpoint.replica_fallbacks")
            if _tm.enabled():
                # cold path: an unservable replica is exceptional
                _tm.event("checkpoint", "replica_fallback", step=step,
                          error=f"{type(e).__name__}: {str(e)[:200]}")
            if isinstance(e, CheckpointIntegrityError):
                self._replicas.drop(step)
            return None
        _tm.count("checkpoint.restore_source", source="peer")
        _tm.count("checkpoint.restores")
        if _tm.enabled():
            # cold path: one event per restore
            _tm.event("checkpoint", "restore_peer", step=step, **info)
        return out

    def restore(self, step: int | None = None) -> Any:
        """Load ``step``; with no step given, the latest *restorable*
        one.  With a peer-replica store attached the replica tier is
        tried FIRST (memory/interconnect speed, zero disk reads —
        ``checkpoint.restore_source`` records which tier served); disk
        is the fallback.  A partially-published step directory — no
        publish marker (``steps()`` already skips those), or a marker
        whose payload is missing/corrupt (a crash or fault mid-write) —
        is skipped with a journaled fallback to the previous complete
        step instead of raising mid-restore; an explicitly requested
        ``step`` stays strict on the disk tier."""
        self.wait()
        if step is not None:
            out = self._restore_replica(step)
            if out is not None:
                return out
            d = self._step_dir(step)
            if not (d / _META).exists():
                raise FileNotFoundError(f"no checkpoint for step {step} in "
                                        f"{self.directory}")
            out = load(d)
            _tm.count("checkpoint.restore_source", source="disk")
            if _tm.enabled():
                # cold path: one event per restore — the disk-tier twin
                # of restore_peer, so incident reconstruction names which
                # tier actually served
                _tm.event("checkpoint", "restore_disk", step=step)
            return out
        done = self.steps()
        rep_steps = self._replicas.steps() if self._replicas is not None \
            else []
        candidates = sorted(set(done) | set(rep_steps))
        if not candidates:
            raise FileNotFoundError(
                f"no completed checkpoints in {self.directory}")
        last_exc: BaseException | None = None
        for s in reversed(candidates):
            out = self._restore_replica(s)
            if out is not None:
                return out
            if s not in done:
                continue                     # replica-only step: no disk dir
            try:
                out = load(self._step_dir(s))
                _tm.count("checkpoint.restore_source", source="disk")
                if _tm.enabled():
                    # cold path: one event per restore (see above)
                    _tm.event("checkpoint", "restore_disk", step=s)
                return out
            except Exception as e:  # noqa: BLE001 — fall back, then re-raise
                last_exc = e
                _tm.count("checkpoint.restore_fallbacks")
                if _tm.enabled():
                    # cold path: a partial/corrupt step is exceptional
                    _tm.event("checkpoint", "restore_fallback",
                              step=s, error=f"{type(e).__name__}: "
                                            f"{str(e)[:200]}")
                if isinstance(e, CheckpointIntegrityError):
                    # bytes on disk are provably bad: quarantine the step
                    # so no later restore (or rotation census) ever
                    # trusts it again — partial steps merely fall back,
                    # corrupt ones are evicted
                    self._quarantine(s)
        raise FileNotFoundError(
            f"no restorable checkpoint in {self.directory}: every "
            f"completed step failed to load") from last_exc

    def _quarantine(self, step: int) -> None:
        """Move a corrupt step directory to a hidden ``.quarantine_*``
        name: it stops counting as a completed step (``steps()`` only
        sees ``step_*``) but stays on disk for forensics."""
        src = self._step_dir(step)
        dst = self.directory / f".quarantine_{src.name}"
        try:
            if dst.exists():
                shutil.rmtree(dst)
            os.replace(src, dst)
        except OSError:
            # a quarantine that cannot rename still must not block the
            # fallback restore; the step will fail integrity again next
            # time and re-enter here
            return
        _tm.count("checkpoint.quarantines")
        if _tm.enabled():
            # cold path: quarantining a corrupt step is exceptional
            _tm.event("checkpoint", "quarantine", step=step,
                      path=str(dst))

    def discard_from(self, step: int) -> list[int]:
        """Delete every published step ``>= step`` (and drain pending
        saves first).  The timeline-rewind primitive: a trainer that
        restored step ``S`` and is about to recompute forward must
        discard the now-stale later steps, or a future restore could
        resurrect state from the abandoned timeline (e.g. a pre-shrink
        device layout).  Returns the discarded step numbers."""
        self.wait()
        dropped = [s for s in self.steps() if s >= step]
        for s in dropped:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
        if self._replicas is not None:
            # the memory tier rewinds with the disk tier, or a future
            # peer-first restore would resurrect the abandoned timeline
            dropped = sorted(set(dropped)
                             | set(self._replicas.drop_from(step)))
        if dropped and _tm.enabled():
            # cold path: a timeline rewind is a recovery-path event
            _tm.event("checkpoint", "discard_from", step=step,
                      dropped=len(dropped))
        return dropped

    def wait(self) -> None:
        """Block until every pending async save has been published (and
        re-raise the first background failure, if any)."""
        with self._lock:
            self._reap(wait=True)  # dalint: disable=DAL008 — wait() IS the quiesce API: holding the lock while IO drains is its contract (no save may interleave)

    def close(self) -> None:
        self.wait()
        if self._pool is not None:
            self._pool.shutdown(wait=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
