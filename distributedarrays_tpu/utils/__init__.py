from . import checkpoint, debug, native, profiling  # noqa: F401
from .checkpoint import load, save
from .profiling import OpTimer, annotate, trace
