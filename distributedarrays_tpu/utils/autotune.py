"""Block-size autotuning registry for the Pallas kernels.

The hand-written kernels (flash attention, GEMM) take block-size knobs
whose best values depend on shape, dtype, and chip generation — measured
on a v5e, causal 8k flash attention runs ~20x faster at 1024² blocks than
at 128².  The reference has no analog (its hot loops are BLAS calls); this
is the TPU-native tuning surface.

Three pieces:

- a process-global registry mapping ``(kernel, key) -> config`` that the
  kernels consult when their block arguments are left ``None``;
- ``sweep(...)``: time a list of candidate configs with an injectable
  timer and record the winner;
- optional JSON persistence (``save``/``load``) so a one-off tuning run
  (bench.py's hardware sweep, or a user-driven ``sweep``) carries across
  processes via the ``DAT_AUTOTUNE_CACHE`` env var, loaded lazily on
  first lookup.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Iterable, Mapping

from .. import telemetry as _tm

__all__ = ["get", "record", "sweep", "save", "load", "clear", "key_for",
           "device_key_for", "valid_ints",
           "default_cache_path", "save_default", "seed_path"]

_LOCK = threading.RLock()
_REGISTRY: dict[str, dict[str, Any]] = {}
_LOADED_ENV = False


def key_for(*parts) -> str:
    """Canonical string key from shape/dtype/flag parts."""
    return "|".join(str(p) for p in parts)


def device_key_for(*parts) -> str:
    """``key_for`` with the default device's platform and kind appended.
    Every kernel-tuning registry (flash blocks, ring hop blocks, GEMM
    tiles, impl choices) keys through this: a winner measured on one
    platform (CPU/interpret validation run, v4, v5e...) must never drive
    dispatch on another, even through the shared persisted cache
    (ADVICE round-4)."""
    import jax
    dev = jax.devices()[0]
    return key_for(*parts, dev.platform, dev.device_kind)


def valid_ints(entry, lengths: tuple[int, ...]):
    """Parse a registry entry as a tuple of positive ints of an accepted
    length, or None — a stale/hand-edited/malformed cache entry must
    degrade to the caller's default, never break dispatch.  Shared by
    every kernel that stores block tuples."""
    if not isinstance(entry, (list, tuple)):
        return None      # a string would "parse" via its characters
    try:
        vals = [int(x) for x in entry]
        if len(vals) in lengths and all(v > 0 for v in vals):
            return tuple(vals)
    except Exception:
        pass
    return None


def default_cache_path() -> str:
    """Where tuning results persist across processes: the
    ``DAT_AUTOTUNE_CACHE`` env var if set; in a repo CHECKOUT, an
    ``AUTOTUNE_CACHE.json`` next to the package (gitignored) so bench.py's
    hardware sweep is picked up by every later process in the same tree;
    for an installed package, a per-user cache dir (never site-packages,
    which may be read-only or shared across unrelated projects)."""
    env = os.environ.get("DAT_AUTOTUNE_CACHE")
    if env:
        return env
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # .git is a directory in a normal clone, a FILE in worktrees/submodules
    if os.path.exists(os.path.join(pkg_parent, ".git")):
        return os.path.join(pkg_parent, "AUTOTUNE_CACHE.json")
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "distributedarrays_tpu",
                        "AUTOTUNE_CACHE.json")


def save_default() -> str:
    """Persist the registry to ``default_cache_path()``; returns the path."""
    path = default_cache_path()
    save(path)
    return path


def seed_path() -> str:
    """The TRACKED seed registry (``AUTOTUNE_SEED.json`` at the repo
    root): winners measured on real hardware and committed, so a fresh
    checkout dispatches to measured configs out of the box instead of
    waiting for the user's first tune (VERDICT round-4 weak 3).  Keys
    are device-fenced via ``device_key_for``, so entries for other
    platforms are inert; the live cache overrides the seed on
    collision."""
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "AUTOTUNE_SEED.json")


def _maybe_load_env():
    global _LOADED_ENV
    if _LOADED_ENV:
        return
    _LOADED_ENV = True
    seed = seed_path()
    if os.path.exists(seed):
        try:
            load(seed)
        except Exception:
            pass  # a corrupt seed must never break kernel dispatch
    path = default_cache_path()
    if path and os.path.exists(path):
        try:
            load(path)     # live measurements override the seed
        except Exception:
            pass  # a corrupt cache must never break kernel dispatch


_MISS = object()


def get(kernel: str, key: str, default=None):
    """Tuned config for ``(kernel, key)``, or ``default``.

    Every lookup is counted (telemetry ``autotune.hit`` / ``autotune.miss``
    per kernel); the first miss per (kernel, key) is journaled, so a
    workload silently dispatching on heuristic defaults is queryable."""
    with _LOCK:
        _maybe_load_env()
        entry = _REGISTRY.get(kernel, {}).get(key, _MISS)
    if entry is _MISS:
        _tm.count("autotune.miss", kernel=kernel)
        # per-dispatch lookup path: the once_key f-string must not be
        # built in disabled mode
        if _tm.enabled():
            _tm.event("autotune", "miss", kernel=kernel, key=key,
                      once_key=f"autotune:miss:{kernel}:{key}")
        return default
    _tm.count("autotune.hit", kernel=kernel)
    return entry


def record(kernel: str, key: str, config) -> None:
    with _LOCK:
        _maybe_load_env()
        _REGISTRY.setdefault(kernel, {})[key] = config


def clear() -> None:
    with _LOCK:
        _REGISTRY.clear()


def save(path: str) -> None:
    with _LOCK:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_REGISTRY, f, indent=2, sort_keys=True)
        os.replace(tmp, path)


def load(path: str) -> None:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"autotune cache {path} is not a JSON object")
    with _LOCK:
        for kernel, entries in data.items():
            _REGISTRY.setdefault(kernel, {}).update(entries)


def sweep(kernel: str, key: str, candidates: Iterable,
          timer: Callable[[Any], float],
          record_best: bool = True,
          persist: bool = False) -> tuple[Any, Mapping[Any, float]]:
    """Time every candidate config with ``timer(config) -> seconds``
    (lower is better), record the winner in the registry, and return
    ``(best_config, {config: seconds})``.

    A candidate whose timer raises is skipped (an invalid tiling for the
    shape is an expected outcome, not an error); if every candidate
    fails, the last exception propagates.

    The best-so-far is recorded after EVERY candidate (not just at the
    end), and with ``persist=True`` also written to the default cache
    file each time it improves: a sweep killed mid-run by a watchdog —
    the normal fate of a long hardware sweep through a wedging tunnel —
    still banks the best configuration it measured, on disk.
    """
    results: dict[Any, float] = {}
    last_exc = None
    best = None
    with _tm.span("autotune.sweep", kernel=kernel):
        for cfg in candidates:
            try:
                with _tm.span("autotune.candidate", _journal=False):
                    results[cfg] = float(timer(cfg))
            except Exception as e:  # invalid tiling / VMEM overflow / ...
                last_exc = e
                continue
            if best is None or results[cfg] < results[best]:
                best = cfg
                if record_best:
                    record(kernel, key, best)
                    if persist:
                        save_default()
        if not results:
            raise last_exc if last_exc is not None else \
                ValueError("sweep got no candidates")
        _tm.count("autotune.sweeps", kernel=kernel)
        # cold path: a sweep spends seconds compiling/timing candidates
        _tm.event("autotune", "sweep", kernel=kernel, key=key,  # dalint: disable=DAL003
                  candidates=len(results), best=best,
                  best_s=results[best])
    return best, results
