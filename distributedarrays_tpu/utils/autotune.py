"""Block-size autotuning registry for the Pallas kernels.

The hand-written kernels (flash attention, GEMM) take block-size knobs
whose best values depend on shape, dtype, and chip generation — measured
on a v5e, causal 8k flash attention runs ~20x faster at 1024² blocks than
at 128².  The reference has no analog (its hot loops are BLAS calls); this
is the TPU-native tuning surface.

Three pieces:

- a process-global registry mapping ``(kernel, key) -> config`` that the
  kernels consult when their block arguments are left ``None``;
- ``sweep(...)``: time a list of candidate configs with an injectable
  timer and record the winner;
- optional JSON persistence (``save``/``load``) so a one-off tuning run
  (bench.py's hardware sweep, or a user-driven ``sweep``) carries across
  processes via the ``DAT_AUTOTUNE_CACHE`` env var, loaded lazily on
  first lookup.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Iterable, Mapping

from .. import telemetry as _tm

__all__ = ["get", "record", "sweep", "save", "load", "clear", "key_for",
           "device_key_for", "valid_ints",
           "default_cache_path", "save_default", "seed_path",
           "provenance_for", "provenance_table", "undo", "undo_log"]

_LOCK = threading.RLock()
_REGISTRY: dict[str, dict[str, Any]] = {}
_LOADED_ENV = False

# Provenance sidecar: (kernel, key) -> {"source": ..., "finding": ...,
# "evidence": {...}, ...} for entries written with evidence attached
# (the telemetry advisor).  Persisted under a reserved top-level key in
# the cache JSON so the registry namespace itself stays entries-only.
_PROV_KEY = "__provenance__"
_PROVENANCE: dict[str, dict[str, dict]] = {}

# Bounded undo journal for provenance-stamped writes: each entry captures
# the pre-write state so a tune that regresses under the micro-probe can
# be rolled back exactly (including "there was no entry before").
_UNDO_LIMIT = 64
_UNDO: list[dict] = []


def key_for(*parts) -> str:
    """Canonical string key from shape/dtype/flag parts."""
    return "|".join(str(p) for p in parts)


def device_key_for(*parts) -> str:
    """``key_for`` with the default device's platform and kind appended.
    Every kernel-tuning registry (flash blocks, ring hop blocks, GEMM
    tiles, impl choices) keys through this: a winner measured on one
    platform (CPU/interpret validation run, v4, v5e...) must never drive
    dispatch on another, even through the shared persisted cache
    (ADVICE round-4)."""
    import jax
    dev = jax.devices()[0]
    return key_for(*parts, dev.platform, dev.device_kind)


def valid_ints(entry, lengths: tuple[int, ...]):
    """Parse a registry entry as a tuple of positive ints of an accepted
    length, or None — a stale/hand-edited/malformed cache entry must
    degrade to the caller's default, never break dispatch.  Shared by
    every kernel that stores block tuples."""
    if not isinstance(entry, (list, tuple)):
        return None      # a string would "parse" via its characters
    try:
        vals = [int(x) for x in entry]
        if len(vals) in lengths and all(v > 0 for v in vals):
            return tuple(vals)
    except Exception:
        pass
    return None


def default_cache_path() -> str:
    """Where tuning results persist across processes: the
    ``DAT_AUTOTUNE_CACHE`` env var if set; in a repo CHECKOUT, an
    ``AUTOTUNE_CACHE.json`` next to the package (gitignored) so bench.py's
    hardware sweep is picked up by every later process in the same tree;
    for an installed package, a per-user cache dir (never site-packages,
    which may be read-only or shared across unrelated projects)."""
    env = os.environ.get("DAT_AUTOTUNE_CACHE")
    if env:
        return env
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # .git is a directory in a normal clone, a FILE in worktrees/submodules
    if os.path.exists(os.path.join(pkg_parent, ".git")):
        return os.path.join(pkg_parent, "AUTOTUNE_CACHE.json")
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "distributedarrays_tpu",
                        "AUTOTUNE_CACHE.json")


def save_default() -> str:
    """Persist the registry to ``default_cache_path()``; returns the path."""
    path = default_cache_path()
    save(path)
    return path


def seed_path() -> str:
    """The TRACKED seed registry (``AUTOTUNE_SEED.json`` at the repo
    root): winners measured on real hardware and committed, so a fresh
    checkout dispatches to measured configs out of the box instead of
    waiting for the user's first tune (VERDICT round-4 weak 3).  Keys
    are device-fenced via ``device_key_for``, so entries for other
    platforms are inert; the live cache overrides the seed on
    collision."""
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "AUTOTUNE_SEED.json")


def _maybe_load_env():
    global _LOADED_ENV
    if _LOADED_ENV:
        return
    _LOADED_ENV = True
    seed = seed_path()
    if os.path.exists(seed):
        try:
            load(seed)
        except Exception:
            pass  # a corrupt seed must never break kernel dispatch
    path = default_cache_path()
    if path and os.path.exists(path):
        try:
            load(path)     # live measurements override the seed
        except Exception:
            pass  # a corrupt cache must never break kernel dispatch


_MISS = object()


def get(kernel: str, key: str, default=None):
    """Tuned config for ``(kernel, key)``, or ``default``.

    Every lookup is counted (telemetry ``autotune.hit`` / ``autotune.miss``
    per kernel); the first miss per (kernel, key) is journaled, so a
    workload silently dispatching on heuristic defaults is queryable."""
    with _LOCK:
        _maybe_load_env()
        entry = _REGISTRY.get(kernel, {}).get(key, _MISS)
    if entry is _MISS:
        _tm.count("autotune.miss", kernel=kernel)
        # per-dispatch lookup path: the once_key f-string must not be
        # built in disabled mode
        if _tm.enabled():
            _tm.event("autotune", "miss", kernel=kernel, key=key,
                      once_key=f"autotune:miss:{kernel}:{key}")
        return default
    _tm.count("autotune.hit", kernel=kernel)
    return entry


def record(kernel: str, key: str, config, *,
           provenance: Mapping | None = None) -> None:
    """Store ``config`` for ``(kernel, key)``.

    With ``provenance`` (a mapping — conventionally ``source``,
    ``finding``, and ``evidence`` with the measured before-metrics), the
    write is stamped in the provenance sidecar AND journaled in the
    bounded undo log, so :func:`undo` can restore the exact pre-write
    state.  A later plain ``record`` for the same key (a sweep, a user
    write) drops the stale provenance — the entry no longer reflects the
    stamped evidence."""
    with _LOCK:
        _maybe_load_env()
        entries = _REGISTRY.setdefault(kernel, {})
        if provenance is not None:
            _UNDO.append({
                "kernel": kernel, "key": key,
                "had_prev": key in entries,
                "prev": entries.get(key),
                "prev_provenance": _PROVENANCE.get(kernel, {}).get(key),
                "config": config,
                "provenance": dict(provenance),
            })
            del _UNDO[:-_UNDO_LIMIT]
            _PROVENANCE.setdefault(kernel, {})[key] = dict(provenance)
        else:
            _PROVENANCE.get(kernel, {}).pop(key, None)
        entries[key] = config


def provenance_for(kernel: str, key: str) -> dict | None:
    """The provenance stamp for ``(kernel, key)``, or None for entries
    written without evidence (seed, sweep, hand edit)."""
    with _LOCK:
        _maybe_load_env()
        prov = _PROVENANCE.get(kernel, {}).get(key)
        return dict(prov) if prov is not None else None


def provenance_table() -> dict[str, dict[str, dict]]:
    """Snapshot of the whole provenance sidecar (kernel -> key -> stamp)."""
    with _LOCK:
        _maybe_load_env()
        return {k: {key: dict(p) for key, p in v.items()}
                for k, v in _PROVENANCE.items() if v}


def undo_log() -> list[dict]:
    """Snapshot of the bounded undo journal (oldest first)."""
    with _LOCK:
        return [dict(e) for e in _UNDO]


def undo(kernel: str, key: str) -> bool:
    """Roll back the most recent provenance-stamped write for
    ``(kernel, key)``: the entry (and its provenance) is restored to the
    exact pre-write state — including deletion when there was no entry
    before.  Returns False when the undo journal holds no write for the
    pair.  Counted as ``autotune.undo`` and journaled."""
    with _LOCK:
        _maybe_load_env()
        for i in range(len(_UNDO) - 1, -1, -1):
            e = _UNDO[i]
            if e["kernel"] != kernel or e["key"] != key:
                continue
            del _UNDO[i]
            entries = _REGISTRY.setdefault(kernel, {})
            if e["had_prev"]:
                entries[key] = e["prev"]
            else:
                entries.pop(key, None)
            if e["prev_provenance"] is not None:
                _PROVENANCE.setdefault(kernel, {})[key] = \
                    dict(e["prev_provenance"])
            else:
                _PROVENANCE.get(kernel, {}).pop(key, None)
            restored = e["prev"] if e["had_prev"] else None
            break
        else:
            return False
    _tm.count("autotune.undo", kernel=kernel)
    if _tm.enabled():
        _tm.event("autotune", "undo", kernel=kernel, key=key,
                  restored=restored)
    return True


def clear() -> None:
    with _LOCK:
        _REGISTRY.clear()
        _PROVENANCE.clear()
        del _UNDO[:]


def save(path: str) -> None:
    with _LOCK:
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        data: dict[str, Any] = dict(_REGISTRY)
        prov = {k: v for k, v in _PROVENANCE.items() if v}
        if prov:
            data[_PROV_KEY] = prov
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=2, sort_keys=True)
        os.replace(tmp, path)


def load(path: str) -> None:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict):
        raise ValueError(f"autotune cache {path} is not a JSON object")
    with _LOCK:
        prov = data.pop(_PROV_KEY, None)
        if isinstance(prov, dict):
            for kernel, stamps in prov.items():
                if isinstance(stamps, dict):
                    _PROVENANCE.setdefault(kernel, {}).update(
                        {k: dict(v) for k, v in stamps.items()
                         if isinstance(v, dict)})
        for kernel, entries in data.items():
            if kernel.startswith("__"):
                continue   # reserved sidecar namespaces, never entries
            _REGISTRY.setdefault(kernel, {}).update(entries)


def sweep(kernel: str, key: str, candidates: Iterable,
          timer: Callable[[Any], float],
          record_best: bool = True,
          persist: bool = False) -> tuple[Any, Mapping[Any, float]]:
    """Time every candidate config with ``timer(config) -> seconds``
    (lower is better), record the winner in the registry, and return
    ``(best_config, {config: seconds})``.

    A candidate whose timer raises is skipped (an invalid tiling for the
    shape is an expected outcome, not an error); if every candidate
    fails, the last exception propagates.

    The best-so-far is recorded after EVERY candidate (not just at the
    end), and with ``persist=True`` also written to the default cache
    file each time it improves: a sweep killed mid-run by a watchdog —
    the normal fate of a long hardware sweep through a wedging tunnel —
    still banks the best configuration it measured, on disk.
    """
    results: dict[Any, float] = {}
    last_exc = None
    best = None
    with _tm.span("autotune.sweep", kernel=kernel):
        for cfg in candidates:
            try:
                with _tm.span("autotune.candidate", _journal=False):
                    results[cfg] = float(timer(cfg))
            except Exception as e:  # invalid tiling / VMEM overflow / ...
                last_exc = e
                continue
            if best is None or results[cfg] < results[best]:
                best = cfg
                if record_best:
                    record(kernel, key, best)
                    if persist:
                        save_default()
        if not results:
            raise last_exc if last_exc is not None else \
                ValueError("sweep got no candidates")
        _tm.count("autotune.sweeps", kernel=kernel)
        # cold path: a sweep spends seconds compiling/timing candidates
        _tm.event("autotune", "sweep", kernel=kernel, key=key,  # dalint: disable=DAL003
                  candidates=len(results), best=best,
                  best_s=results[best])
    return best, results
