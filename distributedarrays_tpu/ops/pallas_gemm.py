"""Hand-written Pallas TPU kernel for the block GEMM hot path.

The reference's hottest op is the tile-grid GEMM (linalg.jl:189-253); the
framework's default path is one jitted ``jnp.matmul`` (XLA's MXU pipeline,
ops/linalg.py).  This module adds the Pallas alternative for when the
schedule should be owned explicitly — fused epilogues, nonstandard tiling,
mixed precision — following /opt/skills/guides/pallas_guide.md:

- grid ``(M/bm, N/bn, K/bk)`` with the K axis innermost (sequential),
- A/B tiles streamed HBM→VMEM by BlockSpec index maps,
- one float32 VMEM scratch accumulator per (i, j) tile,
- ``preferred_element_type=float32`` so bf16/f32 inputs accumulate in f32
  on the MXU,
- optional fused epilogue applied in-register before the tile is written
  back (saves one full HBM round-trip vs a separate elementwise kernel).

``pallas_matmul`` falls back to interpreter mode off-TPU so the kernel is
unit-testable on the CPU mesh (same discipline as the rest of the suite).
"""

from __future__ import annotations

import functools
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only namespace; absent/unusable off-TPU
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

__all__ = ["pallas_matmul"]


def _pow2_divisor(dim: int, cap: int) -> int:
    """Largest power-of-two divisor of ``dim`` that is <= ``cap`` — the
    shared block-fitting primitive (also used by pallas_stencil and
    flash_block_size)."""
    b = 1
    while b * 2 <= cap and dim % (b * 2) == 0:
        b *= 2
    return b


def _kernel(a_ref, b_ref, o_ref, acc_ref, *, k_steps: int,
            epilogue: Callable | None):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _flush():
        out = acc_ref[:]
        if epilogue is not None:
            out = epilogue(out)
        o_ref[:] = out.astype(o_ref.dtype)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False


@functools.lru_cache(maxsize=64)
def _build(m, n, k, bm, bn, bk, dtype_str, epilogue, interpret):
    if pltpu is None:
        raise RuntimeError(
            "jax.experimental.pallas.tpu is unavailable in this JAX build; "
            "pallas_matmul cannot run (use ops.linalg.matmul instead)")
    dtype = jnp.dtype(dtype_str)
    k_steps = k // bk
    kern = functools.partial(_kernel, k_steps=k_steps, epilogue=epilogue)
    call = pl.pallas_call(
        kern,
        grid=(m // bm, n // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, s: (i, s)),
            pl.BlockSpec((bk, bn), lambda i, j, s: (s, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, s: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )
    return jax.jit(call)


def pallas_matmul(a, b, block: tuple[int, int, int] | None = None,
                  epilogue: Callable | None = None,
                  interpret: bool | None = None):
    """C = epilogue(A @ B) as a Pallas TPU kernel.

    Shapes must divide by ``block`` (pad beforehand otherwise); bf16/f32
    inputs accumulate in f32.  ``block=None`` picks the largest tiling
    that fits VMEM on v5e, measured on hardware: (1024, 1024, 512) for
    2-byte dtypes (151.9 TFLOPS on a 4096^2 bf16 GEMM vs 78.2 at the old
    256^3 default), (512, 512, 512) for f32.  ``epilogue`` (e.g.
    ``jax.nn.gelu``) fuses into the tile flush.  ``interpret`` defaults
    to auto (True off-TPU).

    The kernel cache is keyed on the ``epilogue`` callable's identity —
    pass a module-level function (not a fresh lambda per call) or the
    kernel recompiles on every invocation.
    """
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    m, ka = a.shape
    kb, n = b.shape
    if ka != kb:
        raise ValueError(f"matmul dim mismatch {a.shape} @ {b.shape}")
    if interpret is None:
        interpret = not _on_tpu()
    if block is None:
        from ..utils import autotune
        tuned = autotune.get(
            "pallas_matmul", autotune.key_for(m, n, ka, a.dtype, b.dtype))
        # a stale/hand-edited/malformed cache entry must degrade to the
        # auto heuristic, never break dispatch for the shape
        try:
            tm, tn, tk = (int(v) for v in tuned)
            if (tm > 0 and tn > 0 and tk > 0
                    and m % tm == 0 and n % tn == 0 and ka % tk == 0
                    and (tm % 8 == 0 or tm == m)
                    and (tn % 128 == 0 or tn == n)
                    and (tk % 128 == 0 or tk == ka)):
                block = (tm, tn, tk)
        except Exception:
            pass
    if block is None:
        two_byte = max(jnp.dtype(a.dtype).itemsize,
                       jnp.dtype(b.dtype).itemsize) <= 2
        bm0, bn0, bk0 = (1024, 1024, 512) if two_byte else (512, 512, 512)

        # auto default: whole dim when it fits the cap (the always-valid
        # equal-dims escape and the old default's behavior), else the
        # largest power-of-two divisor under the tuned cap
        def fit(dim, cap):
            return dim if dim <= cap else _pow2_divisor(dim, cap)

        bm, bn, bk = fit(m, bm0), fit(n, bn0), fit(ka, bk0)
        if not interpret and not ((bm % 8 == 0 or bm == m)
                                  and (bn % 128 == 0 or bn == n)
                                  and (bk % 128 == 0 or bk == ka)):
            # Mosaic blocks need their last dim divisible by 128 and
            # second-to-last by 8 (or equal to the array dim); only real
            # TPUs enforce this — interpret mode runs any tiling
            raise ValueError(
                f"shapes ({m},{ka})x({kb},{n}) have no MXU-aligned "
                "power-of-two tiling; pad the operands or pass block=")
    else:
        bm, bn, bk = block
        bm, bn, bk = min(bm, m), min(bn, n), min(bk, ka)
    if m % bm or n % bn or ka % bk:
        raise ValueError(
            f"shapes ({m},{ka})x({kb},{n}) must divide block {(bm, bn, bk)}")
    out_dtype = jnp.result_type(a.dtype, b.dtype)
    fn = _build(m, n, ka, bm, bn, bk, str(out_dtype), epilogue, interpret)
    return fn(a, b)
