"""Hand-written Pallas TPU kernel for the block GEMM hot path.

The reference's hottest op is the tile-grid GEMM (linalg.jl:189-253); the
framework's default path is one jitted ``jnp.matmul`` (XLA's MXU pipeline,
ops/linalg.py).  This module adds the Pallas alternative for when the
schedule should be owned explicitly — fused epilogues, nonstandard tiling,
mixed precision — following /opt/skills/guides/pallas_guide.md:

- grid ``(M/bm, N/bn, K/bk)`` with the K axis innermost (sequential),
- A/B tiles streamed HBM→VMEM by BlockSpec index maps,
- one float32 VMEM scratch accumulator per (i, j) tile,
- ``preferred_element_type=float32`` so bf16/f32 inputs accumulate in f32
  on the MXU,
- optional fused epilogue applied in-register before the tile is written
  back (saves one full HBM round-trip vs a separate elementwise kernel).

``pallas_matmul`` falls back to interpreter mode off-TPU so the kernel is
unit-testable on the CPU mesh (same discipline as the rest of the suite).
"""

from __future__ import annotations

import functools
from typing import Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-only namespace; absent/unusable off-TPU
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .. import telemetry as _tm

__all__ = ["pallas_matmul", "pallas_matmul_int8", "quantized_matmul",
           "quantize_rows", "entry_valid_for_seed"]


# Scoped-VMEM budget for a GEMM tile set: v5e enforces a 16 MiB limit on
# the Pallas stack allocation (measured on silicon: a 17.38M int8 tile set
# was rejected with ~0.4M of Mosaic overhead on top of the raw block
# bytes), so tiles are validated against 15.5 MiB regardless of where the
# block came from (cache entry, explicit block=, heuristic).
_VMEM_LIMIT = int(15.5 * 2**20)


def _vmem_parts_matmul(tm, tn, tk, ab, bb, ob):
    """Scoped-VMEM estimate for a float GEMM tile set, by component.
    The Pallas pipeline DOUBLE-BUFFERS only the REVOLVING blocks — the
    A/B inputs, whose index maps depend on the innermost (sequential) K
    grid axis, so the next tile streams in while the current one computes
    (the ``_x2`` entries).  The output block's index map is ``(i, j)``:
    constant across the K steps of one tile, so it is carried once, like
    the f32 accumulator scratch (ADVICE round-5: counting it double
    rejected legitimate tilings near the budget).  ``ab``/``bb``/``ob``
    are the operand/output itemsizes."""
    return {
        "a_blocks_x2": 2 * tm * tk * ab,
        "b_blocks_x2": 2 * tk * tn * bb,
        "out_block": tm * tn * ob,
        "acc_scratch_f32": tm * tn * 4,
    }


def _vmem_parts_int8(tm, tn, tk, ob):
    """Scoped-VMEM estimate for the int8 GEMM tile set, by component:
    the revolving int8 input blocks double-buffered; the f32 scale
    carriers — lane/sublane-aligned to (bm, 128) and (8, bn), index maps
    ``(i, 0)``/``(0, j)`` constant along the innermost K axis — counted
    once, like the K-constant output block and the int32 accumulator
    scratch (ADVICE round-5)."""
    return {
        "a_blocks_x2": 2 * tm * tk,
        "b_blocks_x2": 2 * tk * tn,
        "scale_carriers": tm * 128 * 4 + 8 * tn * 4,
        "out_block": tm * tn * ob,
        "acc_scratch_i32": tm * tn * 4,
    }


def _resolve_block(m, n, k, block, interpret, *, kernel, dtype_key,
                   caps, m_align, vmem_parts=None):
    """Shared block-resolution path for the GEMM kernels: explicit
    ``block`` > valid autotune-cache entry > auto heuristic (whole dim
    when under the cap, else largest power-of-two divisor).  A
    stale/hand-edited/malformed cache entry must degrade to the auto
    heuristic, never break dispatch — validation includes the Mosaic
    alignment rules (last dim % 128, second-to-last % ``m_align``, or
    equal to the array dim) and, when the caller supplies a
    ``vmem_parts(bm, bn, bk) -> {component: bytes}`` estimator, the
    scoped-VMEM budget; only real TPUs enforce either, interpret mode
    runs any tiling."""
    def aligned(tm, tn, tk):
        return ((tm % m_align == 0 or tm == m)
                and (tn % 128 == 0 or tn == n)
                and (tk % 128 == 0 or tk == k))

    def vmem_ok(tm, tn, tk):
        return (interpret or vmem_parts is None
                or sum(vmem_parts(tm, tn, tk).values()) <= _VMEM_LIMIT)

    if block is None:
        from ..utils import autotune
        vals = autotune.valid_ints(
            autotune.get(kernel, autotune.device_key_for(m, n, k, *dtype_key)),
            (3,))
        if vals is not None:
            tm, tn, tk = vals
            if (m % tm == 0 and n % tn == 0 and k % tk == 0
                    and (interpret or aligned(tm, tn, tk))
                    and vmem_ok(tm, tn, tk)):
                block = (tm, tn, tk)
    if block is None:
        bm0, bn0, bk0 = caps

        def fit(dim, cap):
            return dim if dim <= cap else _pow2_divisor(dim, cap)

        bm, bn, bk = fit(m, bm0), fit(n, bn0), fit(k, bk0)
        if not interpret and not aligned(bm, bn, bk):
            raise ValueError(
                f"shapes ({m},{k})x({k},{n}) have no MXU-aligned "
                "power-of-two tiling; pad the operands or pass block=")
    else:
        bm, bn, bk = block
        bm, bn, bk = min(bm, m), min(bn, n), min(bk, k)
        if not vmem_ok(bm, bn, bk):
            # fail at dispatch with the budget AND the per-component
            # breakdown, not deep in Mosaic with a scoped-vmem stack OOM
            # (the silicon failure mode this guards).  A legitimate
            # near-budget tiling rejection must be diagnosable: the
            # estimate double-buffers the revolving input blocks (the
            # _x2 components) while K-grid-constant output blocks and
            # scale carriers count once — easy to forget when sizing
            # blocks by raw tile bytes.
            parts = vmem_parts(bm, bn, bk)
            total = sum(parts.values())
            breakdown = ", ".join(f"{c}={v}" for c, v in parts.items())
            raise ValueError(
                f"block {(bm, bn, bk)} needs ~{total} bytes of scoped "
                f"VMEM, over the {_VMEM_LIMIT} budget (headroom "
                f"{total - _VMEM_LIMIT} over). Estimate components — "
                f"revolving input blocks double-buffered (the _x2 "
                f"entries), K-constant output/scale blocks once: "
                f"{breakdown}. Pass a smaller block=.")
    if m % bm or n % bn or k % bk:
        raise ValueError(
            f"shapes ({m},{k})x({k},{n}) must divide block {(bm, bn, bk)}")
    return bm, bn, bk


def entry_valid_for_seed(kernel: str, key: str, entry):
    """Validity predicate for promoting an autotune-cache GEMM winner into
    the tracked seed registry (tools/seed_refresh.py): the SAME checks
    ``_resolve_block`` applies at dispatch — well-formed 3-tuple, shape
    divisibility, Mosaic alignment (last dim % 128, M block % m_align),
    and the per-kernel scoped-VMEM estimate — so a winner measured before
    a VMEM-estimator fix can never ship as a dead entry that every later
    dispatch silently rejects (ADVICE round-5).

    Returns ``None`` for kernels this module does not own (no opinion),
    else ``True``/``False``.  ``key`` is ``m|n|k|<dtypes...>|platform|
    device_kind`` as built by ``autotune.device_key_for``.
    """
    if kernel not in ("pallas_matmul", "pallas_matmul_int8"):
        return None
    segs = str(key).split("|")
    # device_key_for produces exactly this arity per kernel (m, n, k,
    # dtype segs, platform, kind); anything else cannot match a lookup
    # and must not ship
    if len(segs) != (7 if kernel == "pallas_matmul" else 6):
        return False
    try:
        m, n, k = (int(x) for x in segs[:3])
    except ValueError:
        return False
    from ..utils.autotune import valid_ints
    vals = valid_ints(entry, (3,))
    if vals is None:
        return False
    bm, bn, bk = vals
    if m % bm or n % bn or k % bk:
        return False
    if kernel == "pallas_matmul_int8":
        m_align = 32
        # dispatch-default f32 output — the layout quantized_matmul uses
        parts = _vmem_parts_int8(bm, bn, bk, 4)
    else:
        m_align = 8
        try:
            ab = jnp.dtype(segs[3]).itemsize
            bb = jnp.dtype(segs[4]).itemsize
            ob = jnp.dtype(jnp.result_type(jnp.dtype(segs[3]),
                                           jnp.dtype(segs[4]))).itemsize
        except TypeError:
            return False
        parts = _vmem_parts_matmul(bm, bn, bk, ab, bb, ob)
    aligned = ((bm % m_align == 0 or bm == m)
               and (bn % 128 == 0 or bn == n)
               and (bk % 128 == 0 or bk == k))
    return aligned and sum(parts.values()) <= _VMEM_LIMIT


def _pow2_divisor(dim: int, cap: int) -> int:
    """Largest power-of-two divisor of ``dim`` that is <= ``cap`` — the
    shared block-fitting primitive (also used by pallas_stencil and
    flash_block_size)."""
    b = 1
    while b * 2 <= cap and dim % (b * 2) == 0:
        b *= 2
    return b


def _kernel(a_ref, b_ref, o_ref, acc_ref, *, k_steps: int,
            epilogue: Callable | None):
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _flush():
        out = acc_ref[:]
        if epilogue is not None:
            out = epilogue(out)
        o_ref[:] = out.astype(o_ref.dtype)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover
        return False


@functools.lru_cache(maxsize=64)
def _build(m, n, k, bm, bn, bk, dtype_str, epilogue, interpret):
    if pltpu is None:
        raise RuntimeError(
            "jax.experimental.pallas.tpu is unavailable in this JAX build; "
            "pallas_matmul cannot run (use ops.linalg.matmul instead)")
    dtype = jnp.dtype(dtype_str)
    k_steps = k // bk
    kern = functools.partial(_kernel, k_steps=k_steps, epilogue=epilogue)
    call = pl.pallas_call(
        kern,
        grid=(m // bm, n // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, s: (i, s)),
            pl.BlockSpec((bk, bn), lambda i, j, s: (s, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, s: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )
    return jax.jit(call)


@_tm.traced(name="pallas.matmul")
def pallas_matmul(a, b, block: tuple[int, int, int] | None = None,
                  epilogue: Callable | None = None,
                  interpret: bool | None = None):
    """C = epilogue(A @ B) as a Pallas TPU kernel.

    Shapes must divide by ``block`` (pad beforehand otherwise); bf16/f32
    inputs accumulate in f32.  ``block=None`` picks the largest tiling
    that fits VMEM on v5e, measured on hardware: (1024, 1024, 512) for
    2-byte dtypes (151.9 TFLOPS on a 4096^2 bf16 GEMM vs 78.2 at the old
    256^3 default), (512, 512, 512) for f32.  ``epilogue`` (e.g.
    ``jax.nn.gelu``) fuses into the tile flush.  ``interpret`` defaults
    to auto (True off-TPU).

    The kernel cache is keyed on the ``epilogue`` callable's identity —
    pass a module-level function (not a fresh lambda per call) or the
    kernel recompiles on every invocation.
    """
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    m, ka = a.shape
    kb, n = b.shape
    if ka != kb:
        raise ValueError(f"matmul dim mismatch {a.shape} @ {b.shape}")
    if interpret is None:
        interpret = not _on_tpu()
    two_byte = max(jnp.dtype(a.dtype).itemsize,
                   jnp.dtype(b.dtype).itemsize) <= 2
    out_dtype = jnp.result_type(a.dtype, b.dtype)
    ab, bb = jnp.dtype(a.dtype).itemsize, jnp.dtype(b.dtype).itemsize
    ob = jnp.dtype(out_dtype).itemsize
    if _tm.enabled():
        # cost stamp on the @traced dispatch span (shapes were unknown
        # when it opened): single-device GEMM, no ICI.  Inline rather
        # than perf.gemm_cost: a and b can carry different dtypes.  The
        # autotune_key names the exact "pallas_matmul" block entry
        # _resolve_block consults, so a low_roofline finding on this
        # span addresses a re-sweepable registry slot.
        from ..utils import autotune as _at
        _tm.annotate(flops=2 * m * n * ka,
                     bytes_hbm=m * ka * ab + ka * n * bb + m * n * ob,
                     bytes_ici=0, shape=[m, ka, n],
                     dtype=[str(a.dtype), str(b.dtype)],
                     autotune_key=_at.device_key_for(m, n, ka, a.dtype,
                                                     b.dtype))

    bm, bn, bk = _resolve_block(
        m, n, ka, block, interpret, kernel="pallas_matmul",
        dtype_key=(a.dtype, b.dtype),
        caps=(1024, 1024, 512) if two_byte else (512, 512, 512), m_align=8,
        vmem_parts=lambda tm, tn, tk: _vmem_parts_matmul(
            tm, tn, tk, ab, bb, ob))
    fn = _build(m, n, ka, bm, bn, bk, str(out_dtype), epilogue, interpret)
    return fn(a, b)


# ---------------------------------------------------------------------------
# int8 quantized GEMM — the MXU runs int8 x int8 -> int32 at 2x the bf16
# rate on the "e"-class chips (v5e ~394 TOPS vs ~197 TFLOPS bf16), so a
# quantization-tolerant GEMM can BEAT the chip's bf16 peak.  No reference
# analog (linalg.jl:189-253 is Float only) — this is a TPU-native extra.
# ---------------------------------------------------------------------------


def _int8_kernel(a_ref, b_ref, sa_ref, sb_ref, o_ref, acc_ref, *,
                 k_steps: int):
    """Int8 tiles accumulate in an int32 VMEM scratch; the flush dequantizes
    in-register with the per-row/per-column scales (one fused epilogue, no
    extra HBM pass): C = (Qa @ Qb) * (sa sb^T)."""
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(a_ref[:], b_ref[:],
                          preferred_element_type=jnp.int32)

    @pl.when(pl.program_id(2) == k_steps - 1)
    def _flush():
        # scale refs arrive lane/sublane-aligned — (bm,128) and (8,bn),
        # value replicated across the padding dims (Mosaic requires the
        # minor block dim % 128, like the attention stats broadcast in
        # pallas_attention) — slice one row/col back out for the outer
        # product
        scale = sa_ref[:, 0:1] * sb_ref[0:1, :]  # (bm,1)*(1,bn) -> (bm,bn)
        o_ref[:] = (acc_ref[:].astype(jnp.float32) * scale
                    ).astype(o_ref.dtype)


@functools.lru_cache(maxsize=64)
def _build_int8(m, n, k, bm, bn, bk, out_dtype_str, interpret):
    if pltpu is None:
        raise RuntimeError(
            "jax.experimental.pallas.tpu is unavailable in this JAX build; "
            "pallas_matmul_int8 cannot run")
    k_steps = k // bk
    kern = functools.partial(_int8_kernel, k_steps=k_steps)
    call = pl.pallas_call(
        kern,
        grid=(m // bm, n // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, s: (i, s)),
            pl.BlockSpec((bk, bn), lambda i, j, s: (s, j)),
            pl.BlockSpec((bm, 128), lambda i, j, s: (i, 0)),
            pl.BlockSpec((8, bn), lambda i, j, s: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, s: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.dtype(out_dtype_str)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        interpret=interpret,
    )
    return jax.jit(call)


@_tm.traced(name="pallas.matmul_int8")
def pallas_matmul_int8(qa, qb, a_scale, b_scale,
                       block: tuple[int, int, int] | None = None,
                       out_dtype=jnp.float32, interpret: bool | None = None):
    """C = (Qa @ Qb) * (a_scale b_scale^T) with int8 operands on the MXU.

    ``qa`` (m,k) int8, ``qb`` (k,n) int8; ``a_scale`` (m,) per-row and
    ``b_scale`` (n,) per-column dequant scales (float32).  Accumulates in
    int32 (no rounding inside the K loop — exact whenever the running sum
    fits int32, guaranteed for K <= ~133k even with fully saturated
    operands; a warning fires above that) and dequantizes in the tile
    flush.  Shapes must divide ``block``; int8 native MXU tiling wants
    the K block % 128 and the M block % 32.
    """
    qa = jnp.asarray(qa)
    qb = jnp.asarray(qb)
    if qa.dtype != jnp.int8 or qb.dtype != jnp.int8:
        raise ValueError(
            f"operands must be int8, got {qa.dtype} x {qb.dtype} "
            "(use quantized_matmul for float inputs)")
    m, ka = qa.shape
    kb, n = qb.shape
    if ka != kb:
        raise ValueError(f"matmul dim mismatch {qa.shape} @ {qb.shape}")
    if interpret is None:
        interpret = not _on_tpu()
    if _tm.enabled():
        # cost stamp: int8 operands, dequantized output through HBM
        from ..telemetry import perf as _perf
        _tm.annotate(shape=[m, ka, n], **_perf.gemm_cost(
            m, n, ka, 1,
            out_itemsize=jnp.dtype(out_dtype).itemsize))
    safe_k = (2**31 - 1) // (127 * 127)
    if ka > safe_k:
        # worst-case saturated operands overflow the int32 accumulator
        # above this K; real data rarely saturates, so warn, don't refuse.
        # Keyed on K so each risky contraction length surfaces once
        # (a single process-wide key would hide later, larger K's).
        from ..utils.debug import warn_once
        warn_once(f"pallas_matmul_int8_overflow:{ka}",
                  f"pallas_matmul_int8: K={ka} exceeds the worst-case "
                  f"int32-exact bound (K <= {safe_k}); saturated operands "
                  "may wrap. Split the contraction if inputs can saturate.")
    # int8 tiles are half the bytes of bf16, so the K cap doubles; int8
    # native MXU tiling wants the M block % 32.  The M cap stays at 512:
    # a 1024^3 tile set was rejected by v5e's 16 MB scoped-VMEM check on
    # silicon (round 5, Mosaic-reported ~17.4 MB stack), so the heuristic
    # never proposes it even though the tightened estimator (K-constant
    # out/scale blocks counted once, ADVICE r5) now prices it at
    # ~12.5 MB — 512x1024x1024 is ~7.5 MB with the same K-step
    # arithmetic intensity.  An explicit block=/cached entry near the
    # budget that Mosaic's own (less favorable) accounting still rejects
    # fails loudly at compile with Mosaic's scoped-vmem error — the
    # dispatch estimate deliberately errs toward admitting, per ADVICE:
    # a conservative guard that rejects legitimate tilings is worse
    ob8 = jnp.dtype(out_dtype).itemsize

    bm, bn, bk = _resolve_block(
        m, n, ka, block, interpret, kernel="pallas_matmul_int8",
        dtype_key=("int8",), caps=(512, 1024, 1024), m_align=32,
        vmem_parts=lambda tm, tn, tk: _vmem_parts_int8(tm, tn, tk, ob8))
    # lane/sublane-aligned scale carriers (see _int8_kernel flush): the
    # replication costs m*512 + n*32 bytes of HBM — noise next to the
    # int8 operands — and keeps every VMEM block Mosaic-legal
    sa = jnp.broadcast_to(jnp.asarray(a_scale, jnp.float32).reshape(m, 1),
                          (m, 128))
    sb = jnp.broadcast_to(jnp.asarray(b_scale, jnp.float32).reshape(1, n),
                          (8, n))
    fn = _build_int8(m, n, ka, bm, bn, bk, str(jnp.dtype(out_dtype)),
                     interpret)
    return fn(qa, qb, sa, sb)


def quantize_rows(x, axis: int):
    """Symmetric per-slice int8 quantization along ``axis`` (the contraction
    axis): returns (q_int8, scale_f32) with x ≈ q * scale broadcast over
    ``axis``.  All-zero slices get scale 0 (q = 0), not NaN."""
    x = jnp.asarray(x, jnp.float32)
    amax = jnp.max(jnp.abs(x), axis=axis, keepdims=True)
    scale = amax / 127.0
    q = jnp.where(scale > 0, jnp.round(x / jnp.where(scale > 0, scale, 1.0)),
                  0.0)
    return q.astype(jnp.int8), jnp.squeeze(scale, axis)


def quantized_matmul(a, b, block: tuple[int, int, int] | None = None,
                     out_dtype=jnp.float32, interpret: bool | None = None):
    """Dynamic-quantization GEMM: float in, float out, int8 on the MXU.

    Per-row (A) / per-column (B) symmetric int8 quantization, exact int32
    accumulation, fused dequant.  Relative error is bounded by the two
    quantization steps (~1/127 per operand worst case, typically ~1e-2
    on Gaussian data) — the trade for ~2x bf16 throughput on e-class
    chips.  For repeated use with a static weight matrix, pre-quantize
    once with ``quantize_rows`` and call ``pallas_matmul_int8`` directly.
    """
    qa, sa = quantize_rows(a, 1)
    qb, sb = quantize_rows(b, 0)
    return pallas_matmul_int8(qa, qb, sa, sb, block=block,
                              out_dtype=out_dtype, interpret=interpret)
