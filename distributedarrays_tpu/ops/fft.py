"""Distributed FFTs on DArrays via the all-to-all transpose algorithm.

No reference analog (the reference ships no spectral ops) — this is the
classic distributed-memory FFT recipe expressed on the framework's
collective substrate: FFT along locally-resident dims is free; an FFT
along the SHARDED dim becomes ``all_to_all`` repartition (the same
collective as the sample-sort scatter, sort.jl:24-55) → local FFT →
``all_to_all`` back.  Everything runs as ONE compiled shard_map program
per call; communication is two tiled all-to-alls over ICI regardless of
the transform size.

Eligibility for the compiled path: even layout, the array sharded on at
most one dim, and every dim divisible by the shard count (all_to_all
tiles evenly).  A sharded DVector takes the four-step (Bailey)
decomposition (``_fft1d_shm_jit``) when its length is divisible by p**2.
Anything else takes the host numpy path with the exact cut structure
kept.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..darray import DArray, _wrap_global, darray_from_cuts

__all__ = ["dfft", "difft", "dfft2", "difft2"]


def _sharded_dim(d: DArray):
    """The single sharded dim of ``d``'s layout, or None if fully local.
    Raises for layouts sharded over more than one dim (host path)."""
    grid = [g for g in d.pids.shape]
    dims = [i for i, g in enumerate(grid) if g > 1]
    if not dims:
        return None
    if len(dims) > 1:
        raise ValueError("multi-dim grid")
    return dims[0]


@functools.lru_cache(maxsize=128)
def _fft_shm_jit(mesh, spec, ax: int, shard_dim: int, name: str,
                 inverse: bool):
    op = jnp.fft.ifft if inverse else jnp.fft.fft
    from ..parallel.collectives import pall_to_all, shard_map_compat

    def kernel(x):
        if ax != shard_dim:
            return op(x, axis=ax)
        # repartition so the transform dim is locally complete, FFT, undo.
        # pick any OTHER dim to shard during the transform
        other = next(i for i in range(x.ndim) if i != ax)
        y = pall_to_all(x, name, split_dim=other, concat_dim=ax)
        y = op(y, axis=ax)
        return pall_to_all(y, name, split_dim=ax, concat_dim=other)

    return jax.jit(shard_map_compat(kernel, mesh=mesh, in_specs=spec,
                                 out_specs=spec))


@functools.lru_cache(maxsize=128)
def _fft1d_shm_jit(mesh, spec, name: str, n: int, p: int, inverse: bool):
    """Distributed 1-D FFT of a block-sharded DVector as ONE shard_map
    program: the four-step (Bailey) decomposition with n1 = p.  View the
    length-``n`` vector as a row-major (p, n/p) matrix A — rank r's
    local shard IS row r.  Then

        1. length-p FFT down the columns (sharded dim) — all_to_all in,
           local FFT, all_to_all back;
        2. twiddle multiply by w_n^(k1*j2) (k1 = rank, local j2);
        3. length-n/p FFT along the resident row;
        4. transpose shuffle C.T.reshape(n) — one more all_to_all plus a
           local transpose, landing each rank exactly its output block.

    Inverse: conjugate twiddles + ifft in both steps (the two 1/len
    normalizations compose to the required 1/n).  Three tiled
    all_to_alls total; no host gather, no full-vector residency.
    """
    op = jnp.fft.ifft if inverse else jnp.fft.fft
    from ..parallel.collectives import pall_to_all, shard_map_compat
    n2 = n // p

    def kernel(x):
        ctype = jnp.result_type(x.dtype, jnp.complex64)
        a = x.reshape(1, n2).astype(ctype)
        # step 1: FFT of length p across the sharded dim
        b = pall_to_all(a, name, split_dim=1, concat_dim=0)   # (p, n2/p)
        b = op(b, axis=0)
        b = pall_to_all(b, name, split_dim=0, concat_dim=1)   # (1, n2)
        # step 2: twiddle — this rank now holds row k1 = rank.  The
        # product k1*j2 < p*n2 = n stays int32-exact (eligibility caps
        # n < 2**31); the f32 cast costs <= 2**-24 relative phase error,
        # below complex64 resolution
        k1 = jax.lax.axis_index(name)
        j2 = jnp.arange(n2)
        sign = 2j if inverse else -2j
        tw = jnp.exp(sign * jnp.pi * (k1 * j2) / n).astype(ctype)
        # step 3: resident-dim FFT of length n/p
        c = op(b * tw, axis=1)                                # (1, n2)
        # step 4: X[k2*p + k1] = C[k1, k2] — shuffle chunk r of every
        # row onto rank r, local transpose, flatten
        d_ = pall_to_all(c, name, split_dim=1, concat_dim=0)  # (p, n2/p)
        return d_.T.reshape(n2)

    return jax.jit(shard_map_compat(kernel, mesh=mesh, in_specs=spec,
                                 out_specs=spec))


def _fft_impl(d: DArray, ax: int, inverse: bool) -> DArray:
    if not isinstance(d, DArray):
        raise TypeError(f"expected DArray, got {type(d).__name__}")
    ax = ax + d.ndim if ax < 0 else ax
    if not 0 <= ax < d.ndim:
        raise ValueError(f"axis out of range for ndim {d.ndim}")
    from .mapreduce import _even_shared_layout
    try:
        shard_dim = _sharded_dim(d)
        eligible = _even_shared_layout((d,))
        if eligible and shard_dim is not None and ax == shard_dim:
            # only the repartitioned case moves data: the all_to_all
            # splits the FIRST other dim p-ways, so only that dim (and
            # the already-evenly-cut ax dim) must divide p
            p = int(np.prod(d.pids.shape))
            if d.ndim == 1:
                # four-step needs the local block (n/p) itself tileable
                # p-ways by the internal all_to_alls: n % p**2 == 0.
                # n < 2**31 keeps the twiddle product k1*j2 (< n by
                # construction) exact in int32 — beyond that the phases
                # would silently wrap
                eligible = (d.dims[0] % (p * p) == 0
                            and d.dims[0] < 2 ** 31)
            else:
                other = next(i for i in range(d.ndim) if i != ax)
                eligible = d.dims[other] % p == 0
    except ValueError:
        eligible = False              # multi-dim grid
        shard_dim = None
    if eligible:
        if d.ndim == 1 and shard_dim is not None and ax == shard_dim:
            p = int(np.prod(d.pids.shape))
            fn = _fft1d_shm_jit(d.sharding.mesh, d.sharding.spec,
                                d.sharding.spec[0], int(d.dims[0]), p,
                                inverse)
        else:
            fn = _fft_shm_jit(d.sharding.mesh, d.sharding.spec, ax,
                              -1 if shard_dim is None else shard_dim,
                              "unused" if shard_dim is None
                              else d.sharding.spec[shard_dim], inverse)
        res = fn(d.garray)
        return _wrap_global(res, procs=[int(q) for q in d.pids.flat],
                            dist=list(d.pids.shape))
    # host path: exact cut structure kept, loud about the gather
    from ..utils.debug import warn_once
    rule = ("a length divisible by p**2 for the four-step path"
            if d.ndim == 1 else
            "the repartition dim divisible by the shard count")
    warn_once(f"dfft-host-{d.pids.shape}-{d.ndim}-{ax}",
              f"dfft: layout (grid {tuple(d.pids.shape)}, dims {d.dims}, "
              f"axis {ax}) is not eligible for the compiled all_to_all "
              f"path (needs an even layout, a single sharded dim, and "
              f"{rule}); gathering to host for a numpy FFT")
    full = np.asarray(d)
    out = (np.fft.ifft if inverse else np.fft.fft)(full, axis=ax)
    # follow the input's complex promotion (complex128 only under x64),
    # matching the compiled path's dtype instead of hard complex64
    ctype = np.result_type(d.dtype, np.complex64)
    if ctype == np.complex128 and not jax.config.jax_enable_x64:
        ctype = np.complex64
    return darray_from_cuts(out.astype(ctype),
                            [int(q) for q in d.pids.flat], d.cuts)


def dfft(d: DArray, axis: int = -1) -> DArray:
    """Distributed 1-D FFT along ``axis`` (complex result, same
    layout).  A resident axis is one local ``jnp.fft.fft``; a sharded
    matrix axis costs two ``all_to_all`` repartitions around it; a
    sharded DVector runs the four-step decomposition (three
    all_to_alls) when ``len(d) % p**2 == 0``."""
    return _fft_impl(d, axis, inverse=False)


def difft(d: DArray, axis: int = -1) -> DArray:
    """Distributed inverse 1-D FFT along ``axis`` (see ``dfft``)."""
    return _fft_impl(d, axis, inverse=True)


def dfft2(d: DArray) -> DArray:
    """Distributed 2-D FFT of a matrix DArray: local FFT along the
    resident dim, repartitioned FFT along the sharded dim."""
    if d.ndim != 2:
        raise ValueError(f"dfft2 needs a 2-D DArray, got ndim {d.ndim}")
    return dfft(dfft(d, axis=1), axis=0)


def difft2(d: DArray) -> DArray:
    """Distributed 2-D inverse FFT (see ``dfft2``)."""
    if d.ndim != 2:
        raise ValueError(f"difft2 needs a 2-D DArray, got ndim {d.ndim}")
    return difft(difft(d, axis=0), axis=1)
