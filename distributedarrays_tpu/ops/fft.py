"""Distributed FFTs on DArrays via the all-to-all transpose algorithm.

No reference analog (the reference ships no spectral ops) — this is the
classic distributed-memory FFT recipe expressed on the framework's
collective substrate: FFT along locally-resident dims is free; an FFT
along the SHARDED dim becomes ``all_to_all`` repartition (the same
collective as the sample-sort scatter, sort.jl:24-55) → local FFT →
``all_to_all`` back.  Everything runs as ONE compiled shard_map program
per call; communication is two tiled all-to-alls over ICI regardless of
the transform size.

Eligibility for the compiled path: even layout, the array sharded on at
most one dim, and every dim divisible by the shard count (all_to_all
tiles evenly).  Anything else takes the host numpy path with the exact
cut structure kept.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ..darray import DArray, _wrap_global, darray_from_cuts

__all__ = ["dfft", "difft", "dfft2", "difft2"]


def _sharded_dim(d: DArray):
    """The single sharded dim of ``d``'s layout, or None if fully local.
    Raises for layouts sharded over more than one dim (host path)."""
    grid = [g for g in d.pids.shape]
    dims = [i for i, g in enumerate(grid) if g > 1]
    if not dims:
        return None
    if len(dims) > 1:
        raise ValueError("multi-dim grid")
    return dims[0]


@functools.lru_cache(maxsize=128)
def _fft_shm_jit(mesh, spec, ax: int, shard_dim: int, name: str,
                 inverse: bool):
    op = jnp.fft.ifft if inverse else jnp.fft.fft
    from ..parallel.collectives import pall_to_all

    def kernel(x):
        if ax != shard_dim:
            return op(x, axis=ax)
        # repartition so the transform dim is locally complete, FFT, undo.
        # pick any OTHER dim to shard during the transform
        other = next(i for i in range(x.ndim) if i != ax)
        y = pall_to_all(x, name, split_dim=other, concat_dim=ax)
        y = op(y, axis=ax)
        return pall_to_all(y, name, split_dim=ax, concat_dim=other)

    return jax.jit(jax.shard_map(kernel, mesh=mesh, in_specs=spec,
                                 out_specs=spec))


def _fft_impl(d: DArray, ax: int, inverse: bool) -> DArray:
    if not isinstance(d, DArray):
        raise TypeError(f"expected DArray, got {type(d).__name__}")
    ax = ax + d.ndim if ax < 0 else ax
    if not 0 <= ax < d.ndim:
        raise ValueError(f"axis out of range for ndim {d.ndim}")
    from .mapreduce import _even_shared_layout
    try:
        shard_dim = _sharded_dim(d)
        eligible = _even_shared_layout((d,))
        if eligible and shard_dim is not None and ax == shard_dim:
            # only the repartitioned case moves data: the all_to_all
            # splits the FIRST other dim p-ways, so only that dim (and
            # the already-evenly-cut ax dim) must divide p
            p = int(np.prod(d.pids.shape))
            if d.ndim == 1:
                eligible = False      # no second dim to repartition onto
            else:
                other = next(i for i in range(d.ndim) if i != ax)
                eligible = d.dims[other] % p == 0
    except ValueError:
        eligible = False              # multi-dim grid
        shard_dim = None
    if eligible:
        fn = _fft_shm_jit(d.sharding.mesh, d.sharding.spec, ax,
                          -1 if shard_dim is None else shard_dim,
                          "unused" if shard_dim is None
                          else d.sharding.spec[shard_dim], inverse)
        res = fn(d.garray)
        return _wrap_global(res, procs=[int(q) for q in d.pids.flat],
                            dist=list(d.pids.shape))
    # host path: exact cut structure kept, loud about the gather
    from ..utils.debug import warn_once
    warn_once(f"dfft-host-{d.pids.shape}-{d.ndim}-{ax}",
              f"dfft: layout (grid {tuple(d.pids.shape)}, dims {d.dims}, "
              f"axis {ax}) is not eligible for the compiled all_to_all "
              "path (needs an even layout, a single sharded dim, and the "
              "repartition dim divisible by the shard count); gathering "
              "to host for a numpy FFT")
    full = np.asarray(d)
    out = (np.fft.ifft if inverse else np.fft.fft)(full, axis=ax)
    return darray_from_cuts(out.astype(np.complex64),
                            [int(q) for q in d.pids.flat], d.cuts)


def dfft(d: DArray, axis: int = -1) -> DArray:
    """Distributed 1-D FFT along ``axis`` (complex64 result, same
    layout).  A resident axis is one local ``jnp.fft.fft``; the sharded
    axis costs two ``all_to_all`` repartitions around it."""
    return _fft_impl(d, axis, inverse=False)


def difft(d: DArray, axis: int = -1) -> DArray:
    """Distributed inverse 1-D FFT along ``axis`` (see ``dfft``)."""
    return _fft_impl(d, axis, inverse=True)


def dfft2(d: DArray) -> DArray:
    """Distributed 2-D FFT of a matrix DArray: local FFT along the
    resident dim, repartitioned FFT along the sharded dim."""
    if d.ndim != 2:
        raise ValueError(f"dfft2 needs a 2-D DArray, got ndim {d.ndim}")
    return dfft(dfft(d, axis=1), axis=0)


def difft2(d: DArray) -> DArray:
    """Distributed 2-D inverse FFT (see ``dfft2``)."""
    if d.ndim != 2:
        raise ValueError(f"difft2 needs a 2-D DArray, got ndim {d.ndim}")
    return difft(difft(d, axis=0), axis=1)
