"""Hand-rolled Pallas TPU RDMA ring collectives: overlap DMA with compute.

Every inter-chip exchange in the framework used to be an XLA collective
(``lax.all_gather`` / ``all_to_all`` / ``ppermute`` via the
``parallel.collectives`` helpers).  XLA's collectives are asynchronous,
but their *schedule* is XLA's: within one program the compiler sequences
the wire time of a ring step against the MXU work of the same step more
often than not.  This module owns the schedule explicitly, following the
Pallas TPU distributed recipe (SNIPPETS.md [1]/[2], the
``make_async_remote_copy`` send/recv-semaphore pattern) and the chunk
decomposition of "Memory-efficient array redistribution through portable
collective communication" (arXiv:2112.01075):

- :func:`ring_all_gather` — forward-from-output ring: each rank DMAs the
  block it most recently received straight out of its own output buffer
  into its right neighbor's output buffer, so the concat IS the transfer
  (zero staging) and the next incoming block rides the wire while the
  previous forward drains (send semaphores double-buffered).
- :func:`ring_reduce_scatter` — chunked traveling-partial ring: each
  chunk runs a p-1-step ring whose per-step receive slots are
  write-once (no reuse race by construction); the next local block's
  HBM→VMEM copy overlaps the partial's RDMA hop; chunk-to-chunk slot
  reuse is gated by a credit DMA from the consuming neighbor.
- :func:`ring_all_to_all` — chunked bidirectional all-to-all: every
  piece is DMA'd directly into its final offset of the destination
  rank's output (write-once, zero staging), alternating ring direction
  per destination distance so both ICI link directions carry traffic.
- :func:`ring_allgather_matmul` / :func:`ring_allgather_matmul_rhs` /
  :func:`ring_matmul_reducescatter` — the fused collective GEMMs: the
  next chunk's RDMA is STARTED before the resident chunk's ``jnp.dot``
  and WAITED after it, inside one kernel, so the MXU covers the wire
  time (the overlap ``ops/collective_matmul`` can only hint to XLA).

Semaphore protocol (shared by every kernel; docs/pallas_collectives.md
has the worked schedule diagrams):

- every remote copy carries a local *send* semaphore (signaled when the
  source bytes have left) and a remote *receive* semaphore (signaled on
  the destination chip when the bytes have landed);
- buffers a peer writes into are either write-once for the kernel's
  lifetime (all_gather, all_to_all, reduce-scatter recv slots within a
  chunk) or revolve under an explicit **credit**: a 4-byte RDMA from
  the consumer back to the producer that grants one more in-flight
  transfer, because a DMA-semaphore wait alone only keeps neighbors
  within one step of each other — one step is exactly the distance at
  which a 2-slot buffer is overwritten mid-read;
- all transfers of one kind are equal-sized, so a single receive
  semaphore can accumulate several landings and be drained with one
  descriptor wait per landing, in any order.

Every kernel body is **emitted from a declarative schedule**
(``ops/ring_schedules.py``): the per-step DMA starts, semaphore waits,
credit grants/takes, and compute steps are data, interpreted at trace
time by :func:`_emit` (regions → ref slices, sems → DMA-semaphore
scratch) and exhaustively model-checked by ``analysis.protocol`` — the
emitter and the checker share one source of truth, so the semaphore
protocol documented in docs/pallas_collectives.md is machine-verified,
not hand-argued (``python -m distributedarrays_tpu.analysis
verify-protocols``).

Dispatch (mirrors ``pallas_gemm``'s ``pltpu is None`` guard): the RDMA
kernels run compiled on real TPUs and in interpreter mode when forced
(tests, ``DA_TPU_RDMA=interpret``); every other platform falls back to
the bit-equivalent ``lax`` collective, counted via ``fallback.hits`` and
warned once when RDMA was explicitly requested.  ``DA_TPU_RDMA=0`` is
the kill switch.  ``DA_TPU_RDMA_CHUNKS`` pins the ring chunk depth;
unset, it is derived from ``DA_TPU_RESHARD_CHUNK_MB`` (one chunk stages
at most one reshard chunk target) with an ``"rdma_chunks"`` autotune
registry entry taking precedence, the ``pallas_gemm`` pattern.

Mesh addressing.  On a 1-D mesh the kernels use LOGICAL device ids
(ring position = device id).  Armed along one axis of a 2-D/3-D mesh —
pass ``mesh_axes`` (the mesh's full axis-name tuple, in mesh order) —
they switch to ``DeviceIdType.MESH``: the peer's device id keeps every
other axis' own coordinate (``lax.axis_index``) and replaces only the
armed axis' coordinate with the ring position, so each combination of
the other axes' coordinates runs an independent sub-ring
(``ring_schedules.mesh_subrings`` is the shared geometry and
``analysis.protocol.check_mesh_schedule`` proves the variants).  The
schedules stay symbolic in the ring position — nothing about the
protocol changes per axis.  One platform gate: Pallas *interpret* mode
only discharges DMAs on 1-D meshes (``dma_start_p``), so multi-axis
arming is compiled-TPU-only and every other platform takes the
bit-equivalent ``lax`` collective fallback (counted as usual).
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU-only namespace; absent/unusable off-TPU
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # pragma: no cover
    pltpu = None

from .. import telemetry as _tm
from ..parallel.collectives import (axis_size as _axis_size, pall_to_all,
                                    pgather)
from . import ring_schedules as _rs

__all__ = ["rdma_mode", "resolve_chunks", "resolve_dispatch",
           "dispatch_key_for", "a2a_chunks_key", "ring_all_gather",
           "ring_reduce_scatter", "ring_all_to_all",
           "ring_allgather_matmul", "ring_allgather_matmul_rhs",
           "ring_matmul_reducescatter", "gemm_ring_eligible"]


RDMA_ENV = "DA_TPU_RDMA"
CHUNKS_ENV = "DA_TPU_RDMA_CHUNKS"

# scoped-VMEM budget for the fused GEMM rings — same silicon-measured
# limit as pallas_gemm's tile sets
_VMEM_LIMIT = int(15.5 * 2**20)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pragma: no cover - uninitialized backend
        return False


def rdma_mode(interpret: bool | None = None) -> str | None:
    """The dispatch decision for one RDMA call site: ``"compiled"`` (real
    TPU), ``"interpret"`` (forced — tests / ``DA_TPU_RDMA=interpret``),
    or ``None`` (take the ``lax`` fallback).

    ``DA_TPU_RDMA=0`` kills the RDMA path everywhere.  An explicit
    ``DA_TPU_RDMA=1`` on a platform that cannot serve it warns once and
    counts every hit (``fallback.hits``); the unset default stays quiet
    off-TPU (nothing was promised)."""
    env = os.environ.get(RDMA_ENV)
    val = (env or "1").strip().lower()
    if val in ("0", "off", "false"):
        return None
    if interpret or val == "interpret":
        return "interpret" if pltpu is not None else None
    if interpret is False:
        # caller demands the compiled kernel or nothing
        return "compiled" if (pltpu is not None and _on_tpu()) else None
    if pltpu is not None and _on_tpu():
        return "compiled"
    if env is not None:
        # RDMA was explicitly requested and cannot be served here
        from ..utils.debug import warn_once
        reason = "no pltpu" if pltpu is None else "platform not tpu"
        warn_once(f"pallas_collectives:{reason}",
                  f"DA_TPU_RDMA requested but unavailable ({reason}); "
                  f"falling back to XLA collectives")
    return None


def _chunk_target_bytes() -> int:
    # late import: parallel.reshard imports this module for its kernels
    from ..parallel.reshard import _chunk_target_bytes as ct
    return ct()


def resolve_chunks(local_bytes: int, *key_parts) -> tuple[int, str]:
    """The ring chunk depth for a transfer of ``local_bytes`` per device:
    ``DA_TPU_RDMA_CHUNKS`` wins, else a valid ``"rdma_chunks"`` autotune
    entry for this shape/platform, else derived so one chunk stays under
    the ``DA_TPU_RESHARD_CHUNK_MB`` target.  Returns ``(chunks, source)``
    — the source is banked as bench provenance and stamped on the
    dispatch span."""
    env = os.environ.get(CHUNKS_ENV)
    if env:
        try:
            return max(int(env), 1), "env"
        except ValueError:
            pass
    from ..utils import autotune
    vals = autotune.valid_ints(
        autotune.get("rdma_chunks", autotune.device_key_for(*key_parts)),
        (1,))
    if vals is not None:
        return vals[0], "autotune"
    derived = -(-int(local_bytes) // _chunk_target_bytes())   # ceil
    return min(max(derived, 1), 64), "derived"


# registry namespace for per-shape-class rdma-vs-xla preferences: entries
# are the literal strings "rdma" | "xla", written by the telemetry
# advisor from dispatch-labeled side-by-side measurements
DISPATCH_KERNEL = "rdma_dispatch"


def dispatch_key_for(op: str, *parts) -> str:
    """The ``rdma_dispatch`` registry key for one dispatch site — the op
    name plus its shape class, device-fenced via ``device_key_for``.
    Stamped on the site's span (``dispatch_key`` label) so the doctor's
    side-by-side overlap stats and the advisor's preference writes
    address the same entry."""
    from ..utils import autotune
    return autotune.device_key_for(op, *parts)


def a2a_chunks_key(local_shape, dtype_str: str, p: int) -> str:
    """The ``rdma_chunks`` registry key :func:`ring_all_to_all` resolves
    its depth under (same parts as :func:`a2a_chunks_for`) — stamped on
    reshard spans so a journaled transfer names the exact autotune entry
    that shaped it."""
    from ..utils import autotune
    return autotune.device_key_for("a2a", *local_shape, dtype_str, p)


def resolve_dispatch(key: str) -> tuple[str | None, str]:
    """Per-shape-class dispatch preference for one call site: an explicit
    ``DA_TPU_RDMA`` env always wins (``(None, "env")`` — the caller's
    :func:`rdma_mode` result stands as-is); else a valid
    ``"rdma_dispatch"`` autotune entry (``"rdma"`` | ``"xla"``, written
    by the telemetry advisor) for ``key``; else ``(None, "default")``.
    Malformed entries degrade to the default, never break dispatch."""
    if os.environ.get(RDMA_ENV):
        return None, "env"
    from ..utils import autotune
    entry = autotune.get(DISPATCH_KERNEL, key)
    if isinstance(entry, str) and entry in ("rdma", "xla"):
        return entry, "autotune"
    return None, "default"


def _record_dispatch(op: str, path: str, x, axis: str, p: int = 0,
                     **labels) -> None:
    """Trace-time dispatch telemetry: a labeled counter plus, on the
    RDMA path, a comm-byte record mirroring
    ``parallel.collectives._rec`` (these helpers run inside shard_map
    tracing — once per compilation, flagged traced).  The ``xla`` path
    only counts the dispatch: its ``lax`` lowering records its own
    bytes, and two records for one transfer would double-count.

    ``p`` (the ring size) adds a ``bytes_ici`` provenance stamp to the
    comm record — PER-DEVICE ring volume, matching this record's
    per-rank-block byte convention (``collectives._rec``).  The
    execution-tier roofline stamp the doctor reads lives on the calling
    op's span (``reshard``, ``matmul.ring_ag``, ``ring_attention``) in
    aggregate-volume convention; a direct ring-kernel call inside a
    user's own shard_map has no execution span and should be wrapped in
    one (see docs/telemetry.md, *Performance observatory*)."""
    _tm.count("pallas_collectives.dispatch", op=op, path=path)
    if path == "rdma" and _tm.enabled():
        if p and p > 1:
            # every ring kernel forwards each resident/received piece
            # p-1 hops: per-device ICI volume = (p-1) x the local payload
            labels = {**labels,
                      "bytes_ici": (p - 1) * _tm.nbytes_of(x)}
        _tm.record_comm(op, _tm.nbytes_of(x), axis=axis, traced=True,
                        dispatch=path,
                        once_key=f"pallas_collectives:{op}:{path}:{axis}:"
                                 f"{labels}", **labels)


def _ds_at(ref, dim: int, start, size: int, ndim: int):
    """``ref.at[..., pl.ds(start, size), ...]`` with the slice on ``dim``."""
    idx = tuple(pl.ds(start, size) if d == dim else slice(None)
                for d in range(ndim))
    return ref.at[idx]


def _mod(a, n: int):
    """Nonnegative ``a % n`` for possibly-negative traced ``a``."""
    return lax.rem(lax.rem(a, n) + n, n)


def _copy(src, dst, sem):
    c = pltpu.make_async_copy(src, dst, sem)
    c.start()
    c.wait()


class _Credit:
    """The 4-byte flow-control grant: ``grant(to)`` DMAs one credit to a
    neighbor; ``take(frm)`` blocks until one credit has landed here.
    Contents are irrelevant (only the receive semaphore's count matters);
    concurrent grants into the same buffer are harmless.  The six ring
    kernels get their credits from the declarative schedules; this
    helper remains for the fused ring-attention kernel
    (``models/ring_attention``), whose blockwise-softmax compute is not
    schedule-emitted yet."""

    def __init__(self, buf_ref, send_sem, recv_sem):
        self.buf, self.ssem, self.rsem = buf_ref, send_sem, recv_sem

    def _desc(self, peer):
        return pltpu.make_async_remote_copy(
            src_ref=self.buf, dst_ref=self.buf,
            send_sem=self.ssem, recv_sem=self.rsem,
            device_id=peer, device_id_type=pltpu.DeviceIdType.LOGICAL)

    def grant(self, to):
        d = self._desc(to)
        d.start()
        d.wait_send()

    def take(self, frm):
        self._desc(frm).wait_recv()


def _credit_scratch():
    return [pltpu.VMEM((1, 1), jnp.int32),
            pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA]


def _mesh_device_id(mesh_axes: tuple, axis: str):
    """MESH device-id builder for a ring armed along ``axis`` of a
    multi-axis mesh: peer position replaces the armed axis' coordinate,
    every other coordinate stays mine — the emitter-side twin of
    ``ring_schedules.mesh_peer`` (the checker refutes any other
    choice)."""
    if axis not in mesh_axes:
        raise ValueError(f"armed axis {axis!r} not in mesh axes "
                         f"{mesh_axes!r}")

    def device_id(pos):
        return tuple(pos if a == axis else lax.axis_index(a)
                     for a in mesh_axes)
    return device_id


def _emit(sched, me, regions, sems, computes=None, device_id=None):
    """Replay a :class:`ring_schedules.Schedule` as Pallas DMA ops.

    ``regions`` maps buffer name → ``fn(key) -> ref slice`` (the
    kernel's geometry — keys arrive with rank expressions already
    evaluated to traced values); ``sems`` maps sem name → scratch ref;
    ``computes`` maps compute tag → ``fn(args dict)``.  Wait
    instructions rebuild an equal-shaped descriptor from their template
    DMA, the same same-size-drains-one semantics the hand-rolled
    kernels used.  Credit grants/takes arrive as ordinary
    start/wait-send/wait-recv instructions over the ``cbuf`` buffer.

    ``device_id`` (from :func:`_mesh_device_id`) maps an evaluated ring
    position to a MESH-coordinate tuple for multi-axis meshes; None
    keeps the 1-D LOGICAL addressing (position = device id)."""
    env = {"me": me, "mod": _mod}
    slots = sched.sem_slots()

    def reg(r):
        buf, key = r
        return regions[buf](_rs.ev(key, env))

    def sref(sm):
        name, idx = sm
        ref = sems[name]
        return ref.at[idx] if slots[name] else ref

    def desc(d):
        if d.peer is None:
            return pltpu.make_async_copy(reg(d.src), reg(d.dst),
                                         sref(d.sem))
        pos = _rs.ev(d.peer, env)
        if device_id is None:
            did, idt = pos, pltpu.DeviceIdType.LOGICAL
        else:
            did, idt = device_id(pos), pltpu.DeviceIdType.MESH
        return pltpu.make_async_remote_copy(
            src_ref=reg(d.src), dst_ref=reg(d.dst),
            send_sem=sref(d.send), recv_sem=sref(d.recv),
            device_id=did, device_id_type=idt)

    for ins in sched.program:
        if isinstance(ins, _rs.Start):
            desc(ins.dma).start()
        elif isinstance(ins, _rs.WaitSend):
            desc(ins.dma).wait_send()
        elif isinstance(ins, _rs.WaitRecv):
            desc(ins.dma).wait_recv()
        elif isinstance(ins, _rs.WaitLocal):
            desc(ins.dma).wait()
        else:
            computes[ins.tag]({k: _rs.ev(v, env) for k, v in ins.args})


def _arm_mesh(mode: str | None, axis: str, mesh_axes) -> tuple:
    """Normalize a kernel's ``(mode, mesh_axes)`` for the armed axis.
    A 1-D (or omitted) mesh keeps LOGICAL addressing (``None``); a
    multi-axis mesh keeps the axis tuple for MESH addressing but
    demotes *interpret* mode to the lax fallback — Pallas interpret
    mode only discharges DMAs on 1-D meshes (``dma_start_p``)."""
    if mesh_axes is None or len(mesh_axes) <= 1:
        return mode, None
    mesh_axes = tuple(mesh_axes)
    if axis not in mesh_axes:
        raise ValueError(f"armed axis {axis!r} not in mesh axes "
                         f"{mesh_axes!r}")
    if mode == "interpret":
        return None, None
    return mode, mesh_axes


# ---------------------------------------------------------------------------
# ring all-gather
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _ag_call(axis: str, p: int, shape: tuple, dtype_str: str, dim: int,
             interpret: bool, mesh_axes: tuple | None = None):
    dtype = jnp.dtype(dtype_str)
    blk = shape[dim]
    ndim = len(shape)
    out_shape = tuple(blk * p if d == dim else s
                      for d, s in enumerate(shape))

    sched = _rs.all_gather_schedule(p)
    did = _mesh_device_id(mesh_axes, axis) if mesh_axes else None

    def kernel(x_ref, o_ref, send_sem, recv_sem, copy_sem):
        _emit(sched, lax.axis_index(axis), regions={
            "x": lambda k: x_ref,
            "out": lambda k: _ds_at(o_ref, dim, k[0] * blk, blk, ndim),
        }, sems={"send": send_sem, "recv": recv_sem, "copy": copy_sem},
            device_id=did)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(out_shape, dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA],
        interpret=interpret,
    )


def ring_all_gather(x, axis: str, *, dim: int = 0,
                    interpret: bool | None = None,
                    mesh_axes: tuple | None = None):
    """``lax.all_gather(x, axis, axis=dim, tiled=True)`` as a Pallas RDMA
    ring (bit-identical: pure data movement).  Falls back to ``pgather``
    off-TPU.  ``mesh_axes`` (the full axis tuple of a multi-axis mesh)
    arms per-axis sub-rings with MESH device ids — compiled TPU only."""
    p = _axis_size(axis)
    if p == 1:
        return x
    mode, mesh_axes = _arm_mesh(rdma_mode(interpret), axis, mesh_axes)
    if mode is None:
        _record_dispatch("ring_all_gather", "xla", x, axis)
        return pgather(x, axis, tiled=True, dim=dim)
    _record_dispatch("ring_all_gather", "rdma", x, axis, p=p, mode=mode)
    shape = tuple(int(s) for s in x.shape)
    return _ag_call(axis, p, shape, str(x.dtype), dim,
                    mode == "interpret", mesh_axes)(x)


# ---------------------------------------------------------------------------
# ring all-to-all
# ---------------------------------------------------------------------------


def _chunk_fit(extent: int, want: int) -> int:
    """Largest divisor of ``extent`` that is <= ``want`` (>= 1)."""
    want = max(min(want, extent), 1)
    for c in range(want, 0, -1):
        if extent % c == 0:
            return c
    return 1


@functools.lru_cache(maxsize=256)
def _a2a_call(axis: str, p: int, shape: tuple, dtype_str: str,
              split_dim: int, concat_dim: int, nchunks: int,
              interpret: bool, mesh_axes: tuple | None = None):
    dtype = jnp.dtype(dtype_str)
    ndim = len(shape)
    sblk = shape[split_dim] // p
    out_shape = tuple(sblk if d == split_dim else
                      (s * p if d == concat_dim else s)
                      for d, s in enumerate(shape))
    cext = shape[concat_dim]
    nc = _chunk_fit(cext, nchunks)
    piece = cext // nc
    sched = _rs.all_to_all_schedule(p, nc)
    did = _mesh_device_id(mesh_axes, axis) if mesh_axes else None

    def kernel(x_ref, o_ref, send_sem, recv_sem, copy_sem):
        def x_reg(k):
            # (dst, c) piece, or (me, "all") — the whole resident block
            if k[1] == "all":
                return _ds_at(x_ref, split_dim, k[0] * sblk, sblk, ndim)
            r = _ds_at(x_ref, split_dim, k[0] * sblk, sblk, ndim)
            return _ds_at(r, concat_dim, k[1] * piece, piece, ndim)

        def o_reg(k):
            # keyed by the SENDER's rank: its piece lands at its own
            # concat offset of the destination's output
            if k[1] == "all":
                return _ds_at(o_ref, concat_dim, k[0] * cext, cext, ndim)
            return _ds_at(o_ref, concat_dim, k[0] * cext + k[1] * piece,
                          piece, ndim)

        _emit(sched, lax.axis_index(axis),
              regions={"x": x_reg, "out": o_reg},
              sems={"send": send_sem, "recv": recv_sem, "copy": copy_sem},
              device_id=did)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(out_shape, dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA,
                        pltpu.SemaphoreType.DMA],
        interpret=interpret,
    )


def a2a_chunks_for(local_shape, dtype_str: str, p: int,
                   concat_dim: int | None = None) -> tuple[int, str]:
    """The chunk depth :func:`ring_all_to_all` will use for a local
    shard of ``local_shape`` — shared with the reshard planner so the
    ``reshard`` span labels the depth the kernel actually runs.  With
    ``concat_dim`` given, the resolved depth is clamped to a divisor of
    that extent exactly like the kernel clamps it (span, bench row, and
    kernel must agree)."""
    nbytes = math.prod(local_shape) * jnp.dtype(dtype_str).itemsize
    nc, src = resolve_chunks(nbytes // max(p, 1), "a2a", *local_shape,
                             dtype_str, p)
    if concat_dim is not None:
        nc = _chunk_fit(int(local_shape[concat_dim]), nc)
    return nc, src


def ring_all_to_all(x, axis: str, *, split_dim: int, concat_dim: int,
                    chunks: int | None = None,
                    interpret: bool | None = None,
                    mesh_axes: tuple | None = None):
    """``lax.all_to_all(x, axis, split_dim, concat_dim, tiled=True)`` as
    chunked bidirectional direct RDMA (bit-identical: pure data movement;
    every piece lands at its final output offset, zero staging).
    ``split_dim == concat_dim`` keeps the ``lax`` path.  ``mesh_axes``
    arms per-axis sub-rings with MESH device ids — compiled TPU only."""
    p = _axis_size(axis)
    if p == 1:
        return x
    shape = tuple(int(s) for s in x.shape)
    # split extent must divide evenly (the lax path raises properly;
    # silent truncation would be wrong data)
    mode = rdma_mode(interpret) if (split_dim != concat_dim
                                    and shape[split_dim] % p == 0) else None
    mode, mesh_axes = _arm_mesh(mode, axis, mesh_axes)
    if mode is None:
        _record_dispatch("ring_all_to_all", "xla", x, axis)
        return pall_to_all(x, axis, split_dim=split_dim,
                           concat_dim=concat_dim)
    nc, src = (chunks, "arg") if chunks else a2a_chunks_for(
        shape, str(x.dtype), p, concat_dim)
    _record_dispatch("ring_all_to_all", "rdma", x, axis, p=p, mode=mode,
                     chunks=nc, chunks_source=src)
    return _a2a_call(axis, p, shape, str(x.dtype), split_dim, concat_dim,
                     nc, mode == "interpret", mesh_axes)(x)


# ---------------------------------------------------------------------------
# ring reduce-scatter
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _rs_call(axis: str, p: int, shape: tuple, dtype_str: str, dim: int,
             nchunks: int, interpret: bool,
             mesh_axes: tuple | None = None):
    dtype = jnp.dtype(dtype_str)
    ndim = len(shape)
    oblk = shape[dim] // p
    out_shape = tuple(oblk if d == dim else s for d, s in enumerate(shape))
    # chunk along the largest axis of the OUTPUT block so the per-chunk
    # staging (p-1 write-once receive slots + 2 revolving partials + 2
    # prefetch slots, all VMEM) stays bounded; prefer an axis other than
    # the scattered dim so the block and chunk slices stay on distinct
    # axes
    cands = sorted(range(ndim), key=lambda d: (d != dim, out_shape[d]))
    cax = cands[-1]
    nc = _chunk_fit(out_shape[cax], nchunks)
    piece = tuple(s // nc if d == cax else s
                  for d, s in enumerate(out_shape))

    sched = _rs.reduce_scatter_schedule(p, nc)
    did = _mesh_device_id(mesh_axes, axis) if mesh_axes else None

    def kernel(x_ref, o_ref, recv, acc, tmp, send_sem, recv_sem, copy_sem,
               tmp_sem, cbuf, csend, crecv):
        def x_piece(k):
            b, c = k
            r = _ds_at(x_ref, dim, b * oblk, oblk, ndim)
            # nc == 1 keeps the block slice whole (also avoids chaining
            # two slices on the same axis when ndim == 1 forces cax==dim)
            return r if nc == 1 else _ds_at(r, cax, c * piece[cax],
                                            piece[cax], ndim)

        def accum(a):
            acc[1 - a["a"]] = recv[a["t"]] + tmp[a["a"]]

        _emit(sched, lax.axis_index(axis), regions={
            "x": x_piece,
            "acc": lambda k: acc.at[k[0]],
            "recv": lambda k: recv.at[k[0]],
            "tmp": lambda k: tmp.at[k[0]],
            "out": lambda k: _ds_at(o_ref, cax, k[0] * piece[cax],
                                    piece[cax], ndim),
            "cbuf": lambda k: cbuf,
        }, sems={"send": send_sem, "recv": recv_sem, "copy": copy_sem,
                 "tmp": tmp_sem, "csend": csend, "crecv": crecv},
            computes={"accum": accum}, device_id=did)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(out_shape, dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.VMEM((p - 1,) + piece, dtype),
                        pltpu.VMEM((2,) + piece, dtype),
                        pltpu.VMEM((2,) + piece, dtype),
                        pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA((p - 1,)),
                        pltpu.SemaphoreType.DMA,
                        pltpu.SemaphoreType.DMA((2,))] + _credit_scratch(),
        interpret=interpret,
    )


def _rs_vmem_bytes(shape, itemsize, p, nc, dim):
    oblk_shape = [s // p if d == dim else s for d, s in enumerate(shape)]
    cands = sorted(range(len(shape)),
                   key=lambda d: (d != dim, oblk_shape[d]))
    cax = cands[-1]
    nc = _chunk_fit(oblk_shape[cax], nc)     # the depth the kernel fits
    piece = math.prod(s // nc if d == cax else s
                      for d, s in enumerate(oblk_shape))
    return (p + 3) * piece * itemsize


def ring_reduce_scatter(x, axis: str, *, dim: int = 0,
                        chunks: int | None = None,
                        interpret: bool | None = None,
                        mesh_axes: tuple | None = None):
    """``lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=True)``
    as a chunked Pallas RDMA traveling-partial ring.  Summation order is
    the ring arrival order (exact for integer-valued data; float results
    differ from XLA's reduction order by rounding only).  Needs the
    scattered dim divisible by the axis size; falls back otherwise.
    ``mesh_axes`` arms per-axis sub-rings — compiled TPU only."""
    p = _axis_size(axis)
    if p == 1:
        return x
    mode, mesh_axes = _arm_mesh(rdma_mode(interpret), axis, mesh_axes)
    shape = tuple(int(s) for s in x.shape)
    itemsize = jnp.dtype(x.dtype).itemsize
    nc = src = None
    if mode is not None and shape[dim] % p == 0:
        blk_bytes = math.prod(shape) * itemsize // p
        # the p-1 receive slots multiply the staged piece: derive with
        # that factor so staging stays under the chunk target
        nc, src = (chunks, "arg") if chunks else resolve_chunks(
            blk_bytes * (p - 1), "rs", *shape, str(x.dtype), p)
        if mode == "compiled" and \
                _rs_vmem_bytes(shape, itemsize, p, nc, dim) > _VMEM_LIMIT:
            mode = None                      # slots cannot fit VMEM
    elif shape[dim] % p:
        mode = None
    if mode is None:
        _record_dispatch("ring_reduce_scatter", "xla", x, axis)
        return lax.psum_scatter(x, axis, scatter_dimension=dim, tiled=True)
    _record_dispatch("ring_reduce_scatter", "rdma", x, axis, p=p, mode=mode,
                     chunks=nc, chunks_source=src)
    return _rs_call(axis, p, shape, str(x.dtype), dim, nc,
                    mode == "interpret", mesh_axes)(x)


# ---------------------------------------------------------------------------
# fused ring GEMMs: DMA started before the resident chunk's dot, waited
# after it — the compute/comm overlap the XLA ring can only hint at
# ---------------------------------------------------------------------------


def gemm_ring_eligible(kind: str, x_shape, w_shape, p: int, itemsize: int,
                       out_itemsize: int = 4) -> bool:
    """VMEM-budget gate for the fused ring GEMMs: the revolving operand
    slots, the resident stationary operand, and the output/accumulator
    must fit the scoped-VMEM budget together."""
    xb = math.prod(x_shape) * itemsize
    wb = math.prod(w_shape) * itemsize
    if kind == "ag":        # out (p*m_loc, n) + 2 slots of x + w
        ob = x_shape[0] * p * w_shape[1] * out_itemsize
        need = 2 * xb + wb + ob
    elif kind == "ag_rhs":  # 2 slots of traveling b (x_shape) + resident
        # a (w_shape = (m_loc, k)) + the (m_loc, n) accumulator
        ob = w_shape[0] * x_shape[1] * out_itemsize
        need = 2 * xb + wb + ob
    else:                   # rs: 2 acc + 2 recv of (m/p, n) + w + x
        ob = (x_shape[0] // p) * w_shape[1] * out_itemsize
        need = 4 * ob + wb + xb
    return need <= _VMEM_LIMIT


@functools.lru_cache(maxsize=128)
def _ag_mm_call(axis: str, p: int, xs: tuple, ws: tuple, dtype_str: str,
                out_dtype_str: str, interpret: bool,
                mesh_axes: tuple | None = None):
    m_loc, k = xs
    n = ws[1]
    dtype = jnp.dtype(dtype_str)
    out_dtype = jnp.dtype(out_dtype_str)

    sched = _rs.ag_matmul_schedule(p)
    did = _mesh_device_id(mesh_axes, axis) if mesh_axes else None

    def kernel(x_ref, w_ref, o_ref, buf, send_sem, recv_sem, copy_sem,
               cbuf, csend, crecv):
        def dot(a):
            # resident chunk multiplies while the forward is in flight;
            # resident chunk originated at rank me + t (the lax path's
            # pshift(-1) = fetch-from-the-right schedule)
            o_ref[pl.ds(a["src"] * m_loc, m_loc)] = jnp.dot(
                buf[a["s"]], w_ref[...],
                preferred_element_type=jnp.float32).astype(out_dtype)

        _emit(sched, lax.axis_index(axis), regions={
            "xin": lambda k: x_ref,
            "buf": lambda k: buf.at[k[0]],
            "cbuf": lambda k: cbuf,
        }, sems={"send": send_sem, "recv": recv_sem, "copy": copy_sem,
                 "csend": csend, "crecv": crecv},
            computes={"dot": dot}, device_id=did)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((p * m_loc, n), out_dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((2, m_loc, k), dtype),
                        pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA] + _credit_scratch(),
        interpret=interpret,
    )


def ring_allgather_matmul(x, w, axis: str, *,
                          interpret: bool | None = None,
                          mesh_axes: tuple | None = None):
    """``allgather_matmul``'s contract as one fused Pallas kernel: the
    next chunk's RDMA is started before the resident chunk's dot and
    waited after it.  Forward-only (no VJP); callers arm it on any
    single mesh axis for inference paths (``mesh_axes`` for multi-axis
    meshes — compiled TPU only)."""
    p = _axis_size(axis)
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    mode, mesh_axes = _arm_mesh(rdma_mode(interpret), axis, mesh_axes)
    if mode == "compiled" and not gemm_ring_eligible(
            "ag", x.shape, w.shape, p,
            jnp.dtype(x.dtype).itemsize,
            jnp.dtype(out_dtype).itemsize):
        mode = None
    if p == 1 or mode is None or x.dtype != w.dtype:
        return None                          # caller takes the lax path
    _record_dispatch("ring_allgather_matmul", "rdma", x, axis, p=p, mode=mode)
    return _ag_mm_call(axis, p, tuple(map(int, x.shape)),
                       tuple(map(int, w.shape)), str(x.dtype),
                       str(out_dtype), mode == "interpret",
                       mesh_axes)(x, w)


@functools.lru_cache(maxsize=128)
def _ag_mm_rhs_call(axis: str, p: int, as_: tuple, bs: tuple,
                    dtype_str: str, out_dtype_str: str, interpret: bool,
                    mesh_axes: tuple | None = None):
    m_loc, _k = as_
    k_loc, n = bs
    dtype = jnp.dtype(dtype_str)
    out_dtype = jnp.dtype(out_dtype_str)

    sched = _rs.ag_matmul_rhs_schedule(p)
    did = _mesh_device_id(mesh_axes, axis) if mesh_axes else None

    def kernel(a_ref, b_ref, o_ref, buf, send_sem, recv_sem, copy_sem,
               cbuf, csend, crecv):
        def accum_rhs(a):
            # resident chunk contracts against its column slice of a —
            # cast per step like the lax path's ``part``
            part = jnp.dot(a_ref[:, pl.ds(a["src"] * k_loc, k_loc)],
                           buf[a["s"]],
                           preferred_element_type=jnp.float32
                           ).astype(out_dtype)
            if a["t"] == 0:
                o_ref[...] = part
            else:
                o_ref[...] = o_ref[...] + part

        _emit(sched, lax.axis_index(axis), regions={
            "xin": lambda k: b_ref,
            "buf": lambda k: buf.at[k[0]],
            "cbuf": lambda k: cbuf,
        }, sems={"send": send_sem, "recv": recv_sem, "copy": copy_sem,
                 "csend": csend, "crecv": crecv},
            computes={"accum_rhs": accum_rhs}, device_id=did)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m_loc, n), out_dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((2, k_loc, n), dtype),
                        pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA] + _credit_scratch(),
        interpret=interpret,
    )


def ring_allgather_matmul_rhs(a, b, axis: str, *,
                              interpret: bool | None = None,
                              mesh_axes: tuple | None = None):
    """``allgather_matmul_rhs``'s contract fused: the traveling B chunk's
    forward RDMA overlaps the resident chunk's contraction."""
    p = _axis_size(axis)
    out_dtype = jnp.result_type(a.dtype, b.dtype)
    mode, mesh_axes = _arm_mesh(rdma_mode(interpret), axis, mesh_axes)
    if mode == "compiled" and not gemm_ring_eligible(
            "ag_rhs", b.shape, a.shape, p,
            jnp.dtype(b.dtype).itemsize,
            jnp.dtype(out_dtype).itemsize):
        mode = None
    if p == 1 or mode is None or a.dtype != b.dtype:
        return None
    _record_dispatch("ring_allgather_matmul_rhs", "rdma", b, axis, p=p,
                     mode=mode)
    return _ag_mm_rhs_call(axis, p, tuple(map(int, a.shape)),
                           tuple(map(int, b.shape)), str(a.dtype),
                           str(out_dtype), mode == "interpret",
                           mesh_axes)(a, b)


@functools.lru_cache(maxsize=128)
def _mm_rs_call(axis: str, p: int, xs: tuple, ws: tuple, dtype_str: str,
                interpret: bool, mesh_axes: tuple | None = None):
    m, k_loc = xs
    n = ws[1]
    m_loc = m // p
    dtype = jnp.dtype(dtype_str)

    sched = _rs.matmul_reducescatter_schedule(p)
    did = _mesh_device_id(mesh_axes, axis) if mesh_axes else None

    def kernel(x_ref, w_ref, o_ref, acc, recv, send_sem, recv_sem,
               cbuf, csend, crecv):
        # the lax path: acc seeds with destination (me - 1), forwards to
        # the RIGHT, and accumulates block (me - 1 - t) at step t; the
        # in-flight-hop GEMM parks in ``tmp`` until the wait completes
        tmp = {}

        def block(d):
            return jnp.dot(x_ref[pl.ds(d * m_loc, m_loc)], w_ref[...],
                           preferred_element_type=jnp.float32
                           ).astype(dtype)

        def gemm(a):
            if a["acc_slot"] is None:
                tmp["g"] = block(a["d"])
            else:
                acc[a["acc_slot"]] = block(a["d"])

        def accum(a):
            acc[1 - a["a"]] = recv[a["s"]] + tmp["g"]

        _emit(sched, lax.axis_index(axis), regions={
            "acc": lambda k: acc.at[k[0]],
            "recv": lambda k: recv.at[k[0]],
            "o": lambda k: o_ref,
            "cbuf": lambda k: cbuf,
        }, sems={"send": send_sem, "recv": recv_sem, "csend": csend,
                 "crecv": crecv},
            computes={"gemm": gemm, "accum": accum}, device_id=did)

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m_loc, n), dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.VMEM((2, m_loc, n), dtype),
                        pltpu.VMEM((2, m_loc, n), dtype),
                        pltpu.SemaphoreType.DMA((2,)),
                        pltpu.SemaphoreType.DMA((2,))] + _credit_scratch(),
        interpret=interpret,
    )


def ring_matmul_reducescatter(x, w, axis: str, *,
                              interpret: bool | None = None,
                              mesh_axes: tuple | None = None):
    """``matmul_reducescatter``'s contract fused: each destination
    block's GEMM runs while the traveling partial's RDMA is in flight."""
    p = _axis_size(axis)
    mode, mesh_axes = _arm_mesh(rdma_mode(interpret), axis, mesh_axes)
    if mode == "compiled" and not gemm_ring_eligible(
            "rs", x.shape, w.shape, p, jnp.dtype(x.dtype).itemsize,
            jnp.dtype(jnp.result_type(x.dtype, w.dtype)).itemsize):
        mode = None
    if p == 1 or mode is None or x.dtype != w.dtype or x.shape[0] % p:
        return None
    _record_dispatch("ring_matmul_reducescatter", "rdma", x, axis, p=p,
                     mode=mode)
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    return _mm_rs_call(axis, p, tuple(map(int, x.shape)),
                       tuple(map(int, w.shape)), str(out_dtype),
                       mode == "interpret",
                       mesh_axes)(x.astype(out_dtype),
                                  w.astype(out_dtype))
